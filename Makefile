# Developer entry points. CI runs the same commands
# (.github/workflows/); the driver runs bench.py directly.

.PHONY: test native bench bench-smoke soak soak-smoke distributed \
	chaos lint analyze-device query-dryrun fleetquery-dryrun \
	trace-dryrun churn-smoke clean

native:
	$(MAKE) -C retina_tpu/native

test: native
	python -m pytest tests/ -q

# Real-TPU benchmark (one JSON line; device step + e2e system number).
bench: native
	python bench.py

bench-smoke: native
	python bench.py --smoke

# Time-travel closed loop: burst detection -> range-query attribution
# -> targeted capture, with the query API under concurrent load.
query-dryrun: native
	python bench.py --query-dryrun

# Fleet query plane + detector diversity, CI-sized: 8 simulated nodes
# under a query storm with a mid-storm kill, plus all three builtin
# detectors driving the closed capture loop. The 64-node headline run
# is `python bench.py --fleetquery-dryrun` on hardware.
fleetquery-dryrun: native
	python bench.py --fleetquery-dryrun --smoke

# Multi-process fleet churn, CI-sized: 12 real node-agent processes,
# 3 zone relays re-shipping to a root aggregator, rolling restart +
# both asymmetric partitions + a live seed rotation, scored against
# exact ground truth. The 64-process acceptance run is
# `python bench.py --churn-dryrun`. See docs/operations.md §10.
churn-smoke: native
	python bench.py --churn-dryrun --smoke

# Flight-recorder acceptance: the <3% overhead guard, the debug
# endpoints, and the fleet dryrun's cross-process span-lineage check
# (ship span and aggregator merge span share the window-epoch trace
# ID). See docs/observability.md.
trace-dryrun: native
	python -m pytest tests/test_obs.py \
	    tests/test_chaos.py::test_fleet_node_dropout_rollup_continues -q

# 5-minute paced soak with rate/loss/RSS/scrape budgets.
soak: native
	RETINA_SOAK=1 RETINA_SOAK_SECONDS=300 \
	    python -m pytest tests/test_soak.py -q

# Endurance soak, CI-sized: live agent + 2 heavy-tail regimes + 1
# injected fault, every leak sentinel sampled per window, <=90 s.
# Emits SOAK_*.json; exit code is the sentinel verdict. The full
# rotation (>=30 min, 6 regimes, alternating faults) is
# `python bench.py --soak --soak-seconds 1800` on hardware.
soak-smoke: native
	python bench.py --soak --smoke

# Fault-injection suite: every injected fault (transfer error, hung
# harvest, plugin crash, corrupt checkpoint) must recover in-process.
chaos: native
	python -m pytest tests/ -q -m chaos

# Two-process jax.distributed mesh test (spawns 2 JAX procs).
distributed:
	RETINA_DISTRIBUTED_TESTS=1 \
	    python -m pytest tests/test_distributed_two_process.py -q

# Critical-error gate (matches .github/workflows/lint.yaml). The TPU
# image has no ruff/mypy; tools/lint.py runs the tools/analyze suite —
# the offline mirror of the high-precision ruff rules PLUS the
# repo-specific analyzers (thread safety, JAX trace purity,
# metric/config drift). See docs/static-analysis.md.
lint:
	python -m compileall -q retina_tpu tests tools bench.py __graft_entry__.py
	python tools/lint.py

# Device-program analysis (RT300 family): AOT-lowers every registered
# @device_entry program on the CPU backend and checks merge algebra,
# counter overflow, donation, replication and predicate parity.
# Seconds, not milliseconds — separate target so `make lint` stays fast.
analyze-device:
	python tools/lint.py --device

clean:
	$(MAKE) -C retina_tpu/native clean

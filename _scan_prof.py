import time, sys
import numpy as np
import jax, jax.numpy as jnp

def log(m): print(m, file=sys.stderr, flush=True)
B = 1 << 17
N = 16
rng = np.random.default_rng(0)

from retina_tpu.events.synthetic import TrafficGen
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline
from retina_tpu.events.schema import F

cfg = PipelineConfig()
gen = TrafficGen(n_flows=1_000_000, n_pods=2048, seed=42)
batches = jax.device_put(np.stack([gen.batch(B) for _ in range(N)]))
ident = IdentityMap.build_host({0x0A000000+i: i for i in range(1,2048)}, n_slots=1<<16)
p = TelemetryPipeline(cfg)
state = p.init_state()

def scan_time(name, body, carry):
    @jax.jit
    def run(c, bs):
        c, _ = jax.lax.scan(body, c, bs)
        return c
    c = run(carry, batches)
    _ = np.asarray(jax.tree_util.tree_leaves(c)[0]).ravel()[:1]
    t0 = time.perf_counter()
    c = run(c, batches)
    _ = np.asarray(jax.tree_util.tree_leaves(c)[0]).ravel()[:1]
    dt = (time.perf_counter()-t0)/N
    log(f"{name:38s} {dt*1e3:8.2f} ms ({B/dt/1e6:7.1f} M ev/s)")

def cols(rec):
    c = lambda i: rec[:, i]
    return c(F.SRC_IP), c(F.DST_IP), c(F.PORTS), c(F.META), c(F.BYTES), c(F.PACKETS)

def b_noop(s, rec):
    return s + rec[0,0], 0
scan_time("noop (read 1 elem)", b_noop, jnp.uint32(0))

def b_reduce(s, rec):
    return s + jnp.sum(rec), 0
scan_time("sum whole batch (HBM read 8MB)", b_reduce, jnp.uint32(0))

def b_ident(s, rec):
    si, di, po, me, by, pk = cols(rec)
    return s + jnp.sum(ident.lookup(si)) + jnp.sum(ident.lookup(di)), 0
scan_time("identity lookup x2", b_ident, jnp.uint32(0))

def b_cms(s, rec):
    si, di, po, me, by, pk = cols(rec)
    return s.update([si, di, po, me >> 24], pk), 0
scan_time("cms.update (d=4)", b_cms, state.flow_hh.cms)

def b_hh(s, rec):
    si, di, po, me, by, pk = cols(rec)
    return s.update([si, di, po, me >> 24], pk), 0
scan_time("flow_hh.update (cms+slots)", b_hh, state.flow_hh)

def b_hll(s, rec):
    si, di, po, me, by, pk = cols(rec)
    return s.update([si, di, po, me >> 24], jnp.zeros_like(si), jnp.ones((B,), bool)), 0
scan_time("hll_flows", b_hll, state.hll_flows)

def b_hllpod(s, rec):
    si, di, po, me, by, pk = cols(rec)
    return s.update([si], jnp.zeros_like(si), jnp.ones((B,), bool)), 0
scan_time("hll_src_per_pod (G=4096,p=8)", b_hllpod, state.hll_src_per_pod)

def b_ent(s, rec):
    si, di, po, me, by, pk = cols(rec)
    one = jnp.ones((B,), jnp.float32)
    s = s.update([si], jnp.zeros_like(si), one)
    s = s.update([di], jnp.ones_like(si), one)
    s = s.update([po & jnp.uint32(0xFFFF)], jnp.full_like(si, 2), one)
    return s, 0
scan_time("entropy x3", b_ent, state.entropy)

def b_ct(s, rec):
    si, di, po, me, by, pk = cols(rec)
    ct, *_ = s.process(si, di, po, me >> 24, (me >> 16) & jnp.uint32(0xFF), jnp.uint32(1), by, jnp.ones((B,), bool))
    return ct, 0
scan_time("conntrack.process", b_ct, state.conntrack)

def b_dense(s, rec):
    si, di, po, me, by, pk = cols(rec)
    lp = jnp.minimum(ident.lookup(di), jnp.uint32(cfg.n_pods-1))
    d = (me >> 4) & jnp.uint32(1)
    s = s.at[lp, d, 0].add(pk, mode="drop")
    s = s.at[lp, d, 1].add(by, mode="drop")
    return s, 0
scan_time("dense forward (lookup+2 scatters)", b_dense, state.pod_forward)

def b_flags(s, rec):
    si, di, po, me, by, pk = cols(rec)
    lp = jnp.minimum(ident.lookup(di), jnp.uint32(cfg.n_pods-1))
    tf = (me >> 16) & jnp.uint32(0xFF)
    for bit in range(8):
        has = ((tf >> bit) & 1).astype(bool)
        s = s.at[lp, bit].add(jnp.where(has, pk, 0), mode="drop")
    return s, 0
scan_time("tcpflags 8 scatters", b_flags, state.pod_tcpflags)

def b_scatter_raw(s, rec):
    si, di, po, me, by, pk = cols(rec)
    return s.at[si & jnp.uint32(0x7FFF)].add(pk), 0
scan_time("raw scatter-add 131k->32k", b_scatter_raw, jnp.zeros(1<<15, jnp.uint32))

def b_sort(s, rec):
    si, di, po, me, by, pk = cols(rec)
    k, v = jax.lax.sort((si, pk), num_keys=1)
    return s + k[0] + v[-1], 0
scan_time("sort pair 131k", b_sort, jnp.uint32(0))

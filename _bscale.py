import time, sys
import numpy as np
import jax, jax.numpy as jnp

def log(m): print(m, file=sys.stderr, flush=True)
from retina_tpu.events.synthetic import TrafficGen
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline

cfg = PipelineConfig()
gen = TrafficGen(n_flows=1_000_000, n_pods=2048, seed=42)
ident = IdentityMap.build_host({0x0A000000+i: i for i in range(1,2048)}, n_slots=1<<16)
p = TelemetryPipeline(cfg)

for logB in (17, 18, 19, 20):
    B = 1 << logB
    N = max(2, (1 << 21) >> logB)
    batches = jax.device_put(np.concatenate([gen.batch(1<<17) for _ in range(B >> 17)] , axis=0)[None].repeat(N, axis=0)) if False else jax.device_put(np.stack([np.concatenate([gen.batch(1<<17) for _ in range(B >> 17)], axis=0) for _ in range(N)]))
    state = p.init_state()
    def body(s, rec):
        s, _ = p.step(s, rec, jnp.uint32(B), jnp.uint32(1), ident, jnp.uint32(0))
        return s, 0
    @jax.jit
    def run(s, bs):
        s, _ = jax.lax.scan(body, s, bs)
        return s
    t0 = time.perf_counter()
    state = run(state, batches)
    _ = np.asarray(state.totals)[:1]
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    state = run(state, batches)
    _ = np.asarray(state.totals)[:1]
    dt = (time.perf_counter()-t0)/N
    log(f"B=2^{logB}: {dt*1e3:8.2f} ms/step -> {B/dt/1e6:6.2f} M ev/s (compile {compile_s:.0f}s)")

#!/usr/bin/env python3
"""Offline static analysis for retina_tpu (no third-party linters in
the TPU image, so this provides the high-precision subset of ruff's
F/E9/B rules locally; CI additionally runs real ruff+mypy where pip is
available — .github/workflows/lint.yaml).

Checks (all precise, no style opinions):
  F401  module-level import never used (skipped in __init__.py
        re-export surfaces and for names listed in __all__)
  E722  bare `except:`
  B006  mutable default argument (list/dict/set literal)
  F541  f-string without placeholders
  E711  comparison to None with ==/!=
  F601  duplicate dict literal key
  B011  assert on a non-empty tuple (always true)
  F811  duplicate top-level def/class name
  RT100 threading.Thread spawned in engine.py outside the sanctioned
        helpers (start, start_background_warm, _ensure_harvest_thread,
        _request_recovery).
        Every engine thread must be created where shutdown joins it —
        a thread spawned ad hoc escapes the stop/join protocol and the
        device-proxy single-thread invariant review.
  RT101 silent exception swallow in retina_tpu/: an `except` handler
        whose body is only `pass`/`...` hides failures from operators.
        Every swallow must at least log (rate-limited) and bump a
        named error counter; a deliberate swallow carries a
        `# noqa: RT101 — reason` on the except line.
  RT102 unbounded stdlib queue constructed in retina_tpu/: a
        `queue.Queue()` with no maxsize (or maxsize<=0), or a
        `SimpleQueue()`, has no backpressure edge — under overload it
        grows host memory without bound instead of surfacing as
        drop-and-count/shed (docs/operations.md §6). Bounded queues
        whose `.put()` blocks are fine: the bound IS the backpressure
        edge. A deliberately unbounded queue carries a
        `# noqa: RT102 — reason` on the construction line (e.g. the
        engine harvest queue: window-cadence items, trivially small).

`# noqa` (with or without a code) on the flagged line suppresses it.
Exit code 1 if any finding. Usage: python tools/lint.py [paths...]
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path


def _names_loaded(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # a.b.c -> root name a (covers `import a.b` usage)
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    return used


def _all_exports(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str):
                    out.add(elt.value)
    return out


def check_file(path: Path) -> list[tuple[int, str, str]]:
    src = path.read_text()
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, "E999", f"syntax error: {e.msg}")]

    finds: list[tuple[int, str, str]] = []

    def add(lineno: int, code: str, msg: str) -> None:
        if 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]:
            return
        finds.append((lineno, code, msg))

    used = _names_loaded(tree)
    exported = _all_exports(tree)
    is_init = path.name == "__init__.py"

    # F401 — only module-level imports; conftest/test fixtures excluded
    # by the caller's path selection.
    if not is_init:
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    if name not in used and name not in exported:
                        add(node.lineno, "F401",
                            f"`import {a.name}` unused")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    if name not in used and name not in exported:
                        add(node.lineno, "F401",
                            f"`from {node.module} import {a.name}` unused")

    seen_top: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen_top:
                add(node.lineno, "F811",
                    f"`{node.name}` redefines line {seen_top[node.name]}")
            seen_top[node.name] = node.lineno

    # Format specs (f"{x:.1f}") parse as JoinedStr children of
    # FormattedValue — not user f-strings; exclude them from F541.
    spec_ids = {
        id(n.format_spec) for n in ast.walk(tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            add(node.lineno, "E722", "bare `except:`")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in (*node.args.defaults, *node.args.kw_defaults):
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    add(d.lineno, "B006", "mutable default argument")
        elif isinstance(node, ast.JoinedStr):
            if id(node) not in spec_ids and not any(
                    isinstance(v, ast.FormattedValue)
                    for v in node.values):
                add(node.lineno, "F541", "f-string without placeholders")
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if (isinstance(op, (ast.Eq, ast.NotEq))
                        and isinstance(comp, ast.Constant)
                        and comp.value is None):
                    add(node.lineno, "E711",
                        "comparison to None (use `is`/`is not`)")
        elif isinstance(node, ast.Dict):
            keys = [
                k.value for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, (str, int))
            ]
            dupes = {k for k in keys if keys.count(k) > 1}
            if dupes:
                add(node.lineno, "F601",
                    f"duplicate dict key(s): {sorted(map(str, dupes))}")
        elif isinstance(node, ast.Assert):
            if isinstance(node.test, ast.Tuple) and node.test.elts:
                add(node.lineno, "B011",
                    "assert on a tuple is always true")

    # RT100 — engine thread spawns outside the sanctioned helpers.
    # The engine's threads all follow a create-here/join-at-shutdown
    # protocol (feed loop finally block); a Thread() anywhere else in
    # the file is a leak of that protocol until proven otherwise.
    if path.name == "engine.py":
        sanctioned = {
            "start", "start_background_warm", "_ensure_harvest_thread",
            "_request_recovery",
        }

        def _walk_fn(node: ast.AST, fn: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                nxt = fn
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # Nested defs (closures like _warm) belong to the
                    # sanctioned outer helper that defines them.
                    nxt = fn if fn in sanctioned else child.name
                if (isinstance(child, ast.Call)
                        and isinstance(child.func, ast.Attribute)
                        and child.func.attr == "Thread"
                        and isinstance(child.func.value, ast.Name)
                        and child.func.value.id == "threading"
                        and fn not in sanctioned):
                    add(child.lineno, "RT100",
                        "threading.Thread spawned outside sanctioned "
                        f"engine helpers (in `{fn or '<module>'}`)")
                _walk_fn(child, nxt)

        _walk_fn(tree, None)

    # RT101 — silent exception swallows in production code. Handlers
    # whose body is only pass/... make failures invisible; the
    # robustness contract is log-once (rate-limited) + named error
    # counter, or an explicit noqa with a reason.
    if "retina_tpu" in path.parts:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            body_silent = all(
                isinstance(stmt, ast.Pass)
                or (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is Ellipsis)
                for stmt in node.body
            )
            if body_silent:
                add(node.lineno, "RT101",
                    "silent exception swallow (`except ...: pass`) — "
                    "log + count it, or noqa with a reason")

    # RT102 — unbounded stdlib queues in production code. Matches the
    # stdlib classes via `queue`/`queue_mod` attribute access or a
    # direct `from queue import Queue` name; custom bounded queues
    # (e.g. parallel/feed.TransferQueue) are out of scope by name.
    if "retina_tpu" in path.parts:
        q_classes = {"Queue", "LifoQueue", "PriorityQueue"}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            cls = None
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("queue", "queue_mod")):
                cls = func.attr
            elif (isinstance(func, ast.Name)
                    and func.id in (q_classes | {"SimpleQueue"})):
                cls = func.id
            if cls == "SimpleQueue":
                add(node.lineno, "RT102",
                    "SimpleQueue is always unbounded — use a bounded "
                    "queue.Queue(maxsize) or noqa with a reason")
                continue
            if cls not in q_classes:
                continue
            size = None
            if node.args:
                size = node.args[0]
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    size = kw.value
            unbounded = size is None or (
                isinstance(size, ast.Constant)
                and isinstance(size.value, int) and size.value <= 0
            )
            if unbounded:
                add(node.lineno, "RT102",
                    f"unbounded {cls}() — no backpressure edge; pass "
                    "maxsize or noqa with a reason")
    return finds


def main(argv: list[str]) -> int:
    roots = [Path(p) for p in (argv or ["retina_tpu", "tests", "tools",
                                        "bench.py", "__graft_entry__.py"])]
    files: list[Path] = []
    for r in roots:
        if r.is_dir():
            files += sorted(r.rglob("*.py"))
        elif r.suffix == ".py":
            files.append(r)
    n = 0
    for f in files:
        if "__pycache__" in f.parts:
            continue
        for lineno, code, msg in check_file(f):
            print(f"{f}:{lineno}: {code} {msg}")
            n += 1
    print(f"lint: {len(files)} files, {n} finding(s)")
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python3
"""Offline static analysis for retina_tpu — thin entry point.

The rules live in tools/analyze/ (shared driver, one parse per file,
per-finding `# noqa: CODE — reason` suppression, reviewed baseline in
tools/analyze/baseline.json).  Rule catalog and conventions:
docs/static-analysis.md.  `python tools/lint.py --list-rules` prints
the family summary.

Usage: python tools/lint.py [paths...] [--update-baseline] [--device]
Exit code 1 if any non-baselined finding.

`--device` additionally runs the RT300 device-program pass: imports
jax (CPU backend), AOT-lowers every `@device_entry`-registered program
on a tiny synthetic mesh and checks merge algebra, counter-overflow
intervals, donation coverage, replication and host/device predicate
parity (seconds, not milliseconds — hence opt-in; the default lint
stays pure-AST and fast).  `make analyze-device` is the same thing.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.analyze import driver  # noqa: E402


def main(argv: list[str]) -> int:
    return driver.run(argv)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

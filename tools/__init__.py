# Package marker so `tools.analyze` is importable from the repo root
# (tests/test_analyze.py imports the analyzer modules in-process).

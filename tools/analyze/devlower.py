"""Device-program lowering harness for the RT300 family.

Builds every registered ``@device_entry`` program (retina_tpu/
devprog.py) under a tiny synthetic 4-device CPU mesh and hands the
jaxprs / lowered executables to tools/analyze/rt300.py:

- merge jaxprs + their algebra whitelists          (RT300)
- pure-sum counter chains and the overflow envelope (RT301)
- lowered args_info donation audits                (RT302)
- compiled HLO collective scans                    (RT303)
- host/device predicate parity sweeps              (RT304)

This module is the ONLY analysis module that imports jax, and the
import happens at module scope AFTER forcing the CPU backend with 4
synthetic devices — so it must only ever be imported lazily, from
``rt300.check_device`` (the default AST lint never loads it). If jax
was already imported by the host process (in-process test runners),
the env vars are no-ops and the harness degrades to however many
devices exist; `python tools/lint.py --device` always runs in a fresh
process and therefore always gets the full 4-device mesh.

Every shape here is deliberately tiny (width 8 sketches, batch 8):
the checks are properties of the PROGRAM (which primitives, which
donations, which collectives), not of the data, and tiny shapes keep
the full sweep well under the 60s tier-1 budget.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import dataclasses
import itertools
import threading
from typing import Any

import warnings

import jax

# The TPU host's site hook can pin jax_platforms at interpreter start,
# making the JAX_PLATFORMS env var above a no-op there — force the CPU
# backend through the config API too (same belt-and-braces as
# tests/conftest.py). Lowering must never ride the TPU tunnel.
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

# Deliberate policy (RT302): consumed wire/stacked operands are
# donated even where output shapes preclude aliasing — donation makes
# jax delete the caller's reference, so an accidental host reread of a
# consumed buffer errors loudly instead of silently double-using it.
# The advisory "not usable" warning is therefore expected here.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from retina_tpu.devprog import DeviceEntry, load_registry

# ---------------------------------------------------------------------
# Documented analysis envelope (RT301). These are the load-bearing
# assumptions of the no-overflow proof; docs/static-analysis.md RT301
# spells them out and the finding messages reference them.

# Per-node events per 1s window the engine is sized for: 2^28 (~268M
# ev/s) is >100x the measured single-node ceiling (bench.py); every
# u32 pure-sum counter cell can absorb at most the whole window's
# packet weight.
MAX_PACKETS_PER_WINDOW = 1 << 28

# Per combined ROW packet weight entering the HT rescale: a row
# aggregates one flow's quantum within one flush, bounded by the same
# per-window envelope.
MAX_PACKETS_PER_ROW = 1 << 28

U32_MAX = 2**32 - 1


# ---------------------------------------------------------------------
# Algebra whitelists (RT300). STRUCTURAL ops move values without
# combining them; SUM/MAX are the two associative-commutative reduction
# algebras; JOIN is the compare/select join-semilattice of
# TopKTable.merge (lexicographic (count, first-differing-key) max —
# associative, commutative, idempotent).

STRUCTURAL = frozenset({
    "reshape", "broadcast_in_dim", "convert_element_type", "transpose",
    "squeeze", "slice", "concatenate", "pad", "copy", "rev", "iota",
})
SUM = frozenset({"add"})
MAX = frozenset({"max"})
JOIN = frozenset({
    "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "xor", "not",
    "select_n", "argmax", "argmin", "reduce_or", "reduce_and",
    "reduce_max", "reduce_min", "gather",
})
# Batched (stacked-axis) reductions the fleet merge applies.
STACK_REDUCE = frozenset({"reduce_sum", "reduce_max"})

# Call primitives: transparent wrappers the jaxpr walkers recurse into.
CALL_PRIMS = frozenset({
    "pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
})


@dataclasses.dataclass
class MergeRecipe:
    entry: str
    algebra: str  # human label: "sum" | "max" | "join" | composite
    jaxpr: Any  # ClosedJaxpr
    allowed: frozenset[str]


@dataclasses.dataclass
class PurityTarget:
    entry: str  # registry entry the chain lives in
    counter: str  # human path, e.g. "state.flow_hh.cms.table"
    jaxpr: Any  # ClosedJaxpr
    out_idx: int  # flattened output position of the counter
    in_idx: int  # flattened input position of its carry source


@dataclasses.dataclass
class EntryAudit:
    entry: str
    n_args: int
    arg_donated: list[list[bool]]  # per top-level arg, per leaf
    donate_expect: tuple[int, ...]  # args that MUST be donated
    keep_expect: tuple[int, ...]  # args that MUST NOT be donated
    hlo_text: str
    allowed_collectives: frozenset[str]
    aliased: bool  # compiled program aliases at least one input/output


COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "all-to-all", "collective-permute",
    "reduce-scatter",
)


# ---------------------------------------------------------------------
# Tiny fixtures

def _mesh() -> Mesh:
    devs = jax.devices()
    return Mesh(np.array(devs[: min(4, len(devs))]), ("d",))


def _tiny_pipeline():
    from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline

    cfg = PipelineConfig(
        n_pods=16,
        n_drop_reasons=4,
        n_dns_qtypes=4,
        cms_depth=2,
        cms_width=64,
        topk_slots=8,
        hll_precision=4,
        hll_pod_precision=4,
        entropy_buckets=8,
        conntrack_slots=16,
        latency_slots=8,
        latency_buckets=8,
        enable_invertible=True,
        inv_depth=2,
        inv_width=8,
        inv_hi_width=8,
    )
    return TelemetryPipeline(cfg), cfg


def _pipeline_args(pipe):
    """Concrete tiny args for TelemetryPipeline.step (positional)."""
    from retina_tpu.models.identity import IdentityMap

    b = 8
    state = pipe.init_state()
    records = jnp.zeros((b, 16), jnp.uint32)
    n_valid = jnp.uint32(0)
    now_s = jnp.uint32(1)
    ident = IdentityMap.zeros(1 << 4, seed=1)
    apiserver_ip = jnp.uint32(0)
    filt = IdentityMap.zeros(1 << 4, seed=99)
    sample_k = jnp.uint32(1)
    return (
        state, records, n_valid, now_s, ident, apiserver_ip, filt,
        sample_k,
    )


def _protos():
    from retina_tpu.ops.countmin import CountMinSketch
    from retina_tpu.ops.entropy import EntropyWindow
    from retina_tpu.ops.hyperloglog import HyperLogLog
    from retina_tpu.ops.invertible import InvertibleSketch
    from retina_tpu.ops.topk import HeavyHitterSketch, TopKTable

    return {
        "cms": CountMinSketch.zeros(2, 8, seed=1),
        "topk": TopKTable.zeros(2, 8, seed=1),
        "hh": HeavyHitterSketch.zeros(2, depth=2, width=8, n_slots=8, seed=1),
        "hll": HyperLogLog.zeros(2, 4, seed=1),
        "entropy": EntropyWindow.zeros(2, 8, seed=1),
        "inv": InvertibleSketch.zeros(2, 8, 4, seed=1),
    }


# ---------------------------------------------------------------------
# RT300: merge jaxprs

def merge_recipes() -> list[MergeRecipe]:
    p = _protos()
    mk = jax.make_jaxpr

    def jp(a):
        return mk(lambda x, y: x.merge(y))(a, a)

    recipes = [
        MergeRecipe("cms.merge", "sum", jp(p["cms"]), SUM | STRUCTURAL),
        MergeRecipe("hll.merge", "max", jp(p["hll"]), MAX | STRUCTURAL),
        MergeRecipe(
            "entropy.merge", "sum", jp(p["entropy"]), SUM | STRUCTURAL
        ),
        MergeRecipe("inv.merge", "sum", jp(p["inv"]), SUM | STRUCTURAL),
        MergeRecipe(
            "topk.merge", "join", jp(p["topk"]), JOIN | STRUCTURAL
        ),
        MergeRecipe(
            "hh.merge", "sum+join", jp(p["hh"]), SUM | JOIN | STRUCTURAL
        ),
    ]
    recipes.append(_fleet_merge_recipe())
    recipes.append(_timetravel_fold_recipe())
    return recipes


def _fleet_stub():
    from retina_tpu.fleet.aggregator import FleetAggregator

    agg = FleetAggregator.__new__(FleetAggregator)
    agg._merge_cache = {}
    return agg


def _fleet_merge_arrays(n: int = 3) -> tuple[dict, tuple, dict]:
    """A representative stacked-arrays dict: one sum family, one max
    family, one candidate-table pair, so every branch of the fleet
    merge closure is traced."""
    stacked = {
        "flow_cms": jnp.zeros((n, 2, 8), jnp.uint32),
        "flow_keys": jnp.zeros((n, 8, 4), jnp.uint32),
        "flow_counts": jnp.zeros((n, 8), jnp.uint32),
        "hll_flows": jnp.zeros((n, 2, 4), jnp.uint32),
        "entropy": jnp.zeros((n, 2, 8), jnp.float32),
        "totals": jnp.zeros((n, 16), jnp.uint32),
    }
    names = tuple(sorted(stacked))
    seeds = {"flow": 1}
    return stacked, names, seeds


def _fleet_merge_recipe() -> MergeRecipe:
    agg = _fleet_stub()
    stacked, names, seeds = _fleet_merge_arrays()
    fn = agg._merge_fn(3, seeds, names)
    jaxpr = jax.make_jaxpr(fn)(stacked)
    # Union whitelist: the fleet merge folds every family in one
    # program (sums + HLL max + candidate-table join); the per-family
    # strictness comes from the per-op recipes above.
    return MergeRecipe(
        "fleet.merge", "sum+max+join", jaxpr,
        SUM | MAX | JOIN | STRUCTURAL | STACK_REDUCE,
    )


def _timetravel_stub():
    from retina_tpu.timetravel.fold import RangeFold

    return RangeFold()


def _timetravel_fold_recipe() -> MergeRecipe:
    """The time-axis fold (timetravel/fold.py) runs the same batched
    reduction as the fleet merge over stacked RING slots instead of
    stacked nodes — same algebra obligation, same whitelist."""
    fold = _timetravel_stub()
    stacked, names, seeds = _fleet_merge_arrays()
    fn = fold._fold_fn(3, seeds, names)
    jaxpr = jax.make_jaxpr(fn)(stacked)
    return MergeRecipe(
        "timetravel.range_fold", "sum+max+join", jaxpr,
        SUM | MAX | JOIN | STRUCTURAL | STACK_REDUCE,
    )


# ---------------------------------------------------------------------
# Trace-only smokes: update kernels that carry no algebra obligation
# (max/select updates) still get traced so the inventory covers them.

def update_trace_smokes() -> list[tuple[str, Any]]:
    p = _protos()
    mk = jax.make_jaxpr
    k = jnp.zeros((8,), jnp.uint32)
    w = jnp.zeros((8,), jnp.uint32)
    g = jnp.zeros((8,), jnp.uint32)
    m = jnp.zeros((8,), bool)
    return [
        ("topk.update", mk(lambda s: s.update([k, k], w))(p["topk"])),
        ("hh.update", mk(lambda s: s.update([k, k], w))(p["hh"])),
        ("hll.update", mk(lambda s: s.update([k, k], g, m))(p["hll"])),
    ]


# ---------------------------------------------------------------------
# RT301a: pure-sum counter carrier chains

# PipelineState leaves (dotted attribute paths) that are u32 pure-sum
# counters: their whole in-window update path must be scatter-add /
# add so the per-window overflow bound (RT301b) actually applies.
PURE_SUM_COUNTERS = (
    "flow_hh.cms.table",
    "svc_hh.cms.table",
    "dns_hh.cms.table",
    "inv_flow.planes",
    "inv_flow.weights",
    "inv_hi.planes",
    "inv_hi.weights",
    "pod_forward",
    "pod_drop",
    "pod_tcpflags",
    "pod_dns",
    "pod_retrans",
    "lat_hist",
)

# State leaves (path prefixes) deliberately NOT pure-sum, with the
# reviewed reason — kept here so a new counter field must be
# classified one way or the other (rt300 flags unclassified u32
# leaves via classify_state_counters).
COUNTER_EXEMPT = {
    "totals": "documented wrap: u32 lane counters, host keeps exact f64",
    "ct_totals": "two-limb u32 pair with explicit carry (_sum64)",
    "node_counters": "derived per-window tallies (masked selects), "
                     "reset every snapshot cycle",
    "flow_hh.table": "candidate table: join-semilattice, not sums",
    "svc_hh.table": "candidate table: join-semilattice, not sums",
    "dns_hh.table": "candidate table: join-semilattice, not sums",
    "hll_flows": "HLL registers: max algebra",
    "hll_src_per_reason": "HLL registers: max algebra",
    "hll_src_per_pod": "HLL registers: max algebra",
    "entropy": "float32 histogram (IEEE saturates, no wrap)",
    "anomaly": "float EWMA state",
    "conntrack": "slotted connection table: set/overwrite semantics",
    "lat_key": "latency probe keys: overwrite semantics",
    "lat_ts": "latency probe timestamps: overwrite semantics",
}


class _Tag:
    """Unique leaf marker used to recover dotted attribute paths from
    keyless custom pytrees (PipelineState registers without keypaths,
    so tree_flatten_with_path only yields flat indices)."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def _leaf_names(tree) -> dict[int, str]:
    """flat-leaf-index -> dotted attribute path, by mapping every leaf
    to a _Tag and walking the reconstructed pytree's dataclass
    attributes."""
    cnt = itertools.count()
    tagged = jax.tree_util.tree_map(lambda _: _Tag(next(cnt)), tree)
    names: dict[int, str] = {}

    def walk(obj, prefix):
        if isinstance(obj, _Tag):
            names[obj.i] = prefix
        elif dataclasses.is_dataclass(obj):
            for f in dataclasses.fields(obj):
                sub = getattr(obj, f.name)
                walk(sub, f"{prefix}.{f.name}" if prefix else f.name)
        elif isinstance(obj, (list, tuple)):
            for i, sub in enumerate(obj):
                walk(sub, f"{prefix}[{i}]")
        elif isinstance(obj, dict):
            for kk, sub in obj.items():
                walk(sub, f"{prefix}[{kk}]")
        # anything else (aux scalars like seeds) is not a leaf

    walk(tagged, "")
    n = len(jax.tree_util.tree_leaves(tree))
    if len(names) != n:
        raise AssertionError(
            f"leaf-name walk found {len(names)} of {n} leaves — "
            "an unregistered container hides leaves from getattr"
        )
    return names


def step_purity_targets() -> list[PurityTarget]:
    """The pipeline.step jaxpr plus (out_idx, in_idx) pairs for every
    pure-sum counter leaf of PipelineState.

    state is positional arg 0 so its leaves open the jaxpr invars; the
    returned new_state shares the state treedef and flattens first in
    the (new_state, summary) output, so out_idx == in_idx."""
    pipe, _cfg = _tiny_pipeline()
    args = _pipeline_args(pipe)
    closed = jax.make_jaxpr(pipe.step)(*args)
    by_name = {v: k for k, v in _leaf_names(args[0]).items()}
    targets = []
    for c in PURE_SUM_COUNTERS:
        if c not in by_name:
            raise AssertionError(
                f"PURE_SUM_COUNTERS entry is not a PipelineState "
                f"leaf: {c}"
            )
        idx = by_name[c]
        targets.append(
            PurityTarget(
                entry="pipeline.step", counter=c, jaxpr=closed,
                out_idx=idx, in_idx=idx,
            )
        )
    return targets


def op_purity_targets() -> list[PurityTarget]:
    """Per-op pure-sum chains: sketch.update must carry its counter
    through scatter-add/add only."""
    p = _protos()
    k = jnp.zeros((8,), jnp.uint32)
    w = jnp.zeros((8,), jnp.uint32)
    g = jnp.zeros((8,), jnp.uint32)
    out = []

    cms_j = jax.make_jaxpr(lambda s: s.update([k, k], w))(p["cms"])
    out.append(PurityTarget("cms.update", "cms.table", cms_j, 0, 0))

    ent_j = jax.make_jaxpr(lambda s: s.update([k, k], g, w))(p["entropy"])
    out.append(
        PurityTarget("entropy.update", "entropy.counts", ent_j, 0, 0)
    )

    inv_j = jax.make_jaxpr(lambda s: s.update([k, k, k, k], w))(p["inv"])
    out.append(PurityTarget("inv.update", "inv.planes", inv_j, 0, 0))
    out.append(PurityTarget("inv.update", "inv.weights", inv_j, 1, 1))
    return out


def classify_state_counters() -> list[str]:
    """Every u32 PipelineState leaf must be either in
    PURE_SUM_COUNTERS or COUNTER_EXEMPT — returns the unclassified
    (a new counter field fails RT301 until it is classified)."""
    pipe, _cfg = _tiny_pipeline()
    shape = jax.eval_shape(pipe.init_state)
    names = _leaf_names(shape)
    leaves = jax.tree_util.tree_leaves(shape)
    pure = set(PURE_SUM_COUNTERS)
    unclassified = []
    for i, leaf in enumerate(leaves):
        if str(leaf.dtype) != "uint32":
            continue
        name = names[i]
        if name in pure:
            continue
        if any(
            name == e or name.startswith(e + ".") or
            name.startswith(e + "[")
            for e in COUNTER_EXEMPT
        ):
            continue
        unclassified.append(name)
    return unclassified


# ---------------------------------------------------------------------
# RT301b: per-window wrap bound from config maxima

def window_wrap_report() -> dict[str, Any]:
    from retina_tpu.config import Config

    cfg = Config()
    k = max(1, int(cfg.overload_sample_k))
    window = max(1, int(np.ceil(cfg.window_seconds)))
    bound = k * MAX_PACKETS_PER_WINDOW * window
    return {
        "k": k,
        "window_seconds": window,
        "envelope": MAX_PACKETS_PER_WINDOW,
        "bound": bound,
        "ok": bound <= U32_MAX,
    }


# ---------------------------------------------------------------------
# RT301c: HT-rescale interval target

def ht_rescale_target() -> tuple[Any, list[tuple[int, int]]]:
    """(closed_jaxpr, input intervals) for models.pipeline.ht_rescale
    under the documented per-row envelope."""
    from retina_tpu.models.pipeline import ht_rescale

    b = 8
    jaxpr = jax.make_jaxpr(ht_rescale)(
        jnp.zeros((b,), jnp.uint32),
        jnp.zeros((b,), jnp.uint32),
        jnp.zeros((b,), bool),
        jnp.uint32(1),
    )
    from retina_tpu.config import Config

    k = max(1, int(Config().overload_sample_k))
    intervals = [
        (0, MAX_PACKETS_PER_ROW),  # packets
        (0, MAX_PACKETS_PER_ROW),  # bytes (same per-row envelope)
        (0, 1),  # exempt
        (1, k),  # sample_k
    ]
    return jaxpr, intervals


# ---------------------------------------------------------------------
# RT302/RT303: lowered entry audits

def _arg_donated(obj, n_args: int) -> list[list[bool]]:
    """Per top-level positional arg, the donated flag of each leaf."""
    info = obj.args_info
    if (
        isinstance(info, tuple)
        and len(info) == 2
        and isinstance(info[1], dict)
    ):
        info = info[0]
    return [
        [a.donated for a in jax.tree_util.tree_leaves(info[i])]
        for i in range(n_args)
    ]


def _audit(
    entry: str,
    lowered,
    n_args: int,
    donate: tuple[int, ...] = (),
    keep: tuple[int, ...] = (),
    allowed: frozenset[str] = frozenset(),
) -> EntryAudit:
    compiled = lowered.compile() if hasattr(lowered, "compile") else lowered
    hlo = compiled.as_text()
    return EntryAudit(
        entry=entry,
        n_args=n_args,
        arg_donated=_arg_donated(lowered, n_args),
        donate_expect=donate,
        keep_expect=keep,
        hlo_text=hlo,
        allowed_collectives=allowed,
        aliased="input_output_alias" in hlo,
    )


def _engine_stub(mesh: Mesh):
    from retina_tpu.config import Config
    from retina_tpu.engine import SketchEngine

    eng = SketchEngine.__new__(SketchEngine)
    eng.cfg = dataclasses.replace(
        Config(), batch_capacity=16, flow_dict_slots=32
    )
    eng.n_devices = mesh.size
    eng._rec_sharding = NamedSharding(mesh, P("d"))
    eng._replicated = NamedSharding(mesh, P())
    eng._pad_cache = {}
    eng._fd_lock = threading.Lock()
    eng._desc_table = None
    eng._fd_id_bits = max(
        1, (eng.cfg.flow_dict_slots - 1).bit_length()
    )
    # Audit the DEFAULT wire shape (v4 dense known stream); the stub
    # never touches a disk cache, so the AOT signature is inert here.
    eng._fd_dense = bool(eng.cfg.wire_dense_known)
    eng._aot_sig = ""
    return eng


def entry_audits() -> list[EntryAudit]:
    mesh = _mesh()
    audits: list[EntryAudit] = []

    # -- single-chip pipeline ------------------------------------------
    pipe, cfg = _tiny_pipeline()
    args = _pipeline_args(pipe)
    step_low = pipe.jitted_step().lower(*args)
    audits.append(
        _audit(
            "pipeline.step", step_low, len(args),
            donate=(0,),
            keep=(4, 6),  # ident / filter_map are resident tables
        )
    )
    ew_low = pipe.jitted_end_window().lower(args[0], 4.0)
    audits.append(
        _audit("pipeline.end_window", ew_low, 2, donate=(0,))
    )

    from retina_tpu.ops.countmin import CountMinSketch, cms_update_jit

    proto = CountMinSketch.zeros(2, 8, seed=1)
    kcols = [jnp.zeros((8,), jnp.uint32)] * 2
    cms_low = cms_update_jit.lower(
        proto, kcols, jnp.zeros((8,), jnp.uint32)
    )
    audits.append(_audit("cms.update_jit", cms_low, 3, donate=(0,)))

    # -- sharded telemetry programs ------------------------------------
    from retina_tpu.models.identity import IdentityMap
    from retina_tpu.parallel.telemetry import ShardedTelemetry

    st = ShardedTelemetry(cfg, mesh)
    d, b = mesh.size, 8
    state = st.init_state()
    records = jnp.zeros((d, b, 16), jnp.uint32)
    n_valid = jnp.zeros((d,), jnp.uint32)
    ident = IdentityMap.zeros(1 << 4, seed=1)
    filt = IdentityMap.zeros(1 << 4, seed=99)
    u = jnp.uint32(0)

    audits.append(
        _audit(
            "sharded.init_state", st._build_init_state().lower(), 0,
        )
    )
    step_prog = st._build_step()
    audits.append(
        _audit(
            "sharded.step",
            step_prog._jitted.lower(
                state, records, n_valid, u, ident, u, filt, u,
                jnp.uint32(1),
            ),
            9,
            donate=(0,),
            keep=(4, 6),
            allowed=frozenset({"all-reduce"}),
        )
    )
    audits.append(
        _audit(
            "sharded.end_window",
            st._build_end_window()._jitted.lower(
                state, jnp.float32(4.0)
            ),
            2,
            donate=(0,),
            allowed=frozenset({"all-reduce"}),
        )
    )
    audits.append(
        _audit(
            "sharded.snapshot",
            st._build_snapshot()._jitted.lower(state, u),
            2,
            keep=(0,),  # snapshot must NOT consume resident state
            allowed=frozenset({"all-reduce", "all-gather"}),
        )
    )
    audits.append(
        _audit(
            "sharded.fleet_export",
            st._build_fleet_export()._jitted.lower(state),
            1,
            keep=(0,),
            allowed=frozenset({"all-reduce", "all-gather"}),
        )
    )
    audits.append(
        _audit(
            "sharded.inv_decode",
            st._build_inv_decode()._jitted.lower(state, u),
            2,
            keep=(0,),
            allowed=frozenset({"all-reduce"}),
        )
    )
    flat_fn, _leaves, _treedef = st._build_snapshot_flat(state)
    audits.append(
        _audit(
            "sharded.snapshot_flat",
            flat_fn._jitted.lower(state, u),
            2,
            keep=(0,),
            allowed=frozenset({"all-reduce", "all-gather"}),
        )
    )

    # -- engine ingest programs ----------------------------------------
    # Ingest crosses the host->device placement boundary: the wire
    # array arrives sharded but meta is replicated and the derived
    # per-device validity counts must land sharded, so XLA emits
    # placement collectives over the SMALL wire/meta arrays. Those are
    # inherent to ingestion; RT303's teeth are on the state-resident
    # entries above (step/end_window: all-reduce only; merges: none).
    eng = _engine_stub(mesh)
    audits.append(
        _audit(
            "engine.ingest", eng._ingest_fn(8, packed=True), 2,
            donate=(0,),
            allowed=frozenset({"collective-permute"}),
        )
    )
    audits.append(
        _audit(
            "engine.ingest_new", eng._ingest_new_fn(8), 3,
            donate=(0, 2),
            allowed=frozenset({"all-gather", "collective-permute"}),
        )
    )
    audits.append(
        _audit(
            "engine.ingest_known", eng._ingest_known_fn(8), 3,
            donate=(0,),
            keep=(2,),  # resident descriptor table, reread every flush
            allowed=frozenset(
                {"all-reduce", "all-gather", "collective-permute"}
            ),
        )
    )
    audits.append(
        _audit("engine.desc_table", eng._desc_table_fn().lower(), 0)
    )

    # -- fleet merge ---------------------------------------------------
    agg = _fleet_stub()
    stacked, names, seeds = _fleet_merge_arrays()
    fm_low = agg._merge_fn(3, seeds, names).lower(stacked)
    audits.append(_audit("fleet.merge", fm_low, 1, donate=(0,)))

    # -- timetravel range fold ----------------------------------------
    fold = _timetravel_stub()
    stacked, names, seeds = _fleet_merge_arrays()
    tt_low = fold._fold_fn(3, seeds, names).lower(stacked)
    audits.append(_audit("timetravel.range_fold", tt_low, 1, donate=(0,)))

    # -- timetravel range decode --------------------------------------
    # Tiny invertible region: width 8, depth 2, 4 key cols -> 160 bit
    # planes; CMS table at matching width. No donation: the operands
    # are live ring snapshot state.
    from retina_tpu.timetravel.fold import _decode_program

    planes = jnp.zeros((2, 8, 160), jnp.uint32)
    weights = jnp.zeros((2, 8), jnp.uint32)
    table = jnp.zeros((2, 8), jnp.uint32)
    td_low = _decode_program(planes.shape, 9, 1).lower(
        planes, weights, table
    )
    audits.append(_audit("timetravel.range_decode", td_low, 3))

    # -- timetravel range extract -------------------------------------
    # Derived answers over one folded snapshot (shape = stacked[0]).
    from retina_tpu.timetravel.fold import _extract_program

    stacked, _names, seeds = _fleet_merge_arrays()
    sub = {
        k: stacked[k][0]
        for k in ("flow_cms", "flow_keys", "hll_flows", "entropy")
    }
    ex_names = tuple(sorted(sub))
    ex_shapes = tuple(sub[n].shape for n in ex_names)
    ex_low = _extract_program(ex_names, ex_shapes, seeds).lower(sub)
    audits.append(_audit("timetravel.range_extract", ex_low, 1))

    # -- detector scoring programs ------------------------------------
    # Tiny host-built feature inputs (detect/features.py); no donation:
    # the arrays are window accumulators the host reuses.
    from retina_tpu.detect.programs import (
        dnstunnel_program, portscan_program, synflood_program,
    )

    ps_keys = jnp.zeros((16, 4), jnp.uint32)
    ps_w = jnp.zeros((16,), jnp.float32)
    ps_low = portscan_program(16, 8, 4, 0x5CA7).lower(ps_keys, ps_w)
    audits.append(_audit("detect.portscan", ps_low, 2))

    dt_low = dnstunnel_program(64, 0xD25).lower(
        jnp.zeros((1, 64), jnp.float32)
    )
    audits.append(_audit("detect.dnstunnel", dt_low, 1))

    sf_low = synflood_program().lower(jnp.zeros((9,), jnp.float32))
    audits.append(_audit("detect.synflood", sf_low, 1))

    return audits


# ---------------------------------------------------------------------
# RT304: host/device predicate parity

def _ip_domain(rng) -> np.ndarray:
    vals = [0, 1, 0xFF, 0xFFFFFFFF, 0x0A000001, 0xC0A80101]
    vals += [1 << i for i in range(32)]
    vals += list(rng.randint(0, 2**32, size=64, dtype=np.uint64))
    return np.asarray(vals, np.uint32)


def parity_report() -> list[str]:
    """Execute host predicates against their device twins over the
    packed-field bit domain; returns mismatch descriptions."""
    from retina_tpu.models import pipeline as dev
    from retina_tpu.runtime import overload as host

    rng = np.random.RandomState(0)
    problems: list[str] = []

    # priority_class vs priority_class_np -----------------------------
    ips = _ip_domain(rng)
    src = np.tile(ips, len(ips))
    dst = np.repeat(ips, len(ips))
    mask_cases = [
        (0, 0),
        (0xFFFFFF00, 0x0A000000),
        (0xFFFF0000, 0xC0A80000),
        (0x80000000, 0x80000000),
        (1, 1),
        (1, 0),
        (0xFFFFFFFF, 0x0A000001),
    ]
    for mask, match in mask_cases:
        got_dev = np.asarray(
            dev.priority_class(
                jnp.asarray(src), jnp.asarray(dst), mask, match
            )
        )
        got_host = host.priority_class_np(src, dst, mask, match)
        if not np.array_equal(got_dev, got_host):
            n = int(np.sum(got_dev != got_host))
            problems.append(
                f"priority_class: device and host disagree on {n} of "
                f"{len(src)} inputs (mask=0x{mask:08x}, "
                f"match=0x{match:08x})"
            )

    # sample_exempt vs row_tiers > TIER_BACKGROUND --------------------
    from retina_tpu.events.schema import F

    packets_dom = np.asarray(
        [0, 1, 62, 63, 64, 65, 127, 128, 2**16, 2**31, U32_MAX]
        + [1 << i for i in range(32)],
        np.uint32,
    )
    ts_dom = np.asarray([0, 1, 0x80000000, U32_MAX], np.uint32)
    pri_ips = np.asarray([0, 0x0A000001, 0x0A0000FF, 0x0B000001], np.uint32)

    pk = np.tile(
        np.repeat(packets_dom, len(ts_dom) * len(ts_dom)), len(pri_ips)
    )
    tsv = np.tile(
        np.tile(np.repeat(ts_dom, len(ts_dom)), len(packets_dom)),
        len(pri_ips),
    )
    tse = np.tile(
        np.tile(ts_dom, len(ts_dom) * len(packets_dom)), len(pri_ips)
    )
    sip = np.repeat(pri_ips, len(packets_dom) * len(ts_dom) * len(ts_dom))
    n = len(pk)

    class _Cfg:
        overload_exempt_packets = 64
        overload_priority_ip_mask = 0xFFFFFF00
        overload_priority_ip_match = 0x0A000000

    rec = np.zeros((n, 16), np.uint32)
    rec[:, F.PACKETS] = pk
    rec[:, F.TSVAL] = tsv
    rec[:, F.TSECR] = tse
    rec[:, F.SRC_IP] = sip
    host_exempt = host.row_tiers(rec, _Cfg) > host.TIER_BACKGROUND

    is_pri = np.asarray(
        dev.priority_class(
            jnp.asarray(sip), jnp.zeros((n,), jnp.uint32),
            _Cfg.overload_priority_ip_mask,
            _Cfg.overload_priority_ip_match,
        )
    )
    dev_exempt = np.asarray(
        dev.sample_exempt(
            jnp.asarray(pk), jnp.asarray(tsv), jnp.asarray(tse),
            jnp.asarray(is_pri), _Cfg.overload_exempt_packets,
        )
    )
    if not np.array_equal(dev_exempt, host_exempt):
        bad = int(np.sum(dev_exempt != host_exempt))
        problems.append(
            f"sample_exempt: device predicate and host row_tiers "
            f"exemption disagree on {bad} of {n} packed-field inputs"
        )
    return problems


# ---------------------------------------------------------------------
# Inventory parity: which registry entries the recipes above cover.

RECIPE_COVERAGE = {
    # RT300 merge algebra
    "cms.merge": "merge",
    "hll.merge": "merge",
    "entropy.merge": "merge",
    "inv.merge": "merge",
    "topk.merge": "merge",
    "hh.merge": "merge",
    # RT301 purity
    "cms.update": "purity",
    "entropy.update": "purity",
    "inv.update": "purity",
    # trace smokes (max/join updates carry no sum obligation)
    "topk.update": "trace",
    "hh.update": "trace",
    "hll.update": "trace",
    # RT302/RT303 lowered audits
    "pipeline.step": "audit",
    "pipeline.end_window": "audit",
    "cms.update_jit": "audit",
    "sharded.init_state": "audit",
    "sharded.step": "audit",
    "sharded.end_window": "audit",
    "sharded.snapshot": "audit",
    "sharded.fleet_export": "audit",
    "sharded.inv_decode": "audit",
    "sharded.snapshot_flat": "audit",
    "engine.ingest": "audit",
    "engine.ingest_new": "audit",
    "engine.ingest_known": "audit",
    "engine.desc_table": "audit",
    "fleet.merge": "merge+audit",
    "timetravel.range_fold": "merge+audit",
    "timetravel.range_decode": "audit",
    "timetravel.range_extract": "audit",
    "detect.portscan": "audit",
    "detect.dnstunnel": "audit",
    "detect.synflood": "audit",
}


def registry() -> dict[str, DeviceEntry]:
    return load_registry()

"""Interval analysis over jaxprs (RT301c, docs/static-analysis.md).

Propagates integer value intervals through a jaxpr's equations and
reports every operation whose result can leave its dtype's range —
i.e. every place a u32 counter or product can silently wrap on device.
DUNE (arxiv 2212.04816) is the motivating failure: sketch accuracy
collapses when counters saturate, and nothing in the output says so.

Design points:

- **Sound, not complete.** Every transfer function over-approximates:
  the true set of reachable values is inside [lo, hi]. "no wrap
  reported" is therefore a proof under the stated input envelope;
  a reported wrap may be a false alarm (intervals are non-relational).
- **Definite branches prune.** A comparison whose operand intervals
  do not overlap yields [0,0] or [1,1], and ``select_n`` with a
  definite predicate takes exactly one arm — this is what lets the
  Horvitz-Thompson rescale (models/pipeline.py ``ht_rescale``) prove
  its multiply cannot wrap under the documented per-row envelope: the
  saturation guard ``packets > lim`` is definitely false there, so
  the poisoned cap arm never joins the result.
- **Unknown primitives are loud.** An unmodeled primitive gets the
  full dtype range (sound) AND is recorded in ``unknown`` — the
  caller (rt300) turns that into a finding, so new primitives in an
  analyzed program can't silently weaken the proof.

The module is deliberately jax-free: it walks jaxpr objects
duck-typed (``eqn.primitive.name``, ``var.aval``), so the fast AST
lint can import rule modules without ever touching jax.
"""

from __future__ import annotations

import dataclasses
from typing import Any

# dtype name -> (min, max). Missing name (floats) => unbounded, no
# wrap tracking (IEEE saturates to inf, it does not wrap).
_RANGES = {
    "bool": (0, 1),
    "uint8": (0, 2**8 - 1),
    "uint16": (0, 2**16 - 1),
    "uint32": (0, 2**32 - 1),
    "uint64": (0, 2**64 - 1),
    "int8": (-(2**7), 2**7 - 1),
    "int16": (-(2**15), 2**15 - 1),
    "int32": (-(2**31), 2**31 - 1),
    "int64": (-(2**63), 2**63 - 1),
}

_UNBOUNDED = (float("-inf"), float("inf"))


def dtype_range(dtype: Any) -> tuple[float, float]:
    return _RANGES.get(str(dtype), _UNBOUNDED)


@dataclasses.dataclass(frozen=True)
class Interval:
    lo: float
    hi: float

    def __post_init__(self) -> None:
        assert self.lo <= self.hi, (self.lo, self.hi)


@dataclasses.dataclass
class IntervalResult:
    out: list[Interval]
    wrapped: list[str]  # ops whose result can leave its dtype range
    unknown: list[str]  # primitive names with no transfer function

    @property
    def ok(self) -> bool:
        return not self.wrapped and not self.unknown


def _hull(a: Interval, b: Interval) -> Interval:
    return Interval(min(a.lo, b.lo), max(a.hi, b.hi))


# ---------------------------------------------------------------------
# Per-primitive transfer functions. Each takes (eqn, ins) and returns
# the raw (lo, hi) BEFORE dtype clamping; the driver clamps and flags.

def _t_add(eqn, ins):
    return ins[0].lo + ins[1].lo, ins[0].hi + ins[1].hi


def _t_sub(eqn, ins):
    return ins[0].lo - ins[1].hi, ins[0].hi - ins[1].lo


def _t_mul(eqn, ins):
    prods = [
        a * b
        for a in (ins[0].lo, ins[0].hi)
        for b in (ins[1].lo, ins[1].hi)
    ]
    return min(prods), max(prods)


def _t_div(eqn, ins):
    # Integer division with a non-negative numerator (the only form the
    # analyzed programs use). Divisor interval including 0 falls back
    # to the numerator's own range (x // 1 bound).
    a, b = ins
    lo_div = b.hi if b.hi >= 1 else 1
    hi_div = b.lo if b.lo >= 1 else 1
    return a.lo // lo_div, a.hi // hi_div


def _t_max(eqn, ins):
    return max(ins[0].lo, ins[1].lo), max(ins[0].hi, ins[1].hi)


def _t_min(eqn, ins):
    return min(ins[0].lo, ins[1].lo), min(ins[0].hi, ins[1].hi)


def _t_and(eqn, ins):
    # Bitwise AND of non-negative ints: result <= min of either bound.
    return 0, min(ins[0].hi, ins[1].hi)


def _t_or(eqn, ins):
    # a | b <= a + b for non-negative ints.
    return max(ins[0].lo, ins[1].lo), ins[0].hi + ins[1].hi


def _t_xor(eqn, ins):
    return 0, ins[0].hi + ins[1].hi


def _t_not(eqn, ins):
    # Boolean not (the only `not` the analyzed programs produce).
    return 1 - ins[0].hi, 1 - ins[0].lo


def _cmp(kind):
    def t(eqn, ins):
        a, b = ins
        definite = {
            "lt": (a.hi < b.lo, a.lo >= b.hi),
            "le": (a.hi <= b.lo, a.lo > b.hi),
            "gt": (a.lo > b.hi, a.hi <= b.lo),
            "ge": (a.lo >= b.hi, a.hi < b.lo),
            "eq": (a.lo == a.hi == b.lo == b.hi, a.hi < b.lo or a.lo > b.hi),
            "ne": (a.hi < b.lo or a.lo > b.hi, a.lo == a.hi == b.lo == b.hi),
        }[kind]
        if definite[0]:
            return 1, 1
        if definite[1]:
            return 0, 0
        return 0, 1

    return t


def _t_select(eqn, ins):
    pred, cases = ins[0], ins[1:]
    if pred.lo == pred.hi and 0 <= int(pred.lo) < len(cases):
        c = cases[int(pred.lo)]
        return c.lo, c.hi
    lo = min(c.lo for c in cases)
    hi = max(c.hi for c in cases)
    return lo, hi


def _t_identity(eqn, ins):
    return ins[0].lo, ins[0].hi


def _t_convert(eqn, ins):
    return ins[0].lo, ins[0].hi  # clamp (with flag) handled by driver


def _t_reduce_sum(eqn, ins):
    n = _reduce_count(eqn)
    lo = ins[0].lo * n if ins[0].lo < 0 else ins[0].lo
    return lo, ins[0].hi * n


def _reduce_count(eqn) -> int:
    in_sz = _aval_size(eqn.invars[0].aval)
    out_sz = max(1, _aval_size(eqn.outvars[0].aval))
    return max(1, in_sz // out_sz)


def _aval_size(aval) -> int:
    sz = 1
    for d in getattr(aval, "shape", ()):
        sz *= int(d)
    return sz


def _t_shift_left(eqn, ins):
    return ins[0].lo << int(ins[1].lo), ins[0].hi << int(ins[1].hi)


def _t_shift_right(eqn, ins):
    return ins[0].lo >> int(ins[1].hi), ins[0].hi >> int(ins[1].lo)


def _t_iota(eqn, ins):
    return 0, max(0, _aval_size(eqn.outvars[0].aval) - 1)


def _t_pow(eqn, ins):
    y = int(eqn.params.get("y", 1))
    vals = [ins[0].lo ** y, ins[0].hi ** y]
    return min(vals), max(vals)


TRANSFER = {
    "add": _t_add,
    "sub": _t_sub,
    "mul": _t_mul,
    "div": _t_div,
    "max": _t_max,
    "min": _t_min,
    "and": _t_and,
    "or": _t_or,
    "xor": _t_xor,
    "not": _t_not,
    "lt": _cmp("lt"),
    "le": _cmp("le"),
    "gt": _cmp("gt"),
    "ge": _cmp("ge"),
    "eq": _cmp("eq"),
    "ne": _cmp("ne"),
    "select_n": _t_select,
    "convert_element_type": _t_convert,
    "broadcast_in_dim": _t_identity,
    "reshape": _t_identity,
    "squeeze": _t_identity,
    "transpose": _t_identity,
    "slice": _t_identity,
    "rev": _t_identity,
    "copy": _t_identity,
    "stop_gradient": _t_identity,
    "reduce_max": _t_identity,
    "reduce_min": _t_identity,
    "reduce_or": _t_identity,
    "reduce_and": _t_identity,
    "reduce_sum": _t_reduce_sum,
    "shift_left": _t_shift_left,
    "shift_right_logical": _t_shift_right,
    "shift_right_arithmetic": _t_shift_right,
    "iota": _t_iota,
    "integer_pow": _t_pow,
    "concatenate": None,  # handled inline (n-ary hull)
}

_CALL_PRIMS = {"pjit", "closed_call", "custom_jvp_call", "custom_vjp_call"}


def _literal_interval(val) -> Interval:
    try:
        import numpy as _np

        return Interval(float(_np.min(val)), float(_np.max(val)))
    except Exception:
        return Interval(float(val), float(val))


def analyze_jaxpr(
    closed_or_open: Any,
    in_intervals: list[tuple[float, float]],
) -> IntervalResult:
    """Propagate intervals through a jaxpr.

    ``in_intervals`` gives (lo, hi) per flattened input; returns the
    output intervals plus every potentially-wrapping op and every
    unmodeled primitive encountered (including inside pjit calls).
    """
    jaxpr = getattr(closed_or_open, "jaxpr", closed_or_open)
    consts = list(getattr(closed_or_open, "consts", ()))
    res = IntervalResult(out=[], wrapped=[], unknown=[])
    env: dict[Any, Interval] = {}

    for var, cval in zip(jaxpr.constvars, consts):
        env[var] = _literal_interval(cval)
    if len(in_intervals) != len(jaxpr.invars):
        raise ValueError(
            f"expected {len(jaxpr.invars)} input intervals, "
            f"got {len(in_intervals)}"
        )
    for var, (lo, hi) in zip(jaxpr.invars, in_intervals):
        env[var] = Interval(lo, hi)

    def read(v) -> Interval:
        if hasattr(v, "val"):  # Literal
            return _literal_interval(v.val)
        return env[v]

    def run(jx, local_env):
        for i, eqn in enumerate(jx.eqns):
            name = eqn.primitive.name

            def rd(v):
                if hasattr(v, "val"):
                    return _literal_interval(v.val)
                return local_env[v]

            if name in _CALL_PRIMS:
                inner = eqn.params.get("jaxpr") or eqn.params.get(
                    "call_jaxpr"
                )
                inner_jx = getattr(inner, "jaxpr", inner)
                inner_consts = list(getattr(inner, "consts", ()))
                inner_env: dict[Any, Interval] = {}
                for cv, cval in zip(inner_jx.constvars, inner_consts):
                    inner_env[cv] = _literal_interval(cval)
                for iv, ov in zip(inner_jx.invars, eqn.invars):
                    inner_env[iv] = rd(ov)
                run(inner_jx, inner_env)
                for outv, innerv in zip(eqn.outvars, inner_jx.outvars):
                    local_env[outv] = (
                        _literal_interval(innerv.val)
                        if hasattr(innerv, "val")
                        else inner_env[innerv]
                    )
                continue

            ins = [rd(v) for v in eqn.invars]
            out_aval = eqn.outvars[0].aval
            dmin, dmax = dtype_range(getattr(out_aval, "dtype", "?"))

            if name == "concatenate":
                lo = min(x.lo for x in ins)
                hi = max(x.hi for x in ins)
            elif name in ("scatter-add", "scatter_add"):
                # counter.at[idx].add(w): bound = carry.hi + sum of all
                # update weights (every update could land in one cell).
                n_upd = _aval_size(eqn.invars[2].aval)
                lo = ins[0].lo
                hi = ins[0].hi + ins[2].hi * n_upd
            elif name in ("scatter-max", "scatter_max"):
                lo = ins[0].lo
                hi = max(ins[0].hi, ins[2].hi)
            elif name in TRANSFER and TRANSFER[name] is not None:
                lo, hi = TRANSFER[name](eqn, ins)
            else:
                res.unknown.append(name)
                lo, hi = dmin, dmax

            if lo < dmin or hi > dmax:
                if dmax != float("inf"):
                    res.wrapped.append(
                        f"{name} (eqn {i}): range [{lo}, {hi}] exceeds "
                        f"{getattr(out_aval, 'dtype', '?')}"
                    )
                lo, hi = max(lo, dmin), min(hi, dmax)
                if lo > hi:  # entire range out of dtype: clamp fully
                    lo, hi = dmin, dmax
            out_iv = Interval(lo, hi)
            for ov in eqn.outvars:
                local_env[ov] = out_iv

    run(jaxpr, env)
    for v in jaxpr.outvars:
        res.out.append(read(v))
    return res

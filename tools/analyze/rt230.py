"""RT230-RT232 — config-knob drift (whole-program).

The contract: ``retina_tpu/config.py``'s ``Config`` dataclass is the
single source of runtime knobs; every ``cfg.<attr>`` /
``self.cfg.<attr>`` access in the agent resolves to a declared field;
every field is actually read by the runtime and documented in
``docs/configuration.md``:

  RT230 access to a cfg attribute that is not a Config field
        (typo'd knob reads silently as AttributeError at runtime —
        or worse, getattr-with-default hides it forever)
  RT231 Config field never read outside config.py (dead knob:
        operators can set it, nothing changes)
  RT232 Config field missing from docs/configuration.md

Holders are recognized syntactically: a bare name ``cfg`` or any
``*.cfg`` attribute chain (``self.cfg``, ``pool.cfg``) — the repo
convention is that a binding named exactly ``cfg`` always holds the
agent Config.  A function whose ``cfg`` parameter is annotated with a
different type (``cfg: ShellConfig``) opts its whole body out.
``getattr(cfg, "name", default)`` strings count as reads; keyword
names in ``dataclasses.replace(cfg, ...)`` count too.  Tests are
excluded from the read census: a knob only tests exercise is still a
dead knob.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.analyze.core import FileCtx, Reporter

CONFIG_REL = "retina_tpu/config.py"
DOC_REL = "docs/configuration.md"


def _config_class(ctx: FileCtx) -> ast.ClassDef | None:
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return node
    return None


def _fields_and_methods(
    cls: ast.ClassDef,
) -> tuple[dict[str, int], set[str]]:
    fields: dict[str, int] = {}
    methods: set[str] = set()
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name):
            fields[stmt.target.id] = stmt.lineno
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            methods.add(stmt.name)
    return fields, methods


def _is_cfg_holder(node: ast.expr) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "cfg"
    if isinstance(node, ast.Attribute):
        return node.attr == "cfg"
    return False


def check_program(ctxs: list[FileCtx], rep: Reporter, root: Path) -> None:
    by_rel = {c.rel: c for c in ctxs}
    cfg_ctx = by_rel.get(CONFIG_REL)
    if cfg_ctx is None:
        return
    cls = _config_class(cfg_ctx)
    if cls is None:
        return
    fields, methods = _fields_and_methods(cls)
    allowed = set(fields) | methods

    scan = [
        c for c in ctxs
        if (c.rel.startswith("retina_tpu/")
            or c.rel in ("bench.py", "__graft_entry__.py"))
        and c.rel != CONFIG_REL
    ]

    reads: set[str] = set()

    def _foreign_cfg(fn: ast.AST) -> bool:
        """True when `fn` declares a cfg parameter annotated with a
        type other than Config — its body's bare-`cfg` accesses are a
        different object (e.g. shell.py's ShellConfig)."""
        args = getattr(fn, "args", None)
        if args is None:
            return False
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            if a.arg == "cfg" and a.annotation is not None:
                ann = a.annotation
                name = (
                    ann.id if isinstance(ann, ast.Name)
                    else ann.attr if isinstance(ann, ast.Attribute)
                    else None
                )
                if name is not None and name != "Config":
                    return True
        return False

    def _walk(ctx: FileCtx, node: ast.AST, foreign: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _walk(ctx, child, foreign or _foreign_cfg(child))
                continue
            _visit(ctx, child, foreign)
            _walk(ctx, child, foreign)

    def _visit(ctx: FileCtx, node: ast.AST, foreign: bool) -> None:
        if (isinstance(node, ast.Attribute)
                and _is_cfg_holder(node.value)):
            if foreign and isinstance(node.value, ast.Name):
                return
            attr = node.attr
            if attr.startswith("__"):
                return
            reads.add(attr)
            if attr not in allowed:
                rep.add(ctx, node.lineno, "RT230",
                        f"cfg.{attr} is not a Config field "
                        "(typo'd knob?)",
                        key=f"RT230:{ctx.rel}:{attr}")
        elif isinstance(node, ast.Call):
            func = node.func
            # getattr(cfg, "name"[, default])
            if (isinstance(func, ast.Name) and func.id == "getattr"
                    and len(node.args) >= 2
                    and _is_cfg_holder(node.args[0])
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                attr = node.args[1].value
                reads.add(attr)
                if attr not in allowed and len(node.args) == 2:
                    rep.add(ctx, node.lineno, "RT230",
                            f'getattr(cfg, "{attr}") is not a '
                            "Config field",
                            key=f"RT230:{ctx.rel}:{attr}")
            # dataclasses.replace(cfg, field=...) keyword reads
            is_replace = (
                (isinstance(func, ast.Attribute)
                 and func.attr == "replace")
                or (isinstance(func, ast.Name)
                    and func.id == "replace")
            )
            if (is_replace and node.args
                    and _is_cfg_holder(node.args[0])):
                for kw in node.keywords:
                    if kw.arg:
                        reads.add(kw.arg)

    for ctx in scan:
        _walk(ctx, ctx.tree, False)

    doc_path = root / DOC_REL
    doc_text = doc_path.read_text() if doc_path.exists() else ""

    for name, lineno in sorted(fields.items()):
        if name not in reads:
            rep.add(cfg_ctx, lineno, "RT231",
                    f"Config.{name} is never read outside config.py "
                    "(dead knob)",
                    key=f"RT231:{name}")
        if not re.search(rf"\b{re.escape(name)}\b", doc_text):
            rep.add(cfg_ctx, lineno, "RT232",
                    f"Config.{name} is not documented in {DOC_REL}",
                    key=f"RT232:{name}")

"""Repo-specific runtime rules RT100-RT102 (migrated from the
original tools/lint.py, which is now a thin entry point).

  RT100 threading.Thread spawned in engine.py outside the sanctioned
        helpers (start, start_background_warm, _ensure_harvest_thread,
        _request_recovery).
        Every engine thread must be created where shutdown joins it —
        a thread spawned ad hoc escapes the stop/join protocol and the
        device-proxy single-thread invariant review.
  RT101 silent exception swallow in retina_tpu/: an `except` handler
        whose body is only `pass`/`...`/a bare string constant hides
        failures from operators.  Every swallow must at least log
        (rate-limited) and bump a named error counter; a deliberate
        swallow carries a `# noqa: RT101 — reason` on the except line
        or on the handler's last body line.
  RT102 unbounded stdlib queue constructed in retina_tpu/: a
        `queue.Queue()` with no maxsize (or maxsize<=0), or a
        `SimpleQueue()`, has no backpressure edge — under overload it
        grows host memory without bound instead of surfacing as
        drop-and-count/shed (docs/operations.md §6).  Bounded queues
        whose `.put()` blocks are fine: the bound IS the backpressure
        edge.
"""

from __future__ import annotations

import ast

from tools.analyze.core import FileCtx, Reporter

ENGINE_SANCTIONED = {
    "start", "start_background_warm", "_ensure_harvest_thread",
    "_request_recovery",
}


def _check_rt100(ctx: FileCtx, rep: Reporter) -> None:
    if ctx.path.name != "engine.py":
        return

    def _walk_fn(node: ast.AST, fn: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            nxt = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested defs (closures like _warm) belong to the
                # sanctioned outer helper that defines them.
                nxt = fn if fn in ENGINE_SANCTIONED else child.name
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "Thread"
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id == "threading"
                    and fn not in ENGINE_SANCTIONED):
                rep.add(ctx, child.lineno, "RT100",
                        "threading.Thread spawned outside sanctioned "
                        f"engine helpers (in `{fn or '<module>'}`)",
                        key=f"RT100:{ctx.rel}:{fn or '<module>'}")
            _walk_fn(child, nxt)

    _walk_fn(ctx.tree, None)


def _body_is_silent(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does nothing observable.

    `pass`, `...` and bare string constants (docstring-equivalents —
    an explanation is not an action; the failure is still invisible
    to operators) all count as silent.
    """
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and (stmt.value.value is Ellipsis
                 or isinstance(stmt.value.value, str)))
        for stmt in handler.body
    )


def _check_rt101(ctx: FileCtx, rep: Reporter) -> None:
    if "retina_tpu" not in ctx.path.parts:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _body_is_silent(node):
            # A swallow annotated inside the handler (the last body
            # line, where a multi-line explanation naturally ends)
            # is as deliberate as one annotated on the except line.
            last = node.body[-1]
            last_line = getattr(last, "end_lineno", last.lineno)
            rep.add(ctx, node.lineno, "RT101",
                    "silent exception swallow (`except ...: pass`) — "
                    "log + count it, or noqa with a reason",
                    also_noqa_lines=(last_line,))


def _check_rt102(ctx: FileCtx, rep: Reporter) -> None:
    if "retina_tpu" not in ctx.path.parts:
        return
    q_classes = {"Queue", "LifoQueue", "PriorityQueue"}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        cls = None
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in ("queue", "queue_mod")):
            cls = func.attr
        elif (isinstance(func, ast.Name)
                and func.id in (q_classes | {"SimpleQueue"})):
            cls = func.id
        if cls == "SimpleQueue":
            rep.add(ctx, node.lineno, "RT102",
                    "SimpleQueue is always unbounded — use a bounded "
                    "queue.Queue(maxsize) or noqa with a reason")
            continue
        if cls not in q_classes:
            continue
        size = None
        if node.args:
            size = node.args[0]
        for kw in node.keywords:
            if kw.arg == "maxsize":
                size = kw.value
        unbounded = size is None or (
            isinstance(size, ast.Constant)
            and isinstance(size.value, int) and size.value <= 0
        )
        if unbounded:
            rep.add(ctx, node.lineno, "RT102",
                    f"unbounded {cls}() — no backpressure edge; pass "
                    "maxsize or noqa with a reason")


def check(ctx: FileCtx, rep: Reporter) -> None:
    _check_rt100(ctx, rep)
    _check_rt101(ctx, rep)
    _check_rt102(ctx, rep)

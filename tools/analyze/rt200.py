"""RT200-RT204 — thread-safety of the hot runtime classes.

The runtime is a dozen supervised threads sharing engine state; the
correctness contract is "every shared attribute has a declared owner
lock".  This analyzer machine-checks it: every attribute of the
target classes (SketchEngine, OverloadController, FeedWorkerPool,
FeedWorker, Supervisor) is indexed by the THREADS that write it and
the LOCKS held at each write, then:

  RT200 attribute written from >= 2 threads with no common lock and
        no declared guard
  RT201 write to a `# guarded-by:`-declared attribute without the
        declared lock held
  RT202 method escapes as a callback (referenced as a value, passed
        across a class boundary) without a `# runs-on:` annotation —
        the analyzer cannot attribute its writes to a thread, so the
        contract requires the author to declare it
  RT203 `# guarded-by:` names a lock that is not an attribute
        initialized in __init__
  RT204 malformed `# runs-on:` thread name
  RT205 lock-acquisition order cycle: some path acquires lock B while
        holding A and another acquires A while holding B — two threads
        interleaving those paths deadlock.  Edges use the UNION of
        possibly-held locks over call paths (any path creates an
        ordering constraint); self-edges are RLock re-entrancy and are
        skipped

Thread attribution
------------------
Thread roots come from the sanctioned spawn sites and annotations:

  * ``threading.Thread(target=self.m, name="engine-dispatch")`` and
    ``supervisor.spawn("checkpointer", self.m, ...)`` root `m` on the
    named thread;
  * ``run_on_device(fn)`` / ``submit_on_device(fn)`` root `fn` on the
    single ``device-proxy`` thread (utils/device_proxy.py);
  * ``run()`` of a ``threading.Thread`` subclass roots on
    ``<Class>.run*`` — the trailing ``*`` marks a POOL of threads
    (every instance gets one), which alone counts as two writers;
  * ``# runs-on: thread-a, thread-b*`` on a def line declares roots
    the analyzer cannot see (cross-class callbacks);
  * public methods with none of the above run on one shared
    ``external`` caller thread — a deliberate under-approximation
    (concurrent external callers are the API owner's contract, and
    modeling each public method as its own thread would drown real
    findings in noise).

Within a class, ``self.m(...)`` calls propagate threads caller→callee
and entry locks as the INTERSECTION over call sites of (caller entry
locks ∪ locks held at the site) — a lock only counts as guarding a
callee if EVERY path in holds it.  Nested defs are pseudo-methods of
their enclosing method; inline closures start with no inherited locks
(their call site, not their def site, decides what is held), spawn-
target closures root like methods.

Out of scope (documented, deliberate): reads (CPython attribute loads
are atomic; every flagged pattern here is a write-write or write-
reset race); container element mutation (``self.d[k] = v``); writes
through non-self objects (``hb.stalls = 0``); TransferQueue /
TransferMux (lock-free SPSC by design, reviewed in parallel/feed.py).
"""

from __future__ import annotations

import ast
import dataclasses
import re

from tools.analyze.core import FileCtx, Reporter

TARGET_CLASSES = {
    "SketchEngine",
    "OverloadController",
    "FeedWorkerPool",
    "FeedWorker",
    "Supervisor",
}

DEVICE_PROXY_FUNCS = {"run_on_device", "submit_on_device"}
DEVICE_PROXY_THREAD = "device-proxy"
EXTERNAL_THREAD = "external"

RUNS_ON_RE = re.compile(r"#\s*runs-on:\s*([^#]+)")
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(self\.\w+)")
THREAD_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]+\*?$")


@dataclasses.dataclass
class Write:
    attr: str
    lineno: int
    locks: frozenset[str]


@dataclasses.dataclass
class Method:
    name: str  # "m" or "m.closure" for nested defs
    node: ast.FunctionDef | ast.AsyncFunctionDef
    public: bool
    writes: list[Write] = dataclasses.field(default_factory=list)
    calls: list[tuple[str, frozenset[str]]] = dataclasses.field(
        default_factory=list)
    runs_on: tuple[str, ...] = ()
    # (lineno, target-method) for self.<method> value references
    escapes: list[tuple[int, str]] = dataclasses.field(
        default_factory=list)
    is_property: bool = False
    # (acquired-lock, locks already held at the acquisition, lineno) —
    # feeds the RT205 lock-acquisition order graph
    acquires: list[tuple[str, frozenset[str], int]] = dataclasses.field(
        default_factory=list)


def _const_str(node: ast.expr | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _lock_name(node: ast.expr) -> str | None:
    """`with self._lock:` / `with lock:` context -> lock identity."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    return None


class _ClassAnalysis:
    def __init__(self, ctx: FileCtx, cls: ast.ClassDef, rep: Reporter):
        self.ctx = ctx
        self.cls = cls
        self.rep = rep
        self.methods: dict[str, Method] = {}
        self.method_names: set[str] = {
            s.name for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.guarded_by: dict[str, str] = {}  # attr -> "self._lock"
        self.decl_lines: dict[str, int] = {}  # attr -> __init__ lineno
        self.roots: dict[str, set[str]] = {}  # method -> thread names
        self.is_thread_subclass = any(
            (isinstance(b, ast.Attribute) and b.attr == "Thread")
            or (isinstance(b, ast.Name) and b.id == "Thread")
            for b in cls.bases
        )

    # -- annotation parsing -------------------------------------------
    def _runs_on(self, node: ast.FunctionDef) -> tuple[str, ...]:
        line = self.ctx.line_at(node.lineno)
        m = RUNS_ON_RE.search(line)
        if not m:
            return ()
        names = tuple(
            t.strip() for t in m.group(1).split(",") if t.strip())
        for t in names:
            if not THREAD_NAME_RE.match(t):
                self.rep.add(self.ctx, node.lineno, "RT204",
                             f"malformed runs-on thread name {t!r}",
                             key=f"RT204:{self.ctx.rel}:"
                                 f"{self.cls.name}.{node.name}")
        return tuple(t for t in names if THREAD_NAME_RE.match(t))

    def _collect_init_decls(self, init: Method) -> None:
        for node in ast.walk(init.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self.decl_lines.setdefault(t.attr, node.lineno)
                        g = GUARDED_BY_RE.search(
                            self.ctx.line_at(node.lineno))
                        if g:
                            self.guarded_by[t.attr] = g.group(1)

    def _add_root(self, target: str, thread: str) -> None:
        self.roots.setdefault(target, set()).add(thread)

    # -- per-method walk ----------------------------------------------
    def _walk_method(
        self,
        name: str,
        node,
        public: bool,
        outer_defs: dict[str, str] | None = None,
    ) -> None:
        meth = Method(name=name, node=node, public=public,
                      runs_on=self._runs_on(node))
        meth.is_property = any(
            (isinstance(d, ast.Name) and d.id == "property")
            or (isinstance(d, ast.Attribute)
                and d.attr in ("cached_property", "property"))
            for d in node.decorator_list
        )
        self.methods[name] = meth

        call_func_ids: set[int] = set()
        spawn_target_ids: set[int] = set()
        # closure name -> pseudo-method name, visible to this scope
        local_defs: dict[str, str] = dict(outer_defs or {})
        # candidate self.<method> value references: (id, lineno, attr)
        attr_loads: list[tuple[int, int, str]] = []

        def visit(n: ast.AST, locks: list[str]) -> None:
            if isinstance(n, ast.With):
                inner = list(locks)
                for item in n.items:
                    ln = _lock_name(item.context_expr)
                    if ln is not None:
                        # multi-item `with a, b:` acquires in order:
                        # b's held-set already contains a (RT205)
                        meth.acquires.append(
                            (ln, frozenset(inner), n.lineno))
                        inner.append(ln)
                for stmt in n.body:
                    visit(stmt, inner)
                return
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pseudo = f"{name}.{n.name}"
                local_defs[n.name] = pseudo
                # closures start with NO inherited locks: their call
                # site, not their def site, decides what is held
                self._walk_method(pseudo, n, public=False,
                                  outer_defs=dict(local_defs))
                return
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    n.targets if isinstance(n, ast.Assign) else [n.target]
                )
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        meth.writes.append(
                            Write(t.attr, n.lineno, frozenset(locks)))
            if isinstance(n, ast.Call):
                call_func_ids.add(id(n.func))
                self._classify_call(n, meth, frozenset(locks),
                                    local_defs, spawn_target_ids)
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and n.attr in self.method_names
                    and isinstance(n.ctx, ast.Load)):
                attr_loads.append((id(n), n.lineno, n.attr))
            for child in ast.iter_child_nodes(n):
                visit(child, locks)

        for stmt in node.body:
            visit(stmt, [])

        for node_id, lineno, target in attr_loads:
            if node_id in call_func_ids or node_id in spawn_target_ids:
                continue
            meth.escapes.append((lineno, target))

    def _classify_call(
        self,
        call: ast.Call,
        meth: Method,
        locks: frozenset[str],
        local_defs: dict[str, str],
        spawn_target_ids: set[int],
    ) -> None:
        func = call.func

        def resolve_target(node: ast.expr | None) -> str | None:
            """Spawn-target expression -> method/pseudo name."""
            if node is None:
                return None
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in self.method_names):
                spawn_target_ids.add(id(node))
                return node.attr
            if isinstance(node, ast.Name) and node.id in local_defs:
                return local_defs[node.id]
            return None

        # threading.Thread(target=..., name="...")
        if (isinstance(func, ast.Attribute) and func.attr == "Thread"
                and isinstance(func.value, ast.Name)
                and func.value.id == "threading"):
            target = tname = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = resolve_target(kw.value)
                elif kw.arg == "name":
                    tname = _const_str(kw.value)
            if target is not None:
                self._add_root(target, tname or f"thread:{meth.name}")
            return

        # supervisor.spawn("name", target, ...)
        if isinstance(func, ast.Attribute) and func.attr == "spawn":
            tname = _const_str(call.args[0]) if call.args else None
            tnode = call.args[1] if len(call.args) > 1 else None
            for kw in call.keywords:
                if kw.arg == "target":
                    tnode = kw.value
                elif kw.arg == "name":
                    tname = _const_str(kw.value)
            target = resolve_target(tnode)
            if target is not None:
                self._add_root(target, tname or f"spawn:{meth.name}")
            return

        # run_on_device(fn, ...) / submit_on_device(fn, ...)
        fname = (
            func.id if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute)
            else None
        )
        if fname in DEVICE_PROXY_FUNCS and call.args:
            target = resolve_target(call.args[0])
            if target is not None:
                self._add_root(target, DEVICE_PROXY_THREAD)
            return

        # plain intra-class calls: self.m(...) / closure()
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in self.method_names):
            meth.calls.append((func.attr, locks))
        elif isinstance(func, ast.Name) and func.id in local_defs:
            meth.calls.append((local_defs[func.id], locks))

    # -- whole-class analysis -----------------------------------------
    def analyze(self) -> None:
        for stmt in self.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_method(
                    stmt.name, stmt,
                    public=not stmt.name.startswith("_"))

        init = self.methods.get("__init__")
        if init is not None:
            self._collect_init_decls(init)

        # property access = call on the accessor's thread; other
        # escaping references need a runs-on declaration (RT202)
        for meth in list(self.methods.values()):
            if meth.name in ("__init__", "__post_init__"):
                continue
            for lineno, target in meth.escapes:
                tm = self.methods.get(target)
                if tm is not None and tm.is_property:
                    meth.calls.append((target, frozenset()))
                    continue
                if tm is not None and (tm.runs_on or target in self.roots):
                    continue  # thread declared or spawn-rooted
                self.rep.add(
                    self.ctx, lineno, "RT202",
                    f"{self.cls.name}.{target} escapes as a callback "
                    "without a `# runs-on:` annotation on its def line",
                    key=f"RT202:{self.ctx.rel}:{self.cls.name}.{target}",
                    also_noqa_lines=(
                        (tm.node.lineno,) if tm is not None else ()))

        # RT203: guarded-by must name a lock attribute from __init__
        for attr, lock in sorted(self.guarded_by.items()):
            lock_attr = lock.split(".", 1)[1]
            if lock_attr not in self.decl_lines:
                self.rep.add(
                    self.ctx, self.decl_lines.get(attr, 1), "RT203",
                    f"{self.cls.name}.{attr} guarded-by {lock} which "
                    "is not initialized in __init__",
                    key=f"RT203:{self.ctx.rel}:{self.cls.name}.{attr}")

        # -- thread/lock fixpoint -------------------------------------
        threads: dict[str, set[str]] = {m: set() for m in self.methods}
        elocks: dict[str, frozenset[str] | None] = {
            m: None for m in self.methods  # None = not yet reached
        }
        called = {c for m in self.methods.values() for c, _ in m.calls}

        for mname, meth in self.methods.items():
            mroots = set(self.roots.get(mname, ()))
            if self.is_thread_subclass and mname == "run":
                mroots.add(f"{self.cls.name}.run*")
            mroots.update(meth.runs_on)
            if not mroots and not meth.runs_on:
                # default attribution: public API, or a private helper
                # nobody in-class calls (tests / cross-class callers)
                top_level = "." not in mname
                if top_level and (meth.public or mname not in called):
                    mroots.add(EXTERNAL_THREAD)
            if mroots:
                threads[mname] |= mroots
                elocks[mname] = frozenset()

        for _ in range(len(self.methods) + 2):
            changed = False
            for mname, meth in self.methods.items():
                if elocks[mname] is None:
                    continue
                for callee, site_locks in meth.calls:
                    if callee not in self.methods:
                        continue
                    new_t = threads[mname] - threads[callee]
                    if new_t:
                        threads[callee] |= new_t
                        changed = True
                    entry = (elocks[mname] or frozenset()) | site_locks
                    cur = elocks[callee]
                    nxt = entry if cur is None else (cur & entry)
                    if nxt != cur:
                        elocks[callee] = nxt
                        changed = True
            if not changed:
                break

        # -- per-attribute verdicts (construction excluded) -----------
        per_attr: dict[str, list[tuple[str, set[str], Write]]] = {}
        for mname, meth in self.methods.items():
            if mname in ("__init__", "__post_init__"):
                continue
            base = elocks[mname] or frozenset()
            for w in meth.writes:
                per_attr.setdefault(w.attr, []).append(
                    (mname, threads[mname],
                     Write(w.attr, w.lineno, base | w.locks)))

        for attr, writes in sorted(per_attr.items()):
            decl_line = self.decl_lines.get(attr, 0)
            guard = self.guarded_by.get(attr)
            if guard is not None:
                for mname, _, w in writes:
                    if guard not in w.locks:
                        self.rep.add(
                            self.ctx, w.lineno, "RT201",
                            f"write to {self.cls.name}.{attr} in "
                            f"`{mname}` without declared guard {guard}",
                            key=f"RT201:{self.ctx.rel}:"
                                f"{self.cls.name}.{attr}:{mname}")
                continue
            all_threads: set[str] = set()
            for _, tset, _w in writes:
                all_threads |= tset
            # A plural thread counts as 2 writers — EXCEPT the class's
            # own run() pool: each instance's run thread writes that
            # instance's attributes, so "many threads" is still one
            # writer per object.
            own_run = f"{self.cls.name}.run*"
            weight = sum(
                2 if (t.endswith("*") and t != own_run) else 1
                for t in all_threads)
            if weight < 2:
                continue
            common: frozenset[str] | None = None
            for _, _, w in writes:
                common = w.locks if common is None else (common & w.locks)
            if common:
                continue  # consistent undeclared lock discipline: safe
            sites = ", ".join(f"{m}:{w.lineno}" for m, _, w in writes[:6])
            self.rep.add(
                self.ctx, writes[0][2].lineno, "RT200",
                f"{self.cls.name}.{attr} written from threads "
                f"{sorted(all_threads)} with no common lock ({sites}) "
                "— add a lock + `# guarded-by:` on the __init__ "
                "declaration, or noqa with a reason",
                key=f"RT200:{self.ctx.rel}:{self.cls.name}.{attr}",
                also_noqa_lines=(decl_line,) if decl_line else ())

        self._check_lock_order()

    # -- RT205: lock-acquisition ordering -------------------------------
    def _check_lock_order(self) -> None:
        """Two threads taking the same locks in opposite orders can
        deadlock. Build the acquired-while-holding graph (edge h -> l:
        some path acquires l while holding h) and fail on any cycle.

        Held-sets here are the UNION over call paths of possibly-held
        locks (the RT200/RT201 fixpoint uses the INTERSECTION of
        guaranteed-held locks — a lock must be held on EVERY path to
        guard a write, but on ANY path to create an ordering edge).
        Self-edges (re-acquiring the lock you hold) are RLock
        re-entrancy, not an ordering problem — skipped."""
        uentry: dict[str, frozenset[str]] = {
            m: frozenset() for m in self.methods
        }
        for _ in range(len(self.methods) + 2):
            changed = False
            for mname, meth in self.methods.items():
                for callee, site_locks in meth.calls:
                    if callee not in self.methods:
                        continue
                    add = uentry[mname] | site_locks
                    if not add <= uentry[callee]:
                        uentry[callee] = uentry[callee] | add
                        changed = True
            if not changed:
                break

        # edge (held -> acquired) -> first witness site
        edges: dict[tuple[str, str], tuple[str, int]] = {}
        for mname, meth in self.methods.items():
            for lock, held, lineno in meth.acquires:
                for h in uentry[mname] | held:
                    if h != lock and (h, lock) not in edges:
                        edges[(h, lock)] = (mname, lineno)

        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())

        # Tarjan SCC: any component with >1 lock contains an ordering
        # cycle.
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(adj.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        for comp in sccs:
            if len(comp) < 2:
                continue
            cset = set(comp)
            sites = sorted(
                f"{a}->{b} ({m}:{ln})"
                for (a, b), (m, ln) in edges.items()
                if a in cset and b in cset
            )
            first_line = min(
                ln for (a, b), (_m, ln) in edges.items()
                if a in cset and b in cset
            )
            cyc = "<".join(sorted(cset))
            self.rep.add(
                self.ctx, first_line, "RT205",
                f"{self.cls.name}: lock-acquisition order cycle "
                f"between {sorted(cset)} — opposite-order paths can "
                f"deadlock: {'; '.join(sites)}",
                key=f"RT205:{self.ctx.rel}:{self.cls.name}:{cyc}")


def check(ctx: FileCtx, rep: Reporter) -> None:
    if "retina_tpu" not in ctx.path.parts:
        return
    for node in ctx.tree.body:
        if (isinstance(node, ast.ClassDef)
                and node.name in TARGET_CLASSES):
            _ClassAnalysis(ctx, node, rep).analyze()

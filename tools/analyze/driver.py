"""Analysis driver: discover files, parse once, run every rule
module, apply the baseline, exit nonzero on any live finding.

Per-file rules (generic, rt10x, rt200, rt210) see one FileCtx at a
time; whole-program rules (rt220, rt226, rt230) see the full parsed
set —
they cross-reference metric/config declarations, use sites and docs,
so they always scan the complete default file set even when the CLI
restricts which files findings are *reported* for.

Usage:
    python tools/lint.py [paths...] [--update-baseline] [--list-rules]

Exit code 1 if any non-baselined finding survives suppression.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

from tools.analyze import (
    generic, rt10x, rt200, rt210, rt220, rt225, rt226, rt230, rt300,
    rt400,
)
from tools.analyze.core import (
    FileCtx,
    Finding,
    Reporter,
    load_baseline,
    save_baseline,
)

REPO_ROOT = Path(__file__).resolve().parents[2]
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

# Everything the repo ships as Python, minus vendored/derived trees.
DEFAULT_TARGETS = (
    "retina_tpu",
    "tests",
    "tools",
    "bench.py",
    "__graft_entry__.py",
)

FILE_RULES = (
    generic.check, rt10x.check, rt200.check, rt210.check, rt300.check,
)
PROGRAM_RULES = (
    rt220.check_program, rt225.check_program, rt226.check_program,
    rt230.check_program, rt400.check_program,
)

RULE_FAMILIES = {
    "generic": "F401 F541 F601 F811 E711 E722 B006 B011 (+E999)",
    "RT100": "engine thread-spawn outside sanctioned helpers",
    "RT101": "silent exception swallow",
    "RT102": "unbounded stdlib queue",
    "RT200": "cross-thread write without a common/declared lock "
             "(+RT201 guarded-by violation, RT202 unannotated "
             "escaping callback, RT203 unknown guarded-by lock, "
             "RT204 unknown runs-on spelling)",
    "RT210": "side effect inside a traced function (+RT211 host "
             "readback, RT212 tracer branching, RT213 state "
             "mutation, RT214 re-jit inside a traced body)",
    "RT220": "metric registered but not declared (+RT221 literal "
             "metric name, RT222 undocumented series, RT223 doc "
             "mentions unknown series, RT224 declared-but-unused)",
    "RT225": "fleet codec op class unresolvable or lacking a "
             "merge-associativity property test",
    "RT226": "recorder span-name drift (literal/undeclared stage, "
             "stage never emitted, or docs/observability.md stage "
             "table out of sync with the STAGE_ registry)",
    "RT230": "unknown cfg.<attr> access (+RT231 field never read, "
             "RT232 field undocumented)",
    "RT205": "lock-acquisition order cycle (potential deadlock "
             "between threads taking the same locks in opposite "
             "order)",
    "RT400": "hot-path reachability: blocking primitive reachable "
             "from a hot-path root (+RT401 cold compile on the hot "
             "path, RT402 unbounded per-event allocation, RT403 "
             "lock convoy — hot lock held elsewhere across a "
             "blocking call)",
    "RT300": "[--device] merge algebra uses a non-associative/"
             "commutative primitive, or registry/recipe inventory "
             "drift (+RT301 u32 counter can wrap in-window, RT302 "
             "donation coverage, RT303 unexpected collective, RT304 "
             "host/device predicate divergence, RT305 unregistered "
             "jit/shard_map site — RT305 runs in the default lint)",
}


def discover(root: Path) -> list[Path]:
    files: list[Path] = []
    for target in DEFAULT_TARGETS:
        p = root / target
        if p.is_dir():
            files += sorted(p.rglob("*.py"))
        elif p.suffix == ".py" and p.exists():
            files.append(p)
    return [f for f in files if "__pycache__" not in f.parts]


def parse_all(root: Path) -> list[FileCtx]:
    ctxs = []
    for f in discover(root):
        rel = f.relative_to(root).as_posix()
        ctxs.append(FileCtx(f, rel, f.read_text()))
    return ctxs


def analyze(root: Path | None = None, device: bool = False) -> list[Finding]:
    """Run every rule over the default file set; no baseline applied.

    ``device=True`` additionally runs the RT300 device pass, which
    imports jax (CPU backend) and AOT-lowers every registered device
    entry point — seconds, not milliseconds, so it is opt-in
    (``--device`` / ``make analyze-device``)."""
    root = root or REPO_ROOT
    ctxs = parse_all(root)
    rep = Reporter()
    for ctx in ctxs:
        if ctx.syntax_error is not None:
            e = ctx.syntax_error
            rep.add(ctx, e.lineno or 0, "E999", f"syntax error: {e.msg}")
            continue
        for rule in FILE_RULES:
            rule(ctx, rep)
    good = [c for c in ctxs if c.syntax_error is None]
    for prule in PROGRAM_RULES:
        prule(good, rep, root)
    if device:
        rt300.check_device(good, rep, root)
    return rep.findings


def run(
    argv: list[str] | None = None,
    root: Path | None = None,
    out=print,
) -> int:
    argv = list(argv or [])
    root = root or REPO_ROOT
    device = "--device" in argv
    update_baseline = "--update-baseline" in argv
    if "--list-rules" in argv:
        for fam, desc in RULE_FAMILIES.items():
            out(f"{fam:8s} {desc}")
        return 0
    path_args = [a for a in argv if not a.startswith("--")]

    t0 = time.monotonic()
    findings = analyze(root, device=device)

    if path_args:
        # Restrict *reporting* to the requested paths; whole-program
        # rules still analyzed the full tree (they must — drift is a
        # cross-file property).
        wanted = [
            (root / a).resolve().relative_to(root).as_posix()
            for a in path_args
        ]

        def selected(f: Finding) -> bool:
            return any(
                f.path == w or f.path.startswith(w.rstrip("/") + "/")
                for w in wanted
            )

        findings = [f for f in findings if selected(f)]

    baseline = load_baseline(BASELINE_PATH)
    live = [f for f in findings if f.key not in baseline]
    baselined = [f for f in findings if f.key in baseline]
    seen_keys = {f.key for f in findings}
    stale = sorted(k for k in baseline if k not in seen_keys)

    if update_baseline:
        for f in live:
            baseline[f.key] = "TODO(review): baselined by --update-baseline"
        for k in stale:
            baseline.pop(k)
        save_baseline(BASELINE_PATH, baseline)
        out(f"lint: baseline updated ({len(baseline)} entries) — "
            "review the TODO reasons before committing")
        return 0

    for f in sorted(live, key=lambda f: (f.path, f.line, f.code)):
        out(f.render())
    if not path_args:
        for k in stale:
            out(f"warning: stale baseline entry (no longer fires): {k}")
    dt = time.monotonic() - t0
    n_files = len(discover(root))
    out(
        f"lint: {n_files} files, {len(live)} finding(s), "
        f"{len(baselined)} baselined, {dt:.1f}s"
    )
    return 1 if live else 0


def main(argv: list[str]) -> int:
    return run(argv)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Generic correctness rules — the high-precision subset of ruff's
F/E9/B families (no third-party linters in the TPU image; CI runs
real ruff+mypy where pip is available).

  F401  module-level import never used (skipped in __init__.py
        re-export surfaces and for names listed in __all__)
  F541  f-string without placeholders
  F601  duplicate dict literal key
  F811  duplicate top-level def/class name
  E711  comparison to None with ==/!=
  E722  bare `except:`
  B006  mutable default argument (list/dict/set literal)
  B011  assert on a non-empty tuple (always true)
"""

from __future__ import annotations

import ast

from tools.analyze.core import FileCtx, Reporter


def _names_loaded(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # a.b.c -> root name a (covers `import a.b` usage)
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    return used


def _all_exports(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str):
                    out.add(elt.value)
    return out


def check(ctx: FileCtx, rep: Reporter) -> None:
    tree = ctx.tree
    assert tree is not None
    used = _names_loaded(tree)
    exported = _all_exports(tree)

    # F401 — only module-level imports; conftest/test fixtures are
    # excluded by the driver's path selection.
    if ctx.path.name != "__init__.py":
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    if name not in used and name not in exported:
                        rep.add(ctx, node.lineno, "F401",
                                f"`import {a.name}` unused",
                                key=f"F401:{ctx.rel}:{a.name}")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    if name not in used and name not in exported:
                        rep.add(ctx, node.lineno, "F401",
                                f"`from {node.module} import "
                                f"{a.name}` unused",
                                key=f"F401:{ctx.rel}:{name}")

    seen_top: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen_top:
                rep.add(ctx, node.lineno, "F811",
                        f"`{node.name}` redefines line "
                        f"{seen_top[node.name]}",
                        key=f"F811:{ctx.rel}:{node.name}")
            seen_top[node.name] = node.lineno

    # Format specs (f"{x:.1f}") parse as JoinedStr children of
    # FormattedValue — not user f-strings; exclude them from F541.
    spec_ids = {
        id(n.format_spec) for n in ast.walk(tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            rep.add(ctx, node.lineno, "E722", "bare `except:`")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in (*node.args.defaults, *node.args.kw_defaults):
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    rep.add(ctx, d.lineno, "B006",
                            "mutable default argument")
        elif isinstance(node, ast.JoinedStr):
            if id(node) not in spec_ids and not any(
                    isinstance(v, ast.FormattedValue)
                    for v in node.values):
                rep.add(ctx, node.lineno, "F541",
                        "f-string without placeholders")
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if (isinstance(op, (ast.Eq, ast.NotEq))
                        and isinstance(comp, ast.Constant)
                        and comp.value is None):
                    rep.add(ctx, node.lineno, "E711",
                            "comparison to None (use `is`/`is not`)")
        elif isinstance(node, ast.Dict):
            keys = [
                k.value for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, (str, int))
            ]
            dupes = {k for k in keys if keys.count(k) > 1}
            if dupes:
                rep.add(ctx, node.lineno, "F601",
                        f"duplicate dict key(s): "
                        f"{sorted(map(str, dupes))}")
        elif isinstance(node, ast.Assert):
            if isinstance(node.test, ast.Tuple) and node.test.elts:
                rep.add(ctx, node.lineno, "B011",
                        "assert on a tuple is always true")

"""RT225 — sketch merge-associativity test coverage (whole-program).

Every sketch op class named in the fleet codec's ``ARRAY_OP_CLASSES``
catalog participates in the aggregator's batched merge; an op whose
merge silently stops being associative/commutative makes the cluster
rollup depend on node arrival order — a bug no unit test of a single
merge call can see.  The contract: each DISTINCT class in the catalog
must (a) resolve to a real class in the repo and (b) appear in at
least one ``tests/`` file that defines a merge-associativity property
test (a test function whose name contains ``associativ``).

  RT225 catalog op class unresolvable, or with no merge-associativity
        property test under tests/
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.analyze.core import FileCtx, Reporter

CODEC_REL = "retina_tpu/fleet/codec.py"
CATALOG_NAME = "ARRAY_OP_CLASSES"

ASSOC_TEST_RE = re.compile(r"def test\w*associativ", re.IGNORECASE)


def _catalog_classes(ctx: FileCtx) -> dict[str, int]:
    """dotted class path -> first declaring lineno from the
    ARRAY_OP_CLASSES dict literal (None values are plain vector adds,
    associative by construction, and carry no class to test)."""
    out: dict[str, int] = {}
    for stmt in ctx.tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == CATALOG_NAME
            for t in targets
        ):
            continue
        if not isinstance(value, ast.Dict):
            continue
        for k, v in zip(value.keys, value.values):
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.setdefault(v.value, v.lineno)
    return out


def check_program(ctxs: list[FileCtx], rep: Reporter, root: Path) -> None:
    by_rel = {c.rel: c for c in ctxs}
    codec = by_rel.get(CODEC_REL)
    if codec is None:
        return
    classes = _catalog_classes(codec)

    # Test files that contain at least one associativity property test.
    assoc_srcs = [
        c.src for c in ctxs
        if c.rel.startswith("tests/") and ASSOC_TEST_RE.search(c.src)
    ]

    for dotted, lineno in sorted(classes.items()):
        mod, _, cls = dotted.rpartition(".")
        mod_rel = mod.replace(".", "/") + ".py"
        mod_ctx = by_rel.get(mod_rel)
        if mod_ctx is None or not re.search(
            rf"^class {re.escape(cls)}\b", mod_ctx.src, re.MULTILINE
        ):
            rep.add(codec, lineno, "RT225",
                    f"catalog op class {dotted} does not resolve to a "
                    "class in the repo",
                    key=f"RT225:resolve:{dotted}")
            continue
        if not any(cls in src for src in assoc_srcs):
            rep.add(codec, lineno, "RT225",
                    f"catalog op class {dotted} has no "
                    "merge-associativity property test under tests/ "
                    "(a test named *associativ* must exercise it)",
                    key=f"RT225:coverage:{dotted}")

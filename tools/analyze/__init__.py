"""Whole-program static analysis for retina_tpu.

The suite grew out of tools/lint.py (RT100-RT102): cheap AST rules
catch real concurrency and drift bugs in this codebase, so the rules
now live in a shared framework with one parse per file, per-finding
suppression (`# noqa: RTxxx — reason`) and a reviewed baseline file
(tools/analyze/baseline.json) for accepted pre-existing findings.

Rule families (catalog + rationale: docs/static-analysis.md):
  generic  F401 E711 E722 F541 F601 F811 B006 B011  (ruff subset)
  rt10x    RT100 engine thread-spawn protocol
           RT101 silent exception swallow
           RT102 unbounded stdlib queue
  rt200    RT200-RT204 thread-safety: attributes of the hot classes
           indexed by the threads that reach them (spawn sites,
           supervisor.spawn targets, `# runs-on:` annotations); writes
           from >=2 threads need a common lock or a declared
           `# guarded-by: self._lock`.
  rt210    RT210-RT214 JAX trace purity: side effects and tracer
           branching inside jit/shard_map-traced functions.
  rt220    RT220-RT224 metric-name drift between utils/metric_names.py,
           registration sites and docs/metrics.md.
  rt230    RT230-RT232 config-knob drift between config.py fields,
           cfg.<attr> reads and docs/configuration.md.

Entry point: tools/lint.py (CLI) or tools.analyze.driver.run().
"""

from tools.analyze.core import FileCtx, Finding  # noqa: F401
from tools.analyze.driver import run  # noqa: F401

"""RT220-RT224 — metric-name drift (whole-program).

The contract: ``utils/metric_names.py`` is the single registry of
exported series names; every registration in ``metrics.py`` and the
modules resolves to a declared constant; ``docs/metrics.md`` lists
every series and mentions no series that does not exist.  Drift in
any direction (code ahead of docs, docs ahead of code, dead
declarations) is a finding:

  RT220 metric registered under a name not declared in
        utils/metric_names.py
  RT221 metric registered from a string literal / unresolvable
        expression instead of a metric_names constant
  RT222 declared series missing from docs/metrics.md
  RT223 docs/metrics.md mentions a series that is not declared
  RT224 declared series never registered or referenced anywhere

The hubble flow-observability registry (``new_hubble_*``) is a
separate compatibility surface with its own naming (hubble_*) and is
out of scope.  Label-key constants (L_*) are not series names.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.analyze.core import FileCtx, Reporter

METRIC_NAMES_REL = "retina_tpu/utils/metric_names.py"
DOC_REL = "docs/metrics.md"
PREFIX = "networkobservability_"

REG_FUNCS = {
    "new_gauge", "new_counter", "new_histogram",
    "new_adv_gauge", "new_adv_counter", "new_adv_histogram",
}

DOC_SERIES_RE = re.compile(r"networkobservability_[a-z0-9_]+")


def _fold_constants(tree: ast.Module) -> dict[str, str]:
    """Constant-fold the module-level string assignments of
    metric_names.py (NAME = PREFIX + "suffix" chains)."""
    consts: dict[str, str] = {}

    def fold(node: ast.expr) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left, right = fold(node.left), fold(node.right)
            if left is not None and right is not None:
                return left + right
        return None

    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            val = fold(stmt.value)
            if val is not None:
                consts[stmt.targets[0].id] = val
    return consts


def _declared_series(ctx: FileCtx) -> dict[str, tuple[str, int]]:
    """name -> (value, decl lineno) for every exported series."""
    consts = _fold_constants(ctx.tree)
    linenos: dict[str, int] = {}
    for stmt in ctx.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            linenos[stmt.targets[0].id] = stmt.lineno
    out: dict[str, tuple[str, int]] = {}
    for name, value in consts.items():
        if not value.startswith(PREFIX):
            continue
        if name.endswith("PREFIX"):  # building blocks, not series
            continue
        out[name] = (value, linenos.get(name, 1))
    return out


def _registration_aliases(fn: ast.AST) -> set[str]:
    """Local names bound to registration functions, e.g.
    ``g, c = ex.new_gauge, ex.new_counter``."""
    aliases: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for target, value in _assign_pairs(node):
            if (isinstance(target, ast.Name)
                    and isinstance(value, ast.Attribute)
                    and value.attr in REG_FUNCS):
                aliases.add(target.id)
    return aliases


def _assign_pairs(node: ast.Assign):
    for target in node.targets:
        if (isinstance(target, ast.Tuple)
                and isinstance(node.value, ast.Tuple)
                and len(target.elts) == len(node.value.elts)):
            yield from zip(target.elts, node.value.elts)
        else:
            yield target, node.value


def check_program(ctxs: list[FileCtx], rep: Reporter, root: Path) -> None:
    by_rel = {c.rel: c for c in ctxs}
    mn_ctx = by_rel.get(METRIC_NAMES_REL)
    if mn_ctx is None:
        return
    series = _declared_series(mn_ctx)  # const name -> (value, lineno)
    values = {v for v, _ in series.values()}

    prod = [
        c for c in ctxs
        if c.rel.startswith("retina_tpu/") and c.rel != METRIC_NAMES_REL
    ]

    # --- registrations: resolve first args, flag drift -------------
    used_consts: set[str] = set()
    for ctx in prod:
        aliases = _registration_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            # any mn.CONST / imported CONST reference marks the
            # constant as used (values plumbed through variables
            # still originate at one of these references)
            if isinstance(node, ast.Attribute) and node.attr in series:
                used_consts.add(node.attr)
            elif isinstance(node, ast.Name) and node.id in series:
                used_consts.add(node.id)
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_reg = (
                (isinstance(func, ast.Attribute) and func.attr in REG_FUNCS)
                or (isinstance(func, ast.Name) and func.id in aliases)
            )
            if not is_reg or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Attribute) or isinstance(arg, ast.Name):
                continue  # constant reference — handled above
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value in values:
                    rep.add(ctx, node.lineno, "RT221",
                            f'metric "{arg.value}" registered from a '
                            "literal — use the utils.metric_names "
                            "constant",
                            key=f"RT221:{ctx.rel}:{arg.value}")
                else:
                    rep.add(ctx, node.lineno, "RT220",
                            f'metric "{arg.value}" registered but not '
                            "declared in utils/metric_names.py",
                            key=f"RT220:{ctx.rel}:{arg.value}")
            else:
                rep.add(ctx, node.lineno, "RT221",
                        "metric registered from a non-constant "
                        "expression — declare it in "
                        "utils/metric_names.py",
                        key=f"RT221:{ctx.rel}:{node.lineno}")

    # --- docs/metrics.md two-way check -----------------------------
    doc_path = root / DOC_REL
    doc_lines = (
        doc_path.read_text().splitlines() if doc_path.exists() else []
    )
    doc_text = "\n".join(doc_lines)
    for name, (value, lineno) in sorted(series.items()):
        if value not in doc_text and value + "_total" not in doc_text:
            rep.add(mn_ctx, lineno, "RT222",
                    f'series "{value}" ({name}) has no entry in '
                    f"{DOC_REL}",
                    key=f"RT222:{name}")

    # Doc tokens must resolve to declared series.  Prometheus counter
    # exposition appends `_total`; docs may use either spelling.
    doc_ok = values | {v + "_total" for v in values}
    doc_ctx = FileCtx.__new__(FileCtx)  # lightweight shell for .md
    doc_ctx.path = doc_path
    doc_ctx.rel = DOC_REL
    doc_ctx.src = doc_text
    doc_ctx.lines = doc_lines
    doc_ctx.tree = None
    doc_ctx.syntax_error = None
    for i, line in enumerate(doc_lines, start=1):
        for tok in DOC_SERIES_RE.findall(line):
            tok = tok.rstrip("_")
            if tok == PREFIX.rstrip("_"):
                continue  # prose mention of the prefix itself
            if tok in ("networkobservability_adv",
                       "networkobservability_sketch",
                       "networkobservability_fleet",
                       "networkobservability_tpu_timetravel",
                       "networkobservability_tpu_autocapture",
                       "networkobservability_tpu_soak",
                       "networkobservability_tpu_detector",
                       "networkobservability_fleet_query"):
                continue  # prose mention of a family prefix
            if tok not in doc_ok:
                rep.add(doc_ctx, i, "RT223",
                        f'doc mentions "{tok}" which is not declared '
                        "in utils/metric_names.py",
                        key=f"RT223:{tok}")

    # --- declared but never used -----------------------------------
    for name, (value, lineno) in sorted(series.items()):
        if name not in used_consts:
            rep.add(mn_ctx, lineno, "RT224",
                    f"series constant {name} ({value}) is never "
                    "registered or referenced outside metric_names",
                    key=f"RT224:{name}")

"""Shared analysis infrastructure: parsed-file context, findings,
noqa suppression and the reviewed baseline.

Every rule module reports through ``Reporter.add`` so suppression is
uniform: a ``# noqa`` comment on the flagged line (or on an explicitly
nominated companion line, e.g. the attribute's declaration in
``__init__`` for RT200) silences the finding.  ``# noqa: RT101`` is
code-aware — it silences only the listed codes; a bare ``# noqa``
silences everything on that line.

Findings carry a *stable key* (rule-chosen, not a raw line number
where avoidable) so the baseline file survives unrelated edits:
``RT200:retina_tpu/engine.py:SketchEngine._desc_table`` stays valid
however the file shifts.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable

NOQA_RE = re.compile(
    r"#\s*noqa\b(?:\s*:\s*(?P<codes>[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*))?",
)


def noqa_codes(line: str) -> set[str] | None:
    """Return the set of codes a noqa comment on `line` suppresses.

    None  -> no noqa comment at all
    set() -> bare `# noqa` (suppresses every code)
    {...} -> `# noqa: RT101, RT200` (suppresses only those codes)
    """
    m = NOQA_RE.search(line)
    if m is None:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip() for c in codes.split(",")}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str  # repo-relative posix path
    line: int
    code: str
    message: str
    key: str  # stable id used for baseline matching

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class FileCtx:
    """One parsed source file, shared by every rule (parse once)."""

    def __init__(self, path: Path, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.tree: ast.Module | None = None
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(src, filename=rel)
        except SyntaxError as e:  # surfaced as E999 by the driver
            self.syntax_error = e

    def line_at(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, code: str) -> bool:
        codes = noqa_codes(self.line_at(lineno))
        if codes is None:
            return False
        return not codes or code in codes


class Reporter:
    """Collects findings, applying noqa suppression at add() time."""

    def __init__(self) -> None:
        self.findings: list[Finding] = []

    def add(
        self,
        ctx: FileCtx,
        lineno: int,
        code: str,
        message: str,
        key: str | None = None,
        also_noqa_lines: Iterable[int] = (),
    ) -> None:
        """Report `code` at ctx:lineno unless a noqa suppresses it.

        `also_noqa_lines` nominates companion lines whose noqa also
        counts (RT101: the handler's last body line; RT200: the
        attribute's declaration line in __init__).
        `key` defaults to CODE:path:line — rules pass a semantic
        suffix (attr / metric / import name) where one exists so the
        baseline is robust to unrelated line drift.
        """
        for ln in (lineno, *also_noqa_lines):
            if ctx.suppressed(ln, code):
                return
        self.findings.append(
            Finding(
                path=ctx.rel,
                line=lineno,
                code=code,
                message=message,
                key=key or f"{code}:{ctx.rel}:{lineno}",
            )
        )


# ----------------------------------------------------------------------
# Baseline: reviewed pre-existing findings, keyed by Finding.key, each
# with a written reason.  The acceptance bar for this repo is an EMPTY
# baseline (fix at source or noqa with a reason at the site); the file
# exists so a future true-but-deferred finding can land without
# blocking CI, visibly and with an owner-reviewed reason string.

def load_baseline(path: Path) -> dict[str, str]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    entries = data.get("findings", {})
    if isinstance(entries, list):  # tolerate list-of-objects form
        return {e["key"]: e.get("reason", "") for e in entries}
    return dict(entries)


def save_baseline(path: Path, entries: dict[str, str]) -> None:
    payload = {
        "_comment": (
            "Reviewed pre-existing findings. Key -> reason. Keep this "
            "empty: prefer fixing at source or a `# noqa: CODE — "
            "reason` at the site. See docs/static-analysis.md."
        ),
        "findings": dict(sorted(entries.items())),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")

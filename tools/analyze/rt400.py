"""RT400-RT403 — hot-path reachability: no blocking, no cold
compiles, no unbounded allocation on the event path.

Every recent PR re-fixed the same invariant by hand ("window closes
never serialize the feed", "offer() never blocks the close lane", "no
hot-path locks or allocation" in the recorder, "transport handlers
never pay a compile").  This pass machine-checks it: a whole-program
transitive-reachability walk over declared hot-path ROOTS flags,
anywhere reachable from a root:

  RT400 blocking primitives — time.sleep, Thread.join (no timeout),
        blocking socket send/recv/accept, subprocess, file IO,
        Queue.put/get without _nowait / timeout= / block=False
        (put on a provably UNBOUNDED queue never blocks and is not
        flagged — that is RT102's department), Event/Condition .wait()
        without a timeout.  Bounded waits (``ev.wait(0.02)``,
        ``q.get(timeout=...)``, ``t.join(timeout=...)``) are the
        sanctioned backpressure idiom and never fire.
  RT401 potential cold compiles — a bare ``jax.jit`` / ``shard_map``
        dispatch, or a call into a ``@device_entry`` builder that is
        not AOT-warmed / disk-cache-routed (neither the builder nor
        the calling function references ``_compile_cached`` /
        ``_disk_compiled`` / ``aot_disk`` / ``aot_cache``) — the
        static face of the ``fleet_merge_async`` bug class.
  RT402 unbounded per-event allocation (EVENT lane only) —
        ``self.<attr>.append/extend`` (or ``+=``) where the class
        never trims/resets the container, and object building inside
        a loop that iterates a per-record parameter.  Per-call locals
        die with the call and are fine; per-WINDOW containers that a
        non-__init__ method resets or slices are bounded and fine.
  RT403 lock convoy — a hot path acquires a lock that some OTHER
        function holds across a blocking call: the hot thread can
        convoy behind the blocker even though the hot code itself
        never blocks.  Joins the RT400 blocking facts with rt200-style
        ``with self._lock:`` lock facts.

Lane model (docs/static-analysis.md)
------------------------------------
Roots carry a LANE describing the cadence of the path:

  event      per-record rate: engine dispatch, feed-worker fill
             loops, recorder begin/record, record_hook taps.
             All four rules apply.
  close      per-window close on the device proxy: close-lane impl,
             ring/shipper offer.  RT400/401/403 (window-rate
             allocation is fine).
  transport  RPC / pubsub handler threads: Fleet Ship handlers,
             aggregator ingest.  RT400/401/403.
  query      query handlers + the node-answer path.  RT400/401/403.

Roots are declared with ``# hot-path: <lane>`` on a def line, or
derived structurally from STRUCTURAL_ROOTS (the canonical engine /
feed / recorder / shipper / ring / aggregator / hubble / detect /
fleetquery entries — tests/test_analyze.py pins that every structural
entry still resolves against the real tree, so the table cannot rot).

Escape hatches (house style)
----------------------------
  * ``# may-block: <reason>`` on a callee's def line: the walk does
    not descend into it and its facts are excused — the written
    reason is the review.  (For RT403 the callee still counts as
    blocking when some function holds a lock across it: the
    annotation says "this blocks and that is OK *here*", not "this
    does not block".)
  * ``# noqa: RT40x — reason`` on the reported line.
  * the stable-key baseline (tools/analyze/baseline.json).

Resolution is deliberately precision-biased: ``self.m()``, module
functions, ``from``-imports, ``self.<attr>``/local receivers typed by
construction or annotation, return-annotated factories
(``get_recorder().begin``), ``list[T]``-element iteration, and
virtual dispatch from an abstract base to its subclasses.  Unresolved
calls contribute no edges and no facts — a missed finding beats a
wall of false positives (same stance as rt200).
``run_on_device(fn)`` / ``submit_on_device(fn)`` are call edges into
``fn`` (the proxy hop is the sanctioned mechanism, its wait IS the
device work), never blocking primitives themselves.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from tools.analyze.core import FileCtx, Reporter

LANES = ("event", "close", "transport", "query")

HOT_PATH_RE = re.compile(r"#\s*hot-path:\s*([A-Za-z_-]+)")
MAY_BLOCK_RE = re.compile(r"#\s*may-block:(?P<reason>[^#]*)")

# Canonical structural roots: (path suffix, class or None, method,
# lane).  tests/test_analyze.py::test_rt400_structural_roots_resolve
# asserts every entry resolves on the real tree.
STRUCTURAL_ROOTS = (
    ("retina_tpu/engine.py", "SketchEngine", "step_records", "event"),
    ("retina_tpu/engine.py", "SketchEngine", "_dispatch", "event"),
    ("retina_tpu/engine.py", "SketchEngine", "_build_quantum", "event"),
    ("retina_tpu/engine.py", "SketchEngine", "_close_window_impl",
     "close"),
    ("retina_tpu/engine.py", "SketchEngine", "_submit_close_window",
     "close"),
    ("retina_tpu/parallel/feed.py", "FeedWorker", "_loop", "event"),
    ("retina_tpu/parallel/feed.py", "FeedWorker", "push", "event"),
    ("retina_tpu/obs/recorder.py", "FlightRecorder", "begin", "event"),
    ("retina_tpu/obs/recorder.py", "FlightRecorder", "record", "event"),
    ("retina_tpu/fleet/shipper.py", "SnapshotShipper", "offer", "close"),
    ("retina_tpu/timetravel/ring.py", "SnapshotRing", "offer", "close"),
    ("retina_tpu/fleet/aggregator.py", "FleetAggregator", "ingest",
     "transport"),
    ("retina_tpu/hubble/server.py", "HubbleServer", "_fleet_ship",
     "transport"),
    ("retina_tpu/detect/base.py", "DetectorBank", "observe", "event"),
    ("retina_tpu/fleetquery/service.py", "FleetQueryService", "handle",
     "query"),
    ("retina_tpu/fleetquery/service.py", "LocalNodeClient", "query",
     "query"),
    ("retina_tpu/timetravel/query.py", "QueryService", "handle",
     "query"),
)

DEVICE_PROXY_FUNCS = {"run_on_device", "submit_on_device"}

# Source markers that say "this function routes compiles through the
# AOT disk cache" (engine._compile_cached, timetravel.fold's
# _disk_compiled wrapper).  Either the builder or its caller carrying
# one satisfies RT401.
WARM_MARKERS = ("_compile_cached", "_disk_compiled", "aot_disk",
                "aot_cache")

# Parameter names that mean "one block of per-event records" — loops
# iterating one of these row-by-row are per-EVENT loops (RT402).
RECORD_PARAMS = {"records", "recs", "rows", "events", "rec"}

_THREADISH_RE = re.compile(r"thread|proc|worker", re.I)
_SOCKISH_RE = re.compile(r"sock|conn", re.I)
_QUEUEISH_RE = re.compile(r"(^|_)q$|queue", re.I)

# Pseudo-types for receivers we can classify without a class in the
# universe.
Q_UNBOUNDED = "<queue-unbounded>"
Q_BOUNDED = "<queue-bounded>"
T_STR = "<str>"
T_THREAD = "<thread>"


@dataclasses.dataclass
class Fact:
    """One direct blocking/compile/alloc observation in a function."""

    kind: str  # "sleep" | "join" | "socket" | "subprocess" | ...
    lineno: int
    detail: str


@dataclasses.dataclass
class CallSite:
    spec: tuple  # resolution spec, see _classify_call
    lineno: int
    with_depth: int  # how many enclosing with-acquisitions


@dataclasses.dataclass
class Acquire:
    lock: str  # qualified lock id
    lineno: int
    facts_inside: bool
    calls_inside: list[tuple]  # resolution specs made under the lock


class FuncInfo:
    def __init__(self, ctx: FileCtx, node, qualname: str, cls=None):
        self.ctx = ctx
        self.rel = ctx.rel
        self.node = node
        self.qualname = qualname  # "Class.m" | "f" | "f.closure"
        self.cls = cls  # ClassInfo | None
        self.lineno = node.lineno
        self.facts: list[Fact] = []
        self.jit_sites: list[int] = []
        self.entry_calls: list[tuple[str, int]] = []  # (target qual, ln)
        self.calls: list[CallSite] = []
        self.acquires: list[Acquire] = []
        self.appends: list[tuple[str, int, str]] = []  # (attr, ln, op)
        self.loop_allocs: list[tuple[int, str]] = []
        self.local_types: dict[str, object] = {}
        line = ctx.line_at(node.lineno)
        m = HOT_PATH_RE.search(line)
        self.lane_annot = m.group(1) if m else None
        self.lane_annot_line = node.lineno if m else 0
        mb = MAY_BLOCK_RE.search(line)
        self.may_block = mb.group("reason").strip() if mb else None
        self.may_block_present = mb is not None
        self.is_device_entry = any(
            (isinstance(d, ast.Call)
             and ((isinstance(d.func, ast.Name)
                   and d.func.id == "device_entry")
                  or (isinstance(d.func, ast.Attribute)
                      and d.func.attr == "device_entry")))
            for d in node.decorator_list
        )
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        seg = "\n".join(ctx.lines[node.lineno - 1:end])
        self.warm_routed = any(m in seg for m in WARM_MARKERS)
        body = node.body
        self.abstract = (
            len(body) <= 2
            and isinstance(body[-1], ast.Raise)
            and "NotImplementedError" in ast.dump(body[-1])
        )


class ClassInfo:
    def __init__(self, ctx: FileCtx, node: ast.ClassDef):
        self.ctx = ctx
        self.rel = ctx.rel
        self.node = node
        self.name = node.name
        self.methods: dict[str, FuncInfo] = {}
        self.bases = [
            b.id if isinstance(b, ast.Name)
            else b.attr if isinstance(b, ast.Attribute) else None
            for b in node.bases
        ]
        self.attr_types: dict[str, object] = {}
        self.attr_elem_types: dict[str, str] = {}
        # attrs assigned (plain =) in some non-__init__ method, or
        # trimmed with del-slice/pop/clear: growth is bounded per
        # window/call, not per process lifetime.
        self.trimmed_attrs: set[str] = set()


def _ann_name(ann) -> str | None:
    """Type annotation expr -> plain class name, unwrapping Optional/
    quotes; returns None for anything fancier."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        left = _ann_name(ann.left)
        if left is not None and left != "None":
            return left
        return _ann_name(ann.right)
    return None


def _ann_elem_name(ann) -> str | None:
    """``list[T]`` / ``tuple[T, ...]`` annotation -> T's name."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if (isinstance(ann, ast.Subscript)
            and isinstance(ann.value, ast.Name)
            and ann.value.id in ("list", "tuple", "List", "Sequence")):
        sl = ann.slice
        if isinstance(sl, ast.Tuple) and sl.elts:
            sl = sl.elts[0]
        return _ann_name(sl)
    return None


def _call_type(call: ast.Call) -> object | None:
    """Constructor-call expr -> pseudo/class-name type."""
    f = call.func
    name = (f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None)
    if name == "Queue":
        maxsize = None
        if call.args:
            maxsize = call.args[0]
        for kw in call.keywords:
            if kw.arg == "maxsize":
                maxsize = kw.value
        if maxsize is None or (
                isinstance(maxsize, ast.Constant) and maxsize.value == 0):
            return Q_UNBOUNDED
        return Q_BOUNDED
    if name == "Thread":
        return T_THREAD
    return name


class Program:
    """Whole-program index: every function/method in the retina_tpu
    tree, with resolved call edges, blocking facts and lock facts."""

    def __init__(self, ctxs: list[FileCtx]):
        self.funcs: dict[tuple[str, str], FuncInfo] = {}
        self.classes: dict[tuple[str, str], ClassInfo] = {}
        self.class_by_name: dict[str, list[ClassInfo]] = {}
        self.func_by_name: dict[str, list[FuncInfo]] = {}
        self.imports: dict[str, dict[str, tuple[str | None, str]]] = {}
        self.subclasses: dict[str, list[ClassInfo]] = {}
        self.ctxs = [c for c in ctxs
                     if c.rel.startswith("retina_tpu/")
                     and c.tree is not None]
        for ctx in self.ctxs:
            self._index_file(ctx)
        for cls_list in self.class_by_name.values():
            for ci in cls_list:
                for b in ci.bases:
                    if b:
                        self.subclasses.setdefault(b, []).append(ci)
        for fi in list(self.funcs.values()):
            _FuncWalker(self, fi).walk()

    # -- indexing ------------------------------------------------------
    def _index_file(self, ctx: FileCtx) -> None:
        imps: dict[str, tuple[str | None, str]] = {}
        self.imports[ctx.rel] = imps
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                rel = node.module.replace(".", "/") + ".py"
                for a in node.names:
                    imps[a.asname or a.name] = (rel, a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    rel = a.name.replace(".", "/") + ".py"
                    imps[a.asname or a.name.split(".")[0]] = (rel, "")
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(ctx, node)
                self.classes[(ctx.rel, ci.name)] = ci
                self.class_by_name.setdefault(ci.name, []).append(ci)
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = FuncInfo(ctx, stmt,
                                      f"{ci.name}.{stmt.name}", cls=ci)
                        ci.methods[stmt.name] = fi
                        self._register(fi)
                self._collect_class_types(ci)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(ctx, node, node.name)
                self._register(fi)

    def _register(self, fi: FuncInfo) -> None:
        self.funcs[(fi.rel, fi.qualname)] = fi
        self.func_by_name.setdefault(
            fi.qualname.split(".")[-1], []).append(fi)

    def _collect_class_types(self, ci: ClassInfo) -> None:
        init = ci.methods.get("__init__")
        param_anns: dict[str, ast.expr] = {}
        if init is not None:
            for a in init.node.args.args + init.node.args.kwonlyargs:
                if a.annotation is not None:
                    param_anns[a.arg] = a.annotation
            for node in ast.walk(init.node):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    val = node.value
                    for t in targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        if (isinstance(node, ast.AnnAssign)
                                and node.annotation is not None):
                            el = _ann_elem_name(node.annotation)
                            if el:
                                ci.attr_elem_types[t.attr] = el
                            nm = _ann_name(node.annotation)
                            if nm:
                                ci.attr_types.setdefault(t.attr, nm)
                        ty = self._value_type(val, param_anns, ci)
                        if ty is not None:
                            ci.attr_types.setdefault(t.attr, ty)
                        el = self._value_elem_type(val, param_anns)
                        if el is not None:
                            ci.attr_elem_types.setdefault(t.attr, el)
        # trim / per-window-reset detection (source scan of the class)
        grown: set[str] = set()
        for m in ci.methods.values():
            for node in ast.walk(m.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("append", "extend")
                        and isinstance(node.func.value, ast.Attribute)
                        and isinstance(node.func.value.value, ast.Name)
                        and node.func.value.value.id == "self"):
                    grown.add(node.func.value.attr)
                elif (isinstance(node, ast.AugAssign)
                        and isinstance(node.target, ast.Attribute)
                        and isinstance(node.target.value, ast.Name)
                        and node.target.value.id == "self"):
                    grown.add(node.target.attr)
        start = ci.node.lineno - 1
        end = getattr(ci.node, "end_lineno", None) or len(ci.ctx.lines)
        seg = "\n".join(ci.ctx.lines[start:end])
        for attr in grown:
            pats = (f"del self.{attr}[", f"self.{attr}.popleft(",
                    f"self.{attr}.pop(0", f"self.{attr}.clear(",
                    f"self.{attr} = self.{attr}[")
            if any(p in seg for p in pats):
                ci.trimmed_attrs.add(attr)
                continue
            if ci.attr_types.get(attr) == "deque":
                # deque(maxlen=...) bounds itself; a bare deque() is
                # checked via the constructor args below.
                init_line = ""
                for mn, mi in ci.methods.items():
                    if mn != "__init__":
                        continue
                    for node in ast.walk(mi.node):
                        if (isinstance(node, ast.Assign)
                                and isinstance(node.value, ast.Call)):
                            for t in node.targets:
                                if (isinstance(t, ast.Attribute)
                                        and t.attr == attr):
                                    init_line = ast.dump(node.value)
                if "maxlen" in init_line:
                    ci.trimmed_attrs.add(attr)
                    continue
            for mn, mi in ci.methods.items():
                if mn in ("__init__", "__post_init__"):
                    continue
                reset = any(
                    (isinstance(node, ast.Assign)
                     and any(isinstance(t, ast.Attribute)
                             and isinstance(t.value, ast.Name)
                             and t.value.id == "self"
                             and t.attr == attr
                             for t in node.targets))
                    or (isinstance(node, ast.AnnAssign)
                        and node.value is not None
                        and isinstance(node.target, ast.Attribute)
                        and isinstance(node.target.value, ast.Name)
                        and node.target.value.id == "self"
                        and node.target.attr == attr)
                    for node in ast.walk(mi.node)
                )
                if reset:
                    ci.trimmed_attrs.add(attr)
                    break

    def _value_type(self, val, param_anns, ci=None) -> object | None:
        if isinstance(val, ast.BoolOp) and val.values:
            return self._value_type(val.values[-1], param_anns, ci)
        if isinstance(val, ast.Call):
            f = val.func
            fname = (f.id if isinstance(f, ast.Name)
                     else f.attr if isinstance(f, ast.Attribute)
                     else None)
            if fname and fname.startswith("get_"):
                for cand in self.func_by_name.get(fname, ()):
                    ret = _ann_name(cand.node.returns)
                    if ret:
                        return ret
            return _call_type(val)
        if isinstance(val, ast.Name) and val.id in param_anns:
            return _ann_name(param_anns[val.id])
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            return T_STR
        return None

    def _value_elem_type(self, val, param_anns) -> str | None:
        """``self.x = list(param)`` with ``param: list[T]`` -> T."""
        if (isinstance(val, ast.Call) and isinstance(val.func, ast.Name)
                and val.func.id == "list" and val.args
                and isinstance(val.args[0], ast.Name)
                and val.args[0].id in param_anns):
            return _ann_elem_name(param_anns[val.args[0].id])
        return None

    # -- resolution ----------------------------------------------------
    def resolve_class(self, rel: str, name: str) -> ClassInfo | None:
        ci = self.classes.get((rel, name))
        if ci is not None:
            return ci
        imp = self.imports.get(rel, {}).get(name)
        if imp is not None and imp[1]:
            return self.classes.get((imp[0], imp[1]))
        cands = self.class_by_name.get(name, ())
        return cands[0] if len(cands) == 1 else None

    def resolve_func(self, rel: str, name: str) -> FuncInfo | None:
        fi = self.funcs.get((rel, name))
        if fi is not None:
            return fi
        imp = self.imports.get(rel, {}).get(name)
        if imp is not None and imp[1]:
            return self.funcs.get((imp[0], imp[1]))
        return None

    def resolve_method(
        self, ci: ClassInfo, name: str
    ) -> list[FuncInfo]:
        """C.name with abstract-base virtual dispatch."""
        seen: set[str] = set()
        cur: ClassInfo | None = ci
        fi = None
        while cur is not None and cur.name not in seen:
            seen.add(cur.name)
            fi = cur.methods.get(name)
            if fi is not None:
                break
            nxt = None
            for b in cur.bases:
                if b:
                    nxt = self.resolve_class(cur.rel, b)
                    if nxt is not None:
                        break
            cur = nxt
        if fi is None:
            return []
        if not fi.abstract:
            return [fi]
        out = [fi]
        stack = [ci.name]
        visited = set()
        while stack:
            base = stack.pop()
            if base in visited:
                continue
            visited.add(base)
            for sub in self.subclasses.get(base, ()):
                m = sub.methods.get(name)
                if m is not None:
                    out.append(m)
                stack.append(sub.name)
        return out


class _FuncWalker:
    """Single AST walk of one function: collects typed locals, call
    sites, blocking facts, jit facts, alloc facts and lock facts."""

    def __init__(self, prog: Program, fi: FuncInfo):
        self.prog = prog
        self.fi = fi
        self.types: dict[str, object] = {}
        args = fi.node.args
        for a in (args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a is not None and a.annotation is not None:
                nm = _ann_name(a.annotation)
                if nm:
                    self.types[a.arg] = nm
        self.record_params = {
            a.arg for a in args.args + args.kwonlyargs
            if a.arg in RECORD_PARAMS
        }
        self.local_defs: dict[str, str] = {}

    # receiver expr -> type (class name / pseudo-type) or None
    def _recv_type(self, node) -> object | None:
        fi = self.fi
        if isinstance(node, ast.Name):
            return self.types.get(node.id)
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and fi.cls is not None):
            return fi.cls.attr_types.get(node.attr)
        if isinstance(node, ast.Call):
            return self.prog._value_type(node, {}, fi.cls)
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return T_STR
        return None

    def _recv_name(self, node) -> str:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return ""

    def walk(self) -> None:
        fi = self.fi
        for stmt in fi.node.body:
            self._visit(stmt, with_stack=[], loop_record=False)

    def _visit(self, n, with_stack: list[Acquire],
               loop_record: bool) -> None:
        fi = self.fi
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pseudo = f"{fi.qualname}.{n.name}"
            sub = FuncInfo(fi.ctx, n, pseudo, cls=fi.cls)
            self.prog.funcs[(fi.rel, pseudo)] = sub
            self.local_defs[n.name] = pseudo
            _FuncWalker(self.prog, sub).walk()
            return
        if isinstance(n, ast.With):
            inner = list(with_stack)
            for item in n.items:
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    acq = Acquire(lid, n.lineno, False, [])
                    fi.acquires.append(acq)
                    inner.append(acq)
            # the context expressions themselves can be facts
            # (``with open(path) as f:`` is hot-path file IO)
            for item in n.items:
                self._visit(item.context_expr, inner, loop_record)
            for stmt in n.body:
                self._visit(stmt, inner, loop_record)
            return
        if isinstance(n, ast.For):
            rec_loop = loop_record or (
                isinstance(n.iter, ast.Name)
                and n.iter.id in self.record_params
            )
            for child in ast.iter_child_nodes(n):
                self._visit(child, with_stack, rec_loop)
            return
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            ty = self.prog._value_type(n.value, {}, fi.cls)
            if ty is not None:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        self.types[t.id] = ty
        if isinstance(n, ast.AugAssign):
            # ``self.x += [item]`` / ``+= f"..."`` is container/str
            # growth; ``self.n += len(block)`` is a scalar counter and
            # is fine — gate on an unambiguously sequence-building RHS.
            t = n.target
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and isinstance(n.value, (ast.List, ast.ListComp,
                                             ast.JoinedStr))):
                fi.appends.append((t.attr, n.lineno, "+="))
        if loop_record and isinstance(
                n, (ast.ListComp, ast.DictComp, ast.SetComp, ast.Dict,
                    ast.List, ast.JoinedStr)):
            fi.loop_allocs.append(
                (n.lineno, type(n).__name__))
        if isinstance(n, ast.Call):
            self._classify_call(n, with_stack, loop_record)
        for child in ast.iter_child_nodes(n):
            self._visit(child, with_stack, loop_record)

    def _lock_id(self, node) -> str | None:
        fi = self.fi
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            owner = fi.cls.name if fi.cls is not None else fi.qualname
            if ("lock" in node.attr.lower()
                    or "mutex" in node.attr.lower()):
                return f"{fi.rel}:{owner}.{node.attr}"
            ty = (fi.cls.attr_types.get(node.attr)
                  if fi.cls is not None else None)
            if ty in ("Lock", "RLock", "Condition"):
                return f"{fi.rel}:{owner}.{node.attr}"
            return None
        if isinstance(node, ast.Name) and "lock" in node.id.lower():
            return f"{fi.rel}:{node.id}"
        return None

    def _fact(self, kind: str, lineno: int, detail: str,
              with_stack: list[Acquire]) -> None:
        self.fi.facts.append(Fact(kind, lineno, detail))
        for acq in with_stack:
            acq.facts_inside = True

    def _classify_call(self, call: ast.Call,
                       with_stack: list[Acquire],
                       loop_record: bool) -> None:
        fi, prog = self.fi, self.prog
        func = call.func
        kwargs = {kw.arg for kw in call.keywords if kw.arg}
        has_timeout = "timeout" in kwargs or "timeout_s" in kwargs
        nonblocking = any(
            kw.arg == "block"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in call.keywords
        ) or any(
            kw.arg == "blocking"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in call.keywords
        )

        def add_call(spec: tuple) -> None:
            site = CallSite(spec, call.lineno, len(with_stack))
            fi.calls.append(site)
            for acq in with_stack:
                acq.calls_inside.append(spec)

        # jax.jit / pjit / shard_map dispatch sites
        fname = (func.id if isinstance(func, ast.Name)
                 else func.attr if isinstance(func, ast.Attribute)
                 else None)
        if fname in ("jit", "pjit", "shard_map") and not (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id not in ("jax", "pjit")):
            fi.jit_sites.append(call.lineno)

        # run_on_device(fn) / submit_on_device(fn): edge into fn
        if fname in DEVICE_PROXY_FUNCS and call.args:
            tgt = call.args[0]
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                add_call(("self", tgt.attr))
            elif isinstance(tgt, ast.Name):
                if tgt.id in self.local_defs:
                    add_call(("local", self.local_defs[tgt.id]))
                else:
                    add_call(("name", tgt.id))
            return

        if isinstance(func, ast.Name):
            name = func.id
            if name in self.local_defs:
                add_call(("local", self.local_defs[name]))
                return
            if name == "open":
                self._fact("file-io", call.lineno, "open()", with_stack)
                return
            if name == "sleep":
                imp = prog.imports.get(fi.rel, {}).get("sleep")
                if imp and imp[0] == "time.py":
                    self._fact("sleep", call.lineno, "time.sleep",
                               with_stack)
                    return
            add_call(("name", name))
            return

        if not isinstance(func, ast.Attribute):
            return
        meth = func.attr
        recv = func.value

        # module-qualified primitives
        if isinstance(recv, ast.Name):
            base = recv.id
            if base == "time" and meth == "sleep":
                self._fact("sleep", call.lineno, "time.sleep",
                           with_stack)
                return
            if base == "subprocess" and meth in (
                    "run", "Popen", "call", "check_call",
                    "check_output"):
                self._fact("subprocess", call.lineno,
                           f"subprocess.{meth}", with_stack)
                return
            if base == "os" and meth in ("system", "popen"):
                self._fact("subprocess", call.lineno, f"os.{meth}",
                           with_stack)
                return
            # module function call: mod.f()
            imp = prog.imports.get(fi.rel, {}).get(base)
            if imp is not None and not imp[1]:
                tgt = prog.funcs.get((imp[0], meth))
                if tgt is not None:
                    add_call(("func", imp[0], meth))
                    return

        # self.m() / typed-receiver method calls
        rtype = self._recv_type(recv)
        if (isinstance(recv, ast.Name) and recv.id == "self"
                and fi.cls is not None):
            add_call(("method", fi.cls.rel, fi.cls.name, meth))
            return
        if isinstance(rtype, str) and not rtype.startswith("<"):
            ci = prog.resolve_class(fi.rel, rtype)
            if ci is not None:
                add_call(("method", ci.rel, ci.name, meth))
                return
        # iteration element of a list[T] self attribute:
        # ``for d in self.detectors: d.judge(...)`` — handled via
        # local type seeding in _visit's For handling? cheap variant:
        if (isinstance(recv, ast.Name) and fi.cls is not None
                and recv.id not in self.types):
            elem = None
            for attr, el in fi.cls.attr_elem_types.items():
                # single-letter loop vars over self.<attr> iterables
                if recv.id in (el.lower()[:1], attr.rstrip("s"), "d"):
                    elem = el
                    break
            if elem is not None:
                ci = prog.resolve_class(fi.rel, elem)
                if ci is not None:
                    add_call(("method", ci.rel, ci.name, meth))
                    return

        # primitive heuristics on unresolved receivers
        rname = self._recv_name(recv)
        if meth == "join":
            if rtype == T_STR or isinstance(recv, ast.Constant):
                return
            if (rtype == T_THREAD or _THREADISH_RE.search(rname)) \
                    and not has_timeout and not call.args:
                self._fact("thread-join", call.lineno,
                           f"{rname or '?'}.join() without timeout",
                           with_stack)
            return
        if meth in ("recv", "recvfrom", "accept", "sendall"):
            if _SOCKISH_RE.search(rname):
                self._fact("socket", call.lineno,
                           f"{rname}.{meth}()", with_stack)
            return
        if meth in ("read_text", "read_bytes", "write_text",
                    "write_bytes"):
            self._fact("file-io", call.lineno, f"{rname}.{meth}()",
                       with_stack)
            return
        if meth in ("put", "get"):
            queueish = rtype in (Q_BOUNDED, Q_UNBOUNDED) or (
                rtype is None and _QUEUEISH_RE.search(rname))
            if not queueish or has_timeout or nonblocking:
                return
            if meth == "put" and rtype == Q_UNBOUNDED:
                return  # unbounded put never blocks (RT102's beat)
            self._fact("queue-" + meth, call.lineno,
                       f"{rname or 'queue'}.{meth}() without "
                       "timeout/_nowait", with_stack)
            return
        if meth == "wait":
            if not call.args and not has_timeout:
                self._fact("event-wait", call.lineno,
                           f"{rname or '?'}.wait() without timeout",
                           with_stack)
            return
        if meth.endswith("_nowait"):
            return

        # append/extend growth on self attributes (RT402a)
        if (meth in ("append", "extend", "appendleft")
                and isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"):
            fi.appends.append((recv.attr, call.lineno, meth))
            return


# ----------------------------------------------------------------------
# reachability + reporting

def _roots(prog: Program, rep: Reporter) -> list[tuple[FuncInfo, str]]:
    roots: list[tuple[FuncInfo, str]] = []
    seen: set[tuple[str, str]] = set()
    for fi in prog.funcs.values():
        if fi.lane_annot is None:
            continue
        if fi.lane_annot not in LANES:
            rep.add(fi.ctx, fi.lineno, "RT400",
                    f"unknown hot-path lane {fi.lane_annot!r} "
                    f"(expected one of {', '.join(LANES)})",
                    key=f"RT400:{fi.rel}:{fi.qualname}:bad-lane")
            continue
        roots.append((fi, fi.lane_annot))
        seen.add((fi.rel, fi.qualname))
    for rel_sfx, cls, meth, lane in STRUCTURAL_ROOTS:
        qual = f"{cls}.{meth}" if cls else meth
        for (rel, q), fi in prog.funcs.items():
            if rel.endswith(rel_sfx) and q == qual \
                    and (rel, q) not in seen:
                roots.append((fi, lane))
                seen.add((rel, q))
    for fi in prog.funcs.values():
        if fi.may_block_present and not fi.may_block:
            rep.add(fi.ctx, fi.lineno, "RT400",
                    "empty may-block reason — the written reason IS "
                    "the review",
                    key=f"RT400:{fi.rel}:{fi.qualname}:bad-may-block")
    return roots


def _edges(prog: Program, fi: FuncInfo) -> list[FuncInfo]:
    out: list[FuncInfo] = []
    for site in fi.calls:
        out.extend(_resolve_spec(prog, fi, site.spec))
    return out


def _resolve_spec(prog: Program, fi: FuncInfo,
                  spec: tuple) -> list[FuncInfo]:
    """Memoized: the can_block fixpoint re-resolves the same specs
    every iteration."""
    cache = prog.__dict__.setdefault("_spec_cache", {})
    key = (fi.rel, fi.qualname, spec)
    hit = cache.get(key)
    if hit is None:
        hit = cache[key] = _resolve_spec_uncached(prog, fi, spec)
    return hit


def _resolve_spec_uncached(prog: Program, fi: FuncInfo,
                           spec: tuple) -> list[FuncInfo]:
    if spec[0] == "self" and fi.cls is not None:
        return prog.resolve_method(fi.cls, spec[1])
    if spec[0] == "local":
        sub = prog.funcs.get((fi.rel, spec[1]))
        return [sub] if sub is not None else []
    if spec[0] == "name":
        tgt = prog.resolve_func(fi.rel, spec[1])
        return [tgt] if tgt is not None else []
    if spec[0] == "func":
        tgt = prog.funcs.get((spec[1], spec[2]))
        return [tgt] if tgt is not None else []
    if spec[0] == "method":
        ci = prog.classes.get((spec[1], spec[2]))
        if ci is None:
            return []
        return prog.resolve_method(ci, spec[3])
    return []


_FACT_LABEL = {
    "sleep": "time.sleep", "thread-join": "Thread.join",
    "socket": "blocking socket call", "subprocess": "subprocess",
    "file-io": "file IO", "queue-put": "blocking Queue.put",
    "queue-get": "blocking Queue.get",
    "event-wait": "Event.wait without timeout",
}


def check_program(ctxs: list[FileCtx], rep: Reporter,
                  root: Path) -> None:
    prog = Program(ctxs)
    roots = _roots(prog, rep)
    if not roots:
        return

    # BFS per lane; remember one witness path per reached function.
    reached: dict[tuple[str, str], tuple[str, FuncInfo, tuple]] = {}
    for rfi, lane in roots:
        stack: list[tuple[FuncInfo, tuple]] = [(rfi, (rfi.qualname,))]
        while stack:
            fi, path = stack.pop()
            k = (fi.rel, fi.qualname)
            if k in reached:
                continue
            reached[k] = (lane, rfi, path)
            if fi.may_block is not None:
                continue  # reviewed escape hatch: do not descend
            for nxt in _edges(prog, fi):
                nk = (nxt.rel, nxt.qualname)
                if nk not in reached:
                    stack.append((nxt, path + (nxt.qualname,)))

    def via(path: tuple, lane: str) -> str:
        chain = " <- ".join(reversed(path[-4:]))
        return f"[lane={lane}] reached via {chain}"

    reported: set[str] = set()

    def add(fi: FuncInfo, lineno: int, code: str, msg: str,
            key: str) -> None:
        if key in reported:
            return
        reported.add(key)
        rep.add(fi.ctx, lineno, code, msg, key=key)

    for (rel, qual), (lane, rfi, path) in sorted(reached.items()):
        fi = prog.funcs[(rel, qual)]
        if fi.may_block is not None and fi is not rfi:
            continue
        # RT400: blocking primitives
        for f in fi.facts:
            add(fi, f.lineno, "RT400",
                f"{_FACT_LABEL.get(f.kind, f.kind)} on the hot path: "
                f"{f.detail} — {via(path, lane)}. Fix, or "
                "`# may-block: <reason>` on the callee / "
                "`# noqa: RT400 — reason` here",
                key=f"RT400:{rel}:{qual}:{f.kind}")
        # RT401: cold compiles
        if not fi.warm_routed and not fi.is_device_entry:
            for ln in fi.jit_sites:
                add(fi, ln, "RT401",
                    "bare jax.jit/shard_map dispatch on the hot path "
                    f"— first call pays the compile — {via(path, lane)}",
                    key=f"RT401:{rel}:{qual}:jit")
        for site in fi.calls:
            for tgt in _resolve_spec(prog, fi, site.spec):
                if not tgt.is_device_entry:
                    continue
                if tgt.warm_routed or fi.warm_routed:
                    continue
                add(fi, site.lineno, "RT401",
                    f"call into @device_entry builder {tgt.qualname} "
                    "with no AOT warm / disk-cache routing — first "
                    "call on this lane pays the compile "
                    f"(fleet_merge_async bug class) — {via(path, lane)}",
                    key=f"RT401:{rel}:{qual}:{tgt.qualname}")
        # RT402: unbounded per-event allocation (event lane only)
        if lane == "event":
            for attr, ln, op in fi.appends:
                ci = fi.cls
                if ci is not None and attr in ci.trimmed_attrs:
                    continue
                add(fi, ln, "RT402",
                    f"self.{attr}.{op} grows an untrimmed container "
                    f"on the event path — {via(path, lane)}. Bound it "
                    "(trim/reset/deque(maxlen)) or noqa with a reason",
                    key=f"RT402:{rel}:{qual}:{attr}")
            for ln, kind in fi.loop_allocs:
                add(fi, ln, "RT402",
                    f"{kind} allocation inside a per-record loop — "
                    f"{via(path, lane)}. Vectorize the block instead "
                    "of building objects per event",
                    key=f"RT402:{rel}:{qual}:loop:{ln}")

    # RT403: lock convoys — join hot acquisitions with locks held
    # across blocking calls anywhere in the program.
    can_block: dict[tuple[str, str], bool] = {}
    for k, fi in prog.funcs.items():
        can_block[k] = bool(fi.facts) or fi.may_block is not None
    changed = True
    guard = 0
    while changed and guard <= len(prog.funcs) + 2:
        changed = False
        guard += 1
        for k, fi in prog.funcs.items():
            if can_block[k]:
                continue
            for site in fi.calls:
                for tgt in _resolve_spec(prog, fi, site.spec):
                    if can_block.get((tgt.rel, tgt.qualname)):
                        can_block[k] = True
                        changed = True
                        break
                if can_block[k]:
                    break

    held_across_block: dict[str, tuple[FuncInfo, int]] = {}
    for fi in prog.funcs.values():
        for acq in fi.acquires:
            blocking = acq.facts_inside or any(
                can_block.get((t.rel, t.qualname))
                for spec in acq.calls_inside
                for t in _resolve_spec(prog, fi, spec)
            )
            if blocking and acq.lock not in held_across_block:
                held_across_block[acq.lock] = (fi, acq.lineno)

    for (rel, qual), (lane, rfi, path) in sorted(reached.items()):
        fi = prog.funcs[(rel, qual)]
        if fi.may_block is not None and fi is not rfi:
            continue
        for acq in fi.acquires:
            witness = held_across_block.get(acq.lock)
            if witness is None or witness[0] is fi:
                continue
            wfi, wln = witness
            add(fi, acq.lineno, "RT403",
                f"hot path acquires {acq.lock.split(':')[-1]} which "
                f"{wfi.qualname} ({wfi.rel}:{wln}) holds across a "
                f"blocking call — lock convoy — {via(path, lane)}",
                key=f"RT403:{rel}:{qual}:{acq.lock.split(':')[-1]}")

"""RT300 family: device-program analysis + the RT305 registry rule.

Two faces:

- ``check(ctx, rep)`` — RT305, a pure-AST per-file rule that runs in
  the default (fast) lint: every ``jax.jit`` / ``shard_map`` call
  site under ``retina_tpu/`` must live inside a function carrying a
  ``@device_entry(...)`` decorator (retina_tpu/devprog.py), so the
  device-program registry provably covers every program the repo can
  put on an accelerator.

- ``check_device(ctxs, rep, root)`` — the heavy pass behind
  ``python tools/lint.py --device``: lazily imports
  tools/analyze/devlower.py (the ONLY module that imports jax —
  pinned to the CPU backend with 4 synthetic devices), AOT-lowers
  every registered entry point, and walks the jaxprs / compiled HLO:

  RT300  merge algebra — every ``*_merge`` combines state through
         associative/commutative primitives only (add / max / the
         compare-select join), proven at the primitive level.
  RT301  counter overflow — (a) every declared pure-sum u32 counter's
         carry chain is scatter-add/add/structural only, (b) the
         config-derived per-window bound k * envelope * window fits
         u32, (c) interval analysis of the HT-rescale under the
         documented envelope shows no in-window wrap, and (d) every
         u32 state leaf is classified pure-sum or exempt.
  RT302  donation coverage — lowered args_info must show the expected
         donations (hot-path consumed state) and non-donations
         (resident tables the host rereads).
  RT303  sharding audit — compiled HLO may contain only each entry's
         expected collectives; anything else is an implicit gather /
         forced replication.
  RT304  host/device predicate parity — numpy mirrors executed
         against their device twins over the packed-field domain.

Findings anchor at the registered entry's definition line where one
exists (via the DeviceEntry record), else at devlower.py itself.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

from tools.analyze.core import FileCtx, Reporter

# ---------------------------------------------------------------------
# RT305 — registry exhaustiveness (pure AST, default lint)

_SHARD_MAP_NAMES = {"shard_map", "_shard_map", "_exp_shard_map"}


def _is_jit_expr(node: ast.expr) -> bool:
    """jax.jit / jit referenced as a value (e.g. partial(jax.jit, ...))."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _is_program_site(call: ast.Call) -> str | None:
    """Return 'jit' / 'shard_map' if this Call creates a device
    program, else None."""
    f = call.func
    if _is_jit_expr(f):
        return "jit"
    if isinstance(f, ast.Attribute) and f.attr in _SHARD_MAP_NAMES:
        return "shard_map"
    if isinstance(f, ast.Name) and f.id in _SHARD_MAP_NAMES:
        return "shard_map"
    # functools.partial(jax.jit, ...) — the jit reference rides as an
    # argument.
    if isinstance(f, ast.Name) and f.id in {"partial", "_partial"} or (
        isinstance(f, ast.Attribute) and f.attr == "partial"
    ):
        if any(_is_jit_expr(a) for a in call.args):
            return "jit"
    return None


def _has_device_entry(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(d, ast.Name) and d.id == "device_entry":
            return True
        if isinstance(d, ast.Attribute) and d.attr == "device_entry":
            return True
    return False


def check(ctx: FileCtx, rep: Reporter) -> None:
    """RT305: unregistered jax.jit / shard_map site under retina_tpu/."""
    if not ctx.rel.startswith("retina_tpu/"):
        return
    if ctx.rel.endswith("devprog.py"):
        return  # the registry itself
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _is_program_site(node)
        if kind is None:
            continue
        covered = False
        cur = node
        fn_name = "<module>"
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if fn_name == "<module>":
                    fn_name = cur.name
                if _has_device_entry(cur):
                    covered = True
                    break
        if not covered:
            rep.add(
                ctx, node.lineno, "RT305",
                f"{kind} site in `{fn_name}` is not covered by a "
                f"@device_entry registration — the device-program "
                f"analysis (lint.py --device) cannot see it",
                key=f"RT305:{ctx.rel}:{fn_name}",
            )


# ---------------------------------------------------------------------
# Device pass helpers (no jax at module scope — devlower is imported
# inside check_device only)

def _prod_map(jaxpr) -> dict:
    m = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            m[ov] = eqn
    return m


def _sub_jaxpr(eqn):
    sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
    if sub is None:
        return None
    return sub.jaxpr if hasattr(sub, "jaxpr") else sub


def _collect_prims(jaxpr, call_prims, out: list) -> None:
    """(primitive_name, eqn) for every eqn, recursing through call
    primitives (which are transparent and not themselves counted)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in call_prims:
            sub = _sub_jaxpr(eqn)
            if sub is not None:
                _collect_prims(sub, call_prims, out)
                continue
        out.append((eqn.primitive.name, eqn))


def _is_literal(v) -> bool:
    return hasattr(v, "val")


def _algebra_violations(closed, allowed, call_prims) -> list[str]:
    """Primitive names in the jaxpr outside `allowed`. An `add` with a
    literal operand is index arithmetic from gather/take lowering
    (negative-index normalization adds the axis size constant), not a
    state combination — treated as structural."""
    pairs: list = []
    _collect_prims(closed.jaxpr, call_prims, pairs)
    bad = []
    for name, eqn in pairs:
        if name in allowed:
            continue
        if name == "add" and any(_is_literal(v) for v in eqn.invars):
            continue
        bad.append(name)
    return sorted(set(bad))


def _pure_sources(closed, out_idx: int, carry_prims, structural,
                  call_prims) -> frozenset[int]:
    """Flat input positions reachable from output `out_idx` through
    pure carry chains only (scatter-add carries operand 0; add carries
    either operand; structural ops carry all operands; any other
    primitive ends the path). Success-on-any-path: an impure branch is
    simply not a source."""
    jaxpr = closed.jaxpr
    memo: dict = {}

    def rec(jx, var, pm, invar_pos):
        if _is_literal(var):
            return frozenset()
        key = (id(jx), var)
        if key in memo:
            return memo[key]
        memo[key] = frozenset()  # DAG; placeholder for re-reads
        if var in invar_pos:
            res = frozenset({invar_pos[var]})
            memo[key] = res
            return res
        eqn = pm.get(var)
        if eqn is None:  # constvar
            return frozenset()
        nm = eqn.primitive.name
        out: set[int] = set()
        if nm in call_prims:
            sub = _sub_jaxpr(eqn)
            if sub is not None:
                k = eqn.outvars.index(var)
                sub_pm = _prod_map(sub)
                sub_pos = {v: i for i, v in enumerate(sub.invars)}
                for j in rec(sub, sub.outvars[k], sub_pm, sub_pos):
                    out |= rec(jx, eqn.invars[j], pm, invar_pos)
        elif nm in carry_prims and nm.startswith("scatter"):
            out |= rec(jx, eqn.invars[0], pm, invar_pos)
        elif nm in carry_prims or nm in structural:
            for v in eqn.invars:
                out |= rec(jx, v, pm, invar_pos)
        res = frozenset(out)
        memo[key] = res
        return res

    pm = _prod_map(jaxpr)
    invar_pos = {v: i for i, v in enumerate(jaxpr.invars)}
    return rec(jaxpr, jaxpr.outvars[out_idx], pm, invar_pos)


_CARRY_PRIMS = frozenset({"add", "scatter-add"})


# ---------------------------------------------------------------------
# The device pass

def check_device(ctxs: list[FileCtx], rep: Reporter, root: Path) -> None:
    old_limit = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old_limit, 100_000))
    try:
        _check_device(ctxs, rep, root)
    finally:
        sys.setrecursionlimit(old_limit)


def _check_device(ctxs: list[FileCtx], rep: Reporter, root: Path) -> None:
    from tools.analyze import devlower as dl  # imports jax (CPU, 4 dev)
    from tools.analyze.interval import analyze_jaxpr

    by_rel = {c.rel: c for c in ctxs}
    reg = dl.registry()

    fallback = by_rel.get("tools/analyze/devlower.py")
    if fallback is None:  # restricted file set: synthesize the anchor
        p = Path(__file__).resolve().parent / "devlower.py"
        fallback = FileCtx(p, "tools/analyze/devlower.py", p.read_text())
        by_rel[fallback.rel] = fallback

    def report(entry_name: str, code: str, msg: str, subkey: str) -> None:
        e = reg.get(entry_name)
        ctx, line = fallback, 1
        if e is not None:
            c = by_rel.get(e.module.replace(".", "/") + ".py")
            if c is not None:
                ctx, line = c, e.lineno
        rep.add(
            ctx, line, code, msg,
            key=f"{code}:{entry_name}:{subkey}",
        )

    # -- registry <-> recipe inventory parity --------------------------
    cov = dl.RECIPE_COVERAGE
    for name in sorted(set(reg) - set(cov)):
        report(
            name, "RT300",
            f"registered device entry `{name}` has no analysis recipe "
            f"in tools/analyze/devlower.py — the device pass cannot "
            f"see it",
            "uncovered",
        )
    for name in sorted(set(cov) - set(reg)):
        report(
            name, "RT300",
            f"analysis recipe `{name}` has no registered device entry "
            f"— stale RECIPE_COVERAGE row",
            "stale",
        )

    # -- RT300: merge algebra ------------------------------------------
    for recipe in dl.merge_recipes():
        bad = _algebra_violations(
            recipe.jaxpr, recipe.allowed, dl.CALL_PRIMS
        )
        if bad:
            report(
                recipe.entry, "RT300",
                f"merge `{recipe.entry}` ({recipe.algebra} algebra) "
                f"uses non-associative/commutative primitives "
                f"{bad} — cross-node merge order would change results",
                "algebra",
            )

    # trace smokes: building them IS the check (they must still trace
    # under the tiny shapes)
    dl.update_trace_smokes()

    # -- RT301a: pure-sum carry chains ---------------------------------
    targets = dl.step_purity_targets() + dl.op_purity_targets()
    for t in targets:
        srcs = _pure_sources(
            t.jaxpr, t.out_idx, _CARRY_PRIMS, dl.STRUCTURAL,
            dl.CALL_PRIMS,
        )
        if t.in_idx not in srcs:
            report(
                t.entry, "RT301",
                f"counter `{t.counter}` in `{t.entry}` is not carried "
                f"by a pure scatter-add/add chain from its state input "
                f"— the per-window overflow bound does not apply to it "
                f"(classify it in COUNTER_EXEMPT or fix the update "
                f"path)",
                f"purity:{t.counter}",
            )

    # -- RT301d: every u32 state leaf classified -----------------------
    for leaf in dl.classify_state_counters():
        report(
            "pipeline.step", "RT301",
            f"u32 PipelineState leaf `{leaf}` is neither declared a "
            f"pure-sum counter nor exempted with a reason "
            f"(devlower.PURE_SUM_COUNTERS / COUNTER_EXEMPT)",
            f"unclassified:{leaf}",
        )

    # -- RT301b: config-derived per-window wrap bound ------------------
    wrap = dl.window_wrap_report()
    if not wrap["ok"]:
        report(
            "pipeline.step", "RT301",
            f"per-window counter bound k*envelope*window = "
            f"{wrap['k']}*{wrap['envelope']}*{wrap['window_seconds']} "
            f"= {wrap['bound']} exceeds u32 — a pure-sum counter can "
            f"wrap inside one window at the configured maxima",
            "window-bound",
        )

    # -- RT301c: HT-rescale interval analysis --------------------------
    jaxpr, intervals = dl.ht_rescale_target()
    res = analyze_jaxpr(jaxpr, intervals)
    for w in res.wrapped:
        report(
            "pipeline.step", "RT301",
            f"ht_rescale can wrap u32 under the documented envelope "
            f"(packets<=2^28, k<=config): {w}",
            f"ht-rescale:{w.split(':')[0]}",
        )
    for u in sorted(set(res.unknown)):
        report(
            "pipeline.step", "RT301",
            f"interval engine has no transfer function for primitive "
            f"`{u}` in ht_rescale — add it to tools/analyze/"
            f"interval.py TRANSFER (analysis is blind to it)",
            f"ht-rescale-unknown:{u}",
        )

    # -- RT302/RT303: lowered entry audits -----------------------------
    for a in dl.entry_audits():
        for i in a.donate_expect:
            leaves = a.arg_donated[i]
            if not leaves or not all(leaves):
                report(
                    a.entry, "RT302",
                    f"`{a.entry}` arg {i} is hot-path consumed state "
                    f"but not (fully) donated — the old buffer stays "
                    f"live across the call",
                    f"donate:{i}",
                )
        for i in a.keep_expect:
            if any(a.arg_donated[i]):
                report(
                    a.entry, "RT302",
                    f"`{a.entry}` arg {i} is a RESIDENT operand (host "
                    f"rereads it) but is donated — the engine would "
                    f"reread a deleted buffer",
                    f"keep:{i}",
                )
        seen = {c for c in dl.COLLECTIVE_OPS if c in a.hlo_text}
        for c in sorted(seen - a.allowed_collectives):
            report(
                a.entry, "RT303",
                f"`{a.entry}` compiles to an unexpected `{c}` — an "
                f"implicit cross-device gather or forced replication "
                f"not in the entry's expected-collective set",
                f"collective:{c}",
            )

    # -- RT304: host/device predicate parity ---------------------------
    for p in dl.parity_report():
        report(
            "pipeline.step", "RT304",
            f"host/device predicate divergence: {p}",
            f"parity:{p.split(':')[0]}",
        )

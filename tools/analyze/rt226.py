"""RT226 — recorder span-name drift (whole-program).

The contract (the RT220 analog for the flight recorder): the
``STAGE_*`` constants in ``utils/metric_names.py`` are the single
registry of pipeline stage names; every span emitted through
``FlightRecorder.record`` resolves to a registry constant; the stage
table in ``docs/observability.md`` (between the ``stage-table-begin``/
``stage-table-end`` markers) lists every stage and mentions no stage
that does not exist. Drift in any direction is a finding:

  RT226 span recorded under a stage not declared in the registry
        (string literal, unknown STAGE_* reference, or a registry
        constant missing from the STAGES tuple);
        a registry stage never emitted through any recorder; or
        the docs/observability.md stage table out of sync with the
        registry (either direction).

Scope: ``record(...)`` calls under ``retina_tpu/`` whose first
argument is a string literal or a ``STAGE_``-prefixed name — other
``.record(...)`` methods (different first-arg shapes) are out of
scope by construction.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.analyze.core import FileCtx, Reporter

METRIC_NAMES_REL = "retina_tpu/utils/metric_names.py"
DOC_REL = "docs/observability.md"
TABLE_BEGIN = "<!-- stage-table-begin -->"
TABLE_END = "<!-- stage-table-end -->"
DOC_STAGE_RE = re.compile(r"`([a-z0-9_]+)`")


def _stage_registry(ctx: FileCtx) -> dict[str, tuple[str, int]]:
    """STAGE_* const name -> (stage string, decl lineno)."""
    out: dict[str, tuple[str, int]] = {}
    for stmt in ctx.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id.startswith("STAGE_")
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            out[stmt.targets[0].id] = (stmt.value.value, stmt.lineno)
    return out


def _stages_tuple(ctx: FileCtx) -> set[str]:
    """Constant names listed in the ordered STAGES tuple."""
    for stmt in ctx.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "STAGES"
                and isinstance(stmt.value, ast.Tuple)):
            return {
                e.id for e in stmt.value.elts if isinstance(e, ast.Name)
            }
    return set()


def check_program(ctxs: list[FileCtx], rep: Reporter, root: Path) -> None:
    by_rel = {c.rel: c for c in ctxs}
    mn_ctx = by_rel.get(METRIC_NAMES_REL)
    if mn_ctx is None:
        return
    registry = _stage_registry(mn_ctx)  # const name -> (value, lineno)
    values = {v for v, _ in registry.values()}
    in_tuple = _stages_tuple(mn_ctx)

    # A declared constant absent from the ordered STAGES tuple never
    # gets its histogram child pre-ordered in stage_report — drift.
    for name, (value, lineno) in sorted(registry.items()):
        if name not in in_tuple:
            rep.add(mn_ctx, lineno, "RT226",
                    f"stage constant {name} (\"{value}\") is missing "
                    "from the STAGES tuple",
                    key=f"RT226:tuple:{name}")

    # --- emission sites: record(<stage>, ...) under retina_tpu/ ------
    emitted: set[str] = set()
    prod = [
        c for c in ctxs
        if c.rel.startswith("retina_tpu/") and c.rel != METRIC_NAMES_REL
    ]
    for ctx in prod:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "record"):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                rep.add(ctx, node.lineno, "RT226",
                        f'span "{arg.value}" recorded from a literal — '
                        "use the utils.metric_names STAGE_ constant",
                        key=f"RT226:{ctx.rel}:{arg.value}")
                continue
            const = None
            if isinstance(arg, ast.Attribute):
                const = arg.attr
            elif isinstance(arg, ast.Name):
                const = arg.id
            if const is None or not const.startswith("STAGE_"):
                continue  # some other .record() method — out of scope
            if const not in registry:
                rep.add(ctx, node.lineno, "RT226",
                        f"span constant {const} is not declared in "
                        "utils/metric_names.py",
                        key=f"RT226:{ctx.rel}:{const}")
            else:
                emitted.add(const)

    # --- declared but never emitted ----------------------------------
    for name, (value, lineno) in sorted(registry.items()):
        if name not in emitted:
            rep.add(mn_ctx, lineno, "RT226",
                    f"stage constant {name} (\"{value}\") is never "
                    "emitted through a recorder span",
                    key=f"RT226:unused:{name}")

    # --- docs/observability.md stage table, two-way ------------------
    doc_path = root / DOC_REL
    doc_lines = (
        doc_path.read_text().splitlines() if doc_path.exists() else []
    )
    doc_ctx = FileCtx.__new__(FileCtx)  # lightweight shell for .md
    doc_ctx.path = doc_path
    doc_ctx.rel = DOC_REL
    doc_ctx.src = "\n".join(doc_lines)
    doc_ctx.lines = doc_lines
    doc_ctx.tree = None
    doc_ctx.syntax_error = None

    table: dict[str, int] = {}  # stage token -> doc lineno
    inside = False
    for i, line in enumerate(doc_lines, start=1):
        if TABLE_BEGIN in line:
            inside = True
            continue
        if TABLE_END in line:
            inside = False
            continue
        if inside:
            m = DOC_STAGE_RE.search(line)
            if m:
                table.setdefault(m.group(1), i)

    if not table:
        rep.add(doc_ctx, 1, "RT226",
                f"{DOC_REL} has no stage table between the "
                f"{TABLE_BEGIN} / {TABLE_END} markers",
                key="RT226:doc:no-table")
        return
    for value in sorted(values):
        if value not in table:
            rep.add(doc_ctx, 1, "RT226",
                    f'stage "{value}" has no row in the {DOC_REL} '
                    "stage table",
                    key=f"RT226:doc-missing:{value}")
    for tok, lineno in sorted(table.items()):
        if tok not in values:
            rep.add(doc_ctx, lineno, "RT226",
                    f'{DOC_REL} stage table mentions "{tok}" which is '
                    "not declared in utils/metric_names.py",
                    key=f"RT226:doc-unknown:{tok}")

"""RT210-RT214 — Python purity of JAX-traced functions.

A function handed to ``jax.jit`` / ``shard_map`` runs ONCE at trace
time; its Python-level side effects do not re-execute per step, and
host interaction with tracer values either fails outright or silently
constant-folds.  Every such bug class in this repo's history looked
correct in review — so the analyzer encodes them:

  RT210 host side-effect call inside a traced function: ``time.*``,
        ``logging.*`` / ``self.log.*`` / ``print``, Python ``random.*``
        — executes once at trace time, not per step
  RT211 host materialization of a traced value: ``float()/int()/
        bool()/complex()`` on a tracer, ``.item()/.tolist()``,
        ``np.asarray/np.array`` — raises ConcretizationTypeError (or
        silently constant-folds a weak type) at trace time
  RT212 Python control flow on a traced value (``if``/``while``/
        ``assert``/ternary/``for`` over a tracer) — branches are
        resolved once at trace time; use lax.cond/select/fori_loop
  RT213 mutation of non-traced state from inside a traced function
        (``global`` writes, ``self.<attr> = ...``) — happens once at
        trace time, invisible to subsequent steps
  RT214 nested def inside a traced function re-jitted per call
        (``jax.jit`` applied INSIDE a traced body) — retrace storm

Traced-function discovery
-------------------------
Decorator forms (``@jit``, ``@jax.jit``, ``@partial(jax.jit, ...)``,
``@_partial(jax.jit, ...)``), call forms (``jax.jit(fn)``,
``_shard_map(local_step, ...)`` where ``fn`` is a same-scope def), and
same-file transitive callees of traced functions (checked for RT210/
RT213/RT214 only — their parameter taint is unknown, and guessing
would flood RT211/RT212 with false positives).

Taint model
-----------
Parameters of a traced function are tracer-valued (minus ``self``/
``cls`` and ``static_argnames``); taint propagates through simple
assignments and arithmetic.  Static projections UNTAINT: ``.shape``,
``.ndim``, ``.dtype``, ``.size``, ``.sharding``, ``len()``,
``isinstance()``, ``is None`` / ``is not None`` comparisons — all are
Python values at trace time and are legitimate branch conditions.
"""

from __future__ import annotations

import ast

from tools.analyze.core import FileCtx, Reporter

JIT_NAMES = {"jit"}
SHARD_NAMES = {"shard_map", "_shard_map"}
PARTIAL_NAMES = {"partial", "_partial"}

STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type"}
UNTAINT_CALLS = {"len", "isinstance", "type", "hasattr", "range",
                 "enumerate", "zip"}
# Calls returning a Python sequence OF tracers: iterating the
# sequence is ordinary Python (static length), even though each
# element is traced.
PY_SEQUENCE_CALLS = {"tree_leaves", "tree_flatten", "tree_map",
                     "items", "keys", "values", "split"}
CONCRETIZE_CALLS = {"float", "int", "bool", "complex"}
CONCRETIZE_METHODS = {"item", "tolist"}
SIDE_EFFECT_MODULES = {"time", "logging", "random", "os", "sys"}
LOG_METHODS = {"debug", "info", "warning", "error", "exception",
               "critical", "log"}


def _callable_name(func: ast.expr) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_jit_expr(node: ast.expr) -> bool:
    """`jit` / `jax.jit` as a bare expression."""
    return _callable_name(node) in JIT_NAMES and (
        isinstance(node, ast.Name)
        or (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("jax", "jnp"))
    )


def _static_argnames(call: ast.Call | None) -> set[str]:
    names: set[str] = set()
    if call is None:
        return names
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if (isinstance(n, ast.Constant)
                        and isinstance(n.value, str)):
                    names.add(n.value)
    return names


def _traced_defs(
    tree: ast.Module,
) -> tuple[dict[int, tuple[ast.AST, set[str]]], dict[str, ast.AST]]:
    """-> ({id(fn-node): (fn-node, static-argnames)}, {name: fn-node}).

    The name index covers every def in the file (module, class, and
    nested scope) — good enough for same-file call resolution.
    """
    defs_by_name: dict[str, ast.AST] = {}
    traced: dict[int, tuple[ast.AST, set[str]]] = {}

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    traced[id(node)] = (node, set())
                elif isinstance(dec, ast.Call):
                    fname = _callable_name(dec.func)
                    if fname in PARTIAL_NAMES and dec.args \
                            and (_is_jit_expr(dec.args[0])
                                 or _callable_name(dec.args[0])
                                 in SHARD_NAMES):
                        traced[id(node)] = (node, _static_argnames(dec))
                    elif _is_jit_expr(dec.func) \
                            or fname in SHARD_NAMES:
                        traced[id(node)] = (node, _static_argnames(dec))

    # call forms: jax.jit(fn), _shard_map(local_step, mesh, ...)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fname = _callable_name(node.func)
        is_jit = _is_jit_expr(node.func)
        is_shard = fname in SHARD_NAMES
        if not (is_jit or is_shard):
            continue
        arg0 = node.args[0]
        target = None
        if isinstance(arg0, ast.Name):
            target = defs_by_name.get(arg0.id)
        elif (isinstance(arg0, ast.Attribute)
              and isinstance(arg0.value, ast.Name)
              and arg0.value.id == "self"):
            target = defs_by_name.get(arg0.attr)
        if target is not None and id(target) not in traced:
            traced[id(target)] = (target, _static_argnames(node))
    return traced, defs_by_name


class _PurityCheck:
    def __init__(self, ctx: FileCtx, rep: Reporter, fn, statics: set[str],
                 taint_params: bool):
        self.ctx = ctx
        self.rep = rep
        self.fn = fn
        self.tainted: set[str] = set()
        if taint_params:
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg not in ("self", "cls") and a.arg not in statics:
                    self.tainted.add(a.arg)
        self.taint_params = taint_params

    # -- taint ---------------------------------------------------------
    def _tainted(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return False
            return self._tainted(e.value)
        if isinstance(e, ast.Call):
            fname = _callable_name(e.func)
            if fname in UNTAINT_CALLS or fname in CONCRETIZE_CALLS:
                return False  # python-scalar result (RT211 flags misuse)
            if (isinstance(e.func, ast.Attribute)
                    and self._tainted(e.func.value)):
                return True  # tracer method call: x.sum()
            return any(self._tainted(a) for a in e.args) or any(
                self._tainted(kw.value) for kw in e.keywords)
        if isinstance(e, ast.Compare):
            # `x is None` / `x is not None`: identity vs a Python
            # singleton — resolved at trace time, legitimate
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in e.ops):
                return False
            return (self._tainted(e.left)
                    or any(self._tainted(c) for c in e.comparators))
        if isinstance(e, (ast.BinOp,)):
            return self._tainted(e.left) or self._tainted(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._tainted(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self._tainted(v) for v in e.values)
        if isinstance(e, ast.Subscript):
            return self._tainted(e.value)
        if isinstance(e, ast.IfExp):
            return self._tainted(e.body) or self._tainted(e.orelse)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self._tainted(el) for el in e.elts)
        if isinstance(e, ast.Starred):
            return self._tainted(e.value)
        return False

    # -- checks --------------------------------------------------------
    def _check_call(self, n: ast.Call) -> None:
        func = n.func
        fname = _callable_name(func)
        # RT210: host side effects
        if fname == "print":
            self.rep.add(self.ctx, n.lineno, "RT210",
                         f"print() inside traced `{self.fn.name}` runs "
                         "once at trace time (use jax.debug.print)")
        elif (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in SIDE_EFFECT_MODULES):
            self.rep.add(
                self.ctx, n.lineno, "RT210",
                f"{func.value.id}.{func.attr}() inside traced "
                f"`{self.fn.name}` executes once at trace time, not "
                "per step")
        elif (isinstance(func, ast.Attribute)
                and func.attr in LOG_METHODS
                and isinstance(func.value, (ast.Name, ast.Attribute))
                and (_callable_name(func.value) or "").lstrip("_")
                in ("log", "logger")):
            self.rep.add(
                self.ctx, n.lineno, "RT210",
                f"logging call inside traced `{self.fn.name}` fires "
                "once at trace time (use jax.debug.print / callback)")
        # RT214: re-jit inside a traced body
        if _is_jit_expr(func):
            self.rep.add(
                self.ctx, n.lineno, "RT214",
                f"jax.jit applied inside traced `{self.fn.name}` — "
                "the inner function is re-traced on every outer trace")
        if not self.taint_params:
            return
        # RT211: concretization of tracers
        if fname in CONCRETIZE_CALLS and n.args \
                and self._tainted(n.args[0]):
            self.rep.add(
                self.ctx, n.lineno, "RT211",
                f"{fname}() on a traced value in `{self.fn.name}` "
                "raises ConcretizationTypeError at trace time")
        elif (isinstance(func, ast.Attribute)
                and func.attr in CONCRETIZE_METHODS
                and self._tainted(func.value)):
            self.rep.add(
                self.ctx, n.lineno, "RT211",
                f".{func.attr}() on a traced value in "
                f"`{self.fn.name}` forces a host sync at trace time")
        elif (isinstance(func, ast.Attribute)
                and func.attr in ("asarray", "array")
                and isinstance(func.value, ast.Name)
                and func.value.id in ("np", "numpy")
                and n.args and self._tainted(n.args[0])):
            self.rep.add(
                self.ctx, n.lineno, "RT211",
                f"np.{func.attr}() on a traced value in "
                f"`{self.fn.name}` materializes the tracer on host")

    def run(self) -> list[str]:
        """Walk the body; returns same-file callee names for the
        transitive pass."""
        callees: list[str] = []

        def visit(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not self.fn:
                return  # nested defs trace lazily; checked if invoked
            if isinstance(n, ast.Global):
                self.rep.add(
                    self.ctx, n.lineno, "RT213",
                    f"global write inside traced `{self.fn.name}` "
                    "happens once at trace time")
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self.rep.add(
                            self.ctx, n.lineno, "RT213",
                            f"self.{t.attr} mutated inside traced "
                            f"`{self.fn.name}` — trace-time only, "
                            "invisible to later steps")
                # taint propagation through simple assignments
                if self.taint_params and isinstance(n, ast.Assign) \
                        and n.value is not None:
                    is_tainted = self._tainted(n.value)
                    for t in targets:
                        names = [t] if isinstance(t, ast.Name) else [
                            el for el in getattr(t, "elts", [])
                            if isinstance(el, ast.Name)]
                        for nm in names:
                            if is_tainted:
                                self.tainted.add(nm.id)
                            else:
                                self.tainted.discard(nm.id)
            if isinstance(n, ast.Call):
                self._check_call(n)
                if isinstance(n.func, ast.Name):
                    callees.append(n.func.id)
            if self.taint_params:
                if isinstance(n, (ast.If, ast.While)) \
                        and self._tainted(n.test):
                    self.rep.add(
                        self.ctx, n.lineno, "RT212",
                        f"Python branch on a traced value in "
                        f"`{self.fn.name}` resolves once at trace "
                        "time (use lax.cond / jnp.where)")
                if isinstance(n, ast.Assert) and self._tainted(n.test):
                    self.rep.add(
                        self.ctx, n.lineno, "RT212",
                        f"assert on a traced value in "
                        f"`{self.fn.name}` (use checkify or drop it)")
                if isinstance(n, ast.IfExp) and self._tainted(n.test):
                    self.rep.add(
                        self.ctx, n.lineno, "RT212",
                        f"ternary on a traced value in "
                        f"`{self.fn.name}` (use jnp.where)")
                if isinstance(n, ast.For) and self._tainted(n.iter) \
                        and not (
                            isinstance(n.iter, ast.Call)
                            and _callable_name(n.iter.func)
                            in PY_SEQUENCE_CALLS):
                    self.rep.add(
                        self.ctx, n.lineno, "RT212",
                        f"Python loop over a traced value in "
                        f"`{self.fn.name}` unrolls at trace time "
                        "(use lax.fori_loop / scan)")
            for child in ast.iter_child_nodes(n):
                visit(child)

        for stmt in self.fn.body:
            visit(stmt)
        return callees


def check(ctx: FileCtx, rep: Reporter) -> None:
    if "retina_tpu" not in ctx.path.parts:
        return
    traced, defs_by_name = _traced_defs(ctx.tree)
    seen = set(traced)
    queue = list(traced.values())
    first_pass = len(queue)
    i = 0
    while i < len(queue):
        fn, statics = queue[i]
        # transitive callees get RT210/RT213/RT214 only (unknown taint)
        taint_params = i < first_pass
        callees = _PurityCheck(ctx, rep, fn, statics, taint_params).run()
        for name in callees:
            callee = defs_by_name.get(name)
            if callee is not None and id(callee) not in seen:
                seen.add(id(callee))
                queue.append((callee, set()))
        i += 1

"""SketchEngine: the TPU worker that replaces the CPU aggregation loop.

Reference analog (what this replaces, SURVEY.md §3.2): the enricher output
ring → ``Module.run`` goroutine calling every metric's ``ProcessFlow`` per
flow (metrics_module.go:283-303) — single-threaded CPU hash aggregation,
the scaling bottleneck. Per the BASELINE north star, this engine is the
"tpusketch" plugin's backend: plugins feed fixed-width record blocks into
a bounded queue (QueueSink), the feed loop batches them into fixed-shape
device arrays, and ONE jit-compiled step updates every aggregator. Sharded
over a ``jax.sharding.Mesh`` when more than one device is available
(parallel/telemetry.py); scrape-time snapshots merge with psum/pmax/
all_gather over ICI.

Backpressure contract (the reference's universal rule,
packetparser_linux.go:692-697): never block a producer — drop and count.
Snapshot contract: scrapes read a cached merged snapshot at most
``snapshot_max_age_s`` old (<1s target, BASELINE) and never stall the feed
loop; JAX dispatch is async so the feed thread keeps the device busy while
snapshot results transfer back.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from retina_tpu.config import Config
from retina_tpu.devprog import device_entry
from retina_tpu.events.schema import F, NUM_FIELDS
from retina_tpu.fleet.shipper import window_epoch as fleet_epoch
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.models.identity import HostIdentityTable, IdentityMap
from retina_tpu.models.pipeline import PipelineConfig
from retina_tpu.obs.recorder import initialize_recorder
from retina_tpu.parallel.combine import combine_blocks
from retina_tpu.parallel.feed import (
    FeedWorkerPool, TransferMux, TransferQueue,
)
from retina_tpu.parallel.flowdict import flow_dict_stats, make_flow_dict
from retina_tpu.parallel.partition import (
    ShardedBatch, _next_bucket, partition_events,
)
from retina_tpu.parallel.telemetry import ShardedTelemetry, topk_from_snapshot
from retina_tpu.plugins.api import QueueSink
from retina_tpu.runtime import faults
from retina_tpu.runtime.overload import OverloadController
from retina_tpu.runtime.supervisor import (
    Heartbeat, Supervisor, policy_from_config,
)
from retina_tpu.utils import metric_names as mnames
from retina_tpu.utils.device_proxy import (
    fence, fetch_on_device, run_on_device, submit_on_device,
)


def pipeline_config_from(cfg: Config) -> PipelineConfig:
    return PipelineConfig(
        n_pods=cfg.n_pods,
        cms_width=cfg.cms_width,
        cms_depth=cfg.cms_depth,
        topk_slots=cfg.topk_slots,
        hll_precision=cfg.hll_precision,
        entropy_buckets=cfg.entropy_buckets,
        conntrack_slots=cfg.conntrack_slots,
        enable_conntrack=cfg.enable_conntrack_metrics,
        bypass_filter=cfg.bypass_lookup_ip_of_interest
        or not cfg.enable_pod_level,
        # Annotation opt-in: ONLY the filter map (fed by the metrics
        # module's annotated-pod set) decides interest; identity alone
        # must not readmit an un-annotated pod's traffic.
        identity_implies_interest=not cfg.enable_annotations,
        # Low aggregation needs conntrack reports to drive the sketch
        # sampling; without conntrack, fall back to full per-packet feeds
        # (the reference likewise compiles DATA_AGGREGATION_LEVEL into the
        # datapath only alongside conntrack, packetparser.c:214-225).
        data_aggregation_level=(
            cfg.data_aggregation_level
            if cfg.enable_conntrack_metrics
            else "high"
        ),
        # Invertible heavy-key recovery (ops/invertible.py): the sketch
        # arrays live in device state whenever decode may be asked for.
        enable_invertible=cfg.heavy_keys_source in ("invertible", "both"),
        inv_depth=cfg.invertible_depth,
        inv_width=cfg.invertible_width,
        inv_hi_width=cfg.invertible_hi_width,
        priority_ip_mask=cfg.overload_priority_ip_mask,
        priority_ip_match=cfg.overload_priority_ip_match,
    )


class SketchEngine:
    """Owns device state + the feed/window loop; thread-safe facade."""

    def __init__(self, cfg: Config, devices: Optional[list] = None,
                 supervisor: Optional[Supervisor] = None):
        self.cfg = cfg
        self.log = logger("engine")
        # Supervision (runtime/supervisor.py): when attached, every
        # long-lived engine thread registers a heartbeat with the
        # shared watchdog; standalone engines (tests, bench) get
        # detached Heartbeat cells that nothing scans.
        self._supervisor = supervisor
        self.sink = QueueSink(max_blocks=1024)
        self.pcfg = pipeline_config_from(cfg)
        if (
            cfg.data_aggregation_level == "low"
            and self.pcfg.data_aggregation_level == "high"
        ):
            self.log.warning(
                "data_aggregation_level=low requires conntrack metrics; "
                "running at high (full per-packet sketch feeds)"
            )

        devs = devices if devices is not None else jax.devices()
        if cfg.mesh_devices > 0:
            devs = devs[: cfg.mesh_devices]
        self.n_devices = len(devs)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self.mesh = Mesh(np.array(devs), ("data",))
        self.sharded = ShardedTelemetry(
            self.pcfg, self.mesh, aot_cache_dir=cfg.aot_cache_dir
        )
        self.state = self.sharded.init_state()
        # Record batches are pre-placed with the step's input sharding
        # OUTSIDE the state lock, so the lock is held only for the async
        # step dispatch (snapshot-without-stall; VERDICT r1 weak #3).
        self._rec_sharding = NamedSharding(self.mesh, PartitionSpec("data"))
        self._replicated = NamedSharding(self.mesh, PartitionSpec())
        # Device-resident scalar constants (lazily placed on the proxy
        # thread): every Python-scalar jit argument costs its own
        # host->device commit per call — a full link round-trip each on
        # the tunnel backend, several per step before this cache.
        self._zero_u32: Any = None
        self._zthresh: Any = None
        self._api_dev: Any = None
        self._api_val: int = -1
        # Bound on concurrent fire-and-forget device submissions: the
        # dispatch worker packs batch N+1 while the proxy thread still
        # owns batch N's transfer, and the proxy queue holds the rest —
        # the host->device link runs back-to-back transfers instead of
        # idling for a dispatch round-trip between quanta (VERDICT r3
        # weak #1).
        self._inflight = threading.Semaphore(
            max(1, cfg.feed_pipeline_depth)
        )
        # Count of submissions currently in flight on the proxy: the
        # feed loop flushes at flush_interval_s only when this is 0
        # (idle -> latency priority); while dispatches are in flight it
        # accumulates bigger quanta up to flush_max_age_s (throughput
        # priority — bigger quanta combine harder and amortize the
        # per-flush fixed costs).
        self._busy_lock = threading.Lock()
        self._inflight_busy = 0
        # Combiner thread count (native rt_combine_mt; 0 keeps the
        # cores-based default — 1 thread on single-core hosts).
        if cfg.host_combine_threads > 0:
            from retina_tpu.native import set_combine_threads

            set_combine_threads(cfg.host_combine_threads)
        # v2 wire: flow-descriptor dictionary (parallel/flowdict.py).
        # Host side assigns stable device-table slots; the device table
        # itself is created lazily ON device (zeros jit — a host-side
        # 48MB/device upload would saturate the link it exists to save).
        # heavy_keys_source="invertible" removes the dictionary from the
        # hot path ENTIRELY (ISSUE 7 / ROADMAP item 4): the wire falls
        # back to packed full rows and heavy keys come from the window
        # close invertible decode instead of host descriptor slots.
        self._flow_dict = (
            make_flow_dict(cfg.flow_dict_slots)
            if cfg.transfer_packed and cfg.wire_flow_dict
            and cfg.heavy_keys_source != "invertible"
            else None
        )
        # v3 wire: known-flow rows are TWO u32 lanes — [id | packets <<
        # id_bits, bytes] — 8 bytes/row instead of 16. Packets ride the
        # id lane's headroom; rows whose packet count exceeds it (or any
        # new descriptor) ship full rows instead (escalation is
        # idempotent: re-scattering a resident descriptor is a no-op for
        # correctness). Known rows' per-row timestamps are replaced by
        # the flush's base timestamp; rows where exact per-row time
        # matters — TSval/TSecr carriers (RTT matcher) and unstamped
        # rows (TS_REL=0 round-trip) — escalate to the full-row side
        # (see _dispatch_flowdict).
        self._fd_id_bits = max(1, (cfg.flow_dict_slots - 1).bit_length())
        self._fd_pk_bits = 32 - self._fd_id_bits
        # v4 wire: known rows pack DENSE — (id_bits + 10 + 22)
        # contiguous bits per row streamed into one u32 word array
        # (parallel/wire.py dense layer) instead of two full u32 lanes:
        # 6.25 B/row at the default 18-bit id space vs 8. Rows whose
        # PACKETS/BYTES overflow the narrow lanes escalate to the
        # full-row side exactly like the v3 packet-overflow escalation.
        self._fd_dense = bool(cfg.wire_dense_known)
        self._fd_lock = threading.Lock()
        # AOT disk-cache signature for the per-bucket ingest
        # executables (_compile_cached): every config field that
        # changes their lowered programs. The topology/jax-version part
        # of the key lives in telemetry.aot_disk_path.
        self._aot_sig = "|".join(
            str(x) for x in (
                cfg.batch_capacity, cfg.flow_dict_slots,
                int(bool(cfg.transfer_packed)), self._fd_id_bits,
                int(self._fd_dense), NUM_FIELDS,
            )
        )
        # heavy_keys_source="both": host-side per-key packet ground
        # truth (forward-verdict packets by 4-column flow key), fed in
        # _dispatch_flowdict under _fd_lock; the harvest thread scores
        # the invertible decode against it (recall/precision metrics).
        # Cumulative like the device sketches. None = not validating.
        self._hk_counts: Optional[dict] = (
            {} if cfg.heavy_keys_source == "both"
            and self._flow_dict is not None else None
        )  # guarded-by: self._fd_lock
        # Latest decoded heavy-key set (harvest thread writes, readers
        # via invertible_report()).
        self._inv_lock = threading.Lock()
        self._inv_last: Optional[dict] = None  # guarded-by: self._inv_lock
        import os as _os

        # Cached once: the trace flag is read on every dispatch.
        self._feed_trace = _os.environ.get("RETINA_FEED_TRACE") == "1"
        self._desc_table: Any = None  # guarded-by: self._fd_lock
        # Bumped ONLY by failure resyncs (not by capacity-overflow
        # generation clears, which keep the device table intact and are
        # FIFO-safe for in-flight batches): a queued batch whose epoch
        # predates a resync references a table that no longer exists
        # and must drop itself rather than gather zeroed descriptors.
        self._fd_epoch = 0

        self._ident_lock = threading.Lock()
        self.ident = IdentityMap.zeros(cfg.identity_slots)
        # Sized like the identity table: the default deployment loads
        # every tracked pod IP into the IPs-of-interest map (the metrics
        # module filter sync), so 1024 slots overflowed at ~500 pods.
        self.filter_map = IdentityMap.zeros(cfg.identity_slots, seed=99)
        self.apiserver_ip = 0
        # Persistent host mirror for incremental identity churn: one pod
        # event costs O(chain) host mutations + one upload, not a full
        # re-place of every key (VERDICT r1 weak #5).
        self._ident_host = HostIdentityTable(n_slots=cfg.identity_slots)
        self._ident_dict: dict[int, int] = {}

        # (fn, name) pairs: the name lets the overload controller shed
        # a specific enrichment observer (e.g. "dns") by stage.
        self._observers: list[
            tuple[Callable[[np.ndarray, str], None], str]
        ] = []
        # bucket size -> jitted pad-to-capacity kernel (device-side zero
        # extension of a small transfer to the step's static shape).
        self._pad_cache: dict[int, Any] = {}
        self._snap_lock = threading.Lock()
        self._snap_flight = threading.Lock()
        self._snap_cache: dict[str, Any] | None = None
        self._snap_time = 0.0
        # Closed windows' results awaiting publish on the harvest
        # thread (lazily started at the first close). Unbounded BY
        # DESIGN: items are (3,3)-float device handles produced at
        # window cadence (one per window_seconds), so even an
        # hours-long link stall accumulates only trivial host memory —
        # and never shedding means every anomalous window's
        # anomaly_windows increment survives to the next scrape (the
        # counter's contract). Items: ("win", stacked_device_array),
        # ("zero", None) for idle windows (FIFO through the same queue
        # so an in-flight active window can never publish AFTER the
        # idle zeroing and latch a stale anomaly flag), or None to
        # shut the thread down.
        self._harvest_q: queue_mod.Queue = queue_mod.Queue()  # noqa: RT102 — window-cadence items, see above
        self._harvest_thread: threading.Thread | None = None  # guarded-by: self._harvest_lock
        # Set by the shutdown path after the final drain: a straggler
        # (e.g. a warm_close racing stop) must not resurrect the
        # thread, or it would park on the queue forever pinning the
        # engine object graph. The lock serializes spawn-vs-retire: a
        # straggler close checking the flag concurrently with shutdown
        # setting it could otherwise spawn a fresh thread that never
        # sees the None sentinel (already consumed) and parks forever.
        self._harvest_retired = False  # guarded-by: self._harvest_lock
        self._harvest_lock = threading.Lock()
        # Bumped by _restart_harvest when the watchdog replaces a hung
        # harvest thread: a superseded instance exits after finishing
        # (or abandoning) its current item instead of racing the
        # replacement for the queue forever.
        self._harvest_gen = 0
        self._warm_thread: threading.Thread | None = None
        # Set once the background warm has made the window-close
        # program resident (or terminally failed to): until then, while
        # the warm thread is live, window ticks DEFER instead of
        # cold-compiling end_window inline on the proxy mid-feed
        # (windows_deferred counts them; the window just stays open).
        self._close_warmed = threading.Event()
        # Sharded multi-worker feed pool (parallel/feed.py), created by
        # start() when feed_workers resolves to > 1.
        self._feed_pool: Any = None
        # Adaptive overload control (runtime/overload.py): the feed
        # loop ticks the controller against the engine's pressure
        # signals; feed workers sample through it, plugins consult
        # shed_active before enrichment work.
        self._overload = OverloadController(cfg, self._overload_signals)
        # Fleet rollup tier (fleet/): ship the device-merged sketch
        # snapshot at every window close instead of raw samples. The
        # shipper owns its worker thread (start()/stop() track the
        # engine run loop); offer() on the proxy never blocks the close
        # path, and the SHEDDING backoff consults the same controller.
        self._fleet_shipper: Any = None
        if cfg.fleet_enabled:
            from retina_tpu.fleet.shipper import SnapshotShipper

            self._fleet_shipper = SnapshotShipper(
                cfg, overload=self._overload, supervisor=self._supervisor
            )
        # Time-travel snapshot ring (timetravel/): retain the same
        # window-close export the fleet shipper puts on the wire, as N
        # host-side slots served to the range-query API. Shares the
        # shipper's offer/worker shape: O(1) enqueue on the close lane,
        # readback off-proxy.
        self._tt_ring: Any = None
        if cfg.timetravel_enabled:
            from retina_tpu.timetravel.ring import SnapshotRing

            self._tt_ring = SnapshotRing(
                cfg.timetravel_ring_windows, name="engine",
                overload=self._overload, supervisor=self._supervisor,
            )
        # Closed-loop capture hook (timetravel/autocapture.py): the
        # daemon wires AutoCapture.notify here; called from the harvest
        # thread when the entropy detector flags a window (must never
        # block — notify only enqueues).
        self.anomaly_hook: Any = None
        # Record tap (detect/base.py DetectorBank.observe): sees every
        # record block on the ingest path before partitioning — in
        # _build_quantum post-combine on the live feed (inline flush
        # AND feed workers; the bank serializes internally), and in
        # _dispatch for direct callers (step_records, recovery probe).
        # The two sites are disjoint, so no block is tapped twice.
        # Must stay cheap — the bank does vectorized feature folds
        # only; scoring happens at window close. Pre-overload-sampling
        # so detectors judge the full signal, not the sampled residue.
        self.record_hook: Any = None
        # Protected close lane: window ticks acquire THIS semaphore,
        # never the step in-flight one — a saturated step pipeline can
        # delay a close behind queued transfers but can never starve it
        # of a submission slot (a window is always eventually closed).
        # Two slots: one close may still be in flight on a slow link
        # when the next tick lands.
        self._close_inflight = threading.Semaphore(2)
        # Device-resident sample-k scalars, cached per k (same
        # rationale as _device_consts; cleared on recovery rebuild).
        self._sampk_dev: dict[int, Any] = {}
        # Overload signal bookkeeping: handoff-wait rate window and the
        # dispatch-latency EWMA (seconds, updated on the proxy thread
        # where device_step_seconds is observed).
        self._ov_wait_prev = 0.0
        self._ov_wait_t = time.monotonic()
        self._dispatch_lat_ewma = 0.0
        # Timestamp of the last EWMA sample: a stale measurement means
        # the pipeline is idle, not slow, and must not read as
        # pressure (an idle engine would otherwise never de-escalate).
        self._dispatch_lat_t = 0.0
        self.last_window: dict[str, np.ndarray] = {}
        self._state_lock = threading.Lock()
        self.started = threading.Event()
        # Set once start_background_warm has every reachable bucket key
        # compiled (tests and shutdown fences). bucket_warm_failed is
        # its terminal-failure sibling: set when the warm finished but
        # one or more keys failed (the agent stays up; those buckets
        # cold-compile inline) so waiters can fail fast with the real
        # cause instead of timing out on a done-event that will never
        # come.
        self.bucket_warm_done = threading.Event()
        self.bucket_warm_failed = threading.Event()
        self._steps = 0
        self._events_in = 0
        self._closed_events_in = 0
        # Crash-only recovery (runtime/supervisor.py wiring): while
        # _degraded is set, async dispatches drop-and-count (stage
        # "degraded") instead of touching device state mid-rebuild;
        # recovery_failed latches when the recovery loop's circuit
        # opens — /healthz goes unhealthy and the orchestrator owns
        # the restart from there.
        self._degraded = threading.Event()
        self._recover_lock = threading.Lock()
        self._recovering = False
        self._recover_thread: threading.Thread | None = None  # guarded-by: self._recover_lock
        self.recovery_failed = threading.Event()
        self.restarts = 0
        self._last_resume_src = ""
        self._snapshot_path = (
            os.path.join(cfg.snapshot_dir, "sketch_state.npz")
            if cfg.snapshot_dir else None
        )
        # Flight recorder (obs/recorder.py): rebuild the process
        # singleton from config so every span site — here, the feed
        # workers, the fleet shipper/aggregator — shares the same rings
        # and sampling policy. Sites outside the engine fetch it via
        # get_recorder() per call, so the rebuild is visible everywhere.
        self._recorder = initialize_recorder(
            capacity=cfg.trace_ring_spans,
            sample_every=cfg.trace_sample_every,
            enabled=cfg.trace_enabled,
        )
        self._start_monotonic = time.monotonic()
        self._publish_build_info()

    def _publish_build_info(self) -> None:
        """One-shot build/runtime identity gauge (value always 1; the
        labels are the payload) plus the uptime baseline — the classic
        *_build_info join-series pattern."""
        from retina_tpu.utils import buildinfo

        m = get_metrics()
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: RT101 — identity gauge must never block engine boot
            backend = "unknown"
        m.build_info.labels(
            version=buildinfo.VERSION,
            jax=jax.__version__,
            backend=backend,
            devices=str(self.n_devices),
            config=self._aot_sig,
        ).set(1)
        m.uptime_seconds.set(0.0)

    # -- supervision helpers ------------------------------------------
    def _register_hb(  # runs-on: feed-worker*, engine-recover, window-harvest
        self, name: str, deadline_s: float | None = None,
        on_stall: Optional[Callable[[], None]] = None,
    ) -> Heartbeat:
        dl = deadline_s or self.cfg.watchdog_deadline_s
        if self._supervisor is not None:
            return self._supervisor.register(name, dl, on_stall)
        return Heartbeat(name, dl, on_stall)

    def _deregister_hb(self, name: str) -> None:  # runs-on: feed-worker*
        if self._supervisor is not None:
            self._supervisor.deregister(name)

    def _count_error(self, site: str) -> bool:
        """Broad-except audit contract: every swallowed exception bumps
        engine_errors{site} unconditionally; returns True when the
        caller should also emit its (rate-limited) log line."""
        get_metrics().engine_errors.labels(site=site).inc()
        return rate_limited(f"engine.{site}")

    # -- crash-only recovery ------------------------------------------
    @property
    def degraded(self) -> bool:
        return self._degraded.is_set()

    @staticmethod
    def _fatal_device_error(e: BaseException) -> bool:
        """Classify a step/transfer failure: fatal (device/runtime —
        the resident state is suspect, rebuild it) vs a bad-batch
        one-off (already dropped + counted; carry on)."""
        if isinstance(e, faults.InjectedFault):
            return True
        if type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
        msg = str(e).lower()
        return any(
            s in msg
            for s in ("device", "transfer failed", "dma",
                      "resource exhausted", "data loss")
        )

    def _request_recovery(self, reason: str) -> None:
        """Enter degraded drop-and-count mode and kick the recovery
        thread. Idempotent: concurrent fatal errors fold into the one
        in-flight recovery."""
        with self._recover_lock:
            if self._recovering or self.recovery_failed.is_set():
                return
            self._recovering = True
        self._degraded.set()
        get_metrics().degraded_mode.set(1)
        self.log.error(
            "engine entering DEGRADED mode (crash-only recovery): %s",
            reason,
        )
        t = threading.Thread(
            target=self._recover, name="engine-recover", daemon=True
        )
        # Publish under the lock: close()/join readers must never see
        # a half-written reference from a concurrent fatal-error path
        # (the _recovering flip above already serializes spawns, but
        # the reference itself was unguarded).
        with self._recover_lock:
            self._recover_thread = t
        t.start()

    def _recover(self) -> None:
        """Crash-only engine recovery: fence the proxy, tear down and
        rebuild device state, resume from the last periodic checkpoint
        (cold start when there is none), re-warm with a probe dispatch,
        then leave degraded mode. Retries under the restart policy; an
        open circuit latches recovery_failed (unhealthy)."""
        t0 = time.monotonic()
        hb = self._register_hb("engine-recover")
        policy = policy_from_config(self.cfg, seed_key="engine-recover")
        m = get_metrics()
        attempt = 0
        try:
            while True:
                attempt += 1
                hb.beat()
                policy.note_start()
                try:
                    self._recover_once(hb)
                    break
                except Exception:
                    if self._count_error("recovery"):
                        self.log.exception(
                            "engine recovery attempt %d failed", attempt
                        )
                    delay = policy.record_failure()
                    if delay is None:
                        self.log.error(
                            "engine recovery crash-looping; giving up "
                            "(unhealthy until the orchestrator restarts "
                            "the agent)"
                        )
                        self.recovery_failed.set()
                        return
                    hb.park()
                    time.sleep(delay)
            self._degraded.clear()
            m.degraded_mode.set(0)
            m.engine_restarts.inc()
            self.restarts += 1
            dt = time.monotonic() - t0
            m.recovery_seconds.observe(dt)
            self.log.warning(
                "engine recovered in %.2fs (attempt %d, %s)",
                dt, attempt, self._last_resume_src,
            )
        finally:
            with self._recover_lock:
                self._recovering = False
            self._deregister_hb("engine-recover")

    def _recover_once(self, hb: Heartbeat) -> None:
        # Injection site for chaos tests: lets a test hold the engine in
        # degraded mode deterministically (recover:hangN) to observe the
        # drop-and-count path, or fail attempts (recover:raise).
        faults.inject("recover")
        # 1) Drain the proxy queue: no stale closure may touch the
        #    state we are about to replace. Bounded — a wedged proxy
        #    fails this attempt and the policy retries.
        hb.park()
        if not fence(timeout=self.cfg.watchdog_deadline_s):
            raise RuntimeError("device proxy did not drain for recovery")
        hb.beat()
        path = self._snapshot_path

        def rebuild():
            # Device-resident scalars + descriptor table are rebuilt
            # lazily by the next dispatch; the flow dictionary resyncs
            # (epoch bump drops queued pre-recovery batches).
            self._zero_u32 = None
            self._api_val = -1
            self._sampk_dev = {}
            with self._fd_lock:
                self._desc_table = None
                if self._flow_dict is not None:
                    self._flow_dict.clear()
                    self._fd_epoch += 1
            resumed = False
            if path:
                from retina_tpu.checkpoint import load_state

                state, resumed = load_state(path, self.sharded, self.pcfg)
            else:
                state = self.sharded.init_state()
            with self._state_lock:
                self.state = state
            return resumed

        hb.park()  # rebuild may recompile init_state on a cold cache
        resumed = run_on_device(rebuild)
        hb.beat()
        self._last_resume_src = (
            f"resumed from {path}" if resumed else "cold start"
        )
        # 2) Probe: one zero-batch dispatch through the real transfer +
        #    step path proves the device works end to end before async
        #    traffic is readmitted.
        hb.park()
        self._dispatch(
            np.zeros((0, NUM_FIELDS), np.uint32),
            now_s=int(time.time()), record_metrics=False,
        )
        hb.beat()

    # -- identity / filter wiring (set by cache & filtermanager) ------
    def update_identities(self, ip_to_index: dict[int, int]) -> None:
        """Reconcile the device identity table to ``ip_to_index``.

        Incremental: diffs against the previous map and applies only
        changed keys to the persistent host cuckoo table (µs per key),
        then uploads the packed table once. The reference's enricher
        cache likewise mutates one entry per pod event (cache.go:196+).
        """
        new = {ip: idx for ip, idx in ip_to_index.items() if ip != 0}
        if len(new) > self._ident_host.capacity:
            # Clamp-and-count, never crash: an overfull cluster loses
            # observability for the overflow pods (visible in
            # lost_table_entries{table="identity"}) but the agent stays
            # up — the reference likewise counts per-entry map-write
            # failures and carries on (manager_linux.go:62-100).
            # Deterministic subset (sorted IPs) so repeated reconciles
            # keep the SAME pods rather than churning the table. The
            # clamp happens before the diff so a failed insert never
            # leaves the host table half-mutated with _ident_dict stale.
            dropped = len(new) - self._ident_host.capacity
            get_metrics().lost_table_entries.labels(
                table="identity"
            ).inc(dropped)
            self.log.warning(
                "identity map overfull: %d pods into %d slots; "
                "dropping %d (counted in lost_table_entries)",
                len(new), self._ident_host.capacity, dropped,
            )
            new = dict(
                (ip, new[ip])
                for ip in sorted(new)[: self._ident_host.capacity]
            )
        with self._ident_lock:
            old = self._ident_dict
            for ip in old.keys() - new.keys():
                self._ident_host.remove(ip)
            for ip, idx in new.items():
                if old.get(ip) != idx:
                    self._ident_host.insert(ip, idx)
            self._ident_dict = new

        # Upload AND swap inside one proxied closure: dispatches capture
        # self.ident at proxy-execution time, so FIFO order on the
        # proxy queue is exactly the visibility order — an identity
        # update enqueued before a batch's execution is guaranteed
        # visible to that batch, even when compiles/warm keys delay the
        # queue by seconds. The packed table is SNAPSHOTTED here, at
        # call time: uploading the live shared _ident_host from the
        # closure would let a later-enqueued update's host mutations
        # leak into this earlier-enqueued upload (visibility skew in
        # the other direction).
        with self._ident_lock:
            packed = self._ident_host.table.copy()
            seed = self._ident_host.seed

        def apply_ident():
            dev = IdentityMap(table=jnp.asarray(packed), seed=seed)
            with self._ident_lock:
                self.ident = dev

        run_on_device(apply_ident)

    def update_filter_ips(self, ips: set[int]) -> None:
        # Build the cuckoo table on the CALLING thread (pure numpy, O(n)
        # host work); only the device upload ties up the proxy thread.
        host = HostIdentityTable(n_slots=self.cfg.identity_slots, seed=99)
        live = sorted(ip for ip in ips if ip)
        if len(live) > host.capacity:
            # Clamp-and-count (deterministic: lowest IPs win) — an
            # overfull IPs-of-interest set must degrade coverage, not
            # kill the agent; retrying can't fix a deterministic
            # overflow (VERDICT r3 weak #4).
            dropped = len(live) - host.capacity
            get_metrics().lost_table_entries.labels(
                table="filter"
            ).inc(dropped)
            self.log.warning(
                "filter map overfull: %d IPs into %d slots; dropping %d "
                "(counted in lost_table_entries)",
                len(live), host.capacity, dropped,
            )
            live = live[: host.capacity]
        for ip in live:
            host.insert(ip, 1)

        # Upload AND swap in one proxied closure (see update_identities
        # above): a filter update enqueued before a batch executes is
        # visible to that batch — the pre-r5 swap-after-return left a
        # window where a one-shot traffic burst dispatched behind a
        # slow proxy queue was filtered by the OLD (possibly empty)
        # map, dropping it silently.
        def apply_filter():
            fmap = host.to_device()
            with self._ident_lock:
                self.filter_map = fmap

        run_on_device(apply_filter)

    def set_apiserver_ips(self, ips: list[int]) -> None:
        self.apiserver_ip = ips[0] if ips else 0

    def add_observer(
        self, fn: Callable[[np.ndarray, str], None], name: str = ""
    ) -> None:
        """Observers see every accepted record block on the feed thread
        (dns tally, flow export...). Must be fast and never raise.
        ``name`` ties an observer to an overload shed stage: while that
        stage is shed (runtime/overload.py) the observer is skipped and
        the skipped events are counted under events_shed{stage}."""
        self._observers.append((fn, name))

    def _device_consts(self):
        """(proxy thread) Lazily place the replicated scalar constants
        reused across step/window calls, refreshing the apiserver scalar
        when it changed."""
        if self._zero_u32 is None:
            self._zero_u32 = jax.device_put(
                np.uint32(0), self._replicated
            )
            self._zthresh = jax.device_put(
                np.float32(4.0), self._replicated
            )
        api = self.apiserver_ip  # single read: a concurrent
        # set_apiserver_ips must not land between the device_put and the
        # bookkeeping below, or the stale scalar would latch forever
        if self._api_val != api:
            self._api_dev = jax.device_put(
                np.uint32(api & 0xFFFFFFFF), self._replicated
            )
            self._api_val = api

    def _sampk(self, k: int):
        """(proxy thread) Device-resident sample-k scalar, cached per
        distinct k (in practice: 1 and overload_sample_k). Same
        rationale as _device_consts — a Python-scalar jit argument
        costs a host->device commit per call."""
        dev = self._sampk_dev.get(k)
        if dev is None:
            dev = jax.device_put(np.uint32(k), self._replicated)
            self._sampk_dev[k] = dev
        return dev

    # -- lifecycle ----------------------------------------------------
    def compile(self) -> None:
        """Warm the STEADY-STATE jit keys (the clang-compile analog) so
        the feed loop and the first scrape never pay compile latency:
        the full-capacity step, the window close + both snapshot
        programs, and the minimum wire bucket for every dispatch path.

        Deliberately NOT warmed here: the rest of the bucket grid.
        Warming every reachable bucket on the boot critical path cost a
        96s agent boot on a cold persistent cache (BENCH_r04) against
        the reference's 10s plugin-reconcile SLA
        (pluginmanager.go:25-28); the daemon warms the remaining grid in
        the background AFTER ready (start_background_warm), one proxy
        call per key so live dispatches interleave."""
        t0 = time.perf_counter()

        def mark(stage: str) -> None:
            self.log.info(
                "compile: %s at +%.1fs", stage, time.perf_counter() - t0
            )

        # Full-capacity dispatch (the steady-state jit key: packed-wire
        # ingest at bucket == batch_capacity + the step with
        # device-resident scalars) through the REAL dispatch path.
        full = ShardedBatch(
            records=np.zeros(
                (self.n_devices, self.cfg.batch_capacity, NUM_FIELDS),
                np.uint32,
            ),
            n_valid=np.zeros((self.n_devices,), np.uint32),
            lost=0,
        )
        self._dispatch_sharded(full, now_s=1, n_raw=0,
                               record_metrics=False)
        mark("full-capacity dispatch")

        # Window-close + snapshot programs warm in the BACKGROUND
        # (start_background_warm runs them before the bucket grid):
        # they gate only the first scrape / first window tick — not the
        # feed path — and their ~18s of warm-cache load time was most
        # of the boot critical path (44.9s observed in BENCH r5 dry
        # run). A scrape or window tick arriving inside the background
        # warm window compiles inline, exactly as a cold key would.
        # Warm the smallest plain bucket (idle/interval flushes); the
        # rest of the bucket ladder is start_background_warm's job.
        self._dispatch(
            np.zeros((0, NUM_FIELDS), np.uint32), now_s=1,
            record_metrics=False,
        )
        mark("min plain bucket")
        # The min-bucket flow-dict pair (idle/interval-flush keys) is
        # NOT warmed here: it is the first grid entry in
        # start_background_warm (~12s of warm-cache load that would
        # otherwise sit on the ready path); a trickle flush arriving
        # before that warm lands compiles inline.
        self.log.info(
            "engine compiled: %d device(s), batch=%d, %.1fs",
            self.n_devices, self.cfg.batch_capacity,
            time.perf_counter() - t0,
        )

    def _reachable_buckets(self) -> list[int]:
        """Every wire bucket a dispatch can produce: the quantized
        ladder (_next_bucket) from the minimum transfer bucket up to
        batch_capacity * feed_coalesce_windows, inclusive."""
        coal_cap = (
            self.cfg.batch_capacity
            * max(1, self.cfg.feed_coalesce_windows)
        )
        b = self._wire_bucket(0)
        out = [b]
        while b < coal_cap:
            b = min(_next_bucket(b + 1), coal_cap)
            out.append(b)
        return out

    def _warm_close_job(self) -> None:  # runs-on: device-proxy
        """A REAL window close (with the close path's bookkeeping): its
        result rides the harvest queue like any window tick, so traffic
        (and any anomaly) ingested between ready and this warm
        publishes instead of vanishing — the only side effect is that
        the first entropy window is shorter than window_seconds."""
        ingested = self._events_in
        meta = self._overload.window_annotation()
        meta["events"] = ingested - self._closed_events_in
        with self._state_lock:
            self.state, win = self.sharded.end_window(
                self.state, self._zthresh
            )
        stacked = self._win_stack(win)
        self._closed_events_in = ingested
        self._ensure_harvest_thread()
        self._harvest_q.put(("win", stacked, meta))
        get_metrics().windows_closed.inc()

    def _warm_snap_job(self) -> None:  # runs-on: device-proxy
        snap = self.sharded.snapshot(self.state, 1)
        jax.block_until_ready(snap["totals"])

    def _warm_snap_flat_job(self) -> None:  # runs-on: device-proxy
        self.sharded.snapshot_host(self.state, 1)

    def _warm_jobs(self) -> list[tuple[Any, Callable, tuple]]:
        """The background-warm job list, in execution order.

        ``warm_close`` comes FIRST — before even the min-bucket dispatch
        pair: the first live window tick fires window_seconds after
        boot, almost always before any grid key finishes, and it used
        to beat the queued warm and cold-compile end_window inline on
        the proxy mid-feed (the r05 stall). With the close warm at the
        head of the FIFO proxy queue — and _close_window_impl deferring
        ticks until it lands — the first real close always finds the
        program resident. Then the min-bucket dispatch pair (a trickle
        feed needs it on its very first interval flush), the snapshot
        programs (first scrape, in production 15-30s after boot), then
        the rest of the grid in ramp order. All moved off compile()'s
        critical path — together they were ~30s of the 45s boot
        observed in the r5 dry run.

        One flat job list, one throttle policy: every entry is a single
        proxied call followed by a yield, so live dispatches wait
        behind at most ONE trace+lower (multi-program closures parked
        the proxy ~18s)."""
        jobs: list[tuple[Any, Callable, tuple]] = [
            ("window close", self._warm_close_job, ()),
        ]
        if self._flow_dict is not None:
            # Flow-dict dispatch needs the device descriptor table on
            # its very first batch; building it here keeps even that
            # zeros-jit compile off the event path (it also seeds the
            # AOT disk cache entry a post-resync rebuild will hit).
            jobs.append(("desc table", self._ensure_desc_table, ()))
        buckets = self._reachable_buckets()
        for i, b in enumerate(buckets):
            if self._flow_dict is not None:
                jobs.append((("known", b), self._ingest_known_fn, (b,)))
                jobs.append((("new", b), self._ingest_new_fn, (b,)))
            else:
                packed = bool(self.cfg.transfer_packed)
                jobs.append(((b, packed), self._ingest_fn, (b, packed)))
            if i == 0:
                jobs.append(("snapshot", self._warm_snap_job, ()))
                jobs.append(
                    ("snapshot flat", self._warm_snap_flat_job, ())
                )
        return jobs

    def start_background_warm(
        self, stop: threading.Event | None = None
    ) -> threading.Thread:
        """Warm every remaining reachable bucket key OFF the boot
        critical path (VERDICT r4 #2: agent ready in <=15s).

        Runs on its own thread, one ``run_on_device`` per key: the
        window-close program first (see :meth:`_warm_jobs`), then the
        grid smallest bucket first — the proxy queue is FIFO, so a live
        dispatch waits behind at most ONE in-flight warm compile, and a
        post-ready feed ramps through the small/mid buckets before
        saturation reaches the multi-window keys — warming in ramp
        order (small keys also compile fastest) keeps the window where
        a reachable bucket is still cold as short as possible. A bucket
        the feed reaches before its warm simply compiles inline exactly
        as it would have — the warm then finds the key cached and skips
        it.
        ``bucket_warm_done`` is set when the grid is fully resident
        (tests fence on it). ``stop`` is checked between keys; an
        IN-FLIGHT compile cannot be aborted, so a shutdown racing the
        warm still waits for at most one key."""
        def _warm() -> None:
            t0 = time.perf_counter()
            n_warmed = 0
            n_failed = 0
            hb = self._register_hb("engine-bucket-warm")
            # Bounded duty-cycle scheduler: after each warmed key the
            # thread yields cost*(1-d)/d seconds (capped below) so live
            # dispatches interleave. d=0.5 is the historical equal
            # yield (~50% proxy share); bench raises it to finish the
            # warm faster while measurement waits on it.
            duty = min(max(self.cfg.warm_duty_cycle, 0.05), 1.0)
            try:
                jobs = self._warm_jobs()
                for key, fn, args in jobs:
                    if stop is not None and stop.is_set():
                        return
                    if key in self._pad_cache:
                        continue
                    ok = True
                    tk = time.perf_counter()
                    # A cold-cache trace+lower legitimately parks the
                    # proxy for 30-100s — parked, not stalled.
                    hb.park()
                    try:
                        run_on_device(fn, *args)
                        n_warmed += 1
                    except Exception:
                        ok = False
                        n_failed += 1
                        self._count_error("warm_key")
                        self.log.exception(
                            "background warm failed at %s", key
                        )
                    hb.beat()
                    if key == "window close":
                        # Resident — or terminally failed, in which
                        # case ticks must stop deferring and take the
                        # inline compile (better a one-off stall than
                        # windows that never close).
                        self._close_warmed.set()
                    if not ok:
                        continue
                    # Yield to live traffic: each key's trace+lower
                    # parks the proxy for seconds; back-to-back keys
                    # halved the live feed rate for the whole warm.
                    # The per-key yield is capped at 10s (beyond it —
                    # pathological compiles — finishing the warm wins
                    # over fairness).
                    sl = min(
                        (time.perf_counter() - tk)
                        * (1.0 - duty) / duty,
                        10.0,
                    )
                    if sl <= 0:
                        continue
                    if stop is not None:
                        stop.wait(sl)
                    else:
                        time.sleep(sl)
                if n_failed:
                    # A failed key means a reachable bucket can still
                    # cold-compile mid-feed — the done event must NOT
                    # claim otherwise.
                    self.log.warning(
                        "bucket grid warm incomplete: %d key(s) failed",
                        n_failed,
                    )
                    self.bucket_warm_failed.set()
                    return
                self.bucket_warm_done.set()
                if n_warmed:
                    self.log.info(
                        "bucket grid warm: %d key(s) in %.1fs "
                        "(background)",
                        n_warmed, time.perf_counter() - t0,
                    )
            except Exception:
                self._count_error("warm")
                self.log.exception("background bucket warm died")
            finally:
                self._deregister_hb("engine-bucket-warm")

        t = threading.Thread(
            target=_warm, name="engine-bucket-warm", daemon=True
        )
        self._warm_thread = t
        t.start()
        return t

    def step_records(self, records: np.ndarray, now_s: int | None = None) -> None:  # hot-path: event
        """Feed one host block synchronously (tests / direct callers)."""
        self._dispatch(records, now_s or int(time.time()))

    def _dispatch(  # hot-path: event
        self, records: np.ndarray, now_s: int,
        record_metrics: bool = True,
    ) -> None:
        if self.record_hook is not None:
            try:
                self.record_hook(records, now_s)
            except Exception:
                self._count_error("record_hook")
        sb = partition_events(
            records, self.n_devices, self.cfg.batch_capacity,
            min_bucket=self.cfg.transfer_min_bucket,
        )
        self._dispatch_sharded(sb, now_s, n_raw=len(records),
                               record_metrics=record_metrics)

    def _compile_cached(self, tag: str, key, lower):  # runs-on: device-proxy # may-block: AOT disk-cache consult — the warm jobs prefill every reachable key at startup; a miss is once-per-shape and a <10s disk load beats a 100s+ recompile
        """Compile one per-bucket ingest executable, consulting the AOT
        disk cache first. ``lower`` is a thunk returning the
        ``jax.stages.Lowered``; on a miss its compiled executable is
        persisted via ``serialize_executable`` keyed by (jax version,
        topology, engine config signature, tag, bucket key) — a
        restarted daemon then warms the whole bucket grid by
        deserializing instead of re-lowering every key, which is what
        turns the 214s r05 bucket warm into a <10s disk load. Same
        format, path scheme, and hit/miss counters as the telemetry
        step programs (telemetry.aot_disk_*)."""
        from retina_tpu.parallel.telemetry import (
            aot_disk_load, aot_disk_path, aot_disk_save,
        )

        path = None
        if self.cfg.aot_cache_dir:
            path = aot_disk_path(
                self.cfg.aot_cache_dir, self.mesh, tag,
                self._aot_sig, key,
            )
            ex = aot_disk_load(path, tag=tag)
            if ex is not None:
                return ex
        ex = lower().compile()
        if path is not None:
            aot_disk_save(path, ex, tag=tag)
        return ex

    @device_entry("engine.ingest", kind="jit")
    def _ingest_fn(self, bucket: int, packed: bool):  # runs-on: device-proxy
        """Per-bucket jit that turns ONE transferred (D, bucket, P) wire
        array + a small metadata vector into step-ready device inputs:
        unpack the 12-lane wire format (when packed), slice the bucket
        into ceil(bucket/capacity) windows of the step's static
        (D, B, 16) shape (zero-extending the last), and derive each
        window's validity counts — the host->device link carries only the
        bucketed packed rows plus one metadata vector per flush; HBM
        bandwidth makes the expansion free. Coalescing several windows
        into one transfer amortizes per-transfer round-trip latency
        (VERDICT r3 weak #1).

        meta layout (u32): [base_lo, base_hi, now_s, lost, n_valid[D]].
        Returns (windows, window_n_valid, now_s, lost) — all on device,
        so the following step dispatches move no further host data.
        """
        key = (bucket, packed)
        fn = self._pad_cache.get(key)
        if fn is None:
            cap = self.cfg.batch_capacity
            n_win = max(1, -(-bucket // cap))
            from functools import partial as _partial

            from retina_tpu.parallel.wire import (
                PACKED_FIELDS, unpack_records_device,
            )

            out_sh = (
                (self._rec_sharding,) * n_win,
                (self._rec_sharding,) * n_win,
                self._replicated,
                self._replicated,
            )

            # donate_argnums=(0,): the wire array is freshly device_put
            # per flush and read exactly once here — donating it lets
            # XLA reuse the transfer buffer for the unpacked windows
            # instead of allocating a second (D, bucket, 16) block
            # (RT302; found by the device-program donation audit).
            @_partial(jax.jit, out_shardings=out_sh, donate_argnums=(0,))
            def ingest(small, meta):
                if packed:
                    small = unpack_records_device(small, meta[0], meta[1])
                nv = meta[5:].astype(jnp.int32)
                wins, nvs = [], []
                for w in range(n_win):
                    lo = w * cap
                    hi = min(lo + cap, bucket)
                    c = small[:, lo:hi]
                    if hi - lo < cap:
                        c = jnp.pad(
                            c, ((0, 0), (0, cap - (hi - lo)), (0, 0))
                        )
                    wins.append(c)
                    nvs.append(
                        jnp.clip(nv - lo, 0, hi - lo).astype(jnp.uint32)
                    )
                return tuple(wins), tuple(nvs), meta[2], meta[3]

            # AOT-compile from shape specs: warming a bucket key moves
            # NO data over the host->device link (a real-array warm of a
            # 2M-row bucket would push ~100MB through the tunnel), and a
            # cache miss at feed time costs only the compile (persistent
            # XLA cache across restarts), never a mid-feed trace+infer
            # surprise on the proxy thread.
            width = PACKED_FIELDS if packed else NUM_FIELDS
            fn = self._compile_cached("ingest", key, lambda: ingest.lower(
                jax.ShapeDtypeStruct(
                    (self.n_devices, bucket, width), jnp.uint32,
                    sharding=self._rec_sharding,
                ),
                jax.ShapeDtypeStruct(
                    (5 + self.n_devices,), jnp.uint32,
                    sharding=self._replicated,
                ),
            ))
            self._pad_cache[key] = fn
        return fn

    # -- v2 wire: flow-descriptor dictionary path ---------------------
    def _flowdict_resync(self) -> None:
        """Invalidate host dict + device table together after a failure
        that may have desynced them (one descriptor re-upload burst, no
        wrong data) and fence off in-flight batches built against the
        old table."""
        with self._fd_lock:
            self._flow_dict.clear()
            self._fd_epoch += 1
            self._desc_table = None

    @device_entry("engine.desc_table", kind="jit")
    def _desc_table_fn(self):
        """Zeros-on-device jit for the descriptor table (split from
        _ensure_desc_table so the device-program analysis can lower
        and audit the program without executing the ensure path)."""
        from functools import partial as _partial

        from retina_tpu.parallel.wire import PACKED_FIELDS

        shape = (
            self.n_devices, self.cfg.flow_dict_slots, PACKED_FIELDS,
        )

        @_partial(jax.jit, out_shardings=self._rec_sharding)
        def mk():
            return jnp.zeros(shape, jnp.uint32)

        return mk

    def _ensure_desc_table(self):  # runs-on: device-proxy
        """(proxy thread) Device descriptor table, created by a zeros
        jit ON device — never uploaded from host. The build runs
        outside _fd_lock; only this proxy-thread method CREATES the
        table, so a concurrent resync can at worst clear the slot, and
        storing a freshly-zeroed table over that clear is exactly the
        state a resync wants.

        Routed through _compile_cached: _desc_table_fn builds a FRESH
        jit closure per call, so every resync used to re-trace and
        recompile the zeros program inline on the dispatch lane
        (RT401) — the AOT disk cache turns that into a one-time cost,
        and the desc-table background warm job (see _warm_jobs) moves
        even the first touch off the event path."""
        with self._fd_lock:
            table = self._desc_table
        if table is None:
            mk = self._desc_table_fn()
            ex = self._compile_cached("desc_table", "zeros", mk.lower)
            table = ex()
            with self._fd_lock:
                self._desc_table = table
        return table

    @staticmethod
    def _slice_windows(full, nv_i32, bucket: int, cap: int):
        """(traced) Slice a (D, bucket, 16) array into step windows of
        the static (D, cap, 16) shape with per-window validity counts
        (same contract as _ingest_fn's window loop)."""
        n_win = max(1, -(-bucket // cap))
        wins, nvs = [], []
        for w in range(n_win):
            lo = w * cap
            hi = min(lo + cap, bucket)
            c = full[:, lo:hi]
            if hi - lo < cap:
                c = jnp.pad(c, ((0, 0), (0, cap - (hi - lo)), (0, 0)))
            wins.append(c)
            nvs.append(
                jnp.clip(nv_i32 - lo, 0, hi - lo).astype(jnp.uint32)
            )
        return tuple(wins), tuple(nvs)

    @device_entry("engine.ingest_new", kind="jit")
    def _ingest_new_fn(self, bucket: int):  # runs-on: device-proxy
        """Per-bucket jit for NEW flow descriptors: (D, bucket, 13) wire
        of [table_id | 12 packed lanes] + meta + descriptor table ->
        scatter the lanes into the table (donated; id 0 is the overflow
        sentinel slot, sacrificial), unpack, slice into step windows.

        Reference analog: the first packet of a flow inserting its key
        into the kernel map (conntrack.c ct_create entry) — descriptor
        becomes resident; only counters travel afterwards.
        """
        key = ("new", bucket)
        fn = self._pad_cache.get(key)
        if fn is None:
            cap = self.cfg.batch_capacity
            n_win = max(1, -(-bucket // cap))
            from functools import partial as _partial

            from retina_tpu.parallel.wire import (
                PACKED_FIELDS, unpack_records_device,
            )

            out_sh = (
                (self._rec_sharding,) * n_win,
                (self._rec_sharding,) * n_win,
                self._replicated,
                self._replicated,
                self._rec_sharding,
            )

            # donate (0, 2): the descriptor table (2) was always
            # donated (scatter in place); the wire array (0) is also
            # single-use per flush — fresh device_put, read once —
            # so its transfer buffer is reusable too (RT302; found by
            # the device-program donation audit).
            @_partial(
                jax.jit, out_shardings=out_sh, donate_argnums=(0, 2)
            )
            def ingest(wire, meta, table):
                ids = wire[..., 0]
                lanes = wire[..., 1:]
                d_idx = jnp.arange(lanes.shape[0])[:, None]
                table = table.at[d_idx, ids].set(lanes)
                full = unpack_records_device(lanes, meta[0], meta[1])
                nv = meta[5:].astype(jnp.int32)
                wins, nvs = SketchEngine._slice_windows(
                    full, nv, bucket, cap
                )
                return wins, nvs, meta[2], meta[3], table

            fn = self._compile_cached("ingest_new", key, lambda: ingest.lower(
                jax.ShapeDtypeStruct(
                    (self.n_devices, bucket, PACKED_FIELDS + 1),
                    jnp.uint32, sharding=self._rec_sharding,
                ),
                jax.ShapeDtypeStruct(
                    (5 + self.n_devices,), jnp.uint32,
                    sharding=self._replicated,
                ),
                jax.ShapeDtypeStruct(
                    (
                        self.n_devices, self.cfg.flow_dict_slots,
                        PACKED_FIELDS,
                    ),
                    jnp.uint32, sharding=self._rec_sharding,
                ),
            ))
            self._pad_cache[key] = fn
        return fn

    @device_entry("engine.ingest_known", kind="jit")
    def _ingest_known_fn(self, bucket: int):  # runs-on: device-proxy
        """Per-bucket jit for KNOWN flows: counter wire + meta +
        descriptor table -> gather the resident 12-lane descriptors
        from HBM, overlay the per-quantum counters, unpack, slice into
        step windows. meta[4] is the biased TS_REL flag for every known
        row (1 = stamped at the flush base meta[0:2], 0 = unstamped
        flush).

        Wire layout depends on ``_fd_dense`` (wire_dense_known):
          v3 (dense off): (D, bucket, 2) of [id | packets << id_bits,
              bytes] — 8 B/row instead of the 48 B full row.
          v4 (dense on, default): (D, W) bitstream of
              (id_bits + 10 + 22)-bit rows (parallel/wire.py dense
              layer) — 6.25 B/row at the default 18-bit id space; the
              device side unpacks with two-word gathers.

        Reference analog: the kernel map hit path — established flows
        move counters only (conntrack.c ct_process_packet accumulate).
        """
        key = ("known", bucket)
        fn = self._pad_cache.get(key)
        if fn is None:
            cap = self.cfg.batch_capacity
            n_win = max(1, -(-bucket // cap))
            from functools import partial as _partial

            from retina_tpu.parallel.wire import (
                PACKED_FIELDS, dense_known_unpack_device, dense_words,
                unpack_records_device,
            )

            # HOST scalars (np, not jnp), deliberately: a jnp scalar
            # here becomes a committed DEVICE array captured as a
            # trace-closure constant, and lowering such a constant
            # does a device->host _value copy — which, issued from a
            # background-warm lower() while the feed keeps the device
            # queue busy, starved for minutes on the tunnel backend and
            # froze the whole proxy (observed: every measure window at
            # 0 ev/s). np scalars lower to MLIR literals with zero
            # device traffic.
            id_bits = np.uint32(self._fd_id_bits)
            id_mask = np.uint32((1 << self._fd_id_bits) - 1)
            dense = self._fd_dense
            out_sh = (
                (self._rec_sharding,) * n_win,
                (self._rec_sharding,) * n_win,
                self._replicated,
                self._replicated,
            )

            # donate_argnums=(0,): the counter wire is single-use per
            # flush (RT302). The descriptor table (2) must NOT be
            # donated: it is RESIDENT — the same buffer is read by
            # every subsequent known-flow flush.
            @_partial(jax.jit, out_shardings=out_sh, donate_argnums=(0,))
            def ingest(wire, meta, table):
                if dense:
                    ids, pk, by = dense_known_unpack_device(
                        wire, bucket, self._fd_id_bits
                    )
                else:
                    ids = wire[..., 0] & id_mask
                    pk = wire[..., 0] >> id_bits
                    by = wire[..., 1]
                d_idx = jnp.arange(ids.shape[0])[:, None]
                desc = table[d_idx, ids]  # (D, bucket, 12)
                desc = desc.at[..., 6].set(pk)  # PACKETS
                desc = desc.at[..., 5].set(by)  # BYTES
                desc = desc.at[..., 0].set(
                    jnp.broadcast_to(meta[4], ids.shape)  # TS_REL
                )
                full = unpack_records_device(desc, meta[0], meta[1])
                nv = meta[5:].astype(jnp.int32)
                wins, nvs = SketchEngine._slice_windows(
                    full, nv, bucket, cap
                )
                return wins, nvs, meta[2], meta[3]

            wire_shape = (
                (self.n_devices, dense_words(bucket, self._fd_id_bits))
                if dense else (self.n_devices, bucket, 2)
            )
            fn = self._compile_cached("ingest_known", key, lambda: ingest.lower(
                jax.ShapeDtypeStruct(
                    wire_shape, jnp.uint32,
                    sharding=self._rec_sharding,
                ),
                jax.ShapeDtypeStruct(
                    (5 + self.n_devices,), jnp.uint32,
                    sharding=self._replicated,
                ),
                jax.ShapeDtypeStruct(
                    (
                        self.n_devices, self.cfg.flow_dict_slots,
                        PACKED_FIELDS,
                    ),
                    jnp.uint32, sharding=self._rec_sharding,
                ),
            ))
            self._pad_cache[key] = fn
        return fn

    def _wire_bucket(self, n_max: int) -> int:
        cap_total = self.cfg.batch_capacity * max(
            1, self.cfg.feed_coalesce_windows
        )
        return min(
            _next_bucket(max(n_max, self.cfg.transfer_min_bucket)),
            cap_total,
        )

    def _hk_account(self, rows: np.ndarray) -> None:  # runs-on: feed-worker*
        """("both" mode) Fold one dispatch's forward-verdict packets
        into the host ground-truth dict, keyed exactly like the device
        invertible/flow sketches: (src_ip, dst_ip, ports, proto). Caller
        holds self._fd_lock. Counts are post-sampling (unscaled) — under
        SAMPLING the heavy/priority tiers are exempt, so ground truth
        for keys at/above the heavy threshold stays exact."""
        from retina_tpu.events.schema import VERDICT_FORWARDED

        fwd = rows[:, F.VERDICT] == VERDICT_FORWARDED
        if not fwd.any():
            return
        r = rows[fwd]
        keys = np.stack(
            [r[:, F.SRC_IP], r[:, F.DST_IP], r[:, F.PORTS],
             r[:, F.META] >> np.uint32(24)],
            axis=1,
        ).astype(np.uint32)
        pk = r[:, F.PACKETS].astype(np.uint64)
        uniq, inv = np.unique(keys, axis=0, return_inverse=True)
        sums = np.zeros(len(uniq), np.uint64)
        np.add.at(sums, inv, pk)
        hk = self._hk_counts
        for kb, s in zip((u.tobytes() for u in uniq), sums):
            hk[kb] = hk.get(kb, 0) + int(s)

    def _dispatch_flowdict(
        self, sb: "ShardedBatch", now_s: int, n_raw: int,
        sync: bool, record_metrics: bool,
    ) -> None:
        """Flow-dictionary dispatch: split the partitioned batch into
        new-descriptor rows (full 12-lane upload + table insert) and
        known rows (8-byte [id|packets, bytes] tuples against the
        resident table — v3 wire, see __init__). Known rows whose packet
        count overflows the id lane's headroom escalate to the new side
        (idempotent re-scatter). Both ride one proxy submission,
        FIFO-ordered so inserts land before gathers."""
        from retina_tpu.parallel.wire import (
            DENSE_BY_BITS, DENSE_PK_BITS, batch_ts_base,
            dense_known_rows, dense_words, known_rows, pack_records,
        )

        t_d0 = time.monotonic()
        m = get_metrics()
        lost = sb.lost
        D = self.n_devices
        with self._fd_lock:
            per_dev = []
            for d in range(D):
                nv = int(sb.n_valid[d])
                rows = sb.records[d, :nv]
                ids, is_new = self._flow_dict.lookup_or_assign(rows)
                per_dev.append((rows, ids, is_new))
                if self._hk_counts is not None and len(rows):
                    self._hk_account(rows)
            epoch = self._fd_epoch
            # Snapshot here so the published gauges are consistent with
            # THIS batch's assignments (and no second lock acquisition
            # on the hot path).
            fd_entries = len(self._flow_dict)
            fd_generation = self._flow_dict.generation
        base = batch_ts_base(sb.records)
        dense = self._fd_dense
        pk_cap = np.uint32(1) << np.uint32(
            DENSE_PK_BITS if dense else self._fd_pk_bits
        )
        id_bits = np.uint32(self._fd_id_bits)
        # Escalate to the full-row side (exact per-row fields) any known
        # row the narrow lanes cannot represent faithfully: packet
        # counts over the packets lane's headroom, rows carrying
        # TSval/TSecr (the RTT matcher needs their EXACT send time —
        # the flush-base stamp below would record phantom times), and
        # unstamped rows (TS_REL=0 must round-trip to ts 0,
        # wire.py:17-23). The dense wire additionally escalates rows
        # whose BYTES overflow the 22-bit lane (v3 ships bytes as a
        # full u32). The masks are computed once and reused for
        # sizing + build. All in-tree sources stamp and TSval rows are
        # apiserver-RTT traffic only, so escalation stays rare.
        sel_new = [
            x[2]
            | (x[0][:, F.PACKETS] >= pk_cap)
            | ((x[0][:, F.TSVAL] | x[0][:, F.TSECR]) != 0)
            | ((x[0][:, F.TS_LO] | x[0][:, F.TS_HI]) == 0)
            for x in per_dev
        ]
        if dense:
            by_cap = np.uint32(1) << np.uint32(DENSE_BY_BITS)
            for s, x in zip(sel_new, per_dev):
                s |= x[0][:, F.BYTES] >= by_cap
        n_new = [int(s.sum()) for s in sel_new]
        n_known = [len(x[0]) - nn for x, nn in zip(per_dev, n_new)]
        Bn = self._wire_bucket(max(n_new) if n_new else 0)
        Bk = self._wire_bucket(max(n_known) if n_known else 0)
        new_wire = np.zeros((D, Bn, 13), np.uint32)
        known_wire = np.zeros(
            (D, dense_words(Bk, int(id_bits))) if dense else (D, Bk, 2),
            np.uint32,
        )
        nv_new = np.zeros((D,), np.uint32)
        nv_known = np.zeros((D,), np.uint32)
        from retina_tpu.native import flowwire_dense_native, flowwire_native

        for d, (rows, ids, _) in enumerate(per_dev):
            sel = sel_new[d]
            nn, nk = n_new[d], n_known[d]
            if nn > Bn or nk > Bk:
                # Unreachable from in-tree callers (partition capacity
                # == the _wire_bucket cap). Dropping new rows here
                # would be CORRUPTION, not loss: their descriptors are
                # already registered host-side, so later quanta would
                # reference never-written table slots. Fail loudly; the
                # caller's resync handler rebuilds both sides.
                raise RuntimeError(
                    f"flow-dict wire overflow: {nn}/{Bn} new, "
                    f"{nk}/{Bk} known rows on device {d}"
                )
            got = None
            if len(rows):
                # One native pass builds both sides in place — the
                # numpy path below pays two fancy-indexed row copies +
                # a pack pass + two bit-pack passes per device.
                if dense:
                    got = flowwire_dense_native(
                        np.ascontiguousarray(rows), ids,
                        sel.astype(np.uint8), int(base),
                        int(self._fd_id_bits),
                        DENSE_PK_BITS, DENSE_BY_BITS,
                        new_wire[d], known_wire[d],
                    )
                else:
                    got = flowwire_native(
                        np.ascontiguousarray(rows), ids,
                        sel.astype(np.uint8), int(base),
                        int(self._fd_id_bits),
                        new_wire[d], known_wire[d],
                    )
            if got is not None:
                assert got == nn, (got, nn)
            elif len(rows):
                rn, idn = rows[sel], ids[sel]
                rk, idk = rows[~sel], ids[~sel]
                if len(rn):
                    packed12, _, _ = pack_records(rn, base=base)
                    new_wire[d, : len(rn), 0] = idn
                    new_wire[d, : len(rn), 1:] = packed12
                if len(rk):
                    if dense:
                        dense_known_rows(
                            rk, idk, int(id_bits), known_wire[d]
                        )
                    else:
                        known_rows(
                            rk, idk, id_bits, known_wire[d, : len(rk)]
                        )
            nv_new[d] = nn
            nv_known[d] = nk
        if record_metrics and lost:
            m.lost_events.labels(
                stage="partition", plugin="engine"
            ).inc(lost)
        b_lo = np.uint32(base & np.uint64(0xFFFFFFFF))
        b_hi = np.uint32(base >> np.uint64(32))
        meta_new = np.empty((5 + D,), np.uint32)
        meta_new[0], meta_new[1] = b_lo, b_hi
        meta_new[2] = np.uint32(int(now_s) & 0xFFFFFFFF)
        meta_new[3] = np.uint32(int(lost) & 0xFFFFFFFF)
        # Known rows' TS_REL: the flush base itself (rel 1 = "stamped,
        # at base"; 0 = the whole flush is unstamped). A flush spans
        # ~tens of ms, and rows needing exact per-row time (TSval/TSecr
        # carriers, unstamped rows) escalated above, so one
        # representative timestamp per flush is exact enough for
        # conntrack/windowing.
        meta_new[4] = 1 if int(base) > 0 else 0
        meta_new[5:] = nv_new
        have_new = bool(nv_new.any())
        have_known = bool(nv_known.any())
        meta_known = meta_new.copy()
        # Host losses fold into the device totals exactly once: on the
        # new side when it runs, else on the known side.
        meta_known[3] = 0 if have_new else meta_new[3]
        meta_known[5:] = nv_known
        n_events = int(sb.events)
        n_valid_total = int(nv_new.sum() + nv_known.sum())
        samp_k = int(sb.sample_k)

        def xfer_and_step():
            faults.inject("transfer")
            # A failure resync after this batch was built invalidated
            # the table its ids reference — drop rather than gather
            # zeroed descriptors (FIFO makes ordinary overflow clears
            # safe; only resyncs bump the epoch).
            with self._fd_lock:
                if self._fd_epoch != epoch:
                    if record_metrics:
                        m.lost_events.labels(
                            stage="dispatch", plugin="engine"
                        ).inc(n_events)
                    self.log.warning(
                        "dropping in-flight flow-dict batch from "
                        "pre-resync epoch"
                    )
                    return
            self._device_consts()
            # Identity/filter tables captured at proxy-EXECUTION time,
            # not dispatch-build time: update_identities /
            # update_filter_ips swap them inside proxied closures, so
            # FIFO queue order == visibility order — a table update
            # enqueued before this batch is guaranteed applied to it
            # even when warm-key compiles delay the queue by seconds
            # (build-time capture silently filtered a one-shot burst
            # with the pre-update map).
            with self._ident_lock:
                ident = self.ident
                fmap = self.filter_map
            table = self._ensure_desc_table()
            if record_metrics:
                # Wire accounting AFTER the epoch check: a dropped
                # pre-resync batch never ships, and these series are
                # the wire-savings evidence — counted at build time
                # they would overstate exactly in the failure windows
                # an operator inspects. Only sides that actually cross
                # the link count.
                m.transfer_bytes.inc(
                    (new_wire.nbytes if have_new else 0)
                    + (known_wire.nbytes if have_known else 0)
                )
                m.wire_rows.labels(kind="new").inc(int(nv_new.sum()))
                m.wire_rows.labels(kind="known").inc(
                    int(nv_known.sum())
                )
                m.flow_dict_entries.set(fd_entries)
                m.flow_dict_generation.set(fd_generation)
            t_x0 = time.perf_counter()
            # ONE batched device_put for everything this flush moves:
            # separate puts each pay a client round-trip on the tunnel
            # backend.
            host_bufs, shardings = [], []
            if have_new:
                host_bufs += [new_wire, meta_new]
                shardings += [self._rec_sharding, self._replicated]
            if have_known:
                host_bufs += [known_wire, meta_known]
                shardings += [self._rec_sharding, self._replicated]
            devs = jax.device_put(tuple(host_bufs), tuple(shardings))
            devs = list(devs)
            sides = []
            # Skip a side with zero valid rows outright: steady state
            # has almost-no new flows, cold start almost-no known —
            # half the transfers and steps on the hot path either way.
            if have_new:
                new_dev, mn_dev = devs[0], devs[1]
                devs = devs[2:]
                wins, nvs, now_dev, lost_dev, table = (
                    self._ingest_new_fn(Bn)(new_dev, mn_dev, table)
                )
                # Re-check the epoch at the store: a resync landing
                # between this batch's entry check and here already
                # invalidated the ids this table was built against —
                # storing it would resurrect stale descriptors over
                # the resync's cleared table.
                with self._fd_lock:
                    if self._fd_epoch == epoch:
                        self._desc_table = table
                sides.append((wins, nvs, now_dev, lost_dev))
            if have_known:
                known_dev, mk_dev = devs[0], devs[1]
                wins, nvs, now_dev, lost_dev = self._ingest_known_fn(
                    Bk
                )(known_dev, mk_dev, table)
                sides.append((wins, nvs, now_dev, lost_dev))
            t0 = time.perf_counter()
            n_steps = 0
            with self._state_lock:
                st = self.state
                first = True
                for wins, nvs, now_dev, lost_dev in sides:
                    for w in range(len(wins)):
                        st, _ = self.sharded.step(
                            st, wins[w], nvs[w], now_dev, ident,
                            self._api_dev, filter_map=fmap,
                            # meta_known carries lost=0, so folding on
                            # the FIRST side that runs counts host
                            # losses once whichever sides are present.
                            lost=lost_dev if first else self._zero_u32,
                            sample_k=self._sampk(samp_k),
                        )
                        first = False
                        n_steps += 1
                self.state = st
            if record_metrics:
                t_end = time.perf_counter()
                m.transfer_seconds.observe(t0 - t_x0)
                m.device_step_seconds.observe(t_end - t0)
                tid = fleet_epoch(self.cfg.window_seconds)
                self._recorder.record(
                    mnames.STAGE_TRANSFER, t_x0, tid, t1=t0
                )
                self._recorder.record(
                    mnames.STAGE_DEVICE_STEP, t0, tid, t1=t_end
                )
                # Overload signal: EWMA of transfer+step wall time
                # (proxy thread only — no lock needed).
                self._dispatch_lat_ewma = (
                    0.8 * self._dispatch_lat_ewma + 0.2 * (t_end - t_x0)
                )
                self._dispatch_lat_t = time.monotonic()
                m.device_batch_fill.set(
                    n_valid_total
                    / max(D * self.cfg.batch_capacity * n_steps, 1)
                )
                self._steps += n_steps
                self._events_in += n_raw

        if not (have_new or have_known):
            return  # nothing valid (pure padding batch)

        if sync:
            run_on_device(xfer_and_step)
            return

        def safe_xfer_and_step():
            try:
                xfer_and_step()
            except Exception as e:
                if self._count_error("device_step"):
                    self.log.exception("flow-dict device step failed")
                get_metrics().lost_events.labels(
                    stage="device", plugin="engine"
                ).inc(n_events)
                # The donated table may be gone and the host dict no
                # longer matches it — resync by rebuilding both (one
                # re-upload burst, no wrong data); queued batches from
                # this epoch self-drop.
                self._flowdict_resync()
                if self._fatal_device_error(e):
                    self._request_recovery(repr(e))
            finally:
                with self._busy_lock:
                    self._inflight_busy -= 1
                self._inflight.release()

        t_d1 = time.monotonic()
        self._recorder.record(
            mnames.STAGE_WIRE_BUILD, t_d0,
            fleet_epoch(self.cfg.window_seconds), t1=t_d1,
        )
        self._inflight.acquire()
        with self._busy_lock:
            self._inflight_busy += 1
        submit_on_device(safe_xfer_and_step)
        if self._feed_trace:
            self.log.info(
                "dispatch trace: build %.0fms inflight-wait %.0fms "
                "(%d new / %d known rows)",
                (t_d1 - t_d0) * 1e3,
                (time.monotonic() - t_d1) * 1e3,
                int(nv_new.sum()), int(nv_known.sum()),
            )

    def _dispatch_sharded(
        self, sb: "ShardedBatch", now_s: int, n_raw: int,
        sync: bool = True, record_metrics: bool = True,
    ) -> None:
        """Pack + device_put + step dispatch for an already-partitioned
        batch.

        Degraded drop-and-count: while a crash-only recovery is
        rebuilding device state, async feed traffic must not race the
        rebuild — it drops here, counted under lost_events
        stage="degraded". Sync dispatches pass through (the recovery
        probe itself, and direct callers who want the error).

        Packing stays on the CALLING thread (the dispatch worker under
        the feed loop), overlapping the proxy thread's in-flight
        transfer. ``sync=True`` (tests, direct callers) blocks on the
        proxy round-trip and propagates errors; ``sync=False`` (the feed
        pipeline) is fire-and-forget onto the proxy queue, bounded by
        the in-flight semaphore, so transfers run back-to-back on the
        link while this thread packs the next quantum.
        """
        if not sync and self._degraded.is_set():
            if record_metrics:
                get_metrics().lost_events.labels(
                    stage="degraded", plugin="engine"
                ).inc(int(sb.events) + int(sb.lost))
            return
        # The dictionary pays off per ROW saved; a tiny flush (idle
        # agent, interval flush) is cheaper as one plain transfer than
        # as a new/known pair of dispatches. Plain and dict flushes
        # interleave soundly: a plain flush simply ships full rows and
        # leaves the dictionary untouched.
        if self._flow_dict is not None and int(
            sb.n_valid.sum()
        ) >= self.cfg.transfer_min_bucket:
            try:
                self._dispatch_flowdict(
                    sb, now_s, n_raw, sync, record_metrics
                )
            except Exception:
                # ANY failure after lookup_or_assign may leave
                # descriptors registered host-side whose lanes never
                # reached the device table — later "known" references
                # would gather zeros (silent corruption). Rebuild both
                # sides; in-flight batches from before the reset
                # self-drop via the epoch check in their closures.
                self._flowdict_resync()
                if not sync:
                    get_metrics().lost_events.labels(
                        stage="dispatch", plugin="engine"
                    ).inc(int(sb.events) + int(sb.lost))
                    if self._count_error("flowdict_dispatch"):
                        self.log.exception("flow-dict dispatch failed")
                    return
                raise
            return
        m = get_metrics()
        if sb.lost and record_metrics:
            m.lost_events.labels(stage="partition", plugin="engine").inc(sb.lost)
        t_w0 = time.monotonic()
        if self.cfg.transfer_packed:
            from retina_tpu.parallel.wire import pack_records

            wire, b_lo, b_hi = pack_records(sb.records)
            packed = True
        else:
            # Async consumption below: the single-device partition fast
            # path may alias the caller's buffer (ALIASING CONTRACT in
            # partition_events) — copy so the producer can reuse it.
            wire = sb.records if sync else np.array(sb.records)
            b_lo = b_hi = np.uint32(0)
            packed = False
        if record_metrics:
            m.transfer_bytes.inc(wire.nbytes)
        bucket = wire.shape[1]
        meta = np.empty((5 + self.n_devices,), np.uint32)
        meta[0], meta[1] = b_lo, b_hi
        meta[2] = np.uint32(int(now_s) & 0xFFFFFFFF)
        meta[3] = np.uint32(int(sb.lost) & 0xFFFFFFFF)
        meta[4] = 0  # ts_rel_rep: unused on the full-row path
        meta[5:] = sb.n_valid
        n_valid_total = int(sb.n_valid.sum())
        n_events = int(sb.events)
        samp_k = int(sb.sample_k)
        if record_metrics:
            self._recorder.record(
                mnames.STAGE_WIRE_BUILD, t_w0,
                fleet_epoch(self.cfg.window_seconds),
                t1=time.monotonic(),
            )

        def xfer_and_step():
            faults.inject("transfer")
            self._device_consts()
            # Execution-time capture — see _dispatch_flowdict: proxy
            # FIFO order is the table-visibility order.
            with self._ident_lock:
                ident = self.ident
                fmap = self.filter_map
            t_x0 = time.perf_counter()
            # One batched put (wire + meta): separate puts each pay a
            # client round-trip on the tunnel backend.
            wire_dev, meta_dev = jax.device_put(
                (wire, meta), (self._rec_sharding, self._replicated)
            )
            wins, nvs, now_dev, lost_dev = self._ingest_fn(
                bucket, packed
            )(wire_dev, meta_dev)
            t0 = time.perf_counter()
            with self._state_lock:
                st = self.state
                for w in range(len(wins)):
                    st, _ = self.sharded.step(
                        st, wins[w], nvs[w], now_dev, ident,
                        self._api_dev, filter_map=fmap,
                        # Host-partition losses are folded into the
                        # device totals exactly once per flush.
                        lost=lost_dev if w == 0 else self._zero_u32,
                        sample_k=self._sampk(samp_k),
                    )
                self.state = st
            if record_metrics:
                # Warm-up dispatches (compile()) skip observation: a
                # one-shot 30-100s cold-compile sample would inflate
                # the histogram p99/max forever and seed transfer_bytes
                # with a synthetic zero batch.
                t_end = time.perf_counter()
                m.transfer_seconds.observe(t0 - t_x0)
                m.device_step_seconds.observe(t_end - t0)
                tid = fleet_epoch(self.cfg.window_seconds)
                self._recorder.record(
                    mnames.STAGE_TRANSFER, t_x0, tid, t1=t0
                )
                self._recorder.record(
                    mnames.STAGE_DEVICE_STEP, t0, tid, t1=t_end
                )
                # Overload signal: EWMA of transfer+step wall time
                # (proxy thread only — no lock needed).
                self._dispatch_lat_ewma = (
                    0.8 * self._dispatch_lat_ewma + 0.2 * (t_end - t_x0)
                )
                self._dispatch_lat_t = time.monotonic()
                # Fill of the step capacity actually dispatched
                # (windows x batch_capacity): identical to the
                # historical series for single-window batches, and
                # stays a 0..1 ratio for coalesced multi-window
                # transfers.
                m.device_batch_fill.set(
                    n_valid_total
                    / max(
                        self.n_devices
                        * self.cfg.batch_capacity
                        * len(wins),
                        1,
                    )
                )
                self._steps += len(wins)
                self._events_in += n_raw

        if sync:
            run_on_device(xfer_and_step)
            return

        def safe_xfer_and_step():
            try:
                xfer_and_step()
            except Exception as e:
                if self._count_error("device_step"):
                    self.log.exception("device step failed")
                get_metrics().lost_events.labels(
                    stage="device", plugin="engine"
                ).inc(n_events)
                if self._fatal_device_error(e):
                    self._request_recovery(repr(e))
            finally:
                with self._busy_lock:
                    self._inflight_busy -= 1
                self._inflight.release()

        self._inflight.acquire()
        with self._busy_lock:
            self._inflight_busy += 1
        submit_on_device(safe_xfer_and_step)

    def _win_stack(self, win):
        """(proxy thread) Stack the 3 per-dimension window outputs into
        one array so the device->host readback is ONE transfer (per-leaf
        device_get costs a link round-trip per array) and start the copy
        moving without blocking."""
        stacked = jnp.stack(
            [
                jnp.asarray(win["entropy_bits"], jnp.float32),
                jnp.asarray(win["anomaly"], jnp.float32),
                jnp.asarray(win["zscore"], jnp.float32),
            ]
        )
        try:
            stacked.copy_to_host_async()
        except Exception:  # noqa: RT101 — backend without async copy: harvest blocks
            pass
        return stacked

    def _publish_window(
        self,
        win_host: dict[str, np.ndarray],
        meta: dict | None = None,
    ) -> None:
        # ``meta`` is the overload annotation captured AT CLOSE TIME
        # (overload state, sampled_fraction, shed stages, raw events in
        # the window): a window closed under sampling says so forever,
        # however late its readback publishes.
        if meta is not None:
            win_host = dict(win_host)
            win_host["overload"] = meta
        self.last_window = win_host
        m = get_metrics()
        # Uptime rides the window-publish cadence (>= one update per
        # window_seconds) — cheap, and always fresh at scrape time.
        m.uptime_seconds.set(time.monotonic() - self._start_monotonic)
        dims = ["src_ip", "dst_ip", "dst_port"]
        for i, dim in enumerate(dims):
            m.entropy_bits.labels(dimension=dim).set(
                float(win_host["entropy_bits"][i])
            )
            m.anomaly_flag.labels(dimension=dim).set(
                float(win_host["anomaly"][i])
            )
            m.anomaly_zscore.labels(dimension=dim).set(
                float(win_host["zscore"][i])
            )
            if win_host["anomaly"][i]:
                # Counter survives scrape cadence: a 0.2s anomalous
                # window must be visible at a 30s scrape.
                m.anomaly_windows.labels(dimension=dim).inc()
        flagged = [
            dim for i, dim in enumerate(dims)
            if i < len(win_host["anomaly"]) and win_host["anomaly"][i]
        ]
        if flagged and self.anomaly_hook is not None:
            # Closed-loop capture pivot (timetravel/autocapture.py):
            # notify only enqueues — the harvest thread never waits on
            # attribution or capture work.
            try:
                self.anomaly_hook(
                    fleet_epoch(self.cfg.window_seconds), flagged
                )
            except Exception:
                if self._count_error("anomaly_hook"):
                    self.log.exception("anomaly hook failed")

    def _ensure_harvest_thread(self) -> None:
        # Spawn-vs-retire is serialized by _harvest_lock: without it a
        # straggler close could pass the retired check, lose the CPU,
        # and spawn a fresh thread AFTER shutdown consumed the None
        # sentinel — a thread that parks on the queue forever, pinning
        # the engine object graph (ADVICE r5).
        with self._harvest_lock:
            if self._harvest_retired:
                return
            if (
                self._harvest_thread is None
                or not self._harvest_thread.is_alive()
            ):
                gen = self._harvest_gen
                self._harvest_thread = threading.Thread(
                    target=self._harvest_loop, args=(gen,),
                    name="window-harvest", daemon=True,
                )
                self._harvest_thread.start()

    def _restart_harvest(self) -> None:  # runs-on: watchdog
        """Watchdog escalation for a hung harvest thread (a wedged
        device_get on a dead link can block indefinitely): supersede it
        by bumping the generation and spawn a replacement. The hung
        instance exits at its next generation check instead of racing
        the replacement for the queue; its in-flight item publishes
        late (or never) — window gauges are refreshed by every later
        window, so staleness self-heals."""
        with self._harvest_lock:
            if self._harvest_retired:
                return
            self._harvest_gen += 1
            self._harvest_thread = None
        get_metrics().thread_restarts.labels(thread="window-harvest").inc()
        self.log.error(
            "harvest thread stalled; superseding with a replacement "
            "(gen %d)", self._harvest_gen,
        )
        self._ensure_harvest_thread()

    def _harvest_loop(self, gen: int) -> None:
        """(harvest thread) Block on each closed window's device->host
        readback and publish its gauges. Runs OFF the device-proxy
        thread: on backends without async D2H copies (the tunnel) the
        device_get blocks for a full link round-trip per window, which
        measured as ~80% of steady-state proxy wall clock when the
        harvest ran proxy-side — parking every queued step behind
        scrape-cadence gauge traffic. FIFO order preserves window
        order.

        ``gen`` is this instance's generation: when the watchdog
        supersedes a hung instance (_restart_harvest), the stale one
        exits at its next check instead of competing for the queue."""
        hb = self._register_hb(
            "window-harvest", on_stall=self._restart_harvest
        )
        while True:
            hb.park()
            try:
                item = self._harvest_q.get(timeout=1.0)
            except queue_mod.Empty:
                if self._harvest_gen != gen:
                    return  # superseded while idle
                continue
            hb.beat()
            try:
                if item is None:
                    return
                kind, stacked, meta = item
                faults.inject("harvest")
                if kind == "zero":
                    z = np.zeros((3,), np.float32)
                    self._publish_window({
                        "entropy_bits": z, "anomaly": z, "zscore": z,
                    }, meta)
                else:
                    # fetch_on_device, NOT a direct device_get: every
                    # JAX call must ride the proxy thread (tunnel
                    # backend wedges under concurrent runtime access),
                    # but the queue-wait happens here, off-proxy.
                    tid = fleet_epoch(self.cfg.window_seconds)
                    t_h0 = time.perf_counter()
                    host = fetch_on_device(stacked)
                    self._recorder.record(
                        mnames.STAGE_HARVEST, t_h0, tid
                    )
                    t_p0 = time.perf_counter()
                    self._publish_window({
                        "entropy_bits": host[0],
                        "anomaly": host[1],
                        "zscore": host[2],
                    }, meta)
                    self._recorder.record(
                        mnames.STAGE_PUBLISH, t_p0, tid
                    )
                    inv_dec = meta.pop("inv_decode", None)
                    if inv_dec is not None:
                        self._harvest_invertible(inv_dec)
            except Exception:
                if self._count_error("harvest_readback"):
                    self.log.exception("window readback failed")
            finally:
                self._harvest_q.task_done()
            if self._harvest_gen != gen:
                # Superseded mid-item (the watchdog already spawned a
                # replacement): bow out after finishing this one.
                return

    def _harvest_invertible(self, dec) -> None:  # runs-on: window-harvest
        """Read back one window's invertible decode, dedupe (a key can
        decode from up to D row-buckets), publish tpu_invertible_*
        gauges, and — in "both" mode — score recall/precision against
        the host flow-dict ground truth (_hk_account)."""
        ok = np.asarray(fetch_on_device(dec["ok"]), bool)
        keys = np.asarray(fetch_on_device(dec["keys"]))[ok]
        est = np.asarray(fetch_on_device(dec["est"]))[ok]
        tier = np.asarray(fetch_on_device(dec["tier"]))[ok]
        if len(keys):
            uniq, idx = np.unique(keys, axis=0, return_index=True)
            keys, est, tier = uniq, est[idx], tier[idx]
        m = get_metrics()
        m.invertible_keys_recovered.set(len(keys))
        with self._inv_lock:
            self._inv_last = {"keys": keys, "est": est, "tier": tier}
        if self._hk_counts is None:
            return
        thr = max(1, int(self.cfg.invertible_min_weight))
        with self._fd_lock:
            truth = dict(self._hk_counts)
        heavy = {k for k, v in truth.items() if v >= thr}
        rec = {k.tobytes() for k in keys}
        if heavy:
            m.invertible_recall.set(len(heavy & rec) / len(heavy))
        if rec:
            m.invertible_precision.set(
                sum(1 for k in rec if truth.get(k, 0) >= thr) / len(rec)
            )

    def invertible_report(self) -> dict:
        """Latest window's recovered heavy-key set (host arrays):
        ``keys (N, 4) u32`` rows of (src_ip, dst_ip, ports, proto),
        ``est (N,)`` CMS count estimates, ``tier (N,)`` (0 = main
        region, 1 = priority region). Empty arrays before the first
        decoded window or when invertible is disabled."""
        with self._inv_lock:
            last = self._inv_last
        if last is None:
            return {
                "keys": np.zeros((0, 4), np.uint32),
                "est": np.zeros((0,), np.uint32),
                "tier": np.zeros((0,), np.uint32),
            }
        return dict(last)

    def _harvest_window(self, timeout: float | None = None) -> None:
        """Drain pending window readbacks (shutdown / tests): returns
        once every window enqueued so far has published, or after
        ``timeout`` (default cfg.harvest_timeout_s — a wedged link must
        not hang shutdown)."""
        if timeout is None:
            timeout = self.cfg.harvest_timeout_s
        deadline = time.monotonic() + timeout
        while (
            self._harvest_q.unfinished_tasks
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)

    def _close_window(self) -> None:
        """End the entropy/anomaly window (self-proxying: the body —
        including the harvest's device_get — always executes on the
        device-proxy thread, whatever thread calls this)."""
        run_on_device(self._close_window_impl)

    def _close_window_impl(self) -> None:  # hot-path: close
        """(proxy thread) End the entropy/anomaly window. Runs as a
        fire-and-forget proxy submission from the dispatch worker, so it
        stays ordered after the step submissions that fed the window.

        The close only DISPATCHES end_window and hands the stacked
        result to the harvest thread — the blocking device->host
        readback happens there (:meth:`_harvest_loop`), never on the
        proxy. Gauges publish as soon as the copy lands (typically well
        inside the window interval)."""
        # Idle fast path: end_window SKIPS empty windows on-device (no
        # flag, no baseline update — AnomalyEWMA.observe active gating),
        # so when nothing arrived since the last close the dispatch +
        # readback round-trip is pure waste; an idle agent then costs
        # zero device traffic between scrapes.
        wt = self._warm_thread
        if (
            wt is not None
            and wt.is_alive()
            and not self._close_warmed.is_set()
        ):
            # The close program is still queued as the background
            # warm's FIRST job. Running end_window here would
            # cold-compile it inline on the proxy mid-feed — the
            # multi-second stall episodes r05 measured. Defer: the
            # window simply stays open (every event intact) and the
            # next tick closes a longer window against the then-warm
            # program. Bounded by the warm thread's own lifetime — a
            # dead or finished warm never defers a close.
            get_metrics().windows_deferred.inc()
            return
        if self._degraded.is_set():
            # Crash-only recovery in flight: the state is mid-rebuild;
            # defer exactly like the warm case — the window stays open
            # and the next tick closes it against recovered state.
            get_metrics().windows_deferred.inc()
            return
        if self._events_in == self._closed_events_in:
            get_metrics().windows_closed.inc()
            # Mirror what a real empty close reports (flag 0, z 0,
            # entropy 0) so a flag raised by the LAST active window
            # doesn't latch on an idle node. Routed through the harvest
            # queue, NOT set directly: a still-pending active window's
            # readback publishing after a direct zeroing would re-latch
            # the stale flag — FIFO through one queue keeps publish
            # order = close order.
            meta = self._overload.window_annotation()
            meta["events"] = 0  # idle, not stalled: nothing arrived
            self._ensure_harvest_thread()
            self._harvest_q.put(("zero", None, meta))
            return
        ingested = self._events_in
        # Annotation snapshot BEFORE _closed_events_in advances: the
        # raw-event count this window actually ingested, plus the
        # controller's per-window sampling accounting. A window closed
        # while sampling is NEVER reported as empty — its event count
        # and sampled_fraction say exactly what was kept.
        meta = self._overload.window_annotation()
        meta["events"] = ingested - self._closed_events_in

        def close():
            t_c0 = time.perf_counter()
            self._device_consts()
            with self._state_lock:
                if (self._fleet_shipper is not None
                        or self._tt_ring is not None):
                    # Export MUST dispatch before end_window: end_window
                    # resets the entropy window and donates the state
                    # buffers, so this is the last moment the closing
                    # window's sketches exist on device. Pure dispatch —
                    # one export feeds both the fleet shipper and the
                    # time-travel ring; their workers do the blocking
                    # readback off the proxy, and offer() never blocks.
                    try:
                        export = self.sharded.fleet_export(self.state)
                        epoch = fleet_epoch(self.cfg.window_seconds)
                        seeds = self.sharded.fleet_seeds(self.state)
                        if self._fleet_shipper is not None:
                            self._fleet_shipper.offer(
                                epoch, export,
                                self.cfg.window_seconds, seeds,
                            )
                        if self._tt_ring is not None:
                            self._tt_ring.offer(
                                epoch, export,
                                self.cfg.window_seconds, seeds,
                            )
                    except Exception:
                        get_metrics().fleet_ship_errors.inc()
                        if self._count_error("fleet_export"):
                            self.log.exception("fleet export failed")
                inv = None
                if self.pcfg.enable_invertible:
                    # Same before-end_window contract as the fleet
                    # export: decode reads the closing window's sketch
                    # state, end_window donates it. Pure dispatch; the
                    # harvest thread does the blocking readback.
                    try:
                        inv = self.sharded.inv_decode(
                            self.state, self.cfg.invertible_min_weight
                        )
                    except Exception:
                        get_metrics().invertible_decode_failed.inc()
                        if self._count_error("inv_decode"):
                            self.log.exception("invertible decode failed")
                self.state, win = self.sharded.end_window(
                    self.state, self._zthresh
                )
            self._recorder.record(
                mnames.STAGE_WINDOW_CLOSE, t_c0,
                fleet_epoch(self.cfg.window_seconds),
                t1=time.perf_counter(),
            )
            return self._win_stack(win), inv

        stacked, inv_dec = run_on_device(close)
        # Advance only after a SUCCESSFUL dispatch: if end_window
        # raised, the next tick must retry this window, not skip it
        # forever.
        self._closed_events_in = ingested
        if inv_dec is not None:
            meta["inv_decode"] = inv_dec
        self._ensure_harvest_thread()
        self._harvest_q.put(("win", stacked, meta))
        get_metrics().windows_closed.inc()

    def _submit_close_window(self) -> None:  # hot-path: close
        """Fire-and-forget window close on the PROTECTED close lane:
        FIFO-ordered after step submissions on the proxy queue, but
        bounded by its own semaphore — a step pipeline that has eaten
        every in-flight slot can never starve a window tick of a
        submission slot (overload contract: a window is always closed,
        possibly annotated, never silently skipped). Non-blocking: when
        both close slots are in flight behind a stalled link, the tick
        defers (counted) and the next tick closes a longer window."""

        def safe_close():
            try:
                self._close_window()
            except Exception as e:
                if self._count_error("window_close"):
                    self.log.exception("window close failed")
                if self._fatal_device_error(e):
                    self._request_recovery(repr(e))
            finally:
                self._close_inflight.release()

        if not self._close_inflight.acquire(blocking=False):
            get_metrics().windows_deferred.inc()
            return
        submit_on_device(safe_close)

    def _resolve_feed_workers(self) -> int:
        """Feed-worker count: config value, or auto-size to the machine
        (cores minus one for the distributor+dispatch threads, capped at
        4 — staging memory and combine-lock contention grow past that
        with no measured throughput gain). 1 means inline feed."""
        n = self.cfg.feed_workers
        if n <= 0:
            cores = os.cpu_count() or 1
            n = max(1, min(4, cores - 1))
        return n

    def _busy_count(self) -> int:  # runs-on: feed-worker*
        """In-flight dispatch count for feed-worker interval-flush
        gating (same signal the inline feed loop reads)."""
        with self._busy_lock:
            return self._inflight_busy

    # -- adaptive overload control (runtime/overload.py) --------------
    def _overload_signals(self) -> dict[str, float]:
        """Normalized [0,1] pressure signals for the overload
        controller — the max across them is the pipeline pressure.
        Called from the feed loop at tick cadence; every read here is
        lock-free or a single counter load."""
        sig: dict[str, float] = {}
        pool = self._feed_pool
        now = time.monotonic()
        if pool is not None:
            # Worst per-worker staging fill: the first queue to
            # overflow decides when blocks start dropping.
            sig["staging"] = pool.max_staging_fill()
            # Handoff wait RATE (seconds waited per second): workers
            # blocked on a full transfer queue mean the device side
            # can't keep up even though staging still has room.
            wait = pool.handoff_wait_total()
            dt = max(now - self._ov_wait_t, 1e-6)
            sig["handoff_wait"] = min(
                1.0, max(0.0, wait - self._ov_wait_prev) / dt
            )
            self._ov_wait_prev = wait
            self._ov_wait_t = now
        depth = max(1, self.cfg.feed_pipeline_depth)
        sig["inflight"] = min(1.0, self._busy_count() / depth)
        # Harvest lag: closed windows whose readback hasn't landed.
        sig["harvest"] = min(
            1.0, self._harvest_q.unfinished_tasks / 4.0
        )
        # Dispatch latency EWMA against the window budget: device
        # steps eating a whole window interval starve the close lane.
        # A stale sample (no dispatch for >2 windows) means idle, not
        # slow — without the age gate the frozen EWMA would hold the
        # controller above the exit threshold forever.
        if now - self._dispatch_lat_t <= 2.0 * self.cfg.window_seconds:
            sig["dispatch_lat"] = min(
                1.0,
                self._dispatch_lat_ewma
                / max(0.5 * self.cfg.window_seconds, 1e-3),
            )
        # Chaos/bench injection (runtime/faults.py feed.backpressure):
        # a sustained synthetic pressure signal so tests drive the
        # NOMINAL -> SAMPLING -> SHEDDING arc without having to
        # actually saturate the host. 0.95 sits between the shed (0.90)
        # and degrade (0.98) thresholds: DEGRADED stays reserved for
        # real saturation / crash-only recovery.
        if faults.pressure("feed.backpressure"):
            sig["fault"] = 0.95
        # Crash-only recovery pins the controller at DEGRADED for the
        # duration (drop-and-count is the ultimate shed).
        if self._degraded.is_set():
            sig["degraded"] = 1.0
        return sig

    @property
    def overload(self) -> OverloadController:
        """The controller itself (plugins/modules call note_shed on
        it; tests drive tick with injected clocks)."""
        return self._overload

    def shed_active(self, stage: str) -> bool:
        """Plugins/modules consult this before enrichment work (dns
        qname hashing, conntrack scrape, label resolution)."""
        return self._overload.shed_active(stage)

    def overload_stats(self) -> dict[str, Any]:
        """Controller state for the control server's debug var and the
        bench diag."""
        return self._overload.stats()

    def _build_quantum(  # runs-on: feed-worker*  # hot-path: event
        self, blocks: list[np.ndarray], n_raw: int, now_s: int
    ) -> list[tuple]:
        """Combine + partition one flush quantum into dispatchable step
        items. Pure host work, shared by the inline flush and the feed
        workers (parallel/feed.py), where it runs concurrently — the
        native combiner releases the GIL and partition is numpy."""
        cap = self.cfg.batch_capacity * self.n_devices
        coal = cap * max(1, self.cfg.feed_coalesce_windows)
        coal_per_dev = self.cfg.batch_capacity * max(
            1, self.cfg.feed_coalesce_windows
        )
        t_cb0 = self._recorder.begin()
        if self.cfg.host_combine:
            all_rec = combine_blocks(blocks)
            get_metrics().combine_ratio.set(
                n_raw / max(len(all_rec), 1)
            )
        elif len(blocks) == 1:
            all_rec = blocks[0]
        else:
            all_rec = np.concatenate(blocks, axis=0)
        self._recorder.record(
            mnames.STAGE_COMBINE, t_cb0,
            fleet_epoch(self.cfg.window_seconds),
        )
        if self.record_hook is not None:
            try:
                self.record_hook(all_rec, now_s)
            except Exception:
                self._count_error("record_hook")
        # Overload sampling sits POST-combine / PRE-partition: a row's
        # packet weight is final here, so the device step can recompute
        # the same exemption predicate over the same rows and rescale
        # the non-exempt survivors by k (Horvitz-Thompson — see
        # runtime/overload.py). k rides the ShardedBatch to the
        # dispatch paths.
        all_rec, samp_k = self._overload.sample_rows(all_rec)
        items: list[tuple] = []
        for off in range(0, len(all_rec), coal):
            chunk = all_rec[off : off + coal]
            sb = partition_events(
                chunk, self.n_devices, coal_per_dev,
                min_bucket=self.cfg.transfer_min_bucket,
            )
            sb.sample_k = samp_k
            # raw-row accounting goes to the chunk that carries it;
            # chunk boundaries are an implementation detail
            items.append(("step", sb, now_s, n_raw if off == 0 else 0))
        return items

    def feed_stats(self) -> dict[str, Any]:
        """Feed-path self-observability for the control server's
        ``feed`` debug var and bench result JSON: per-worker fill /
        staged backlog / handoff wait, pool drop counters, and the
        flow-dict residency summary."""
        pool = self._feed_pool
        if pool is not None:
            st = pool.stats()
        else:
            st = {"workers": 0, "mode": "inline", "per_worker": []}
        st["flow_dict"] = flow_dict_stats(self._flow_dict)
        st["overload"] = self._overload.stats()
        return st

    def _dispatch_loop(self, q) -> None:
        """Dispatch thread: packs partitioned steps and submits them (and
        window closes) to the device proxy in feed order, without waiting
        for the device round-trip. Packing batch N+1 here overlaps batch
        N's in-flight transfer on the proxy thread, and the bounded proxy
        backlog keeps the host->device link busy back-to-back
        (VERDICT r2 weak #1, r3 weak #1). ``q`` is either the inline
        feed's queue.Queue or a feed-pool TransferMux — both block on
        ``get()`` and deliver ``None`` as the shutdown sentinel. The
        bounded-timeout get keeps the watchdog heartbeat honest: the
        thread parks before each wait and beats only when processing."""
        hb = self._register_hb("engine-dispatch")
        try:
            while True:
                hb.park()
                try:
                    item = q.get(timeout=1.0)
                except queue_mod.Empty:
                    continue
                hb.beat()
                if item is None:
                    return
                kind, payload, now_s, n_raw = item
                try:
                    if kind == "step":
                        self._dispatch_sharded(
                            payload, now_s, n_raw, sync=False
                        )
                    else:
                        self._submit_close_window()
                except Exception:
                    if self._count_error("dispatch"):
                        self.log.exception("%s dispatch failed", kind)
        finally:
            self._deregister_hb("engine-dispatch")

    def start(self, stop: threading.Event) -> None:
        """Feed loop: drain sink → combine → partition → device; close
        windows on time.

        Sits where Enricher.Run + Module.run sit in the reference
        (enricher.go:68-99, metrics_module.go:266-330). With
        ``feed_pipeline_depth > 0`` the device_put + step dispatch run on
        a separate thread behind a bounded queue, so batch N's transfer
        overlaps batch N+1's host-side prep; the queue is the only
        blocking edge (backpressure then reaches the bounded sink, which
        drops and counts — never the producers)."""
        self.started.set()
        if self._fleet_shipper is not None:
            self._fleet_shipper.start()
        if self._tt_ring is not None:
            self._tt_ring.start()
        cap = self.cfg.batch_capacity * self.n_devices
        # Flush threshold: accumulating beyond one device batch raises the
        # combine ratio (more duplicate descriptors per pass); the
        # interval timeout still bounds latency. Coalescing into device
        # batches happens inside _build_quantum.
        quantum = max(cap, self.cfg.flush_max_events)
        depth = self.cfg.feed_pipeline_depth
        # Sharded multi-worker feed (parallel/feed.py): with more than
        # one resolved worker, this loop becomes the DISTRIBUTOR — it
        # drains the sink, runs observers, and deals blocks to the
        # workers, which combine+partition in parallel and hand
        # finished batches to the dispatch thread through the pool's
        # double-buffered transfer mux. Flow-dict/wire/submit stay on
        # the one dispatch thread (v3 ordering contract). Per-worker
        # quantum splits the configured flush quantum so total staged
        # latency stays put as workers scale.
        n_workers = self._resolve_feed_workers() if depth > 0 else 0
        q: Any = None
        worker: threading.Thread | None = None
        pool: FeedWorkerPool | None = None
        inline_tq: TransferQueue | None = None
        if depth > 0 and n_workers <= 1:
            # Inline mode rides the same mux shape as the pool: step
            # items through one bounded TransferQueue, window ticks
            # through the control lane — the protected-lane contract
            # (window closes stay on cadence even under a step
            # backlog) holds in BOTH feed modes.
            inline_data = threading.Event()
            inline_tq = TransferQueue(depth, inline_data)
            q = TransferMux([inline_tq], inline_data)

        def drop_item(item):
            """Dead-worker path: account the loss, never enqueue into a
            queue nobody drains (silent vanishing)."""
            self.log.error("dispatch worker dead; dropping %s", item[0])
            if item[0] == "step":
                # Packet-weighted, like every other loss site: a
                # combined row stands for many events. Include the
                # batch's partition-overflow losses too — they are
                # normally counted inside _dispatch_sharded, which will
                # never run for a dropped item.
                get_metrics().lost_events.labels(
                    stage="dispatch", plugin="engine"
                ).inc(int(item[1].events) + int(item[1].lost))

        def submit(item):
            if q is not None:
                if item[0] != "step":
                    # Window/control items (both feed modes) ride the
                    # mux control lane: closes overtake the step
                    # backlog and stay on cadence under overload.
                    if worker is None or not worker.is_alive():
                        drop_item(item)
                    else:
                        q.put_ctl(item)
                else:
                    # Inline mode only (pool workers hand step items
                    # off directly). Block only while the worker
                    # lives: if it died (fatal runtime error escaping
                    # its catch), drop + count rather than wedging the
                    # feed loop on a full queue forever.
                    if not inline_tq.put(
                        item, alive=lambda: worker.is_alive()
                    ):
                        drop_item(item)
            elif item[0] == "step":
                self._dispatch_sharded(item[1], item[2], item[3])
            else:
                # Fire-and-forget close on the protected lane, same as
                # pipeline mode: the proxy FIFO still orders it after
                # every step submitted before the tick, but the feed
                # loop no longer waits out the device round-trip — a
                # blocking close here serialized the feed for the full
                # end_window dispatch and was the single biggest
                # stall-window source in depth==0 runs (BENCH_r05
                # 0.00M windows). Errors are handled inside the
                # submission (safe_close), including fatal-device
                # recovery.
                self._submit_close_window()

        if depth > 0:
            if n_workers > 1:
                pool = FeedWorkerPool(
                    n_workers=n_workers,
                    quantum=max(cap, quantum // n_workers),
                    staging_blocks=self.cfg.feed_staging_blocks,
                    flush_interval_s=self.cfg.flush_interval_s,
                    flush_max_age_s=self.cfg.flush_max_age_s,
                    build_steps=self._build_quantum,
                    drop=drop_item,
                    busy=self._busy_count,
                    alive=lambda: (
                        worker is not None and worker.is_alive()
                    ),
                    register_hb=self._register_hb,
                    deregister_hb=self._deregister_hb,
                    restart_policy=lambda name: policy_from_config(
                        self.cfg, seed_key=name
                    ),
                )
                self._feed_pool = pool
                q = pool.mux
            worker = threading.Thread(
                target=self._dispatch_loop, args=(q,),
                name="engine-dispatch", daemon=True,
            )
            worker.start()
            if pool is not None:
                pool.start()

        m = get_metrics()
        pending: list[np.ndarray] = []
        n_pending = 0
        last_flush = time.monotonic()
        next_window = time.monotonic() + self.cfg.window_seconds

        feed_trace = self._feed_trace
        trace_acc = {"accum": 0.0, "build": 0.0,
                     "submit": 0.0, "n": 0, "ev": 0}
        t_flush_end = time.monotonic()

        def flush():
            nonlocal pending, n_pending, last_flush, t_flush_end
            t0 = time.monotonic()
            n_raw = n_pending
            blocks = pending
            pending = []
            n_pending = 0
            last_flush = time.monotonic()
            # Shared combine+sample+partition path (_build_quantum) —
            # the SAME code the feed workers run, so overload sampling
            # applies identically in inline mode.
            items = self._build_quantum(blocks, n_raw, int(time.time()))
            t1 = time.monotonic()
            for item in items:
                submit(item)
            if feed_trace:
                t3 = time.monotonic()
                trace_acc["accum"] += t0 - t_flush_end
                trace_acc["build"] += t1 - t0
                trace_acc["submit"] += t3 - t1
                trace_acc["n"] += 1
                trace_acc["ev"] += n_raw
                t_flush_end = t3
                if trace_acc["n"] % 8 == 0:
                    per = {k: trace_acc[k] / trace_acc["n"]
                           for k in ("accum", "build", "submit")}
                    self.log.info(
                        "feed trace: %d flushes, %.2fM ev/flush, "
                        "accum %.0fms build %.0fms submit %.0fms",
                        trace_acc["n"],
                        trace_acc["ev"] / trace_acc["n"] / 1e6,
                        per["accum"] * 1e3, per["build"] * 1e3,
                        per["submit"] * 1e3,
                    )

        hb_feed = self._register_hb("engine-feed")
        try:
            while not stop.is_set():
                hb_feed.beat()
                # Overload controller tick: cheap no-op inside
                # overload_tick_s; transitions happen here, on the one
                # thread that sees every block.
                self._overload.tick()
                blocks = self.sink.drain(max_blocks=64)
                shed_dns = self._overload.shed_active("dns")
                # Span covers the emit handoff: generator blocks leave
                # the sink and are dealt into the feed (observers +
                # staging) — begin() only when there IS a drain, so an
                # idle spin never burns sampling ticks.
                t_g0 = self._recorder.begin() if blocks else 0.0
                for rec, plugin in blocks:
                    for obs, oname in self._observers:
                        if shed_dns and oname == "dns":
                            # SHEDDING: dns qname hashing is the first
                            # enrichment stage dropped — raw events
                            # still reach the device untouched.
                            self._overload.note_shed("dns", len(rec))
                            continue
                        try:
                            obs(rec, plugin)
                        except Exception:
                            # Observers run per block — a persistently
                            # failing one must not log at feed rate.
                            if self._count_error("observer"):
                                self.log.exception("observer failed")
                    if pool is not None:
                        # Sharded mode: deal the block to a worker and
                        # move on — the distributor NEVER blocks on a
                        # saturated pool (backpressure contract: drop
                        # and count, packet-weighted like every other
                        # loss site).
                        if not pool.stage(rec):
                            pool.count_drop(len(rec))
                            m.lost_events.labels(
                                stage="handoff", plugin="engine"
                            ).inc(int(rec[:, F.PACKETS].sum()))
                        continue
                    pending.append(rec)
                    n_pending += len(rec)
                    # Flush in bounded quanta AS blocks accumulate: a
                    # backlogged sink must never turn into one multi-GB
                    # concat+combine — each flush handles at most one
                    # quantum plus a block's worth of overshoot.
                    if n_pending >= quantum:
                        flush()
                if blocks:
                    self._recorder.record(
                        mnames.STAGE_GENERATOR_EMIT, t_g0,
                        fleet_epoch(self.cfg.window_seconds),
                    )
                now = time.monotonic()
                if n_pending and now - last_flush >= self.cfg.flush_interval_s:
                    # Interval flushes serve LATENCY and only make sense
                    # when the dispatch pipeline is idle; with work in
                    # flight, keep accumulating (bigger quanta combine
                    # harder and amortize per-flush fixed costs) up to
                    # the hard age bound. Without this gate the fast
                    # async pipeline settles into many tiny flushes
                    # whose fixed costs cap throughput.
                    with self._busy_lock:
                        busy = self._inflight_busy
                    if busy == 0 or (
                        now - last_flush >= self.cfg.flush_max_age_s
                    ):
                        flush()
                if now >= next_window:
                    submit(("window", None, 0, 0))
                    # Batched tick: one close per catch-up, however many
                    # boundaries a stall skipped. Advancing by the missed
                    # count keeps the cadence phase-locked to the start
                    # time (ticks do not drift later under load) without
                    # queueing a burst of back-to-back closes on the ctl
                    # lane after the stall clears.
                    n_missed = int(
                        (now - next_window) // self.cfg.window_seconds
                    )
                    next_window += (
                        (n_missed + 1) * self.cfg.window_seconds
                    )
                if not blocks:
                    stop.wait(0.002)
        finally:
            hb_feed.park()
            self._deregister_hb("engine-feed")
            if pool is not None:
                # Stop the workers FIRST so their final flushes land in
                # the transfer mux, then send the sentinel down the
                # control lane — the mux hands it to the dispatch
                # thread only after every worker queue drains, so
                # nothing staged at shutdown is silently lost.
                pool.stop(timeout=30.0)
                q.put_ctl(None)
                worker.join(timeout=30.0)
            elif q is not None:
                # Mux sentinel: delivered only after the step queue
                # drains (same contract as pool mode), and put_ctl
                # never blocks — the join timeout bounds a wedged
                # worker.
                q.put_ctl(None)
                worker.join(timeout=30.0)
            # Drain fire-and-forget submissions (FIFO fence) so the
            # state a follow-up checkpoint saves includes every batch
            # submitted before shutdown. Bounded like the queue/join
            # above: a wedged proxy must not hang shutdown forever.
            if not fence(timeout=60.0):
                self.log.error(
                    "device proxy did not drain within 60s at shutdown"
                )
            else:
                # Publish the final window's pending readback so
                # shutdown gauges aren't one window stale.
                try:
                    self._harvest_window()
                except Exception:
                    self._count_error("harvest_final")
                    self.log.exception("final window harvest failed")
            # Retire the harvest thread (it closes over self: left
            # parked on the queue it would pin the engine object graph
            # across restart cycles). Join the background warm FIRST —
            # a warm key in flight past its stop check could otherwise
            # enqueue one more window after the sentinel; the retired
            # flag then stops _ensure_harvest_thread from resurrecting
            # the thread for any straggler that still slips through.
            if self._warm_thread is not None:
                self._warm_thread.join(timeout=30.0)
            with self._harvest_lock:
                self._harvest_retired = True
                ht = self._harvest_thread
            if ht is not None:
                self._harvest_q.put(None)
                ht.join(timeout=5.0)
            # Stop the fleet shipper AFTER the fence: the final close's
            # export is already queued by then, so the last window still
            # ships before the worker parks.
            if self._fleet_shipper is not None:
                self._fleet_shipper.stop()
            # Same ordering for the time-travel ring: the final close's
            # export is queued before the fence returns.
            if self._tt_ring is not None:
                self._tt_ring.stop()

    @property
    def timetravel_ring(self):
        """The engine's snapshot ring (None unless timetravel_enabled);
        the daemon wires it into the QueryService."""
        return self._tt_ring

    # -- scrape-time readout -----------------------------------------
    def snapshot(self, max_age_s: float = 0.5) -> dict[str, Any]:
        """Merged numpy snapshot, cached up to ``max_age_s`` (scrape
        latency budget: <1s per BASELINE)."""
        now = time.monotonic()
        with self._snap_lock:
            if self._snap_cache is not None and now - self._snap_time < max_age_s:
                return self._snap_cache
        # Single-flight: with the fire-and-forget feed pipeline the
        # proxy queue may hold several in-flight transfers ahead of this
        # snapshot; concurrent readers must share ONE queued readback
        # (each re-checks the cache after acquiring), not pile N of them
        # behind the backlog.
        with self._snap_flight:
            with self._snap_lock:
                if (
                    self._snap_cache is not None
                    and time.monotonic() - self._snap_time < max_age_s
                ):
                    return self._snap_cache

            def snap_dispatch():
                # ONE device->host transfer for the whole tree (leaves
                # are concatenated on device): per-leaf readback paid a
                # full link round trip per array — measured 2.7-21s at
                # production shapes on a congested link vs the <1s
                # scrape budget. Only the DISPATCH runs on the proxy
                # (ordered against in-flight steps; later donating
                # steps execute after it on the device stream); the
                # queue-wait for the result happens on THIS thread via
                # fetch_on_device's readiness polling, so scrape/GC
                # traffic never parks the step pipeline — while every
                # actual JAX call still rides the proxy (tunnel backend
                # wedges under concurrent runtime access).
                with self._state_lock:
                    return self.sharded.snapshot_flat_dispatch(
                        self.state, int(time.time())
                    )

            flat_dev = run_on_device(snap_dispatch)
            flat_host = fetch_on_device(flat_dev)
            host = self.sharded.snapshot_flat_finish(flat_host)
            get_metrics().readback_bytes.inc(int(flat_host.nbytes))
            host["steps"] = self._steps
            host["events_in"] = self._events_in
            with self._snap_lock:
                self._snap_cache = host
                self._snap_time = time.monotonic()
            return host

    def top_flows(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "flow_hh", k)

    def top_services(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "svc_hh", k)

    def top_dns(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "dns_hh", k)

    def conntrack_gc(self) -> dict[str, int]:
        """Scrape conntrack liveness + accounting (expiry itself is
        timestamp-based in the table — the GC 'loop' is an accounting
        pass, like the reference GC summing conntrackmetadata while
        iterating the map, conntrack_linux.go:95-163).

        packets/bytes are the cumulative totals carried by conntrack
        reports, reassembled from per-device two-limb u32 counters.
        """
        snap = self.snapshot(max_age_s=5.0)
        totals = snap["totals"]
        ctt = np.asarray(snap["ct_totals"]).reshape(-1, 4).astype(np.uint64)
        pkts = int((ctt[:, 0] + (ctt[:, 1] << np.uint64(32))).sum())
        byts = int((ctt[:, 2] + (ctt[:, 3] << np.uint64(32))).sum())
        return {
            "active": int(snap["active_conns"]),
            "reports": int(totals[6]),
            "packets": pkts,
            "bytes": byts,
        }

    # -- checkpoint/resume (reference: pinned BPF maps survive agent
    # restarts, pkg/bpf/setup_linux.go; SURVEY.md §5.4) ---------------
    def save_snapshot_state(self, path: str) -> None:
        from retina_tpu.checkpoint import save_state

        def save():
            # Snapshot the reference only: state is replaced
            # functionally (never mutated in place), so the file write
            # — seconds of IO — must not hold _state_lock and convoy
            # the dispatch/close lanes behind it (RT403).
            with self._state_lock:
                state = self.state
            save_state(path, state, self.pcfg)

        run_on_device(save)

    def load_snapshot_state(self, path: str) -> bool:
        """Restore sketch state from ``path``. Crash-only: a missing or
        unusable checkpoint cold-starts (quarantined by load_state) —
        returns True only when state was actually resumed."""
        from retina_tpu.checkpoint import load_state

        def load():
            state, resumed = load_state(path, self.sharded, self.pcfg)
            with self._state_lock:
                self.state = state
            return resumed

        return run_on_device(load)

"""SketchEngine: the TPU worker that replaces the CPU aggregation loop.

Reference analog (what this replaces, SURVEY.md §3.2): the enricher output
ring → ``Module.run`` goroutine calling every metric's ``ProcessFlow`` per
flow (metrics_module.go:283-303) — single-threaded CPU hash aggregation,
the scaling bottleneck. Per the BASELINE north star, this engine is the
"tpusketch" plugin's backend: plugins feed fixed-width record blocks into
a bounded queue (QueueSink), the feed loop batches them into fixed-shape
device arrays, and ONE jit-compiled step updates every aggregator. Sharded
over a ``jax.sharding.Mesh`` when more than one device is available
(parallel/telemetry.py); scrape-time snapshots merge with psum/pmax/
all_gather over ICI.

Backpressure contract (the reference's universal rule,
packetparser_linux.go:692-697): never block a producer — drop and count.
Snapshot contract: scrapes read a cached merged snapshot at most
``snapshot_max_age_s`` old (<1s target, BASELINE) and never stall the feed
loop; JAX dispatch is async so the feed thread keeps the device busy while
snapshot results transfer back.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from retina_tpu.config import Config
from retina_tpu.events.schema import NUM_FIELDS
from retina_tpu.log import logger
from retina_tpu.metrics import get_metrics
from retina_tpu.models.identity import HostIdentityTable, IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline
from retina_tpu.parallel.partition import partition_events
from retina_tpu.parallel.telemetry import ShardedTelemetry, topk_from_snapshot
from retina_tpu.plugins.api import QueueSink


def pipeline_config_from(cfg: Config) -> PipelineConfig:
    return PipelineConfig(
        n_pods=cfg.n_pods,
        cms_width=cfg.cms_width,
        cms_depth=cfg.cms_depth,
        topk_slots=cfg.topk_slots,
        hll_precision=cfg.hll_precision,
        entropy_buckets=cfg.entropy_buckets,
        conntrack_slots=cfg.conntrack_slots,
        enable_conntrack=cfg.enable_conntrack_metrics,
        bypass_filter=cfg.bypass_lookup_ip_of_interest
        or not cfg.enable_pod_level,
        # Annotation opt-in: ONLY the filter map (fed by the metrics
        # module's annotated-pod set) decides interest; identity alone
        # must not readmit an un-annotated pod's traffic.
        identity_implies_interest=not cfg.enable_annotations,
        # Low aggregation needs conntrack reports to drive the sketch
        # sampling; without conntrack, fall back to full per-packet feeds
        # (the reference likewise compiles DATA_AGGREGATION_LEVEL into the
        # datapath only alongside conntrack, packetparser.c:214-225).
        data_aggregation_level=(
            cfg.data_aggregation_level
            if cfg.enable_conntrack_metrics
            else "high"
        ),
    )


class SketchEngine:
    """Owns device state + the feed/window loop; thread-safe facade."""

    def __init__(self, cfg: Config, devices: Optional[list] = None):
        self.cfg = cfg
        self.log = logger("engine")
        self.sink = QueueSink(max_blocks=1024)
        self.pcfg = pipeline_config_from(cfg)
        if (
            cfg.data_aggregation_level == "low"
            and self.pcfg.data_aggregation_level == "high"
        ):
            self.log.warning(
                "data_aggregation_level=low requires conntrack metrics; "
                "running at high (full per-packet sketch feeds)"
            )

        devs = devices if devices is not None else jax.devices()
        if cfg.mesh_devices > 0:
            devs = devs[: cfg.mesh_devices]
        self.n_devices = len(devs)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self.mesh = Mesh(np.array(devs), ("data",))
        self.sharded = ShardedTelemetry(self.pcfg, self.mesh)
        self.state = self.sharded.init_state()
        # Record batches are pre-placed with the step's input sharding
        # OUTSIDE the state lock, so the lock is held only for the async
        # step dispatch (snapshot-without-stall; VERDICT r1 weak #3).
        self._rec_sharding = NamedSharding(self.mesh, PartitionSpec("data"))

        self._ident_lock = threading.Lock()
        self.ident = IdentityMap.zeros(cfg.identity_slots)
        self.filter_map = IdentityMap.zeros(1 << 10, seed=99)
        self.apiserver_ip = 0
        # Persistent host mirror for incremental identity churn: one pod
        # event costs O(chain) host mutations + one upload, not a full
        # re-place of every key (VERDICT r1 weak #5).
        self._ident_host = HostIdentityTable(n_slots=cfg.identity_slots)
        self._ident_dict: dict[int, int] = {}

        self._observers: list[Callable[[np.ndarray, str], None]] = []
        self._snap_lock = threading.Lock()
        self._snap_cache: dict[str, Any] | None = None
        self._snap_time = 0.0
        self.last_window: dict[str, np.ndarray] = {}
        self._state_lock = threading.Lock()
        self.started = threading.Event()
        self._steps = 0
        self._events_in = 0
        self._closed_events_in = 0

    # -- identity / filter wiring (set by cache & filtermanager) ------
    def update_identities(self, ip_to_index: dict[int, int]) -> None:
        """Reconcile the device identity table to ``ip_to_index``.

        Incremental: diffs against the previous map and applies only
        changed keys to the persistent host cuckoo table (µs per key),
        then uploads the packed table once. The reference's enricher
        cache likewise mutates one entry per pod event (cache.go:196+).
        """
        new = {ip: idx for ip, idx in ip_to_index.items() if ip != 0}
        if len(new) > self._ident_host.capacity:
            # Validate up front so a failed reconcile never leaves the
            # host table half-mutated with _ident_dict stale (ghost
            # entries would survive all later diffs).
            raise ValueError(
                f"identity map overfull: {len(new)} pods into "
                f"{self.cfg.identity_slots} slots"
            )
        with self._ident_lock:
            old = self._ident_dict
            for ip in old.keys() - new.keys():
                self._ident_host.remove(ip)
            for ip, idx in new.items():
                if old.get(ip) != idx:
                    self._ident_host.insert(ip, idx)
            self._ident_dict = new
            self.ident = self._ident_host.to_device()

    def update_filter_ips(self, ips: set[int]) -> None:
        fmap = IdentityMap.build_host(
            {ip: 1 for ip in ips}, n_slots=1 << 10, seed=99
        )
        with self._ident_lock:
            self.filter_map = fmap

    def set_apiserver_ips(self, ips: list[int]) -> None:
        self.apiserver_ip = ips[0] if ips else 0

    def add_observer(self, fn: Callable[[np.ndarray, str], None]) -> None:
        """Observers see every accepted record block on the feed thread
        (dns tally, flow export...). Must be fast and never raise."""
        self._observers.append(fn)

    # -- lifecycle ----------------------------------------------------
    def compile(self) -> None:
        """Warm every jit cache (the clang-compile analog) so the feed
        loop and the first scrape never pay compile latency."""
        t0 = time.perf_counter()
        zero = jax.device_put(
            np.zeros(
                (self.n_devices, self.cfg.batch_capacity, NUM_FIELDS),
                np.uint32,
            ),
            self._rec_sharding,  # same placement as _dispatch, same jit key
        )
        nv = np.zeros((self.n_devices,), np.uint32)
        self.state, _ = self.sharded.step(
            self.state, zero, nv, 1, self.ident, self.apiserver_ip,
            filter_map=self.filter_map,
        )
        self.state, _ = self.sharded.end_window(self.state)
        snap = self.sharded.snapshot(self.state, 1)
        jax.block_until_ready(snap["totals"])
        self.log.info(
            "engine compiled: %d device(s), batch=%d, %.1fs",
            self.n_devices, self.cfg.batch_capacity,
            time.perf_counter() - t0,
        )

    def step_records(self, records: np.ndarray, now_s: int | None = None) -> None:
        """Feed one host block synchronously (tests / direct callers)."""
        self._dispatch(records, now_s or int(time.time()))

    def _dispatch(self, records: np.ndarray, now_s: int) -> None:
        sb = partition_events(
            records, self.n_devices, self.cfg.batch_capacity
        )
        with self._ident_lock:
            ident = self.ident
            fmap = self.filter_map
        m = get_metrics()
        if sb.lost:
            m.lost_events.labels(stage="partition", plugin="engine").inc(sb.lost)
        # Host->device transfer happens here, before the lock: a scrape
        # thread dispatching a snapshot never waits on the copy, and the
        # feed thread holds the lock only for the (async) step dispatch.
        rec_dev = jax.device_put(sb.records, self._rec_sharding)
        t0 = time.perf_counter()
        with self._state_lock:
            self.state, _ = self.sharded.step(
                self.state, rec_dev, sb.n_valid, now_s, ident,
                self.apiserver_ip, filter_map=fmap, lost=sb.lost,
            )
        m.device_step_seconds.observe(time.perf_counter() - t0)
        m.device_batch_fill.set(float(sb.n_valid.sum()) / (
            self.n_devices * self.cfg.batch_capacity))
        self._steps += 1
        self._events_in += len(records)

    def _close_window(self) -> None:
        # Idle fast path: end_window SKIPS empty windows on-device (no
        # flag, no baseline update — AnomalyEWMA.observe active gating),
        # so when nothing arrived since the last close the dispatch +
        # readback round-trip is pure waste; an idle agent then costs
        # zero device traffic between scrapes.
        if self._events_in == self._closed_events_in:
            m = get_metrics()
            m.windows_closed.inc()
            # Mirror what a real empty close reports (flag 0, z 0,
            # entropy 0) so a flag raised by the LAST active window
            # doesn't latch on an idle node.
            for dim in ("src_ip", "dst_ip", "dst_port"):
                m.entropy_bits.labels(dimension=dim).set(0.0)
                m.anomaly_flag.labels(dimension=dim).set(0.0)
                m.anomaly_zscore.labels(dimension=dim).set(0.0)
            return
        ingested = self._events_in
        with self._state_lock:
            self.state, win = self.sharded.end_window(self.state)
        # Advance only after a SUCCESSFUL close: if end_window raised,
        # the next tick must retry this window, not skip it forever.
        self._closed_events_in = ingested
        self.last_window = {k: np.asarray(v) for k, v in win.items()}
        m = get_metrics()
        m.windows_closed.inc()
        dims = ["src_ip", "dst_ip", "dst_port"]
        for i, dim in enumerate(dims):
            m.entropy_bits.labels(dimension=dim).set(
                float(self.last_window["entropy_bits"][i])
            )
            m.anomaly_flag.labels(dimension=dim).set(
                float(self.last_window["anomaly"][i])
            )
            m.anomaly_zscore.labels(dimension=dim).set(
                float(self.last_window["zscore"][i])
            )
            if self.last_window["anomaly"][i]:
                # Counter survives scrape cadence: a 0.2s anomalous
                # window must be visible at a 30s scrape.
                m.anomaly_windows.labels(dimension=dim).inc()

    def start(self, stop: threading.Event) -> None:
        """Feed loop: drain sink → batch → device; close windows on time.

        Sits where Enricher.Run + Module.run sit in the reference
        (enricher.go:68-99, metrics_module.go:266-330)."""
        self.started.set()
        cap = self.cfg.batch_capacity * self.n_devices
        pending: list[np.ndarray] = []
        n_pending = 0
        last_flush = time.monotonic()
        next_window = time.monotonic() + self.cfg.window_seconds
        while not stop.is_set():
            blocks = self.sink.drain(max_blocks=256)
            for rec, plugin in blocks:
                for obs in self._observers:
                    try:
                        obs(rec, plugin)
                    except Exception:
                        self.log.exception("observer failed")
                pending.append(rec)
                n_pending += len(rec)
            now = time.monotonic()
            flush_due = n_pending > 0 and (
                n_pending >= cap or now - last_flush >= self.cfg.flush_interval_s
            )
            if flush_due:
                if len(pending) == 1:
                    all_rec = pending[0]  # skip the concat copy
                else:
                    all_rec = np.concatenate(pending, axis=0)
                pending.clear()
                n_pending = 0
                last_flush = now
                for off in range(0, len(all_rec), cap):
                    self._dispatch(
                        all_rec[off : off + cap], int(time.time())
                    )
            if now >= next_window:
                try:
                    self._close_window()
                except Exception:
                    self.log.exception("window close failed")
                next_window = now + self.cfg.window_seconds
            if not blocks and not flush_due:
                stop.wait(0.002)

    # -- scrape-time readout -----------------------------------------
    def snapshot(self, max_age_s: float = 0.5) -> dict[str, Any]:
        """Merged numpy snapshot, cached up to ``max_age_s`` (scrape
        latency budget: <1s per BASELINE)."""
        now = time.monotonic()
        with self._snap_lock:
            if self._snap_cache is not None and now - self._snap_time < max_age_s:
                return self._snap_cache
        with self._state_lock:
            dev_snap = self.sharded.snapshot(self.state, int(time.time()))
        # ONE batched device→host transfer for the whole tree: per-leaf
        # np.asarray would pay a blocking tunnel round-trip per array
        # (measured >2s at production shapes vs the <1s scrape budget).
        host = jax.device_get(dev_snap)
        host["steps"] = self._steps
        host["events_in"] = self._events_in
        with self._snap_lock:
            self._snap_cache = host
            self._snap_time = time.monotonic()
        return host

    def top_flows(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "flow_hh", k)

    def top_services(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "svc_hh", k)

    def top_dns(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "dns_hh", k)

    def conntrack_gc(self) -> dict[str, int]:
        """Scrape conntrack liveness + accounting (expiry itself is
        timestamp-based in the table — the GC 'loop' is an accounting
        pass, like the reference GC summing conntrackmetadata while
        iterating the map, conntrack_linux.go:95-163).

        packets/bytes are the cumulative totals carried by conntrack
        reports, reassembled from per-device two-limb u32 counters.
        """
        snap = self.snapshot(max_age_s=5.0)
        totals = snap["totals"]
        ctt = np.asarray(snap["ct_totals"]).reshape(-1, 4).astype(np.uint64)
        pkts = int((ctt[:, 0] + (ctt[:, 1] << np.uint64(32))).sum())
        byts = int((ctt[:, 2] + (ctt[:, 3] << np.uint64(32))).sum())
        return {
            "active": int(snap["active_conns"]),
            "reports": int(totals[6]),
            "packets": pkts,
            "bytes": byts,
        }

    # -- checkpoint/resume (reference: pinned BPF maps survive agent
    # restarts, pkg/bpf/setup_linux.go; SURVEY.md §5.4) ---------------
    def save_snapshot_state(self, path: str) -> None:
        from retina_tpu.checkpoint import save_state

        with self._state_lock:
            save_state(path, self.state, self.pcfg)

    def load_snapshot_state(self, path: str) -> None:
        from retina_tpu.checkpoint import load_state

        with self._state_lock:
            self.state = load_state(path, self.sharded, self.pcfg)

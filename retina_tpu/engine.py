"""SketchEngine: the TPU worker that replaces the CPU aggregation loop.

Reference analog (what this replaces, SURVEY.md §3.2): the enricher output
ring → ``Module.run`` goroutine calling every metric's ``ProcessFlow`` per
flow (metrics_module.go:283-303) — single-threaded CPU hash aggregation,
the scaling bottleneck. Per the BASELINE north star, this engine is the
"tpusketch" plugin's backend: plugins feed fixed-width record blocks into
a bounded queue (QueueSink), the feed loop batches them into fixed-shape
device arrays, and ONE jit-compiled step updates every aggregator. Sharded
over a ``jax.sharding.Mesh`` when more than one device is available
(parallel/telemetry.py); scrape-time snapshots merge with psum/pmax/
all_gather over ICI.

Backpressure contract (the reference's universal rule,
packetparser_linux.go:692-697): never block a producer — drop and count.
Snapshot contract: scrapes read a cached merged snapshot at most
``snapshot_max_age_s`` old (<1s target, BASELINE) and never stall the feed
loop; JAX dispatch is async so the feed thread keeps the device busy while
snapshot results transfer back.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from retina_tpu.config import Config
from retina_tpu.events.schema import NUM_FIELDS
from retina_tpu.log import logger
from retina_tpu.metrics import get_metrics
from retina_tpu.models.identity import HostIdentityTable, IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline
from retina_tpu.parallel.combine import combine_records
from retina_tpu.parallel.partition import ShardedBatch, partition_events
from retina_tpu.parallel.telemetry import ShardedTelemetry, topk_from_snapshot
from retina_tpu.plugins.api import QueueSink
from retina_tpu.utils.device_proxy import run_on_device


def pipeline_config_from(cfg: Config) -> PipelineConfig:
    return PipelineConfig(
        n_pods=cfg.n_pods,
        cms_width=cfg.cms_width,
        cms_depth=cfg.cms_depth,
        topk_slots=cfg.topk_slots,
        hll_precision=cfg.hll_precision,
        entropy_buckets=cfg.entropy_buckets,
        conntrack_slots=cfg.conntrack_slots,
        enable_conntrack=cfg.enable_conntrack_metrics,
        bypass_filter=cfg.bypass_lookup_ip_of_interest
        or not cfg.enable_pod_level,
        # Annotation opt-in: ONLY the filter map (fed by the metrics
        # module's annotated-pod set) decides interest; identity alone
        # must not readmit an un-annotated pod's traffic.
        identity_implies_interest=not cfg.enable_annotations,
        # Low aggregation needs conntrack reports to drive the sketch
        # sampling; without conntrack, fall back to full per-packet feeds
        # (the reference likewise compiles DATA_AGGREGATION_LEVEL into the
        # datapath only alongside conntrack, packetparser.c:214-225).
        data_aggregation_level=(
            cfg.data_aggregation_level
            if cfg.enable_conntrack_metrics
            else "high"
        ),
    )


class SketchEngine:
    """Owns device state + the feed/window loop; thread-safe facade."""

    def __init__(self, cfg: Config, devices: Optional[list] = None):
        self.cfg = cfg
        self.log = logger("engine")
        self.sink = QueueSink(max_blocks=1024)
        self.pcfg = pipeline_config_from(cfg)
        if (
            cfg.data_aggregation_level == "low"
            and self.pcfg.data_aggregation_level == "high"
        ):
            self.log.warning(
                "data_aggregation_level=low requires conntrack metrics; "
                "running at high (full per-packet sketch feeds)"
            )

        devs = devices if devices is not None else jax.devices()
        if cfg.mesh_devices > 0:
            devs = devs[: cfg.mesh_devices]
        self.n_devices = len(devs)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self.mesh = Mesh(np.array(devs), ("data",))
        self.sharded = ShardedTelemetry(self.pcfg, self.mesh)
        self.state = self.sharded.init_state()
        # Record batches are pre-placed with the step's input sharding
        # OUTSIDE the state lock, so the lock is held only for the async
        # step dispatch (snapshot-without-stall; VERDICT r1 weak #3).
        self._rec_sharding = NamedSharding(self.mesh, PartitionSpec("data"))

        self._ident_lock = threading.Lock()
        self.ident = IdentityMap.zeros(cfg.identity_slots)
        # Sized like the identity table: the default deployment loads
        # every tracked pod IP into the IPs-of-interest map (the metrics
        # module filter sync), so 1024 slots overflowed at ~500 pods.
        self.filter_map = IdentityMap.zeros(cfg.identity_slots, seed=99)
        self.apiserver_ip = 0
        # Persistent host mirror for incremental identity churn: one pod
        # event costs O(chain) host mutations + one upload, not a full
        # re-place of every key (VERDICT r1 weak #5).
        self._ident_host = HostIdentityTable(n_slots=cfg.identity_slots)
        self._ident_dict: dict[int, int] = {}

        self._observers: list[Callable[[np.ndarray, str], None]] = []
        # bucket size -> jitted pad-to-capacity kernel (device-side zero
        # extension of a small transfer to the step's static shape).
        self._pad_cache: dict[int, Any] = {}
        self._snap_lock = threading.Lock()
        self._snap_cache: dict[str, Any] | None = None
        self._snap_time = 0.0
        self.last_window: dict[str, np.ndarray] = {}
        self._state_lock = threading.Lock()
        self.started = threading.Event()
        self._steps = 0
        self._events_in = 0
        self._closed_events_in = 0

    # -- identity / filter wiring (set by cache & filtermanager) ------
    def update_identities(self, ip_to_index: dict[int, int]) -> None:
        """Reconcile the device identity table to ``ip_to_index``.

        Incremental: diffs against the previous map and applies only
        changed keys to the persistent host cuckoo table (µs per key),
        then uploads the packed table once. The reference's enricher
        cache likewise mutates one entry per pod event (cache.go:196+).
        """
        new = {ip: idx for ip, idx in ip_to_index.items() if ip != 0}
        if len(new) > self._ident_host.capacity:
            # Validate up front so a failed reconcile never leaves the
            # host table half-mutated with _ident_dict stale (ghost
            # entries would survive all later diffs).
            raise ValueError(
                f"identity map overfull: {len(new)} pods into "
                f"{self.cfg.identity_slots} slots"
            )
        with self._ident_lock:
            old = self._ident_dict
            for ip in old.keys() - new.keys():
                self._ident_host.remove(ip)
            for ip, idx in new.items():
                if old.get(ip) != idx:
                    self._ident_host.insert(ip, idx)
            self._ident_dict = new
            # Device upload on the proxy thread (all JAX interaction is
            # single-threaded through it; utils/device_proxy.py).
            self.ident = run_on_device(self._ident_host.to_device)

    def update_filter_ips(self, ips: set[int]) -> None:
        # Build the cuckoo table on the CALLING thread (pure numpy, O(n)
        # host work); only the device upload ties up the proxy thread.
        host = HostIdentityTable(n_slots=self.cfg.identity_slots, seed=99)
        if len(ips) > host.capacity:
            raise ValueError(
                f"filter map overfull: {len(ips)} IPs into "
                f"{self.cfg.identity_slots} slots"
            )
        for ip in ips:
            if ip:
                host.insert(ip, 1)
        fmap = run_on_device(host.to_device)
        with self._ident_lock:
            self.filter_map = fmap

    def set_apiserver_ips(self, ips: list[int]) -> None:
        self.apiserver_ip = ips[0] if ips else 0

    def add_observer(self, fn: Callable[[np.ndarray, str], None]) -> None:
        """Observers see every accepted record block on the feed thread
        (dns tally, flow export...). Must be fast and never raise."""
        self._observers.append(fn)

    # -- lifecycle ----------------------------------------------------
    def compile(self) -> None:
        """Warm every jit cache (the clang-compile analog) so the feed
        loop and the first scrape never pay compile latency."""
        t0 = time.perf_counter()

        def warm():
            zero = jax.device_put(
                np.zeros(
                    (self.n_devices, self.cfg.batch_capacity, NUM_FIELDS),
                    np.uint32,
                ),
                self._rec_sharding,  # same placement as step, same jit key
            )
            nv = np.zeros((self.n_devices,), np.uint32)
            self.state, _ = self.sharded.step(
                self.state, zero, nv, 1, self.ident, self.apiserver_ip,
                filter_map=self.filter_map,
            )
            self.state, _ = self.sharded.end_window(self.state)
            # Warm BOTH snapshot programs: the device-dict one (tests,
            # direct consumers) and the flat single-transfer one the
            # scrape path uses (a cold compile here cost the first
            # scrape ~40s on the tunnel).
            snap = self.sharded.snapshot(self.state, 1)
            jax.block_until_ready(snap["totals"])
            self.sharded.snapshot_host(self.state, 1)

        run_on_device(warm)
        # Warm the bucketed-ingest jits (wire unpack + pad) for the
        # smallest bucket; other buckets compile on first use (same tiny
        # kernel, ~sub-second each).
        self._dispatch(
            np.zeros((0, NUM_FIELDS), np.uint32), now_s=1
        )
        self.log.info(
            "engine compiled: %d device(s), batch=%d, %.1fs",
            self.n_devices, self.cfg.batch_capacity,
            time.perf_counter() - t0,
        )

    def step_records(self, records: np.ndarray, now_s: int | None = None) -> None:
        """Feed one host block synchronously (tests / direct callers)."""
        self._dispatch(records, now_s or int(time.time()))

    def _dispatch(self, records: np.ndarray, now_s: int) -> None:
        sb = partition_events(
            records, self.n_devices, self.cfg.batch_capacity,
            min_bucket=self.cfg.transfer_min_bucket,
        )
        self._dispatch_sharded(sb, now_s, n_raw=len(records))

    def _ingest_fn(self, bucket: int, packed: bool):
        """Per-bucket jit that turns a transferred (D, bucket, P) array
        into the step's static (D, B, 16) shape ON DEVICE: unpack the
        12-lane wire format (when packed) and zero-extend to capacity —
        the host->device link carries only the bucketed packed rows; HBM
        bandwidth makes the expansion free."""
        key = (bucket, packed)
        fn = self._pad_cache.get(key)
        if fn is None:
            cap = self.cfg.batch_capacity
            pad_n = cap - bucket
            from functools import partial as _partial

            from retina_tpu.parallel.wire import unpack_records_device

            @_partial(jax.jit, out_shardings=self._rec_sharding)
            def ingest(small, base_lo, base_hi):
                if packed:
                    small = unpack_records_device(small, base_lo, base_hi)
                if pad_n:
                    small = jnp.pad(small, ((0, 0), (0, pad_n), (0, 0)))
                return small

            fn = self._pad_cache[key] = ingest
        return fn

    def _dispatch_sharded(
        self, sb: "ShardedBatch", now_s: int, n_raw: int
    ) -> None:
        """device_put + async step dispatch for an already-partitioned
        batch. Runs on the dispatch thread when the feed pipeline is on."""
        with self._ident_lock:
            ident = self.ident
            fmap = self.filter_map
        m = get_metrics()
        if sb.lost:
            m.lost_events.labels(stage="partition", plugin="engine").inc(sb.lost)
        # Packing stays on the calling thread (host CPU work overlaps the
        # proxy's in-flight transfer); the transfer + step dispatch run
        # on the device-proxy thread.
        tt = time.perf_counter()
        if self.cfg.transfer_packed:
            from retina_tpu.parallel.wire import pack_records

            wire, b_lo, b_hi = pack_records(sb.records)
            packed = True
        else:
            wire, b_lo, b_hi = sb.records, np.uint32(0), np.uint32(0)
            packed = False
        m.transfer_bytes.inc(wire.nbytes)

        def xfer_and_step():
            rec_dev = jax.device_put(wire, self._rec_sharding)
            if packed or wire.shape[1] != self.cfg.batch_capacity:
                rec_dev = self._ingest_fn(wire.shape[1], packed)(
                    rec_dev, jnp.uint32(b_lo), jnp.uint32(b_hi)
                )
            t0 = time.perf_counter()
            with self._state_lock:
                self.state, _ = self.sharded.step(
                    self.state, rec_dev, sb.n_valid, now_s, ident,
                    self.apiserver_ip, filter_map=fmap, lost=sb.lost,
                )
            return t0

        t0 = run_on_device(xfer_and_step)
        m.transfer_seconds.observe(t0 - tt)
        m.device_step_seconds.observe(time.perf_counter() - t0)
        m.device_batch_fill.set(float(sb.n_valid.sum()) / (
            self.n_devices * self.cfg.batch_capacity))
        self._steps += 1
        self._events_in += n_raw

    def _close_window(self) -> None:
        # Idle fast path: end_window SKIPS empty windows on-device (no
        # flag, no baseline update — AnomalyEWMA.observe active gating),
        # so when nothing arrived since the last close the dispatch +
        # readback round-trip is pure waste; an idle agent then costs
        # zero device traffic between scrapes.
        if self._events_in == self._closed_events_in:
            m = get_metrics()
            m.windows_closed.inc()
            # Mirror what a real empty close reports (flag 0, z 0,
            # entropy 0) so a flag raised by the LAST active window
            # doesn't latch on an idle node.
            for dim in ("src_ip", "dst_ip", "dst_port"):
                m.entropy_bits.labels(dimension=dim).set(0.0)
                m.anomaly_flag.labels(dimension=dim).set(0.0)
                m.anomaly_zscore.labels(dimension=dim).set(0.0)
            return
        ingested = self._events_in

        def close():
            with self._state_lock:
                self.state, win = self.sharded.end_window(self.state)
            return jax.device_get(win)

        win_host = run_on_device(close)
        # Advance only after a SUCCESSFUL close: if end_window raised,
        # the next tick must retry this window, not skip it forever.
        self._closed_events_in = ingested
        self.last_window = win_host
        m = get_metrics()
        m.windows_closed.inc()
        dims = ["src_ip", "dst_ip", "dst_port"]
        for i, dim in enumerate(dims):
            m.entropy_bits.labels(dimension=dim).set(
                float(self.last_window["entropy_bits"][i])
            )
            m.anomaly_flag.labels(dimension=dim).set(
                float(self.last_window["anomaly"][i])
            )
            m.anomaly_zscore.labels(dimension=dim).set(
                float(self.last_window["zscore"][i])
            )
            if self.last_window["anomaly"][i]:
                # Counter survives scrape cadence: a 0.2s anomalous
                # window must be visible at a 30s scrape.
                m.anomaly_windows.labels(dimension=dim).inc()

    def _dispatch_loop(self, q) -> None:
        """Dispatch thread: executes partitioned steps + window closes in
        feed order. The transfer (device_put) runs here, OVERLAPPED with
        the feed thread's combining/partitioning of the next batch — the
        host->device link and the host CPU work proceed concurrently
        instead of serially (VERDICT r2 weak #1)."""
        while True:
            item = q.get()
            if item is None:
                return
            kind, payload, now_s, n_raw = item
            try:
                if kind == "step":
                    self._dispatch_sharded(payload, now_s, n_raw)
                else:
                    self._close_window()
            except Exception:
                self.log.exception("%s dispatch failed", kind)

    def start(self, stop: threading.Event) -> None:
        """Feed loop: drain sink → combine → partition → device; close
        windows on time.

        Sits where Enricher.Run + Module.run sit in the reference
        (enricher.go:68-99, metrics_module.go:266-330). With
        ``feed_pipeline_depth > 0`` the device_put + step dispatch run on
        a separate thread behind a bounded queue, so batch N's transfer
        overlaps batch N+1's host-side prep; the queue is the only
        blocking edge (backpressure then reaches the bounded sink, which
        drops and counts — never the producers)."""
        self.started.set()
        cap = self.cfg.batch_capacity * self.n_devices
        # Flush threshold: accumulating beyond one device batch raises the
        # combine ratio (more duplicate descriptors per pass); the
        # interval timeout still bounds latency.
        quantum = max(cap, self.cfg.flush_max_events)
        depth = self.cfg.feed_pipeline_depth
        q: queue_mod.Queue | None = None
        worker = None
        if depth > 0:
            q = queue_mod.Queue(maxsize=depth)
            worker = threading.Thread(
                target=self._dispatch_loop, args=(q,),
                name="engine-dispatch", daemon=True,
            )
            worker.start()

        def drop_item(item):
            """Dead-worker path: account the loss, never enqueue into a
            queue nobody drains (silent vanishing)."""
            self.log.error("dispatch worker dead; dropping %s", item[0])
            if item[0] == "step":
                n = int(item[1].n_valid.sum())
                get_metrics().lost_events.labels(
                    stage="dispatch", plugin="engine"
                ).inc(n)

        def submit(item):
            if q is not None:
                # Block only while the worker lives: if it died (fatal
                # runtime error escaping its catch), drop + count rather
                # than wedging the feed loop on a full queue forever —
                # and check liveness BEFORE enqueueing, or items that
                # still fit in the queue would vanish uncounted.
                while True:
                    if not worker.is_alive():
                        drop_item(item)
                        return
                    try:
                        q.put(item, timeout=1.0)
                        return
                    except queue_mod.Full:
                        pass
            elif item[0] == "step":
                self._dispatch_sharded(item[1], item[2], item[3])
            else:
                try:
                    self._close_window()
                except Exception:
                    self.log.exception("window close failed")

        m = get_metrics()
        pending: list[np.ndarray] = []
        n_pending = 0
        last_flush = time.monotonic()
        next_window = time.monotonic() + self.cfg.window_seconds

        def flush():
            nonlocal pending, n_pending, last_flush
            if len(pending) == 1:
                all_rec = pending[0]  # skip the concat copy
            else:
                all_rec = np.concatenate(pending, axis=0)
            pending = []
            n_pending = 0
            last_flush = time.monotonic()
            n_raw = len(all_rec)
            if self.cfg.host_combine:
                all_rec = combine_records(all_rec)
                m.combine_ratio.set(n_raw / max(len(all_rec), 1))
            now_s = int(time.time())
            for off in range(0, len(all_rec), cap):
                chunk = all_rec[off : off + cap]
                sb = partition_events(
                    chunk, self.n_devices, self.cfg.batch_capacity,
                    min_bucket=self.cfg.transfer_min_bucket,
                )
                # raw-row accounting goes to the chunk that carries it;
                # chunk boundaries are an implementation detail
                submit(("step", sb, now_s, n_raw if off == 0 else 0))

        try:
            while not stop.is_set():
                blocks = self.sink.drain(max_blocks=64)
                for rec, plugin in blocks:
                    for obs in self._observers:
                        try:
                            obs(rec, plugin)
                        except Exception:
                            self.log.exception("observer failed")
                    pending.append(rec)
                    n_pending += len(rec)
                    # Flush in bounded quanta AS blocks accumulate: a
                    # backlogged sink must never turn into one multi-GB
                    # concat+combine — each flush handles at most one
                    # quantum plus a block's worth of overshoot.
                    if n_pending >= quantum:
                        flush()
                now = time.monotonic()
                if n_pending and now - last_flush >= self.cfg.flush_interval_s:
                    flush()
                if now >= next_window:
                    submit(("window", None, 0, 0))
                    next_window = now + self.cfg.window_seconds
                if not blocks:
                    stop.wait(0.002)
        finally:
            if q is not None:
                try:
                    # Bounded: a wedged worker with a full queue must not
                    # hang shutdown before the join timeout gets its say.
                    q.put(None, timeout=30.0)
                except queue_mod.Full:
                    self.log.error("dispatch queue stuck at shutdown")
                worker.join(timeout=30.0)

    # -- scrape-time readout -----------------------------------------
    def snapshot(self, max_age_s: float = 0.5) -> dict[str, Any]:
        """Merged numpy snapshot, cached up to ``max_age_s`` (scrape
        latency budget: <1s per BASELINE)."""
        now = time.monotonic()
        with self._snap_lock:
            if self._snap_cache is not None and now - self._snap_time < max_age_s:
                return self._snap_cache
        def snap():
            # ONE device->host transfer for the whole tree (leaves are
            # concatenated on device): per-leaf readback paid a full
            # link round trip per array — measured 2.7-21s at production
            # shapes on a congested link vs the <1s scrape budget.
            with self._state_lock:
                return self.sharded.snapshot_host(
                    self.state, int(time.time())
                )

        host = run_on_device(snap)
        host["steps"] = self._steps
        host["events_in"] = self._events_in
        with self._snap_lock:
            self._snap_cache = host
            self._snap_time = time.monotonic()
        return host

    def top_flows(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "flow_hh", k)

    def top_services(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "svc_hh", k)

    def top_dns(self, k: int = 20) -> tuple[np.ndarray, np.ndarray]:
        return topk_from_snapshot(self.snapshot(), "dns_hh", k)

    def conntrack_gc(self) -> dict[str, int]:
        """Scrape conntrack liveness + accounting (expiry itself is
        timestamp-based in the table — the GC 'loop' is an accounting
        pass, like the reference GC summing conntrackmetadata while
        iterating the map, conntrack_linux.go:95-163).

        packets/bytes are the cumulative totals carried by conntrack
        reports, reassembled from per-device two-limb u32 counters.
        """
        snap = self.snapshot(max_age_s=5.0)
        totals = snap["totals"]
        ctt = np.asarray(snap["ct_totals"]).reshape(-1, 4).astype(np.uint64)
        pkts = int((ctt[:, 0] + (ctt[:, 1] << np.uint64(32))).sum())
        byts = int((ctt[:, 2] + (ctt[:, 3] << np.uint64(32))).sum())
        return {
            "active": int(snap["active_conns"]),
            "reports": int(totals[6]),
            "packets": pkts,
            "bytes": byts,
        }

    # -- checkpoint/resume (reference: pinned BPF maps survive agent
    # restarts, pkg/bpf/setup_linux.go; SURVEY.md §5.4) ---------------
    def save_snapshot_state(self, path: str) -> None:
        from retina_tpu.checkpoint import save_state

        def save():
            with self._state_lock:
                save_state(path, self.state, self.pcfg)

        run_on_device(save)

    def load_snapshot_state(self, path: str) -> None:
        from retina_tpu.checkpoint import load_state

        def load():
            with self._state_lock:
                self.state = load_state(path, self.sharded, self.pcfg)

        run_on_device(load)

"""Host-side feature extraction for the detector programs.

Each helper turns one (N, NUM_FIELDS) record block into the tiny
fixed-shape feature array its detector program consumes. These run on
the record tap (engine dispatch / dryrun feed), so they are single
vectorized numpy passes — no per-row Python.
"""

from __future__ import annotations

import numpy as np

from retina_tpu.detect.programs import DNSTUNNEL_BINS, SYNFLOOD_LANES
from retina_tpu.events.schema import F, PROTO_TCP

# Flow-key batches pad to the next power of two so the portscan
# program compiles once per size class, not once per window (the
# _KEY_PAD idiom from the timetravel dryrun, adaptive because the tap
# sees raw blocks of varying size).
_PAD_MIN = 1 << 6


def padded_flow_keys(rec: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, NUM_FIELDS) records -> ((P, 4) u32 keys, (P,) f32 weights)
    with P the next power of two >= N; padding rows carry weight 0 and
    are masked out of the HLL update."""
    n = int(len(rec))
    cap = _PAD_MIN
    while cap < n:
        cap <<= 1
    keys = np.zeros((cap, 4), np.uint32)
    w = np.zeros((cap,), np.float32)
    if n:
        keys[:n, 0] = rec[:, F.SRC_IP]
        keys[:n, 1] = rec[:, F.DST_IP]
        keys[:n, 2] = rec[:, F.META] >> np.uint32(24)
        keys[:n, 3] = rec[:, F.PORTS] & np.uint32(0xFFFF)
        w[:n] = rec[:, F.PACKETS]
    return keys, w


def tcpflag_lanes(rec: np.ndarray) -> np.ndarray:
    """(SYNFLOOD_LANES,) f32 packet counts: lane b = packets with TCP
    flag bit b set (schema.py TCP_*), lane 8 = total TCP packets."""
    lanes = np.zeros((SYNFLOOD_LANES,), np.float32)
    if not len(rec):
        return lanes
    meta = rec[:, F.META]
    tcp = (meta >> np.uint32(24)) == PROTO_TCP
    if not tcp.any():
        return lanes
    flags = (meta[tcp] >> np.uint32(16)) & np.uint32(0xFF)
    pk = rec[tcp, F.PACKETS].astype(np.float64)
    for bit in range(8):
        lanes[bit] = float(pk[(flags >> np.uint32(bit)) & 1 == 1].sum())
    lanes[8] = float(pk.sum())
    return lanes


def qname_length_hist(
    rec: np.ndarray, nbins: int = DNSTUNNEL_BINS
) -> np.ndarray:
    """(1, nbins) f32 histogram of DNS qname lengths, read from the
    F.DNS low byte (synthetic.py packs it; pcap-decoded records carry
    a 1/2 req-resp marker there, which lands in the short-name bins
    and stays far below any tunneling entropy)."""
    hist = np.zeros((1, nbins), np.float32)
    if not len(rec):
        return hist
    dns = rec[:, F.DNS]
    sel = dns != 0
    if not sel.any():
        return hist
    ln = np.clip(dns[sel] & np.uint32(0xFF), 0, nbins - 1).astype(np.int64)
    hist[0] = np.bincount(ln, minlength=nbins).astype(np.float32)
    return hist

"""Pluggable detection subsystem (PSketch-style multi-detector bank).

Generalizes the single entropy-burst trigger AutoCapture shipped with
(engine harvest -> ``anomaly_hook``) into a registry of derived
detectors, each a small device program over sketch features the
pipeline already extracts — no new per-packet state, just new
reductions over it:

- ``portscan``   HLL of distinct dst ports per source hash-group
- ``dnstunnel``  entropy over DNS qname lengths
- ``synflood``   SYN:ACK asymmetry over the tcpflags families

Every detector feeds the SAME closed loop: detect -> range-query the
snapshot ring -> invertible-attribute -> targeted capture
(timetravel/autocapture.py), arbitrated per window by priority with a
per-detector cooldown, published as ``tpu_detector_*`` series.
"""

from retina_tpu.detect.base import (  # noqa: F401
    Detection,
    Detector,
    DetectorBank,
    build_default_bank,
    register,
    registered,
)

"""The three builtin detectors (PSketch's priority-diverse trio).

Thresholds are set against the synthetic regime catalog
(events/synthetic.py) and the fixture replays: every benign preset
(zipf, uniform, elephant_mice — a 5-port service mix at ~5% SYN)
scores far below each ``fire_thresh``; each matching attack regime
(portscan sweep, dns_flood/tunnel lengths, syn_storm/ddos) scores far
above it. tests/test_detectors.py pins both sides.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from retina_tpu.detect import features, programs
from retina_tpu.detect.base import Detector, register


@register
class SynFloodDetector(Detector):
    """SYN:ACK asymmetry over the tcpflags lanes. Highest priority:
    a volumetric flood is the regime where capture evidence decays
    fastest."""

    name = "synflood"
    priority = 3
    dims = ("src_ip",)
    fire_thresh = 3.0  # benign steady state is ~0.05 SYN per ACK
    min_score = 1.5
    MIN_TCP = 64.0  # packets; below this a window has no TCP story

    def begin_window(self) -> None:
        self._lanes = np.zeros((programs.SYNFLOOD_LANES,), np.float32)

    def add_records(
        self, rec: np.ndarray, extras: Optional[dict] = None
    ) -> None:
        if extras is not None and "tcpflag_lanes" in extras:
            self._lanes += np.asarray(
                extras["tcpflag_lanes"], np.float32
            )
        else:
            self._lanes += features.tcpflag_lanes(rec)

    def score(self) -> float | None:
        if self._lanes[8] < self.MIN_TCP:
            return None
        out = np.asarray(
            programs.synflood_program()(jnp.asarray(self._lanes))
        )
        return float(out[0])


@register
class PortScanDetector(Detector):
    """Distinct dst ports per source hash-group (HLL bank). Benign
    feeds touch a handful of service ports per group; a vertical sweep
    concentrates dozens under one source's group."""

    name = "portscan"
    priority = 2
    dims = ("dst_port",)
    fire_thresh = 12.0  # benign mixes peak ~5 ports/group; sweeps >= 24
    min_score = 8.0

    def begin_window(self) -> None:
        self._blocks: list[np.ndarray] = []

    def add_records(
        self, rec: np.ndarray, extras: Optional[dict] = None
    ) -> None:
        self._blocks.append(np.asarray(rec))

    def score(self) -> float | None:
        if not self._blocks:
            return None
        rec = (
            self._blocks[0] if len(self._blocks) == 1
            else np.concatenate(self._blocks)
        )
        if not len(rec):
            return None
        keys, w = features.padded_flow_keys(rec)
        fn = programs.portscan_program(
            len(keys), programs.PORTSCAN_GROUPS,
            programs.PORTSCAN_PRECISION, programs.PORTSCAN_SEED,
        )
        est = np.asarray(fn(jnp.asarray(keys), jnp.asarray(w)))
        return float(est.max())


@register
class DnsTunnelDetector(Detector):
    """Entropy over qname lengths. Features come from the F.DNS low
    byte on the record tap, or from the dns plugin's live string table
    (``extras["qname_hist"]``, DnsPlugin.qname_length_hist) when the
    daemon runs the real qname path."""

    name = "dnstunnel"
    priority = 1
    dims = ("src_ip",)
    fire_thresh = 4.2  # benign lengths cluster in <= 9 bins (< 3.2 bits)
    min_score = 3.6
    MIN_DNS = 32.0  # queries; below this the histogram is noise

    def begin_window(self) -> None:
        self._hist = np.zeros((1, programs.DNSTUNNEL_BINS), np.float32)

    def add_records(
        self, rec: np.ndarray, extras: Optional[dict] = None
    ) -> None:
        if extras is not None and "qname_hist" in extras:
            self._hist = self._hist + np.asarray(
                extras["qname_hist"], np.float32
            ).reshape(1, -1)
        else:
            self._hist = self._hist + features.qname_length_hist(rec)

    def score(self) -> float | None:
        if float(self._hist.sum()) < self.MIN_DNS:
            return None
        fn = programs.dnstunnel_program(
            self._hist.shape[1], programs.DNSTUNNEL_SEED
        )
        out = np.asarray(fn(jnp.asarray(self._hist)))
        return float(out[0])

"""Detector registry + window-aligned multi-detector bank.

A ``Detector`` accumulates host features for the current window
(``add_records``), scores the closed window through its registered
device program, and judges the score two ways:

- **absolute**: ``score >= fire_thresh`` fires regardless of history —
  the regimes the bank exists for (a port sweep, a tunnel, a SYN
  flood) are categorically outside benign range, so detection must not
  depend on how many clean windows preceded the attack;
- **adaptive**: an ``AnomalyEWMA`` z-flag (same estimator as the
  entropy detector) fires on drift past ``z_thresh``, floored by
  ``min_score`` so a near-zero-variance baseline cannot convert noise
  into a firing.

The ``DetectorBank`` closes windows on epoch rollover, applies the
per-detector cooldown, arbitrates simultaneous firings by priority
(highest wins — the capture queue is one deep, so only one detection
per window reaches the sink), forwards the winner to the capture sink
(``AutoCapture.notify``), and publishes every ``tpu_detector_*``
series.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np

from retina_tpu.log import logger
from retina_tpu.metrics import get_metrics
from retina_tpu.ops.entropy import AnomalyEWMA

_log = logger("detect")

# Bank-level bound on records accumulated per window (memory guard on
# the daemon record tap; a 1s window at millions of events would
# otherwise buffer unbounded host copies).
MAX_WINDOW_RECORDS = 1 << 16


@dataclasses.dataclass(frozen=True)
class Detection:
    """One accepted firing, in AutoCapture.notify terms."""

    detector: str
    epoch: int
    score: float
    zscore: float
    dims: tuple[str, ...]
    priority: int


class Detector:
    """Base class; subclasses registered via ``@register``."""

    name = "base"
    priority = 0  # higher wins same-window arbitration
    dims: tuple[str, ...] = ("src_ip",)  # capture-pivot dimensions
    fire_thresh = float("inf")  # absolute firing floor
    min_score = 0.0  # adaptive (z-path) firing floor

    def __init__(
        self,
        z_thresh: float = 8.0,
        min_windows: int = 3,
        cooldown_s: float = 60.0,
    ) -> None:
        self.z_thresh = float(z_thresh)
        self.min_windows = int(min_windows)
        self.cooldown_s = float(cooldown_s)
        self._ewma = AnomalyEWMA.zeros(1)
        self.last_score = 0.0
        self.last_z = 0.0
        self.begin_window()

    # -- per-window feature accumulation (host, record tap) ------------
    def begin_window(self) -> None:
        raise NotImplementedError

    def add_records(
        self, rec: np.ndarray, extras: Optional[dict] = None
    ) -> None:
        raise NotImplementedError

    def score(self) -> float | None:
        """Score the accumulated window; None = not enough signal to
        judge (e.g. no DNS traffic for the tunnel detector) — the EWMA
        baseline is not advanced on such windows."""
        raise NotImplementedError

    # -- judgment ------------------------------------------------------
    def judge(self, epoch: int) -> Detection | None:
        s = self.score()
        if s is None:
            return None
        self._ewma, flags, z = self._ewma.observe(
            jnp.asarray([s], jnp.float32),
            z_thresh=self.z_thresh,
            min_windows=self.min_windows,
        )
        self.last_score = float(s)
        self.last_z = float(np.asarray(z)[0])
        fired = s >= self.fire_thresh or (
            bool(np.asarray(flags)[0]) and s >= self.min_score
        )
        if not fired:
            return None
        return Detection(
            detector=self.name, epoch=int(epoch), score=self.last_score,
            zscore=self.last_z, dims=self.dims, priority=self.priority,
        )


# -- registry ----------------------------------------------------------

_REGISTRY: dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator: add a Detector subclass to the inventory.
    Re-registering the same class is idempotent; two different classes
    claiming one name is the same rot devprog.device_entry rejects."""
    prev = _REGISTRY.get(cls.name)
    if prev is not None and prev is not cls:
        raise ValueError(
            f"detector {cls.name!r} registered twice: "
            f"{prev.__qualname__} and {cls.__qualname__}"
        )
    _REGISTRY[cls.name] = cls
    return cls


def registered() -> dict[str, type]:
    """The full inventory (imports the builtin detectors first, so
    callers always see the complete set)."""
    from retina_tpu.detect import detectors  # noqa: F401

    return dict(_REGISTRY)


# -- the bank ----------------------------------------------------------

class DetectorBank:
    """Window-aligned evaluation of many detectors toward ONE sink."""

    def __init__(
        self,
        detectors: list[Detector],
        sink: Optional[Callable[[int, list[str]], Any]] = None,
        enabled: bool = True,
    ) -> None:
        self.detectors = list(detectors)
        self.sink = sink
        self.enabled = enabled
        self._epoch: int | None = None
        self._window_rows = 0
        self._last_fire: dict[str, float] = {}
        self._lock = threading.Lock()
        self.fired: list[Detection] = []  # last accepted firings

    def observe(  # hot-path: event
        self,
        epoch: int,
        records: np.ndarray | None,
        extras: Optional[dict] = None,
        now_s: float | None = None,
    ) -> list[Detection]:
        """Feed one record block for window ``epoch``. Rolling to a new
        epoch closes the previous window (score + judge + arbitrate);
        returns the detections accepted for the closed window."""
        with self._lock:
            out: list[Detection] = []
            if self._epoch is not None and epoch != self._epoch:
                out = self._close(self._epoch, now_s)
            if self._epoch != epoch:
                self._epoch = int(epoch)
                self._window_rows = 0
                for d in self.detectors:
                    d.begin_window()
            if records is not None and len(records):
                room = MAX_WINDOW_RECORDS - self._window_rows
                if room > 0:
                    block = records[:room]
                    self._window_rows += len(block)
                    for d in self.detectors:
                        d.add_records(block, extras)
            return out

    def flush(self, now_s: float | None = None) -> list[Detection]:
        """Close the in-progress window without starting a new one
        (shutdown / end of a bounded feed)."""
        with self._lock:
            if self._epoch is None:
                return []
            out = self._close(self._epoch, now_s)
            self._epoch = None
            return out

    # -- window close (under _lock) ------------------------------------
    def _close(self, epoch: int, now_s: float | None) -> list[Detection]:
        now = float(now_s) if now_s is not None else time.time()
        m = get_metrics()
        cands: list[Detection] = []
        for d in self.detectors:
            try:
                det = d.judge(epoch)
            except Exception:
                _log.exception("detector %s failed", d.name)
                continue
            m.detector_score.labels(detector=d.name).set(d.last_score)
            m.detector_zscore.labels(detector=d.name).set(d.last_z)
            if det is None:
                continue
            if not self.enabled:
                m.detector_suppressed.labels(
                    detector=d.name, reason="disabled"
                ).inc()
                continue
            last = self._last_fire.get(d.name)
            if last is not None and (now - last) < d.cooldown_s:
                m.detector_suppressed.labels(
                    detector=d.name, reason="cooldown"
                ).inc()
                continue
            cands.append(det)
        if not cands:
            return []
        cands.sort(key=lambda c: -c.priority)
        winner = cands[0]
        for c in cands[1:]:
            m.detector_suppressed.labels(
                detector=c.detector, reason="arbitration"
            ).inc()
        self._last_fire[winner.detector] = now
        m.detector_fired.labels(detector=winner.detector).inc()
        m.detector_last_epoch.labels(detector=winner.detector).set(
            winner.epoch
        )
        self.fired.append(winner)
        del self.fired[:-16]
        if self.sink is not None:
            try:
                self.sink(winner.epoch, list(winner.dims))
            except Exception:
                _log.exception("detector sink failed")
        return [winner]


def build_default_bank(
    cfg=None, sink: Optional[Callable[[int, list[str]], Any]] = None
) -> DetectorBank:
    """Every registered detector at the config-driven judgment knobs."""
    z = float(getattr(cfg, "detector_z_thresh", 8.0))
    mw = int(getattr(cfg, "detector_min_windows", 3))
    cd = float(getattr(cfg, "detector_cooldown_s", 60.0))
    dets = [
        cls(z_thresh=z, min_windows=mw, cooldown_s=cd)
        for _, cls in sorted(registered().items())
    ]
    return DetectorBank(dets, sink=sink)

"""Detector device programs — derived reductions over existing ops.

Each detector's scoring kernel is a registered device entry
(devprog.py) so the RT300-RT305 device pass lowers and audits it like
every other program: the portscan program is an HLL bank keyed by
source hash-group, the dnstunnel program is the plug-in entropy of a
qname-length histogram, the synflood program is a flag-asymmetry
ratio over the tcpflags count lanes. All three are cached jit builders
(the fold.py idiom): one compile per static signature, reused across
windows.

Inputs are tiny host-built feature arrays (detect/features.py), so the
programs cost microseconds — the point is that the SCORING algebra is
in the audited inventory, not that it needs a big accelerator.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from retina_tpu.devprog import device_entry
from retina_tpu.ops.entropy import EntropyWindow
from retina_tpu.ops.hyperloglog import HyperLogLog

# Portscan: sources are folded into this many hash-groups, each group
# an HLL of the distinct dst ports its sources probed. Precision 8
# (256 registers) bounds the estimate error well under the decision
# margin (benign feeds touch a handful of service ports; a sweep
# touches dozens).
PORTSCAN_GROUPS = 32
PORTSCAN_PRECISION = 8
PORTSCAN_SEED = 0x5CA7

# DNS tunneling: qname lengths bucketed 0..63 (labels >255B are
# rejected at parse time; 64 bins covers the exfil-relevant range).
DNSTUNNEL_BINS = 64
DNSTUNNEL_SEED = 0xD25

# Synflood input: 8 per-flag-bit packet counts (index = TCP flag bit
# position, schema.py TCP_*) + total TCP packets in lane 8.
SYNFLOOD_LANES = 9

_PORTSCAN_CACHE: dict[Any, Any] = {}
_DNSTUNNEL_CACHE: dict[Any, Any] = {}
_SYNFLOOD_CACHE: dict[Any, Any] = {}


@device_entry("detect.portscan", kind="jit")
def portscan_program(n: int, groups: int, precision: int, seed: int):
    """Jitted scan scorer: (keys (N,4) u32, weights (N,) f32) ->
    (G,) distinct-dst-port estimates per source hash-group.

    Group = multiplicative hash of src ip — a single scanning source
    lands in ONE group, so its probe breadth is not diluted across the
    bank; benign groups aggregate a few sources sharing a few service
    ports. Zero-weight rows (padding) are masked out of the HLL."""
    key = (n, groups, precision, seed)
    fn = _PORTSCAN_CACHE.get(key)
    if fn is not None:
        return fn

    def run(keys, weights):
        src = keys[:, 0]
        dport = keys[:, 3]
        group = (src * jnp.uint32(2654435761)) % jnp.uint32(groups)
        hll = HyperLogLog.zeros(groups, precision, seed=seed)
        hll = hll.update([dport], group, weights > 0)
        return hll.estimate()

    fn = jax.jit(run)
    _PORTSCAN_CACHE[key] = fn
    return fn


@device_entry("detect.dnstunnel", kind="jit")
def dnstunnel_program(nbins: int, seed: int):
    """Jitted tunnel scorer: (hist (1, nbins) f32 qname-length
    histogram) -> (2,) [entropy_bits, total_queries].

    Benign qnames cluster in a narrow length band (low entropy);
    tunneled payloads spread toward the label-length ceiling (high
    entropy) — the Sketchy/PSketch exfil signature."""
    key = (nbins, seed)
    fn = _DNSTUNNEL_CACHE.get(key)
    if fn is not None:
        return fn

    def run(hist):
        bits = EntropyWindow(counts=hist, seed=seed).entropy_bits()
        return jnp.stack([bits[0], jnp.sum(hist)])

    fn = jax.jit(run)
    _DNSTUNNEL_CACHE[key] = fn
    return fn


@device_entry("detect.synflood", kind="jit")
def synflood_program():
    """Jitted flood scorer: (lanes (9,) f32 tcpflag counts) ->
    (3,) [syn/ack ratio, syn fraction, syn count].

    A healthy TCP mix acknowledges what it opens (ratio << 1 per the
    ~1 SYN : many ACK steady state); a half-open flood inverts the
    asymmetry. Denominators floor at 1 so an all-SYN window scores by
    raw SYN volume instead of dividing by zero."""
    fn = _SYNFLOOD_CACHE.get(0)
    if fn is not None:
        return fn

    def run(lanes):
        syn = lanes[1]  # TCP_SYN = 1 << 1
        ack = lanes[4]  # TCP_ACK = 1 << 4
        total = lanes[8]
        return jnp.stack([
            syn / jnp.maximum(ack, 1.0),
            syn / jnp.maximum(total, 1.0),
            syn,
        ])

    fn = jax.jit(run)
    _SYNFLOOD_CACHE[0] = fn
    return fn

"""Closed-loop overload controller: degrade resolution, never availability.

BENCH_r05 showed the old failure mode: under sustained load the pipeline
either ran at full rate or collapsed to 0 ev/s windows (binary
nominal/degraded from the supervised-runtime PR). PSketch (PAPERS.md)
and "Sketchy With a Chance of Adoption" argue a production sketch
monitor must shed LOW-VALUE work first and keep heavy-hitter accuracy;
this module is that control loop.

The controller watches normalized pressure signals the engine feeds it
(per-worker staging fill, dispatch in-flight fill, handoff wait rate,
harvest lag — plus the ``feed.backpressure`` fault site for chaos
tests) and moves the pipeline through explicit states with hysteresis::

    NOMINAL ──p≥enter──► SAMPLING ──p≥shed──► SHEDDING ──p≥degrade──► DEGRADED
       ◄──p≤exit for dwell_s── (one level per dwell period)

* ``SAMPLING``: feed workers keep 1-in-k of the combined rows.
  Priority-aware: heavy-hitter candidates (combined packet weight ≥
  ``overload_exempt_packets``) and apiserver latency probes
  (TSVAL/TSECR lanes) are exempt; the device step rescales the
  surviving non-exempt rows by k (models/pipeline.py) so Count-Min /
  HLL / entropy estimates stay unbiased (Horvitz-Thompson). The weight
  synthesized by that rescaling is accounted in ``accuracy_debt``.
* ``SHEDDING``: enrichment stages are dropped in the declared order
  (``overload_shed_order``: DNS qname hashing → conntrack accounting →
  per-pod label resolution) before any raw event is lost; the shed set
  widens one stage per ``overload_shed_escalate_s`` while pressure
  stays at/above the shed threshold.
* ``DEGRADED``: every stage shed + sampling active; this is also where
  crash-only recovery (engine._degraded) pins the controller.

Window ticks ride the transfer mux control lane and a dedicated close
semaphore (engine._submit_close_window), so a window is ALWAYS closed —
annotated with ``sampled_fraction`` — never silently emitted as zero.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

import numpy as np

from retina_tpu.events.schema import F
from retina_tpu.log import logger
from retina_tpu.metrics import get_metrics

NOMINAL, SAMPLING, SHEDDING, DEGRADED = 0, 1, 2, 3
STATE_NAMES = ("NOMINAL", "SAMPLING", "SHEDDING", "DEGRADED")

# Enrichment stages sheddable in SHEDDING, in the only legal order:
# cheapest-to-lose first (docs/operations.md §6).
SHED_STAGES = ("dns", "conntrack", "labels")

# Priority-tier lattice (PSketch, arxiv 2509.07338): higher tiers are
# exempt from sampling, and the invertible high-priority sketch region
# (models/pipeline.py inv_hi) only ever sees TIER_PRIORITY rows — so
# priority tenants keep exact counters while background degrades first.
TIER_BACKGROUND = 0  # sampled 1-in-k under SAMPLING+
TIER_PRIORITY = 1  # per-(tenant,service) priority class (IP mask match)
TIER_HEAVY = 2  # heavy-hitter candidates (packet weight)
TIER_CONTROL = 3  # apiserver latency probes / control lane


def priority_class_np(
    src_ip: np.ndarray, dst_ip: np.ndarray, mask: int, match: int
) -> np.ndarray:
    """Host mirror of models.pipeline.priority_class — the two MUST stay
    bit-identical: the feed worker drops rows with this predicate and
    the device step rescales survivors with the jnp twin; any skew
    biases the Horvitz-Thompson estimate. mask == 0 disables the class
    (no row is priority)."""
    if mask == 0:
        return np.zeros(src_ip.shape, bool)
    m, v = np.uint32(mask), np.uint32(match)
    return ((src_ip & m) == v) | ((dst_ip & m) == v)


def row_tiers(rec: np.ndarray, cfg) -> np.ndarray:
    """Classify combined rows into the priority lattice: (N,) uint8 of
    TIER_* values, taking the HIGHEST tier each row qualifies for.
    Exemption from sampling is simply ``tier > TIER_BACKGROUND``."""
    tiers = np.zeros(rec.shape[0], np.uint8)
    tiers[
        priority_class_np(
            rec[:, F.SRC_IP], rec[:, F.DST_IP],
            int(getattr(cfg, "overload_priority_ip_mask", 0)),
            int(getattr(cfg, "overload_priority_ip_match", 0)),
        )
    ] = TIER_PRIORITY
    heavy = rec[:, F.PACKETS] >= np.uint32(cfg.overload_exempt_packets)
    tiers[heavy] = TIER_HEAVY
    control = (rec[:, F.TSVAL] | rec[:, F.TSECR]) != 0
    tiers[control] = TIER_CONTROL
    return tiers


class OverloadController:
    """State machine + host-side sampler. Thread-safe; ``tick`` is called
    from the engine feed loop (bounded by ``overload_tick_s``), readers
    (``sample_rows``/``shed_active``) run on feed workers and plugin
    threads."""

    def __init__(
        self,
        cfg,
        signals: Callable[[], dict[str, float]] | None = None,
    ) -> None:
        self.cfg = cfg
        self._signals = signals or (lambda: {})
        self.log = logger("overload")
        self._lock = threading.Lock()
        self._state = NOMINAL
        self._shed_level = 0
        self._pressure = 0.0
        self._sigvals: dict[str, float] = {}
        self._last_tick = 0.0
        self._below_since: float | None = None
        self._shed_above_since: float | None = None
        self._transitions = 0
        self._last_change = time.monotonic()
        self._phase = 0  # rotating 1-in-k phase  # guarded-by: self._lock
        # Window-scoped accounting the engine snapshots+resets at close.
        self._win_sampled = 0  # events dropped  # guarded-by: self._lock
        self._win_kept = 0  # events admitted  # guarded-by: self._lock
        self._win_priority = 0  # priority-tier events  # guarded-by: self._lock

    # -- state machine -------------------------------------------------
    def tick(self, now: float | None = None) -> int:  # runs-on: engine-dispatch
        """Advance the state machine from the current pressure signals.
        Cheap when called faster than ``overload_tick_s``."""
        cfg = self.cfg
        if not getattr(cfg, "overload_enabled", True):
            return self._state
        now = time.monotonic() if now is None else now
        if now - self._last_tick < cfg.overload_tick_s:
            return self._state
        self._last_tick = now
        try:
            sig = self._signals() or {}
        except Exception:
            self.log.exception("overload signal read failed")
            sig = {}
        p = max(sig.values(), default=0.0)
        with self._lock:
            self._pressure = p
            self._sigvals = dict(sig)
            self._advance(p, now)
            return self._state

    def _advance(self, p: float, now: float) -> None:
        cfg = self.cfg
        # Escalation is immediate: sustained saturation must not wait
        # out a dwell period while queues overflow.
        target = NOMINAL
        if p >= cfg.overload_enter_pressure:
            target = SAMPLING
        if p >= cfg.overload_shed_pressure:
            target = SHEDDING
        if p >= cfg.overload_degrade_pressure:
            target = DEGRADED
        if target > self._state:
            self._set_state(target, p, now)
            self._below_since = None
            self._shed_above_since = now
            return
        # De-escalation: one level per dwell period with pressure at or
        # below the EXIT threshold (enter > exit = the hysteresis band;
        # brief dips never flap the state).
        if self._state > NOMINAL and p <= cfg.overload_exit_pressure:
            if self._below_since is None:
                self._below_since = now
            elif now - self._below_since >= cfg.overload_dwell_s:
                self._set_state(self._state - 1, p, now)
                self._below_since = now
        else:
            self._below_since = None
        # Within SHEDDING, widen the shed set one stage per escalate
        # period while pressure holds at/above the shed threshold.
        if self._state == SHEDDING and p >= cfg.overload_shed_pressure:
            if self._shed_above_since is None:
                self._shed_above_since = now
            elif (
                now - self._shed_above_since >= cfg.overload_shed_escalate_s
                and self._shed_level < len(self._shed_order())
            ):
                self._shed_level += 1
                self._shed_above_since = now
                self.log.warning(
                    "overload: shedding widened to %s (pressure %.2f)",
                    list(self._shed_order()[: self._shed_level]), p,
                )
        elif self._state != SHEDDING:
            self._shed_above_since = None

    def _set_state(self, state: int, p: float, now: float) -> None:
        old = self._state
        self._state = state
        self._transitions += 1
        self._last_change = now
        if state >= SHEDDING:
            self._shed_level = max(1, self._shed_level)
        if state == DEGRADED:
            self._shed_level = len(self._shed_order())
        if state < SHEDDING:
            self._shed_level = 0
        get_metrics().overload_state.set(state)
        log = self.log.warning if state > old else self.log.info
        log(
            "overload: %s -> %s (pressure %.2f, signals %s)",
            STATE_NAMES[old], STATE_NAMES[state], p,
            {k: round(v, 3) for k, v in self._sigvals.items()},
        )

    def _shed_order(self) -> tuple[str, ...]:
        return tuple(getattr(self.cfg, "overload_shed_order", SHED_STAGES))

    # -- read side ------------------------------------------------------
    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return STATE_NAMES[self._state]

    @property
    def sample_k(self) -> int:
        if self._state >= SAMPLING:
            return max(1, int(self.cfg.overload_sample_k))
        return 1

    def shed_stages(self) -> tuple[str, ...]:
        return self._shed_order()[: self._shed_level]

    def shed_active(self, stage: str) -> bool:
        return stage in self._shed_order()[: self._shed_level]

    # -- sampler (feed-worker side) ------------------------------------
    def sample_rows(self, rec: np.ndarray) -> tuple[np.ndarray, int]:  # runs-on: feed-worker*
        """Apply priority-aware 1-in-k sampling to combined rows.

        Runs POST-combine (parallel/combine.py) and PRE-partition so a
        row's packet weight is final: the device step recomputes the
        SAME exemption predicate over the same rows and scales the
        non-exempt survivors by k (models/pipeline.py), keeping every
        packet-weighted estimate unbiased. Exempt (never sampled): any
        row above TIER_BACKGROUND in the priority lattice (row_tiers) —
        heavy-hitter candidates (packets >= overload_exempt_packets),
        apiserver latency probes (TSVAL/TSECR != 0), and the configured
        per-(tenant,service) priority IP class; window ticks never pass
        through here at all (control lane).

        Returns ``(kept_rows, k)`` where k is 1 when not sampling.
        """
        k = self.sample_k
        n = rec.shape[0]
        if k <= 1 or n == 0:
            if n:
                kept_ev = int(rec[:, F.PACKETS].sum())
                with self._lock:
                    self._win_kept += kept_ev
            return rec, 1
        pk = rec[:, F.PACKETS]
        tiers = row_tiers(rec, self.cfg)
        exempt = tiers > TIER_BACKGROUND
        idx = np.nonzero(~exempt)[0]
        # Under the lock: N feed workers sample concurrently, and an
        # unlocked += here loses increments against both sibling
        # workers and window_annotation's snapshot-and-reset — the
        # window's sampled_fraction then lies about admitted traffic.
        # Only the scalar bookkeeping is locked; the row selection
        # stays outside.
        with self._lock:
            phase = self._phase
            self._phase = (phase + idx.size) % k
        keep = exempt.copy()
        keep[idx[(np.arange(idx.size) + phase) % k == 0]] = True
        kept = rec[keep]
        dropped_ev = int(pk.sum()) - int(kept[:, F.PACKETS].sum())
        if dropped_ev:
            m = get_metrics()
            m.events_sampled.inc(dropped_ev)
            # Weight the device will synthesize back via x k scaling on
            # the surviving non-exempt rows: the estimated (not
            # observed) share of every sketch/counter.
            debt = (k - 1) * int(kept[~exempt[keep], F.PACKETS].sum())
            if debt:
                m.accuracy_debt.inc(debt)
        kept_ev = int(kept[:, F.PACKETS].sum())
        pri_ev = int(pk[tiers == TIER_PRIORITY].sum())
        with self._lock:
            self._win_sampled += dropped_ev
            self._win_kept += kept_ev
            self._win_priority += pri_ev
        return kept, k

    def note_shed(self, stage: str, amount: int = 1) -> None:
        """Account one shed enrichment unit (events for dns, passes for
        conntrack/labels — see docs/metrics.md)."""
        if amount:
            get_metrics().events_shed.labels(stage=stage).inc(amount)

    # -- window annotation ---------------------------------------------
    def window_annotation(self) -> dict:  # runs-on: device-proxy
        """Snapshot + reset the per-window sampling accounting; the
        engine attaches this to every closed window (harvest item)."""
        with self._lock:
            sampled, kept = self._win_sampled, self._win_kept
            priority = self._win_priority
            self._win_sampled = 0
            self._win_kept = 0
            self._win_priority = 0
            total = sampled + kept
            return {
                "overload_state": STATE_NAMES[self._state],
                "sampled_fraction":
                    (sampled / total) if total else 0.0,
                "events_sampled": sampled,
                "priority_exempt_events": priority,
                "shed": list(self.shed_stages()),
            }

    # -- observability --------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "state": STATE_NAMES[self._state],
                "pressure": round(self._pressure, 4),
                "signals": {
                    k: round(v, 4) for k, v in self._sigvals.items()
                },
                "sample_k": self.sample_k,
                "shed": list(self.shed_stages()),
                "transitions": self._transitions,
                "since_change_s": round(
                    time.monotonic() - self._last_change, 1
                ),
            }


def validate_shed_order(order: Iterable[str]) -> tuple[str, ...]:
    """Config-time check: a permutation-prefix of the known stages."""
    order = tuple(order)
    if len(set(order)) != len(order):
        raise ValueError(f"overload_shed_order has duplicates: {order}")
    unknown = set(order) - set(SHED_STAGES)
    if unknown:
        raise ValueError(
            f"unknown overload shed stage(s) {sorted(unknown)}; "
            f"known: {list(SHED_STAGES)}"
        )
    return order

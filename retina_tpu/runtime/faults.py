"""Deterministic fault injection for the chaos suite.

Armed from config/env (``cfg.fault_spec`` / ``RETINA_FAULT_SPEC``) with
a comma-separated spec; each entry is ``site:action[@N]``:

    transfer:raise@3            raise InjectedFault on the 3rd transfer
    harvest:hang@1              hang the harvest thread on its 1st item
    plugin.packetparser:raise@1 crash the plugin's 1st start attempt
    checkpoint:corrupt@1        torn-write the next checkpoint save
    feed.backpressure:press     synthetic queue saturation (sustained)

Actions: ``raise`` (InjectedFault), ``hang`` (block on a module Event
until ``release_hangs()``/``clear()``; ``hang5`` bounds it to 5 s),
``corrupt`` (queried by the checkpoint writer via ``should_corrupt``),
``press`` (sustained saturation queried via ``pressure`` — active from
the first query until ``clear()``, or for ``press5`` = 5 s; drives the
overload controller, runtime/overload.py).
``@N`` fires on exactly the Nth hit of that site; ``@0`` / omitted
fires on every hit. Disarmed (the default) every hook is a single
boolean check — zero cost on the hot path.

This module is intentionally global state: the hooks live deep in the
engine/plugin hot paths where threading a handle through would touch
every constructor. ``configure``/``clear`` own the lifecycle; tests
must ``clear()`` in teardown (the chaos conftest fixture does).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional

from retina_tpu.log import logger

_log = logger("faults")


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` rule — recovery paths treat it as
    an unrecoverable device/runtime error."""


class _Rule:
    __slots__ = ("site", "action", "nth", "hang_s", "hits", "fired",
                 "since")

    def __init__(self, site: str, action: str, nth: int,
                 hang_s: Optional[float]):
        self.site = site
        self.action = action
        self.nth = nth
        self.hang_s = hang_s  # also the press duration for "press"
        self.hits = 0
        self.fired = 0
        self.since: Optional[float] = None  # first press query (monotonic)


_lock = threading.Lock()
_rules: Dict[str, _Rule] = {}
_armed = False  # fast-path gate: hooks return immediately when False
_unhang = threading.Event()

_ENTRY = re.compile(
    r"^(?P<site>[\w.\-]+):(?P<action>raise|corrupt"
    r"|hang(?P<hang_s>\d+(\.\d+)?)?"
    r"|press(?P<press_s>\d+(\.\d+)?)?)"
    r"(?:@(?P<nth>\d+))?$"
)


def configure(spec: str) -> None:
    """Arm the layer from a spec string; empty/blank disarms."""
    global _armed
    entries: Dict[str, _Rule] = {}
    for raw in (spec or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _ENTRY.match(raw)
        if m is None:
            raise ValueError(
                f"bad fault spec entry {raw!r} "
                "(want site:action[@N], action in "
                "raise|hang[secs]|corrupt|press[secs])"
            )
        action = m.group("action")
        hang_s: Optional[float] = None
        if action.startswith("hang"):
            hang_s = float(m.group("hang_s")) if m.group("hang_s") else None
            action = "hang"
        elif action.startswith("press"):
            hang_s = float(m.group("press_s")) if m.group("press_s") else None
            action = "press"
        entries[m.group("site")] = _Rule(
            m.group("site"), action, int(m.group("nth") or 0), hang_s
        )
    with _lock:
        _unhang.set()  # free anything hung by a previous spec
        _rules.clear()
        _rules.update(entries)
        _armed = bool(entries)
        if _armed:
            _unhang.clear()
    if entries:
        _log.warning(
            "fault injection ARMED: %s",
            ",".join(f"{r.site}:{r.action}@{r.nth}" for r in entries.values()),
        )


def clear() -> None:
    """Disarm and release any hung threads."""
    global _armed
    with _lock:
        _armed = False
        _rules.clear()
        _unhang.set()


def release_hangs() -> None:
    """Unblock threads currently parked in a ``hang`` rule without
    disarming the remaining rules."""
    _unhang.set()


def armed() -> bool:
    return _armed


def inject(site: str) -> None:
    """Hot-path hook: no-op unless armed with a matching rule whose
    Nth hit this is. ``raise`` rules raise InjectedFault; ``hang``
    rules block until released (or their bound elapses)."""
    if not _armed:
        return
    with _lock:
        r = _rules.get(site)
        if r is None:
            return
        r.hits += 1
        if r.nth and r.hits != r.nth:
            return
        r.fired += 1
        action, hang_s, hit = r.action, r.hang_s, r.hits
    if action == "raise":
        raise InjectedFault(f"injected fault at {site} (hit {hit})")
    if action == "hang":
        _log.warning("injected hang at %s (hit %d)", site, hit)
        _unhang.wait(hang_s)


def should_corrupt(site: str) -> bool:
    """Queried by writers (checkpoint save) that implement corruption
    themselves; True on the armed Nth hit of a ``corrupt`` rule."""
    if not _armed:
        return False
    with _lock:
        r = _rules.get(site)
        if r is None or r.action != "corrupt":
            return False
        r.hits += 1
        if r.nth and r.hits != r.nth:
            return False
        r.fired += 1
        return True


def pressure(site: str) -> bool:
    """Sustained query-style saturation: True while an armed ``press``
    rule for ``site`` is active. Unlike ``inject`` this does not
    consume hits one-shot — the overload controller polls it every
    tick; an unbounded rule stays active until ``clear()``, a bounded
    one (``press5``) for that many seconds after its first query."""
    if not _armed:
        return False
    import time as _time

    with _lock:
        r = _rules.get(site)
        if r is None or r.action != "press":
            return False
        r.hits += 1
        now = _time.monotonic()
        if r.since is None:
            r.since = now
            r.fired += 1
            _log.warning("injected backpressure at %s active", site)
        if r.hang_s is not None and now - r.since > r.hang_s:
            return False
        return True


def stats() -> dict:
    with _lock:
        return {
            "armed": _armed,
            "rules": {
                s: {"action": r.action, "nth": r.nth,
                    "hits": r.hits, "fired": r.fired}
                for s, r in _rules.items()
            },
        }

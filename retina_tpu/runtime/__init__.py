"""Supervised-runtime primitives: heartbeat watchdog, restart policies
with circuit breaking, and the deterministic fault-injection layer the
chaos suite drives (ROADMAP: crash-only posture for the metrics path).
"""

"""Supervision tree for the agent's long-lived threads.

Three cooperating pieces, kept deliberately dependency-light so the
engine can use them standalone (tests construct a SketchEngine without
a ControllerManager):

  Heartbeat      — a per-thread liveness cell. The owning thread calls
                   ``beat()`` each loop iteration and ``park()`` right
                   before an intentional blocking wait (queue.get,
                   Event.wait, a device fence) so the watchdog does not
                   mistake idleness for a stall.
  Supervisor     — the registry + watchdog scan thread. A heartbeat
                   whose age exceeds its deadline while not parked is a
                   stall: logged, counted in ``watchdog_stalls`` and
                   escalated through the heartbeat's ``on_stall``
                   callback (e.g. the engine replaces a hung harvest
                   thread). Escalation re-fires once per deadline while
                   the stall persists and re-arms on the next beat.
  RestartPolicy  — exponential backoff + jitter with a crash-loop
                   circuit breaker (closed → open after
                   ``max_failures`` consecutive crashes → half_open
                   probe after ``half_open_after_s`` → closed again
                   once a probe run stays healthy for ``window_s``).

``Supervisor.spawn`` ties them together into a supervised thread: the
target is restarted under the policy until it returns cleanly, the
stop event fires, or the circuit gives up to half-open probing.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Callable, Dict, Optional

from retina_tpu.log import logger

_log = logger("supervisor")


class Heartbeat:
    """Liveness cell for one long-lived thread.

    ``beat()`` is wait-free for the owner (a monotonic-clock store);
    the watchdog reads it from its own thread. ``park()`` marks the
    thread as intentionally blocked so idle waits never count as
    stalls — only work that *started* (a beat after the last park) and
    then stopped making progress does.
    """

    __slots__ = ("name", "deadline_s", "on_stall", "_last", "_parked",
                 "_stalled_since", "_last_escalation", "stalls")

    def __init__(self, name: str, deadline_s: float = 30.0,
                 on_stall: Optional[Callable[[], None]] = None):
        self.name = name
        self.deadline_s = float(deadline_s)
        self.on_stall = on_stall
        self._last = time.monotonic()
        self._parked = False
        self._stalled_since: Optional[float] = None
        self._last_escalation = 0.0
        self.stalls = 0

    def beat(self) -> None:
        self._last = time.monotonic()
        self._parked = False
        self._stalled_since = None

    def park(self) -> None:
        """Declare an intentional blocking wait (queue.get / Event.wait
        / device fence). The watchdog skips parked heartbeats."""
        self._last = time.monotonic()
        self._parked = True

    @property
    def parked(self) -> bool:
        return self._parked

    def age(self, now: Optional[float] = None) -> float:
        return (time.monotonic() if now is None else now) - self._last

    def stats(self) -> dict:
        return {
            "age_s": round(self.age(), 3),
            "deadline_s": self.deadline_s,
            "parked": self._parked,
            "stalled": self._stalled_since is not None,
            "stalls": self.stalls,
        }


class RestartPolicy:
    """Exponential backoff + crash-loop circuit breaker.

    States: ``closed`` (normal; crashes get a backoff delay),
    ``open`` (``max_failures`` consecutive crashes — the caller should
    stop hammering and surface unhealthy), ``half_open`` (one probe
    run allowed; a crash re-opens, staying healthy for ``window_s``
    closes). A run that lives longer than ``window_s`` resets the
    consecutive-failure count, so sporadic crashes spread over time
    never open the circuit.
    """

    def __init__(self, base_s: float = 0.2, max_s: float = 30.0,
                 jitter: float = 0.2, max_failures: int = 5,
                 window_s: float = 60.0, half_open_after_s: float = 30.0,
                 seed: Optional[int] = None):
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self.max_failures = int(max_failures)
        self.window_s = float(window_s)
        self.half_open_after_s = float(half_open_after_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._started: Optional[float] = None
        self.restarts = 0  # total crashes recorded over the lifetime

    # -- state ---------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_close_locked(time.monotonic())
            return self._state

    def _maybe_close_locked(self, now: float) -> None:
        # A half-open probe that has stayed up past the healthy window
        # closes the circuit; same window resets closed-state streaks.
        if self._started is None:
            return
        if now - self._started >= self.window_s:
            self._consecutive = 0
            if self._state == "half_open":
                self._state = "closed"

    def note_start(self) -> None:
        """Record that a supervised run (or probe) just started."""
        with self._lock:
            self._started = time.monotonic()

    def record_failure(self) -> Optional[float]:
        """Record a crash. Returns the backoff delay to wait before the
        next attempt, or ``None`` when the circuit just opened (caller
        should go unhealthy and fall back to half-open probing)."""
        now = time.monotonic()
        with self._lock:
            self._maybe_close_locked(now)
            self.restarts += 1
            self._started = None
            if self._state == "half_open":
                self._state = "open"
                return None
            self._consecutive += 1
            if self._consecutive >= self.max_failures:
                self._state = "open"
                return None
            d = min(self.base_s * (2.0 ** (self._consecutive - 1)),
                    self.max_s)
            return d * (1.0 + self.jitter * self._rng.random())

    def wait_half_open(self, stop: threading.Event) -> bool:
        """Block (stop-interruptibly) until the half-open probe window,
        then transition open → half_open. False if stop fired."""
        if stop.wait(self.half_open_after_s):
            return False
        with self._lock:
            if self._state == "open":
                self._state = "half_open"
        return True

    def reset(self) -> None:
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._started = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "restarts": self.restarts,
            }


def policy_from_config(cfg, seed_key: str = "") -> RestartPolicy:
    """Build a RestartPolicy from the agent Config knobs. ``seed_key``
    derives a stable per-thread jitter seed so backoff schedules are
    reproducible across runs (and decorrelated across threads)."""
    seed = zlib.crc32(seed_key.encode()) if seed_key else None
    return RestartPolicy(
        base_s=cfg.restart_backoff_base_s,
        max_s=cfg.restart_backoff_max_s,
        jitter=cfg.restart_backoff_jitter,
        max_failures=cfg.restart_max_failures,
        window_s=cfg.restart_window_s,
        half_open_after_s=cfg.circuit_half_open_s,
        seed=seed,
    )


class Supervisor:
    """Heartbeat registry + watchdog.

    Threads register once (idempotent by name — a replacement thread
    re-registering under the same name takes over the cell) and beat;
    the watchdog scans every ``interval_s`` and escalates stalls. The
    watchdog itself is crash-proof: a throwing ``on_stall`` callback is
    contained and counted, never kills the scan loop.
    """

    def __init__(self, deadline_s: float = 30.0, interval_s: float = 0.5):
        self.deadline_s = float(deadline_s)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._beats: Dict[str, Heartbeat] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registry ------------------------------------------------------
    def register(self, name: str, deadline_s: Optional[float] = None,
                 on_stall: Optional[Callable[[], None]] = None) -> Heartbeat:
        hb = Heartbeat(name, deadline_s or self.deadline_s, on_stall)
        with self._lock:
            old = self._beats.get(name)
            if old is not None:
                hb.stalls = old.stalls  # cumulative across replacements
            self._beats[name] = hb
        return hb

    def deregister(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)

    def heartbeat(self, name: str) -> Optional[Heartbeat]:
        with self._lock:
            return self._beats.get(name)

    # -- watchdog ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.interval_s))
        self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scan_once()
            except Exception:
                _log.exception("watchdog scan failed")

    def scan_once(self, now: Optional[float] = None) -> list:
        """One watchdog pass; returns the names escalated this pass
        (exposed for deterministic tests)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            beats = list(self._beats.values())
        escalated = []
        for hb in beats:
            if hb.parked or hb.age(now) <= hb.deadline_s:
                continue
            # Escalate at most once per deadline while the stall lasts.
            if now - hb._last_escalation < hb.deadline_s:
                continue
            hb._last_escalation = now
            if hb._stalled_since is None:
                hb._stalled_since = now
            hb.stalls += 1
            escalated.append(hb.name)
            _log.error(
                "watchdog: thread %s stalled (no beat for %.1fs, "
                "deadline %.1fs)", hb.name, hb.age(now), hb.deadline_s,
            )
            self._count_stall(hb.name)
            if hb.on_stall is not None:
                try:
                    hb.on_stall()
                except Exception:
                    _log.exception(
                        "watchdog: on_stall for %s failed", hb.name
                    )
        return escalated

    @staticmethod
    def _count_stall(name: str) -> None:
        # Late import keeps bare unit tests from paying the exporter
        # registry cost until a stall actually happens.
        from retina_tpu.metrics import get_metrics

        get_metrics().watchdog_stalls.labels(thread=name).inc()

    # -- supervised threads -------------------------------------------
    def spawn(self, name: str, target: Callable[[], None],
              stop: threading.Event,
              policy: Optional[RestartPolicy] = None) -> threading.Thread:
        """Run ``target`` on a named daemon thread, restarting it under
        ``policy`` when it raises. A clean return ends supervision; an
        open circuit falls back to half-open probing until stop."""
        pol = policy or RestartPolicy()

        def _runner() -> None:
            while not stop.is_set():
                pol.note_start()
                try:
                    target()
                    return
                except Exception:
                    if stop.is_set():
                        return
                    delay = pol.record_failure()
                    if delay is None:
                        _log.exception(
                            "supervised thread %s crash-looping; circuit "
                            "OPEN (half-open probe in %.0fs)",
                            name, pol.half_open_after_s,
                        )
                        if not pol.wait_half_open(stop):
                            return
                        continue
                    _log.exception(
                        "supervised thread %s crashed; restart in %.2fs",
                        name, delay,
                    )
                    self._count_restart(name)
                    if stop.wait(delay):
                        return

        t = threading.Thread(target=_runner, name=name, daemon=True)
        t.start()
        return t

    @staticmethod
    def _count_restart(name: str) -> None:
        from retina_tpu.metrics import get_metrics

        get_metrics().thread_restarts.labels(thread=name).inc()

    # -- introspection -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {name: hb.stats() for name, hb in self._beats.items()}

    def summary(self) -> dict:
        with self._lock:
            beats = list(self._beats.values())
        return {
            "threads": len(beats),
            "stalled": sum(
                1 for hb in beats if hb._stalled_since is not None
            ),
            "stalls_total": sum(hb.stalls for hb in beats),
        }

"""Basic metric declarations + control-plane self metrics.

Reference analog: pkg/metrics/metrics.go:14-120 — ``InitializeMetrics``
creates every node-level gauge and control-plane counter once at daemon
start, into the default registry. Names come from utils.metric_names
(networkobservability_*). Advanced (pod-level) metric families are created
by the metrics module on reconcile instead (module/metrics.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from retina_tpu.exporter import Exporter, get_exporter
from retina_tpu.log import logger
from retina_tpu.utils import metric_names as mn

_log = logger("metrics")


class Metrics:
    """All basic gauges/counters, created against one Exporter."""

    def __init__(self, exporter: Optional[Exporter] = None) -> None:
        ex = exporter or get_exporter()
        g, c = ex.new_gauge, ex.new_counter
        # node-level data-plane gauges (metrics.go:14-80)
        self.drop_count = g(mn.DROP_COUNT, [mn.L_REASON, mn.L_DIRECTION])
        self.drop_bytes = g(mn.DROP_BYTES, [mn.L_REASON, mn.L_DIRECTION])
        self.forward_count = g(mn.FORWARD_COUNT, [mn.L_DIRECTION])
        self.forward_bytes = g(mn.FORWARD_BYTES, [mn.L_DIRECTION])
        self.tcp_state = g(mn.TCP_STATE, [mn.L_STATE])
        self.tcp_connection_remote = g(
            mn.TCP_CONNECTION_REMOTE, [mn.L_IP, mn.L_PORT]
        )
        self.tcp_connection_stats = g(mn.TCP_CONNECTION_STATS, [mn.L_STAT])
        self.tcp_flag_counters = g(mn.TCP_FLAG_COUNTERS, [mn.L_FLAG])
        self.ip_connection_stats = g(mn.IP_CONNECTION_STATS, [mn.L_STAT])
        self.udp_connection_stats = g(mn.UDP_CONNECTION_STATS, [mn.L_STAT])
        self.interface_stats = g(
            mn.INTERFACE_STATS, [mn.L_INTERFACE, mn.L_STAT]
        )
        self.infiniband_counter_stats = g(
            mn.INFINIBAND_COUNTER_STATS, ["device", "port", mn.L_STAT]
        )
        self.infiniband_status_params = g(
            mn.INFINIBAND_STATUS_PARAMS, ["interface", mn.L_STAT]
        )
        self.dns_request_count = g(mn.DNS_REQUEST_COUNT, [mn.L_QTYPE])
        self.dns_response_count = g(
            mn.DNS_RESPONSE_COUNT, [mn.L_QTYPE, mn.L_RCODE]
        )
        self.conntrack_packets = g(mn.CONNTRACK_PACKETS, [mn.L_DIRECTION])
        self.active_connections = g(mn.ACTIVE_CONNECTIONS, [])
        # Declared for external connectivity probers to set, exactly as
        # the reference declares them unconsumed (metrics.go:49-60).
        self.node_connectivity_status = g(
            mn.NODE_CONNECTIVITY_STATUS, ["source_node", "target_node"]
        )
        self.node_connectivity_latency = g(
            mn.NODE_CONNECTIVITY_LATENCY, ["source_node", "target_node"]
        )
        self.conntrack_bytes = g(mn.CONNTRACK_BYTES, [mn.L_DIRECTION])

        # sketch-derived node-level series
        self.distinct_flows = g(mn.DISTINCT_FLOWS, [])
        self.distinct_src_per_reason = g(
            mn.DISTINCT_SRC_PER_REASON, [mn.L_REASON]
        )
        self.entropy_bits = g(mn.ENTROPY_BITS, [mn.L_DIMENSION])
        self.anomaly_flag = g(mn.ANOMALY_FLAG, [mn.L_DIMENSION])
        self.anomaly_zscore = g(mn.ANOMALY_ZSCORE, [mn.L_DIMENSION])
        self.anomaly_windows = c(mn.ANOMALY_WINDOWS, [mn.L_DIMENSION])

        # control-plane self metrics (metrics.go:100-120)
        self.plugin_reconcile_failures = c(
            mn.PLUGIN_RECONCILE_FAILURES, [mn.L_PLUGIN]
        )
        self.lost_events = c(mn.LOST_EVENTS, [mn.L_STAGE, mn.L_PLUGIN])
        self.lost_table_entries = c(mn.LOST_TABLE_ENTRIES, [mn.L_TABLE])
        self.filter_push_failures = c(mn.FILTER_PUSH_FAILURES, [])
        self.flow_dict_entries = g(mn.FLOW_DICT_ENTRIES, [])
        self.flow_dict_generation = g(mn.FLOW_DICT_GENERATION, [])
        self.wire_rows = c(mn.WIRE_ROWS, [mn.L_KIND])
        self.parsed_packets = c(mn.PARSED_PACKETS, [mn.L_PLUGIN])
        self.device_step_seconds = ex.new_histogram(
            mn.DEVICE_STEP_SECONDS,
            [],
            buckets=[1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0],
        )
        self.device_batch_fill = g(mn.DEVICE_BATCH_FILL, [])
        self.windows_closed = c(mn.WINDOWS_CLOSED, [])
        # Window ticks deferred while the close program was still
        # queued in the background warm (stall-free close contract).
        self.windows_deferred = c(mn.WINDOWS_DEFERRED, [])
        # Sharded feed-worker backpressure (parallel/feed.py).
        self.feed_worker_fill = g(mn.FEED_WORKER_FILL, [mn.L_WORKER])
        self.feed_handoff_wait = c(mn.FEED_HANDOFF_WAIT, [mn.L_WORKER])
        self.feed_blocks_dropped = c(
            mn.FEED_BLOCKS_DROPPED, [mn.L_WORKER]
        )
        # events-in / rows-transferred of the host combiner (the kernel-map
        # aggregation factor; parallel/combine.py). 1.0 = nothing merged.
        self.combine_ratio = g(mn.COMBINE_RATIO, [])
        self.transfer_seconds = ex.new_histogram(
            mn.TRANSFER_SECONDS,
            [],
            buckets=[1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0],
        )
        self.transfer_bytes = c(mn.TRANSFER_BYTES, [])
        # Supervised-runtime robustness series (runtime/supervisor.py;
        # see metric_names for semantics).
        self.engine_restarts = c(mn.ENGINE_RESTARTS, [])
        self.watchdog_stalls = c(mn.WATCHDOG_STALLS, [mn.L_THREAD])
        self.plugin_restarts = c(mn.PLUGIN_RESTARTS, [mn.L_PLUGIN])
        self.thread_restarts = c(mn.THREAD_RESTARTS, [mn.L_THREAD])
        self.engine_errors = c(mn.ENGINE_ERRORS, [mn.L_SITE])
        self.degraded_mode = g(mn.DEGRADED_MODE, [])
        self.recovery_seconds = ex.new_histogram(
            mn.RECOVERY_SECONDS,
            [],
            buckets=[0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 60.0, 120.0],
        )
        # Adaptive overload control (runtime/overload.py; see
        # metric_names for semantics).
        self.overload_state = g(mn.OVERLOAD_STATE, [])
        self.events_sampled = c(mn.EVENTS_SAMPLED, [])
        self.events_shed = c(mn.EVENTS_SHED, [mn.L_STAGE])
        self.accuracy_debt = c(mn.ACCURACY_DEBT, [])
        # Device->host bytes (snapshot readbacks): on a serialized
        # tunnel link they share the same pipe as transfer_bytes, so
        # link-utilization math must sum both directions.
        self.readback_bytes = c(mn.READBACK_BYTES, [])
        # Fleet rollup tier (fleet/; see metric_names for semantics).
        # Node-side shipper:
        self.fleet_snapshots_shipped = c(mn.FLEET_SNAPSHOTS_SHIPPED, [])
        self.fleet_ship_bytes = c(mn.FLEET_SHIP_BYTES, [])
        self.fleet_ship_deferred = c(mn.FLEET_SHIP_DEFERRED, [])
        self.fleet_ship_dropped = c(mn.FLEET_SHIP_DROPPED, [])
        self.fleet_ship_errors = c(mn.FLEET_SHIP_ERRORS, [])
        # Send-failure survival (fleet/shipper.py): spool occupancy
        # events, the replay on heal, channel re-dials, and the
        # circuit-open health gauge (1 while the relay is unreachable).
        self.fleet_ship_spooled = c(mn.FLEET_SHIP_SPOOLED, [])
        self.fleet_ship_spool_evicted = c(mn.FLEET_SHIP_SPOOL_EVICTED, [])
        self.fleet_ship_spool_replayed = c(
            mn.FLEET_SHIP_SPOOL_REPLAYED, []
        )
        self.fleet_ship_reconnects = c(mn.FLEET_SHIP_RECONNECTS, [])
        self.fleet_ship_circuit_open = g(mn.FLEET_SHIP_CIRCUIT_OPEN, [])
        # Two-level rollup: merged epochs re-shipped to the parent
        # (root) aggregator.
        self.fleet_rollups_reshipped = c(mn.FLEET_ROLLUPS_RESHIPPED, [])
        # Operator-side aggregator:
        self.fleet_snapshots_received = c(
            mn.FLEET_SNAPSHOTS_RECEIVED, [mn.L_NODE]
        )
        self.fleet_snapshots_dropped = c(
            mn.FLEET_SNAPSHOTS_DROPPED, [mn.L_REASON]
        )
        self.fleet_windows_merged = c(mn.FLEET_WINDOWS_MERGED, [])
        self.fleet_windows_stragglers = c(mn.FLEET_WINDOWS_STRAGGLERS, [])
        self.fleet_merge_errors = c(mn.FLEET_MERGE_ERRORS, [])
        self.fleet_merge_seconds = g(mn.FLEET_MERGE_SECONDS, [])
        self.fleet_nodes_reporting = g(mn.FLEET_NODES_REPORTING, [])
        # Keyed cluster families (cleared + re-published per epoch;
        # label space bounded by the fleet guardrail knobs).
        self.fleet_top_flows = g(mn.FLEET_TOP_FLOWS, [mn.L_KEY])
        self.fleet_tenant_top_flows = g(
            mn.FLEET_TENANT_TOP_FLOWS, [mn.L_TENANT, mn.L_KEY]
        )
        self.fleet_service_cardinality = g(
            mn.FLEET_SERVICE_CARDINALITY, [mn.L_SERVICE]
        )
        self.fleet_entropy_bits = g(mn.FLEET_ENTROPY_BITS, [mn.L_DIMENSION])
        self.fleet_distinct_flows = g(mn.FLEET_DISTINCT_FLOWS, [])
        self.fleet_tenant_series = g(mn.FLEET_TENANT_SERIES, [mn.L_TENANT])
        self.fleet_series_capped = c(mn.FLEET_SERIES_CAPPED, [])
        self.fleet_tenants_shed = c(mn.FLEET_TENANTS_SHED, [])
        # Invertible sketch decode (ops/invertible.py; see metric_names
        # for semantics). Node side:
        self.invertible_keys_recovered = g(mn.INVERTIBLE_KEYS_RECOVERED, [])
        self.invertible_decode_failed = c(mn.INVERTIBLE_DECODE_FAILED, [])
        self.invertible_recall = g(mn.INVERTIBLE_RECALL, [])
        self.invertible_precision = g(mn.INVERTIBLE_PRECISION, [])
        # Fleet side (cleared + re-published per epoch like the other
        # keyed cluster families):
        self.fleet_invertible_keys = g(mn.FLEET_INVERTIBLE_KEYS, [])
        self.fleet_invertible_sources = g(
            mn.FLEET_INVERTIBLE_SOURCES, [mn.L_KEY]
        )
        self.fleet_invertible_decode_failed = c(
            mn.FLEET_INVERTIBLE_DECODE_FAILED, []
        )
        # Time-travel query ring + closed-loop capture (timetravel/).
        self.timetravel_ring_appended = c(
            mn.TIMETRAVEL_RING_APPENDED, [mn.L_RING]
        )
        self.timetravel_ring_dropped = c(
            mn.TIMETRAVEL_RING_DROPPED, [mn.L_RING]
        )
        self.timetravel_ring_depth = g(
            mn.TIMETRAVEL_RING_DEPTH, [mn.L_RING]
        )
        self.timetravel_queries = c(mn.TIMETRAVEL_QUERIES, [mn.L_STATUS])
        self.timetravel_query_seconds = ex.new_histogram(
            mn.TIMETRAVEL_QUERY_SECONDS, [],
            buckets=[1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0],
        )
        self.timetravel_query_windows = g(mn.TIMETRAVEL_QUERY_WINDOWS, [])
        self.autocapture_triggered = c(mn.AUTOCAPTURE_TRIGGERED, [])
        self.autocapture_suppressed = c(
            mn.AUTOCAPTURE_SUPPRESSED, [mn.L_REASON]
        )
        self.autocapture_completed = c(mn.AUTOCAPTURE_COMPLETED, [])
        self.autocapture_failed = c(mn.AUTOCAPTURE_FAILED, [])
        self.autocapture_attributed_keys = g(mn.AUTOCAPTURE_KEYS, [])
        self.autocapture_artifact_bytes = g(
            mn.AUTOCAPTURE_ARTIFACT_BYTES, []
        )
        self.autocapture_last_epoch = g(mn.AUTOCAPTURE_LAST_EPOCH, [])
        # Pluggable detector bank (detect/): per-detector firing
        # telemetry; label space is the fixed detector registry.
        self.detector_fired = c(mn.DETECTOR_FIRED, [mn.L_DETECTOR])
        self.detector_suppressed = c(
            mn.DETECTOR_SUPPRESSED, [mn.L_DETECTOR, mn.L_REASON]
        )
        self.detector_score = g(mn.DETECTOR_SCORE, [mn.L_DETECTOR])
        self.detector_zscore = g(mn.DETECTOR_ZSCORE, [mn.L_DETECTOR])
        self.detector_last_epoch = g(
            mn.DETECTOR_LAST_EPOCH, [mn.L_DETECTOR]
        )
        # Fleet query plane (fleetquery/): scatter-gather fan-out
        # telemetry; buckets match timetravel_query_seconds so node
        # and fleet p99s read off the same grid.
        self.fleet_query_requests = c(
            mn.FLEET_QUERY_REQUESTS, [mn.L_STATUS]
        )
        self.fleet_query_seconds = ex.new_histogram(
            mn.FLEET_QUERY_SECONDS, [],
            buckets=[1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0],
        )
        self.fleet_query_nodes_answered = g(
            mn.FLEET_QUERY_NODES_ANSWERED, []
        )
        self.fleet_query_node_errors = c(
            mn.FLEET_QUERY_NODE_ERRORS, [mn.L_REASON]
        )
        self.fleet_query_hedges = c(mn.FLEET_QUERY_HEDGES, [])
        self.fleet_query_coverage = g(mn.FLEET_QUERY_COVERAGE, [])
        # Endurance soak harness (soak/runner.py): phase progress +
        # sentinel verdicts, scrapeable mid-soak.
        self.soak_phases = c(mn.TPU_SOAK_PHASES, [])
        self.soak_sentinel_failures = c(
            mn.TPU_SOAK_SENTINEL_FAILURES, [mn.L_SENTINEL]
        )
        self.soak_recovery_seconds = g(mn.TPU_SOAK_RECOVERY_SECONDS, [])
        # Flight recorder (obs/recorder.py): per-stage span latency.
        # Label space is the FIXED stage registry (mn.STAGES); buckets
        # span sub-ms host hops to multi-second device round-trips.
        self.stage_seconds = ex.new_histogram(
            mn.TPU_STAGE_SECONDS,
            [mn.L_STAGE],
            buckets=[1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2,
                     0.1, 0.3, 1.0, 3.0],
        )
        # Build identity + process uptime (set once / ticked by the
        # engine; docs/observability.md).
        self.build_info = g(
            mn.RETINA_BUILD_INFO,
            ["version", "jax", "backend", "devices", "config"],
        )
        self.uptime_seconds = g(mn.TPU_UPTIME_SECONDS, [])


_singleton: Metrics | None = None
_lock = threading.Lock()


def initialize_metrics(exporter: Optional[Exporter] = None) -> Metrics:
    """Idempotent metric creation (reference InitializeMetrics)."""
    global _singleton
    with _lock:
        if _singleton is None:
            _singleton = Metrics(exporter)
        return _singleton


def get_metrics() -> Metrics:
    m = _singleton
    if m is None:
        return initialize_metrics()
    return m


def reset_for_tests() -> None:
    global _singleton
    with _lock:
        _singleton = None

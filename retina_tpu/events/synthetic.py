"""Synthetic traffic generation (the trafficgen analog).

Reference analog: the reference generates test/e2e traffic with agnhost/
kapinger deployments and deny-all network policies to force drops
(test/trafficgen/{agnhost,kapinger,deny}.yaml, SURVEY.md §4). With no
cluster in the loop, the TPU framework's equivalent is a vectorized
host-side generator producing (N, NUM_FIELDS) record arrays directly:
Zipf-weighted flow popularity (heavy hitters exist by construction, so
benchmarks can score recall/F1 against ground truth), a configurable drop
fraction, DNS query mix, and a DDoS burst mode for the entropy detector
(BASELINE config 4).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from retina_tpu.events.schema import (
    EV_DNS_REQ,
    EV_DNS_RESP,
    EV_DROP,
    EV_FORWARD,
    F,
    NUM_FIELDS,
    OP_FROM_NETWORK,
    OP_TO_NETWORK,
    PROTO_TCP,
    PROTO_UDP,
    TCP_ACK,
    TCP_SYN,
    DIR_EGRESS,
    DIR_INGRESS,
    VERDICT_DROPPED,
    VERDICT_FORWARDED,
)

POD_NET = 0x0A000000  # 10.0.0.0/8: pod IPs are POD_NET + pod_index

# Generator regime presets (cfg.gen_preset): parameter overrides
# applied on top of the TrafficGen defaults. "zipf" is the heavy-tail
# regime the detector/attribution arc is validated against — a steeper
# exponent concentrates traffic on a handful of flows (the PSketch
# skew on real eBPF feeds); "uniform" flattens the flow-size
# distribution toward the top-k worst case. "default" applies nothing.
#
# The four named attack/churn regimes are the PSketch workloads real
# eBPF feeds produce (PAPERS.md, arxiv 2509.07338) — each sets
# ``mode`` (a batch-shaping pass in TrafficGen.batch) plus the
# distribution params that make the regime adversarial for a specific
# subsystem: dns_flood hammers the qname path + DNS string table,
# syn_storm floods half-open TCP (entropy detector + drop accounting),
# conntrack_churn gives almost every event a fresh ephemeral 5-tuple
# (flow-dict/descriptor-table churn), elephant_mice splits bytes
# bimodally between a few huge flows and a long mouse tail (top-k vs
# CMS tension).
#
# This table is the SINGLE source of legal preset names:
# config.Config.validate checks ``gen_preset`` against it, and
# tests/test_soak_harness.py cross-checks table ↔ validation ↔ docs so
# a preset added in one place cannot drift from the others (the RT230
# knob-drift philosophy applied to regimes).
PRESETS: dict[str, dict[str, float | str]] = {
    "default": {},
    "zipf": {"zipf_a": 1.6},
    "uniform": {"zipf_a": 1.001},
    "dns_flood": {"mode": "dns_flood", "dns_fraction": 0.8,
                  "zipf_a": 1.5},
    "syn_storm": {"mode": "syn_storm", "zipf_a": 1.05,
                  "drop_fraction": 0.15},
    "conntrack_churn": {"mode": "conntrack_churn", "zipf_a": 1.05},
    "elephant_mice": {"mode": "elephant_mice", "zipf_a": 2.0},
    # Vertical port sweep: a handful of scanner sources probe many
    # dst ports on one victim (detect.portscan's matching regime).
    "portscan": {"mode": "portscan", "zipf_a": 1.2},
    # Banked-capture replay: batches come from the real pcap fixtures
    # under tests/fixtures/real via sources/pcapreplay.py (timestamp
    # rebasing per pass) instead of the synthetic sampler — realistic
    # negatives for the detector bank, real byte-stream provenance.
    "pcap_replay": {"mode": "pcap_replay"},
}

# Legal TrafficGen.mode values ("mix" is the default mixed TCP/UDP
# forward/drop/DNS blend the original generator produced).
MODES = ("mix", "dns_flood", "syn_storm", "conntrack_churn",
         "elephant_mice", "portscan", "pcap_replay")


def preset_params(name: str) -> dict[str, float | str]:
    """Overrides for one preset; unknown names raise (config.validate
    rejects them earlier — this guards direct library callers)."""
    try:
        return dict(PRESETS[name])
    except KeyError:
        raise ValueError(f"unknown gen_preset {name!r}") from None


def pod_ip(index: int) -> int:
    return POD_NET + index


@dataclasses.dataclass
class TrafficGen:
    """Vectorized flow-event generator with Zipf flow popularity.

    A fixed table of ``n_flows`` 5-tuples between ``n_pods`` pod IPs is
    drawn once; batches sample flow ids from a Zipf law so a handful of
    flows dominate (ground truth for heavy-hitter scoring via
    ``true_counts``).
    """

    n_flows: int = 100_000
    n_pods: int = 256
    zipf_a: float = 1.2
    drop_fraction: float = 0.02
    dns_fraction: float = 0.01
    # Batch-shaping regime (MODES): "mix" is the classic blend; the
    # named attack/churn regimes reshape each batch after the base
    # sampling pass (see _shape_regime); "pcap_replay" bypasses the
    # sampler and serves rebased passes over the banked captures.
    mode: str = "mix"
    seed: int = 0
    # pcap_replay inputs; empty = the repo's banked fixtures
    # (tests/fixtures/real/*.pcap).
    pcap_paths: tuple[str, ...] = ()

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"TrafficGen mode must be one of {MODES}, "
                f"got {self.mode!r}"
            )
        if self.mode == "pcap_replay":
            self._init_replay()
        rng = np.random.default_rng(self.seed)
        n = self.n_flows
        self.src_pod = rng.integers(1, self.n_pods, n).astype(np.uint32)
        self.dst_pod = rng.integers(1, self.n_pods, n).astype(np.uint32)
        self.src_ip = (POD_NET + self.src_pod).astype(np.uint32)
        self.dst_ip = (POD_NET + self.dst_pod).astype(np.uint32)
        self.sport = rng.integers(1024, 65536, n).astype(np.uint32)
        self.dport = rng.choice(
            np.array([80, 443, 53, 8080, 5432], np.uint32), n
        ).astype(np.uint32)
        self.proto = np.where(
            rng.random(n) < 0.8, PROTO_TCP, PROTO_UDP
        ).astype(np.uint32)
        # Zipf ranks: flow id k gets weight (k+1)^-a.
        w = (np.arange(1, n + 1, dtype=np.float64)) ** (-self.zipf_a)
        self.flow_probs = w / w.sum()
        self._rng = rng
        self._counts = np.zeros(n, np.int64)
        self._now_ns = 1_700_000_000 * 1_000_000_000

    # -- pcap replay (mode="pcap_replay") ------------------------------
    def _init_replay(self) -> None:
        """Decode the banked captures once; batches then come from
        looping, timestamp-rebased passes (sources/pcapreplay.py)."""
        import pathlib

        from retina_tpu.sources.pcapreplay import (
            PcapReplaySource, safe_decode_bytes,
        )

        paths = [pathlib.Path(p) for p in self.pcap_paths]
        if not paths:
            fixture_dir = (
                pathlib.Path(__file__).resolve().parents[2]
                / "tests" / "fixtures" / "real"
            )
            paths = sorted(fixture_dir.glob("*.pcap"))
        blocks = []
        for p in paths:
            dec = safe_decode_bytes(p.read_bytes())
            if len(dec.result.records):
                blocks.append(dec.result.records)
        if not blocks:
            raise ValueError(
                "pcap_replay: no decodable records in "
                + (", ".join(str(p) for p in paths) or "<no files>")
            )
        self._replay_src = PcapReplaySource(np.concatenate(blocks))
        self._replay_blocks = self._replay_src.blocks()
        self._replay_buf = np.zeros((0, NUM_FIELDS), np.uint32)
        self._replay_pos = 0

    def _replay_batch(self, n_events: int) -> np.ndarray:
        out = []
        have = 0
        while have < n_events:
            if self._replay_pos >= len(self._replay_buf):
                blk = next(self._replay_blocks, None)
                if blk is None:  # pass done -> next rebased pass
                    self._replay_blocks = self._replay_src.blocks()
                    blk = next(self._replay_blocks)
                self._replay_buf, self._replay_pos = blk, 0
            take = min(
                n_events - have, len(self._replay_buf) - self._replay_pos
            )
            out.append(
                self._replay_buf[self._replay_pos:self._replay_pos + take]
            )
            self._replay_pos += take
            have += take
        return np.concatenate(out).astype(np.uint32)

    # ------------------------------------------------------------------
    def batch(self, n_events: int) -> np.ndarray:
        """Generate (n_events, NUM_FIELDS) uint32 records."""
        if self.mode == "pcap_replay":
            return self._replay_batch(n_events)
        rng = self._rng
        fid = rng.choice(self.n_flows, n_events, p=self.flow_probs)
        np.add.at(self._counts, fid, 1)
        rec = np.zeros((n_events, NUM_FIELDS), np.uint32)
        ts = self._now_ns + np.arange(n_events, dtype=np.int64) * 1000
        self._now_ns = int(ts[-1]) + 1000
        rec[:, F.TS_LO] = (ts & 0xFFFFFFFF).astype(np.uint32)
        rec[:, F.TS_HI] = (ts >> 32).astype(np.uint32)
        rec[:, F.SRC_IP] = self.src_ip[fid]
        rec[:, F.DST_IP] = self.dst_ip[fid]
        rec[:, F.PORTS] = (self.sport[fid] << np.uint32(16)) | self.dport[fid]
        flags = np.where(
            rng.random(n_events) < 0.05, TCP_SYN, TCP_ACK
        ).astype(np.uint32)
        obs = np.where(
            rng.random(n_events) < 0.5, OP_FROM_NETWORK, OP_TO_NETWORK
        ).astype(np.uint32)
        direction = np.where(
            obs == OP_FROM_NETWORK, DIR_INGRESS, DIR_EGRESS
        ).astype(np.uint32)
        rec[:, F.META] = (
            (self.proto[fid] << np.uint32(24))
            | (flags << np.uint32(16))
            | (obs << np.uint32(8))
            | (direction << np.uint32(4))
        )
        rec[:, F.BYTES] = rng.integers(64, 1500, n_events).astype(np.uint32)
        rec[:, F.PACKETS] = 1
        dropped = rng.random(n_events) < self.drop_fraction
        rec[:, F.VERDICT] = np.where(
            dropped, VERDICT_DROPPED, VERDICT_FORWARDED
        ).astype(np.uint32)
        rec[:, F.DROP_REASON] = np.where(
            dropped, rng.integers(1, 8, n_events), 0
        ).astype(np.uint32)
        rec[:, F.EVENT_TYPE] = np.where(dropped, EV_DROP, EV_FORWARD).astype(
            np.uint32
        )
        # DNS sprinkle: rewrite a small fraction as query/response pairs.
        is_dns = rng.random(n_events) < self.dns_fraction
        is_resp = is_dns & (rng.random(n_events) < 0.5)
        rec[is_dns, F.EVENT_TYPE] = np.where(
            is_resp[is_dns], EV_DNS_RESP, EV_DNS_REQ
        ).astype(np.uint32)
        qtype = rng.choice(np.array([1, 28, 5], np.uint32), n_events)
        # F.DNS low byte carries the qname length (schema leaves it
        # free: qtype<<16 | rcode<<8 | len). Benign names cluster in a
        # narrow 8..16 band — the detect.dnstunnel baseline.
        qlen = rng.integers(8, 17, n_events).astype(np.uint32)
        rec[is_dns, F.DNS] = (
            (qtype[is_dns] << np.uint32(16)) | qlen[is_dns]
        ).astype(np.uint32)
        rec[is_dns, F.DNS_QHASH] = (fid[is_dns] & 0xFFFF).astype(np.uint32)
        return self._shape_regime(rec, fid)

    def _shape_regime(self, rec: np.ndarray, fid: np.ndarray) -> np.ndarray:
        """Reshape one sampled batch into the active attack/churn
        regime (PRESETS table). Runs after the base "mix" pass so every
        regime keeps the same ground-truth flow accounting
        (``true_counts`` tracks fid regardless of shaping)."""
        if self.mode == "mix":
            return rec
        rng = self._rng
        n = len(rec)
        if self.mode == "dns_flood":
            # Query flood: the dns_fraction share (0.8 under the
            # preset) all targets a handful of resolver pods over
            # UDP:53 with tiny frames — the qname-hash path and the
            # host DNS string table carry the regime's weight.
            is_dns = np.isin(
                rec[:, F.EVENT_TYPE],
                np.array([EV_DNS_REQ, EV_DNS_RESP], np.uint32),
            )
            resolvers = (POD_NET + 1 + (fid % 4)).astype(np.uint32)
            rec[is_dns, F.DST_IP] = resolvers[is_dns]
            rec[is_dns, F.PORTS] = (
                rec[is_dns, F.PORTS] & np.uint32(0xFFFF0000)
            ) | np.uint32(53)
            rec[is_dns, F.META] = (
                rec[is_dns, F.META] & np.uint32(0x00FFFFFF)
            ) | (np.uint32(PROTO_UDP) << np.uint32(24))
            rec[is_dns, F.BYTES] = rng.integers(
                64, 140, int(is_dns.sum())
            ).astype(np.uint32)
            # Encoded-payload qnames: lengths spread toward the label
            # ceiling instead of the benign 8..16 cluster — the
            # detect.dnstunnel entropy signature.
            qlen = rng.integers(24, 64, int(is_dns.sum())).astype(
                np.uint32
            )
            rec[is_dns, F.DNS] = (
                rec[is_dns, F.DNS] & np.uint32(0xFFFFFF00)
            ) | qlen
        elif self.mode == "syn_storm":
            # Half-open flood: most rows become 64-byte TCP SYNs from
            # spoofed (non-pod) sources onto a few victim pods —
            # src-IP entropy spikes, dst-IP entropy collapses, and the
            # preset's drop_fraction models the policy drops.
            storm = rng.random(n) < 0.9
            ns = int(storm.sum())
            rec[storm, F.SRC_IP] = rng.integers(
                0xC6000000, 0xC7000000, ns
            ).astype(np.uint32)
            victims = (POD_NET + 1 + (fid % 8)).astype(np.uint32)
            rec[storm, F.DST_IP] = victims[storm]
            rec[storm, F.META] = (
                (np.uint32(PROTO_TCP) << np.uint32(24))
                | (np.uint32(TCP_SYN) << np.uint32(16))
                | (np.uint32(OP_FROM_NETWORK) << np.uint32(8))
                | (np.uint32(DIR_INGRESS) << np.uint32(4))
            )
            rec[storm, F.BYTES] = 64
        elif self.mode == "conntrack_churn":
            # Short-lived connections: every event gets a fresh
            # ephemeral source port, so nearly every combined row is a
            # DISTINCT 5-tuple — the flow-descriptor dictionary and
            # conntrack table churn instead of settling (the regime
            # the soak fd-churn sentinel bounds).
            eph = rng.integers(1024, 65536, n).astype(np.uint32)
            rec[:, F.PORTS] = (eph << np.uint32(16)) | (
                rec[:, F.PORTS] & np.uint32(0xFFFF)
            )
            syn = rng.random(n) < 0.3
            rec[syn, F.META] = (
                rec[syn, F.META] & np.uint32(0xFF00FFFF)
            ) | (np.uint32(TCP_SYN) << np.uint32(16))
        elif self.mode == "portscan":
            # Vertical sweep: most rows become SYN probes from a few
            # scanner sources walking dst ports 1..1024 on one victim
            # — per-source distinct-dst-port counts explode while the
            # remaining mix keeps the benign floor visible.
            scan = rng.random(n) < 0.6
            ns = int(scan.sum())
            scanners = (np.uint32(0xC9000000) + (fid % 4).astype(
                np.uint32
            ))
            rec[scan, F.SRC_IP] = scanners[scan]
            rec[scan, F.DST_IP] = pod_ip(1)
            sweep = rng.integers(1, 1025, ns).astype(np.uint32)
            rec[scan, F.PORTS] = (np.uint32(40000) << np.uint32(16)) | sweep
            rec[scan, F.META] = (
                (np.uint32(PROTO_TCP) << np.uint32(24))
                | (np.uint32(TCP_SYN) << np.uint32(16))
                | (np.uint32(OP_FROM_NETWORK) << np.uint32(8))
                | (np.uint32(DIR_INGRESS) << np.uint32(4))
            )
            rec[scan, F.BYTES] = 64
        elif self.mode == "elephant_mice":
            # Bimodal sizes: the steep-Zipf head flows carry MTU-sized
            # frames while the mouse tail sends minimum-size ones —
            # byte-weighted top-k and count-weighted CMS disagree by
            # construction.
            elephant = fid < max(1, self.n_flows // 100)
            rec[elephant, F.BYTES] = rng.integers(
                1400, 1501, int(elephant.sum())
            ).astype(np.uint32)
            rec[~elephant, F.BYTES] = rng.integers(
                64, 200, int((~elephant).sum())
            ).astype(np.uint32)
        return rec

    def true_counts(self) -> np.ndarray:
        """(n_flows,) exact per-flow event counts generated so far."""
        return self._counts.copy()

    def true_top_k(self, k: int) -> np.ndarray:
        """Flow ids of the k most frequent flows so far."""
        return np.argsort(self._counts)[::-1][:k]

    # ------------------------------------------------------------------
    def ddos_batch(
        self, n_events: int, target_pod: int = 1, n_sources: int = 50_000
    ) -> np.ndarray:
        """A volumetric attack: many random sources -> one destination.

        Spikes src-IP entropy and collapses dst-IP entropy — the signature
        the EntropyWindow/AnomalyEWMA detector (BASELINE config 4) flags.
        """
        rng = self._rng
        rec = np.zeros((n_events, NUM_FIELDS), np.uint32)
        ts = self._now_ns + np.arange(n_events, dtype=np.int64) * 100
        self._now_ns = int(ts[-1]) + 100
        rec[:, F.TS_LO] = (ts & 0xFFFFFFFF).astype(np.uint32)
        rec[:, F.TS_HI] = (ts >> 32).astype(np.uint32)
        rec[:, F.SRC_IP] = rng.integers(
            0xC0000000, 0xC0000000 + n_sources, n_events
        ).astype(np.uint32)
        rec[:, F.DST_IP] = pod_ip(target_pod)
        rec[:, F.PORTS] = (
            rng.integers(1024, 65536, n_events).astype(np.uint32) << np.uint32(16)
        ) | np.uint32(80)
        rec[:, F.META] = (
            (np.uint32(PROTO_TCP) << np.uint32(24))
            | (np.uint32(TCP_SYN) << np.uint32(16))
            | (np.uint32(OP_FROM_NETWORK) << np.uint32(8))
            | (np.uint32(DIR_INGRESS) << np.uint32(4))
        )
        rec[:, F.BYTES] = 64
        rec[:, F.PACKETS] = 1
        rec[:, F.VERDICT] = VERDICT_FORWARDED
        rec[:, F.EVENT_TYPE] = EV_FORWARD
        return rec

    def portscan_batch(
        self,
        n_events: int,
        target_pod: int = 1,
        n_scanners: int = 4,
        n_ports: int = 24,
    ) -> np.ndarray:
        """A vertical port sweep with ATTRIBUTABLE ground truth: few
        scanner sources × few probed ports = few distinct flow keys,
        each heavy enough for invertible decode, while per-source
        distinct dst ports spike (detect.portscan's signature)."""
        rng = self._rng
        rec = np.zeros((n_events, NUM_FIELDS), np.uint32)
        ts = self._now_ns + np.arange(n_events, dtype=np.int64) * 100
        self._now_ns = int(ts[-1]) + 100
        rec[:, F.TS_LO] = (ts & 0xFFFFFFFF).astype(np.uint32)
        rec[:, F.TS_HI] = (ts >> 32).astype(np.uint32)
        scanner = rng.integers(0, n_scanners, n_events).astype(np.uint32)
        rec[:, F.SRC_IP] = np.uint32(0xC9000000) + scanner
        rec[:, F.DST_IP] = pod_ip(target_pod)
        port = (1 + rng.integers(0, n_ports, n_events)).astype(np.uint32)
        rec[:, F.PORTS] = (np.uint32(40000) << np.uint32(16)) | port
        rec[:, F.META] = (
            (np.uint32(PROTO_TCP) << np.uint32(24))
            | (np.uint32(TCP_SYN) << np.uint32(16))
            | (np.uint32(OP_FROM_NETWORK) << np.uint32(8))
            | (np.uint32(DIR_INGRESS) << np.uint32(4))
        )
        rec[:, F.BYTES] = 64
        rec[:, F.PACKETS] = 1
        rec[:, F.VERDICT] = VERDICT_FORWARDED
        rec[:, F.EVENT_TYPE] = EV_FORWARD
        return rec

    def tunnel_batch(
        self,
        n_events: int,
        resolver_pod: int = 2,
        n_clients: int = 48,
    ) -> np.ndarray:
        """DNS exfiltration with attributable ground truth: clients
        stream TXT queries with long, varied qname lengths at one
        resolver — (client, resolver, UDP, 53) keys are few and heavy
        while qname-length entropy spikes (detect.dnstunnel)."""
        rng = self._rng
        rec = np.zeros((n_events, NUM_FIELDS), np.uint32)
        ts = self._now_ns + np.arange(n_events, dtype=np.int64) * 100
        self._now_ns = int(ts[-1]) + 100
        rec[:, F.TS_LO] = (ts & 0xFFFFFFFF).astype(np.uint32)
        rec[:, F.TS_HI] = (ts >> 32).astype(np.uint32)
        client = rng.integers(0, n_clients, n_events).astype(np.uint32)
        rec[:, F.SRC_IP] = np.uint32(0xCA000000) + client
        rec[:, F.DST_IP] = pod_ip(resolver_pod)
        eph = rng.integers(1024, 65536, n_events).astype(np.uint32)
        rec[:, F.PORTS] = (eph << np.uint32(16)) | np.uint32(53)
        rec[:, F.META] = (
            (np.uint32(PROTO_UDP) << np.uint32(24))
            | (np.uint32(OP_FROM_NETWORK) << np.uint32(8))
            | (np.uint32(DIR_INGRESS) << np.uint32(4))
        )
        qlen = rng.integers(24, 64, n_events).astype(np.uint32)
        rec[:, F.DNS] = (np.uint32(16) << np.uint32(16)) | qlen  # TXT
        rec[:, F.DNS_QHASH] = rng.integers(
            0, 1 << 16, n_events
        ).astype(np.uint32)
        rec[:, F.BYTES] = rng.integers(100, 300, n_events).astype(
            np.uint32
        )
        rec[:, F.PACKETS] = 1
        rec[:, F.VERDICT] = VERDICT_FORWARDED
        rec[:, F.EVENT_TYPE] = EV_DNS_REQ
        return rec

"""Sketch-state checkpoint/resume.

Reference analog (SURVEY.md §5.4): the reference's persistent state is
pinned BPF maps on bpffs that survive agent restarts
(pkg/bpf/setup_linux.go:19-56, retina_filter.c:20, conntrack.c:96); the
agent itself is stateless. Here the analog is the device-resident sketch
state: snapshot it to disk on shutdown (or every snapshot_interval_s) and
restore on boot, so counters/sketches survive a restart the way pinned
maps do.

Format: one .npz of the flattened pytree leaves + a config fingerprint.
The tree structure is a pure function of PipelineConfig, so leaves alone
reconstruct the state; a config mismatch (different table shapes) refuses
to load — the reference equivalent is recreating maps whose spec changed.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import numpy as np

from retina_tpu.log import logger
from retina_tpu.models.pipeline import PipelineConfig

_log = logger("checkpoint")


def _fingerprint(pcfg: PipelineConfig) -> str:
    return json.dumps(dataclasses.asdict(pcfg), sort_keys=True)


def save_state(path: str, state, pcfg: PipelineConfig) -> None:
    """Atomic checkpoint write: full npz to a same-directory temp file,
    fsync, then rename over ``path`` — a crash mid-write leaves the old
    checkpoint intact, never a torn one."""
    from retina_tpu.runtime import faults

    leaves = jax.tree.flatten(state)[0]
    host = [np.asarray(x) for x in leaves]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    np.savez_compressed(
        tmp,
        __config__=np.frombuffer(
            _fingerprint(pcfg).encode(), np.uint8
        ),
        **{f"leaf_{i}": a for i, a in enumerate(host)},
    )
    # np.savez appends .npz when missing; normalize then atomically swap.
    actual_tmp = tmp if tmp.endswith(".npz") else tmp + ".npz"
    if faults.should_corrupt("checkpoint"):
        # Chaos hook: simulate the torn write the tmp+rename protocol
        # exists to prevent, so load_state's corruption path is
        # exercised end to end.
        size = os.path.getsize(actual_tmp)
        with open(actual_tmp, "r+b") as fh:
            fh.truncate(max(16, size // 2))
    with open(actual_tmp, "rb") as fh:
        os.fsync(fh.fileno())
    os.replace(actual_tmp, path)
    _log.info("state checkpoint written: %s (%d leaves)", path, len(host))


def _quarantine(path: str, why: str) -> None:
    _log.warning(
        "checkpoint unusable (%s): %s — quarantining to %s.bad and "
        "cold-starting", why, path, path,
    )
    try:
        os.replace(path, path + ".bad")
    except OSError:
        _log.warning("could not quarantine %s", path, exc_info=True)


def load_state(path: str, sharded, pcfg: PipelineConfig):
    """Restore into a zero state built by ``sharded.init_state()``.

    Crash-only contract: a missing, truncated, corrupt, or
    fingerprint-mismatched checkpoint never raises — the bad file is
    quarantined to ``path + ".bad"`` and a clean zero state is
    returned. Returns ``(state, resumed)`` where ``resumed`` is False
    on any cold start.
    """
    zero = sharded.init_state()
    if not os.path.exists(path):
        return zero, False
    try:
        with np.load(path) as z:
            stored_cfg = bytes(z["__config__"]).decode()
            if stored_cfg != _fingerprint(pcfg):
                _quarantine(
                    path, "config fingerprint mismatch — table shapes changed"
                )
                return zero, False
            leaves, treedef = jax.tree.flatten(zero)
            loaded = []
            for i, leaf in enumerate(leaves):
                a = z[f"leaf_{i}"]
                if a.shape != leaf.shape or a.dtype != leaf.dtype:
                    _quarantine(
                        path,
                        f"leaf {i} shape/dtype mismatch "
                        f"({a.shape}/{a.dtype} vs {leaf.shape}/{leaf.dtype})",
                    )
                    return zero, False
                loaded.append(a)
    except Exception as e:
        # zipfile/np.load raise a zoo of types on truncated or garbage
        # files (BadZipFile, EOFError, KeyError, OSError, ValueError);
        # all of them mean the same thing here: not a usable checkpoint.
        _quarantine(path, f"{type(e).__name__}: {e}")
        return zero, False
    state = jax.tree.unflatten(treedef, loaded)
    _log.info("state checkpoint restored: %s", path)
    return state, True

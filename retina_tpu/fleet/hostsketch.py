"""Pure-numpy node-window sketch builder (JAX-free child processes).

The churn harness (fleet/churn.py) runs ≥64 node agents as separate OS
processes; importing JAX in every child costs seconds of startup and
hundreds of MB each, and the child never touches a device. This module
builds the full RFLT array catalog with numpy only, mirroring the
device builders bit-for-bit where the algebra demands it:

- CMS tables, HLL register banks, and entropy histograms are
  BIT-IDENTICAL to ops/countmin.py / ops/hyperloglog.py /
  ops/entropy.py (same fmix32 hash family via ops/hashing.py's
  ``*_np`` mirrors, same index math, wrapping uint32 adds).
- Heavy-hitter candidate tables reproduce the device's two-pass
  scatter-max/winner-write. On equal-estimate ties the device scatter
  keeps an unspecified winning lane; this builder keeps the last batch
  row, which is a valid candidate of equal count — the documented
  contract (ops/topk.py), so counts match exactly and key rows match
  on any tie-free batch.

Shapes/seeds are the fleet dryrun's (fleet/dryrun.py) so frames from
real child processes and simulated in-process agents are
interchangeable on the wire.

Traffic generation lives here too: :func:`epoch_traffic` derives one
node-epoch's flows from ``default_rng((run_seed, node_index, epoch))``,
so the harness parent recomputes EXACT per-flow ground truth for any
(node, epoch) pair without any IPC — restart-safe by construction (a
respawned node regenerates the same stream).
"""

from __future__ import annotations

from collections import Counter

import numpy as np

from retina_tpu.ops.hashing_np import hash_cols_np, reduce_range_np

# Mirror of fleet/dryrun.py's simulated-agent shapes (the dryrun cannot
# import from here being re-exported back without a cycle risk, so the
# authoritative values are asserted equal in tests/test_fleet_churn.py).
SLOTS = 1 << 10
WIDTH = 1 << 12
DEPTH = 4
PODS = 16
HLL_FLOWS_P = 10
HLL_POD_P = 6
ENTROPY_BUCKETS = 1 << 10

BASE_SEEDS = {
    "flow": 1, "svc": 2, "dns": 3,
    "hll_flows": 4, "hll_src_per_pod": 6, "entropy": 7,
}

# Slot/register hash-chain constants (must match ops/topk.py,
# ops/hyperloglog.py, ops/entropy.py).
_TOPK_SALT = 0x70CC
_HLL_SALT = 0xC0FFEE
_ENT_SALT = 0xE17209


def rotated_seeds(gen: int) -> dict[str, int]:
    """Seed set for one rotation generation (gen 0 = BASE_SEEDS).

    A rotation re-keys every hash family at once; the +1000·gen offset
    keeps generations disjoint while staying deterministic fleet-wide.
    """
    return {k: v + 1000 * int(gen) for k, v in BASE_SEEDS.items()}


def cms_update_np(
    table: np.ndarray, key_cols: list[np.ndarray],
    weights: np.ndarray, seed: int,
) -> np.ndarray:
    """In-place plain Count-Min add (ops/countmin.py twin)."""
    depth, width = table.shape
    seeds = (
        np.arange(1, depth + 1, dtype=np.uint32) + np.uint32(seed)
    ).reshape(depth, 1)
    h = hash_cols_np([c[None, :] for c in key_cols], seeds)
    idx = reduce_range_np(h, width)  # (depth, B)
    wts = weights.astype(table.dtype)
    for d in range(depth):
        np.add.at(table[d], idx[d], wts)
    return table


def cms_query_np(
    table: np.ndarray, key_cols: list[np.ndarray], seed: int
) -> np.ndarray:
    """Point estimates: min over depth rows (ops/countmin.py twin)."""
    depth, width = table.shape
    seeds = (
        np.arange(1, depth + 1, dtype=np.uint32) + np.uint32(seed)
    ).reshape(depth, 1)
    idx = reduce_range_np(
        hash_cols_np([c[None, :] for c in key_cols], seeds), width
    )
    return np.min(
        np.take_along_axis(table, idx.astype(np.int64), axis=1), axis=0
    )


def topk_update_np(
    key_rows: np.ndarray, counts: np.ndarray,
    key_cols: list[np.ndarray], estimates: np.ndarray, seed: int,
) -> None:
    """In-place candidate-table offer (ops/topk.py update twin):
    scatter-max estimates into slot counts, then winner rows (estimate
    == post-max slot count, estimate > 0) overwrite slot keys."""
    s = counts.shape[0]
    slot = reduce_range_np(
        hash_cols_np(key_cols, np.uint32(_TOPK_SALT) + np.uint32(seed)), s
    )
    est = estimates.astype(np.uint32)
    np.maximum.at(counts, slot, est)
    win = (est == counts[slot]) & (est > 0)
    rows = np.stack(key_cols, axis=1).astype(np.uint32)
    key_rows[slot[win]] = rows[win]


def hll_update_np(
    registers: np.ndarray, key_cols: list[np.ndarray],
    group: np.ndarray, seed: int,
) -> None:
    """In-place HLL register scatter-max (ops/hyperloglog.py twin;
    every batch row observed — callers pre-filter masked rows)."""
    g, m = registers.shape
    h = hash_cols_np(key_cols, np.uint32(_HLL_SALT) + np.uint32(seed))
    idx = reduce_range_np(h, m)
    p = int(m).bit_length() - 1
    rest = h >> np.uint32(p)
    folded = rest.copy()
    for shift in (1, 2, 4, 8, 16):
        folded |= folded >> np.uint32(shift)
    hsb = np.bitwise_count(folded).astype(np.int64) - 1  # -1 if rest==0
    rho = ((32 - p) - hsb).astype(np.uint32)
    np.maximum.at(
        registers.reshape(-1),
        group.astype(np.uint64) * np.uint64(m) + idx.astype(np.uint64),
        rho,
    )


def entropy_update_np(
    hist: np.ndarray, key_cols: list[np.ndarray],
    group: np.ndarray, weights: np.ndarray, seed: int,
) -> None:
    """In-place hashed-histogram add (ops/entropy.py twin)."""
    g, k = hist.shape
    h = hash_cols_np(key_cols, np.uint32(_ENT_SALT) + np.uint32(seed))
    idx = reduce_range_np(h, k)
    np.add.at(
        hist.reshape(-1),
        group.astype(np.uint64) * np.uint64(k) + idx.astype(np.uint64),
        weights.astype(np.float32),
    )


def sketch_arrays_np(
    keys: np.ndarray, w: np.ndarray, seeds: dict[str, int],
) -> dict[str, np.ndarray]:
    """One node-window's full wire array catalog from (B, 4) uint32 keys
    + integer weights — the numpy twin of dryrun._sketch_arrays."""
    cols = [np.ascontiguousarray(keys[:, i], np.uint32) for i in range(4)]
    wu = w.astype(np.uint32)
    out: dict[str, np.ndarray] = {}
    for fam, fam_cols in (
        ("flow", cols), ("svc", cols[:2]), ("dns", [cols[3]]),
    ):
        seed = int(seeds[fam])
        cms = np.zeros((DEPTH, WIDTH), np.uint32)
        cms_update_np(cms, fam_cols, wu, seed)
        est = cms_query_np(cms, fam_cols, seed)
        est = np.where(wu > 0, est, np.uint32(0))
        key_rows = np.zeros((SLOTS, len(fam_cols)), np.uint32)
        counts = np.zeros((SLOTS,), np.uint32)
        topk_update_np(key_rows, counts, fam_cols, est, seed)
        out[f"{fam}_cms"] = cms
        out[f"{fam}_keys"] = key_rows
        out[f"{fam}_counts"] = counts
    hllf = np.zeros((1, 1 << HLL_FLOWS_P), np.uint32)
    hll_update_np(
        hllf, cols, np.zeros(len(w), np.int64), int(seeds["hll_flows"])
    )
    out["hll_flows"] = hllf
    hllp = np.zeros((PODS, 1 << HLL_POD_P), np.uint32)
    pods = (cols[1] % np.uint32(PODS)).astype(np.int64)
    hll_update_np(hllp, [cols[0]], pods, int(seeds["hll_src_per_pod"]))
    out["hll_src_per_pod"] = hllp
    ent = np.zeros((3, ENTROPY_BUCKETS), np.float32)
    for g, c in enumerate((cols[0], cols[1], cols[3])):
        entropy_update_np(
            ent, [c], np.full(len(w), g, np.int64), w,
            int(seeds["entropy"]),
        )
    out["entropy"] = ent
    totals = np.zeros(8, np.uint32)
    totals[0] = np.uint32(min(int(w.sum()), 0xFFFFFFFF))
    out["totals"] = totals
    return out


# -- deterministic traffic (shared child/parent ground truth) ----------

def heavy_keys(run_seed: int, n: int) -> np.ndarray:
    """Fleet-global heavy flow keys: every node carries a share every
    epoch, so cluster totals exist on no single node."""
    rng = np.random.default_rng((int(run_seed), 999_999))
    return rng.integers(0, 2**32, size=(n, 4), dtype=np.uint32)


def epoch_traffic(
    run_seed: int, node_index: int, epoch: int,
    n_heavy: int, n_light: int,
) -> tuple[np.ndarray, np.ndarray]:
    """(keys (B, 4) uint32, weights (B,) int64) for one node-epoch.

    Seeded by (run_seed, node_index, epoch): any party — the child that
    ships it, the parent that scores it, a respawned replacement after
    a restart — regenerates the identical stream.
    """
    rng = np.random.default_rng(
        (int(run_seed), int(node_index), int(epoch))
    )
    hk = heavy_keys(run_seed, n_heavy)
    hw = rng.integers(100, 200, size=n_heavy)
    lkeys = rng.integers(0, 2**32, size=(n_light, 4), dtype=np.uint32)
    lw = rng.integers(1, 4, size=n_light)
    keys = np.concatenate([hk, lkeys])
    w = np.concatenate([hw, lw]).astype(np.int64)
    return keys, w


def exact_counter(
    run_seed: int, node_index: int, epoch: int,
    n_heavy: int, n_light: int,
) -> Counter:
    """Exact per-flow Counter for one node-epoch (scoring side)."""
    keys, w = epoch_traffic(run_seed, node_index, epoch, n_heavy, n_light)
    c: Counter = Counter()
    for row, wt in zip(keys, w):
        c[tuple(int(x) for x in row)] += int(wt)
    return c

"""Multi-agent fleet rollup dryrun (``bench.py --fleet-dryrun``).

Simulates N node agents in ONE process: each agent thread owns real
sketch objects (ops/), a real :class:`SnapshotShipper`, and ships real
RFLT frames over the in-process pubsub bus to one
:class:`FleetAggregator` — the full wire path minus the engines and the
gRPC hop. Exact per-flow ground-truth counts ride alongside, so the run
scores cluster top-k recall against the exact merged counts of the
nodes each rollup actually merged (late/dead nodes excluded on BOTH
sides — the acceptance contract is "unaffected beyond the dropped
share").

One agent is killed mid-run (``kill_after``): epochs after the kill
must still close via the straggler timeout, never blocking on the dead
node.

:func:`run_invertible_dryrun` (``bench.py --invertible-dryrun``) is the
key-RECOVERY variant: nodes ship ONLY counter arrays (flow CMS + the
invertible bit planes, no candidate key tables at all), the aggregator
decodes cluster-wide heavy keys from the merged sketch state, and the
scorecard checks recall >= 0.95 against exact ground truth — including
under a forced SHEDDING episode where background flows are 1-in-k
sampled with Horvitz-Thompson rescale while priority-class flows ride
the never-sampled full-accuracy region and must keep recall 1.0.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import Counter
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

from retina_tpu.config import Config
from retina_tpu.fleet.aggregator import FleetAggregator
from retina_tpu.obs.recorder import get_recorder
from retina_tpu.utils import metric_names as mn
from retina_tpu.fleet.shipper import SnapshotShipper
from retina_tpu.ops.countmin import CountMinSketch
from retina_tpu.ops.entropy import EntropyWindow
from retina_tpu.ops.hyperloglog import HyperLogLog
from retina_tpu.ops.invertible import InvertibleSketch
from retina_tpu.ops.topk import HeavyHitterSketch

# Sketch shapes for the simulated agents: small enough that 8+ agents
# build a window in milliseconds, wide enough that CMS noise stays far
# below the heavy/light weight separation.
_SLOTS = 1 << 10
_WIDTH = 1 << 12
_DEPTH = 4
_PODS = 16

SEEDS = {
    "flow": 1, "svc": 2, "dns": 3,
    "hll_flows": 4, "hll_src_per_pod": 6, "entropy": 7,
}

# Invertible-dryrun shapes/seeds (mirror the engine's inv_flow/inv_hi
# region split; sizes small enough to build a window in milliseconds).
INV_SEEDS = dict(SEEDS, inv_flow=9, inv_hi=10)
_INV_DEPTH = 2
_INV_WIDTH = 1 << 9
_INV_HI_WIDTH = 1 << 7


def _sketch_arrays(keys: np.ndarray, w: np.ndarray) -> dict[str, np.ndarray]:
    """One node-window's wire arrays from (B, 4) uint32 keys + integer
    weights — the same array catalog the engine's fleet_export emits."""
    b = keys.shape[0]
    cols = [jnp.asarray(keys[:, i]) for i in range(4)]
    wv = jnp.asarray(w, jnp.float32)
    ones = jnp.ones((b,), jnp.float32)
    g0 = jnp.zeros((b,), jnp.int32)
    flow = HeavyHitterSketch.zeros(
        4, depth=_DEPTH, width=_WIDTH, n_slots=_SLOTS, seed=SEEDS["flow"]
    ).update(cols, wv)
    svc = HeavyHitterSketch.zeros(
        2, depth=_DEPTH, width=_WIDTH, n_slots=_SLOTS, seed=SEEDS["svc"]
    ).update(cols[:2], wv)
    dns = HeavyHitterSketch.zeros(
        1, depth=_DEPTH, width=_WIDTH, n_slots=_SLOTS, seed=SEEDS["dns"]
    ).update([cols[3]], wv)
    hllf = HyperLogLog.zeros(1, 10, seed=SEEDS["hll_flows"]).update(
        cols, g0, ones
    )
    pods = jnp.asarray(keys[:, 1] % np.uint32(_PODS), jnp.int32)
    hllp = HyperLogLog.zeros(
        _PODS, 6, seed=SEEDS["hll_src_per_pod"]
    ).update([cols[0]], pods, ones)
    ent = EntropyWindow.zeros(3, 1 << 10, seed=SEEDS["entropy"])
    for g, c in enumerate((cols[0], cols[1], cols[3])):
        ent = ent.update([c], jnp.full((b,), g, jnp.int32), wv)
    totals = np.zeros(8, np.uint32)
    totals[0] = np.uint32(min(int(w.sum()), 0xFFFFFFFF))
    return {
        "flow_cms": np.asarray(flow.cms.table),
        "flow_keys": np.asarray(flow.table.key_rows),
        "flow_counts": np.asarray(flow.table.counts),
        "svc_cms": np.asarray(svc.cms.table),
        "svc_keys": np.asarray(svc.table.key_rows),
        "svc_counts": np.asarray(svc.table.counts),
        "dns_cms": np.asarray(dns.cms.table),
        "dns_keys": np.asarray(dns.table.key_rows),
        "dns_counts": np.asarray(dns.table.counts),
        "hll_flows": np.asarray(hllf.registers),
        "hll_src_per_pod": np.asarray(hllp.registers),
        "entropy": np.asarray(ent.counts),
        "totals": totals,
    }


def run_dryrun(
    nodes: int = 8,
    epochs: int = 5,
    kill_after: int = 2,
    heavy_flows: int = 40,
    light_flows: int = 192,
    seed: int = 0,
    straggler_timeout_s: float | None = None,
    log: Callable[[str], None] = lambda s: None,
) -> dict[str, Any]:
    """Run the simulation; returns the scorecard dict (see module doc).

    ``kill_after``: the last agent stops shipping after this many epochs
    (node-dropout chaos); epochs 0..kill_after-1 close on full quorum.

    ``straggler_timeout_s`` defaults to 0.1s per node (floor 1s): at
    100 simulated agents the GIL serialises the per-node sketch
    builds, so epoch-0 arrivals spread over seconds — a fixed 1s
    timeout would close the bucket early and misreport full-quorum
    epochs as straggled.
    """
    assert nodes >= 2 and epochs >= 1
    if straggler_timeout_s is None:
        straggler_timeout_s = max(1.0, 0.1 * nodes)
    rng = np.random.default_rng(seed)
    base = Config(
        fleet_enabled=True,
        fleet_aggregator=True,
        fleet_expected_nodes=nodes,
        fleet_straggler_timeout_s=straggler_timeout_s,
        fleet_topk_k=32,
        fleet_max_tenants=4,
        fleet_tenant_series_max=8,
    )
    k = base.fleet_topk_k
    agg = FleetAggregator(base)
    agg.start(subscribe=True)

    # Global heavy flows: every node carries a share every epoch, so the
    # cluster totals exist on NO single node — recall against exact
    # merged counts proves the cross-node CMS summation.
    heavy = rng.integers(0, 2**32, size=(heavy_flows, 4), dtype=np.uint32)
    victim = nodes - 1
    exact_lock = threading.Lock()
    # (epoch, node) -> Counter of exact per-flow weights SHIPPED.
    exact: dict[tuple[int, str], Counter] = {}

    shippers: list[SnapshotShipper] = []
    for i in range(nodes):
        cfg_i = dataclasses.replace(
            base,
            fleet_node_name=f"sim{i:02d}",
            fleet_tenant=f"tenant{i % 4}",
            fleet_priority=i % 4,
        )
        s = SnapshotShipper(cfg_i)
        s.start()
        shippers.append(s)

    # Prewarm the sketch-build jit grid at the real batch shape before
    # pacing starts: first-call compiles take seconds and would skew
    # epoch-0 arrivals past the straggler timeout, closing buckets early
    # and dropping the stragglers' frames as late.
    _sketch_arrays(
        np.zeros((heavy_flows + light_flows, 4), np.uint32),
        np.ones(heavy_flows + light_flows),
    )

    epoch_interval = 0.25
    t0 = time.monotonic()

    def agent(i: int) -> None:
        node_rng = np.random.default_rng(seed * 1000 + i)
        ship = shippers[i]
        for e in range(epochs):
            if i == victim and e >= kill_after:
                return  # killed mid-run: stops shipping, no goodbye
            # Pace agents onto a shared epoch cadence (NTP-close clocks).
            wait = t0 + e * epoch_interval - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            hw = node_rng.integers(100, 200, size=heavy_flows)
            lkeys = node_rng.integers(
                0, 2**32, size=(light_flows, 4), dtype=np.uint32
            )
            lw = node_rng.integers(1, 4, size=light_flows)
            keys = np.concatenate([heavy, lkeys])
            w = np.concatenate([hw, lw]).astype(np.float64)
            arrays = _sketch_arrays(keys, w)
            c = Counter()
            for row, wt in zip(keys, w):
                c[tuple(int(x) for x in row)] += int(wt)
            with exact_lock:
                exact[(e, ship.node)] = c
            ship.offer(e, arrays, 15.0, dict(SEEDS))

    threads = [
        threading.Thread(target=agent, args=(i,), name=f"fleet-sim{i}")
        for i in range(nodes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Let the straggler timeout close the post-kill epochs. Generous
    # ceiling: the first n-node and (n-1)-node batched-merge programs
    # compile cold here (seconds each, and stack-width scales the
    # compile with the fleet size); the loop exits as soon as every
    # epoch is merged, so healthy runs never wait this long.
    deadline = (
        time.monotonic() + straggler_timeout_s * 4 + 60.0 + 2.0 * nodes
    )
    while agg.epochs_merged < epochs and time.monotonic() < deadline:
        time.sleep(0.05)
    for s in shippers:
        s.stop()
    agg.stop()

    # -- scorecard -----------------------------------------------------
    rollups = list(agg.rollups)
    recalls: dict[int, float] = {}
    top_err: dict[int, float] = {}
    for r in rollups:
        e = r["epoch"]
        merged_exact: Counter = Counter()
        for node in r["nodes"]:
            merged_exact.update(exact.get((e, node), Counter()))
        if not merged_exact:
            continue
        exact_top = [
            kk for kk, _ in merged_exact.most_common(k)
        ]
        keys_arr, counts_arr = r["top_flow"]
        got = {tuple(int(x) for x in row) for row in keys_arr}
        recalls[e] = sum(1 for kk in exact_top if kk in got) / len(exact_top)
        # Count accuracy on the true heaviest flow (CMS may overestimate,
        # never under): relative error of the reported cluster count.
        kk = exact_top[0]
        for row, cnt in zip(keys_arr, counts_arr):
            if tuple(int(x) for x in row) == kk:
                top_err[e] = abs(float(cnt) - merged_exact[kk]) / max(
                    merged_exact[kk], 1
                )
                break
    recall = min(recalls.values()) if recalls else 0.0
    tenants_seen = max((len(r["tenants"]) for r in rollups), default=0)
    series_obs = max(
        (
            len(tr["top_flows"][0])
            for r in rollups for tr in r["tenants"].values()
        ),
        default=0,
    )
    bound = min(base.fleet_topk_k, base.fleet_tenant_series_max)
    straggled = sum(1 for r in rollups if r.get("straggled"))
    post_kill = [
        r for r in rollups if r["epoch"] >= kill_after
    ]
    # Span lineage across the wire: the shipper's send span and the
    # aggregator's merge span for the same window must share the
    # window-epoch trace ID (shipped in the RFLT trace-context header),
    # so a flamegraph of one epoch is followable node -> aggregator.
    spans = get_recorder().spans()
    ship_tids = {
        s["trace_id"] for s in spans if s["stage"] == mn.STAGE_SHIP_SEND
    }
    merge_tids = {
        s["trace_id"] for s in spans if s["stage"] == mn.STAGE_AGG_MERGE
    }
    merged_epochs = {r["epoch"] for r in rollups}
    lineage_ok = bool(merged_epochs) and merged_epochs <= (
        ship_tids & merge_tids
    )
    res = {
        "nodes": nodes,
        "epochs": epochs,
        "epochs_merged": agg.epochs_merged,
        "recall_min": round(recall, 4),
        "recall_per_epoch": {e: round(v, 4) for e, v in recalls.items()},
        "top_count_rel_err": {
            e: round(v, 4) for e, v in top_err.items()
        },
        "killed_node": shippers[victim].node,
        "kill_after": kill_after,
        "straggled_epochs": straggled,
        "post_kill_nodes": (
            [len(r["nodes"]) for r in post_kill]
        ),
        "frames_shipped": sum(s.shipped for s in shippers),
        "tenants_seen": tenants_seen,
        "tenant_series_bound": bound,
        "tenant_series_max_observed": series_obs,
        "epoch_history_bound": int(base.fleet_epoch_history),
        "open_buckets_max": agg.open_buckets_max,
        "trace_lineage_ok": lineage_ok,
        "ok": bool(
            agg.epochs_merged >= epochs
            and recall >= 0.95
            and series_obs <= bound
            and tenants_seen <= base.fleet_max_tenants
            and agg.open_buckets_max <= base.fleet_epoch_history
            and lineage_ok
        ),
    }
    log(
        f"fleet dryrun: {nodes} agents, {agg.epochs_merged}/{epochs} "
        f"epochs merged, min recall {recall:.3f}, "
        f"{straggled} straggled (node {shippers[victim].node} killed "
        f"after epoch {kill_after - 1}), tenant series "
        f"{series_obs}<={bound}"
    )
    return res


def _invertible_arrays(
    keys: np.ndarray, w: np.ndarray, is_pri: np.ndarray
) -> dict[str, np.ndarray]:
    """One node-window's COUNTER-ONLY wire arrays: flow CMS plus the two
    invertible regions. Deliberately no ``flow_keys``/``flow_counts`` —
    the whole point of ``--invertible-dryrun`` is that the frame carries
    zero raw keys and the aggregator still names the heavy flows."""
    cols = [jnp.asarray(keys[:, i]) for i in range(4)]
    wv = jnp.asarray(w, jnp.uint32)
    cms = CountMinSketch.zeros(
        depth=_DEPTH, width=_WIDTH, seed=INV_SEEDS["flow"]
    ).update(cols, wv)
    pri = jnp.asarray(is_pri)
    inv_flow = InvertibleSketch.zeros(
        depth=_INV_DEPTH, width=_INV_WIDTH, seed=INV_SEEDS["inv_flow"]
    ).update(cols, jnp.where(pri, 0, wv))
    inv_hi = InvertibleSketch.zeros(
        depth=_INV_DEPTH, width=_INV_HI_WIDTH, seed=INV_SEEDS["inv_hi"]
    ).update(cols, jnp.where(pri, wv, 0))
    return {
        "flow_cms": np.asarray(cms.table),
        "inv_flow_planes": np.asarray(inv_flow.planes),
        "inv_flow_weights": np.asarray(inv_flow.weights),
        "inv_hi_planes": np.asarray(inv_hi.planes),
        "inv_hi_weights": np.asarray(inv_hi.weights),
    }


def run_invertible_dryrun(
    nodes: int = 4,
    epochs: int = 3,
    shed_from: int = 1,
    shed_k: int = 8,
    heavy_flows: int = 32,
    light_flows: int = 256,
    priority_flows: int = 8,
    seed: int = 0,
    straggler_timeout_s: float = 1.0,
    log: Callable[[str], None] = lambda s: None,
) -> dict[str, Any]:
    """Cluster key-recovery dryrun (see module doc). Epochs at or past
    ``shed_from`` run a forced SHEDDING episode: background (light)
    flows are 1-in-``shed_k`` sampled with Horvitz-Thompson weight
    rescale — exactly the overload controller's degraded-accuracy
    contract — while heavy and priority-class flows stay exempt per the
    priority-tier lattice. Scorecard: heavy-key recall >= 0.95 every
    epoch, priority recall == 1.0 INCLUDING shedding epochs."""
    assert nodes >= 2 and epochs >= 1
    rng = np.random.default_rng(seed)
    base = Config(
        fleet_enabled=True,
        fleet_aggregator=True,
        fleet_expected_nodes=nodes,
        fleet_straggler_timeout_s=straggler_timeout_s,
        fleet_topk_k=64,
    )
    agg = FleetAggregator(base)
    agg.start(subscribe=True)

    # Global heavy flows (every node carries a share) + priority-class
    # flows (src_ip in the 10.x/8 analog: top byte 0x0A).
    heavy = rng.integers(0, 2**32, size=(heavy_flows, 4), dtype=np.uint32)
    pri = rng.integers(0, 2**32, size=(priority_flows, 4), dtype=np.uint32)
    pri[:, 0] = (pri[:, 0] & np.uint32(0x00FFFFFF)) | np.uint32(0x0A000000)

    shippers: list[SnapshotShipper] = []
    for i in range(nodes):
        cfg_i = dataclasses.replace(
            base,
            fleet_node_name=f"inv{i:02d}",
            fleet_tenant=f"tenant{i % 2}",
            fleet_priority=i % 2,
        )
        s = SnapshotShipper(cfg_i)
        s.start()
        shippers.append(s)

    # Prewarm the sketch-build jit grid at the real batch shape (same
    # rationale as run_dryrun: cold compiles would straggle epoch 0).
    n_rows = heavy_flows + priority_flows + light_flows
    _invertible_arrays(
        np.zeros((n_rows, 4), np.uint32),
        np.ones(n_rows),
        np.zeros(n_rows, bool),
    )

    epoch_interval = 0.25
    t0 = time.monotonic()

    def agent(i: int) -> None:
        node_rng = np.random.default_rng(seed * 1000 + i)
        ship = shippers[i]
        for e in range(epochs):
            wait = t0 + e * epoch_interval - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            hw = node_rng.integers(100, 200, size=heavy_flows)
            # Priority flows are LIGHT on any one node — only the
            # never-sampled hi region makes them recoverable.
            pw = node_rng.integers(5, 15, size=priority_flows)
            lkeys = node_rng.integers(
                0, 2**32, size=(light_flows, 4), dtype=np.uint32
            )
            # Keep ambient light keys out of the priority class so the
            # hi region holds exactly the priority flows.
            lkeys[:, 0] |= np.uint32(0x80000000)
            lw = node_rng.integers(1, 4, size=light_flows).astype(np.int64)
            if e >= shed_from:
                # Forced SHEDDING: background tier only, HT rescale.
                keep = node_rng.random(light_flows) < 1.0 / shed_k
                lw = np.where(keep, lw * shed_k, 0)
            keys = np.concatenate([heavy, pri, lkeys])
            w = np.concatenate([hw, pw, lw]).astype(np.int64)
            is_pri = (keys[:, 0] >> 24) == 0x0A
            ship.offer(
                e, _invertible_arrays(keys, w, is_pri), 15.0,
                dict(INV_SEEDS),
            )

    threads = [
        threading.Thread(target=agent, args=(i,), name=f"inv-sim{i}")
        for i in range(nodes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + straggler_timeout_s * 4 + 60.0
    while agg.epochs_merged < epochs and time.monotonic() < deadline:
        time.sleep(0.05)
    for s in shippers:
        s.stop()
    agg.stop()

    # -- scorecard -----------------------------------------------------
    rollups = list(agg.rollups)
    heavy_set = {tuple(int(x) for x in row) for row in heavy}
    pri_set = {tuple(int(x) for x in row) for row in pri}
    recalls: dict[int, float] = {}
    hi_recalls: dict[int, float] = {}
    precisions: dict[int, float] = {}
    for r in rollups:
        e = r["epoch"]
        inv = r.get("invertible")
        if inv is None:
            recalls[e] = hi_recalls[e] = precisions[e] = 0.0
            continue
        got = {tuple(int(x) for x in row) for row in inv["keys"]}
        recalls[e] = (
            len(heavy_set & got) / len(heavy_set) if heavy_set else 1.0
        )
        hi_recalls[e] = (
            len(pri_set & got) / len(pri_set) if pri_set else 1.0
        )
        truth = heavy_set | pri_set
        precisions[e] = len(truth & got) / max(len(got), 1)
    recall = min(recalls.values()) if recalls else 0.0
    hi_recall = min(hi_recalls.values()) if hi_recalls else 0.0
    shed_epochs = [e for e in recalls if e >= shed_from]
    res = {
        "nodes": nodes,
        "epochs": epochs,
        "epochs_merged": agg.epochs_merged,
        "recall_min": round(recall, 4),
        "recall_per_epoch": {e: round(v, 4) for e, v in recalls.items()},
        "hi_recall_min": round(hi_recall, 4),
        "hi_recall_per_epoch": {
            e: round(v, 4) for e, v in hi_recalls.items()
        },
        "precision_per_epoch": {
            e: round(v, 4) for e, v in precisions.items()
        },
        "shed_from": shed_from,
        "shed_k": shed_k,
        "shed_epochs_scored": len(shed_epochs),
        "frames_shipped": sum(s.shipped for s in shippers),
        "raw_keys_on_wire": 0,  # structural: no *_keys arrays shipped
        "ok": bool(
            agg.epochs_merged >= epochs
            and recall >= 0.95
            and hi_recall >= 1.0
            and len(shed_epochs) >= 1
        ),
    }
    log(
        f"invertible dryrun: {nodes} agents, "
        f"{agg.epochs_merged}/{epochs} epochs merged, min recall "
        f"{recall:.3f}, priority recall {hi_recall:.3f} "
        f"(shedding from epoch {shed_from}, 1-in-{shed_k})"
    )
    return res

"""Operator-side fleet aggregator: epoch alignment + on-device merge.

Ingests wire frames (fleet/codec.py) from N node agents, buckets them
by window epoch, and closes an epoch when either every expected node
has reported (``fleet_expected_nodes``) or the straggler timeout
expires after the FIRST arrival (``fleet_straggler_timeout_s``) — the
rollup never blocks on a dead node. Duplicates (same node+epoch) and
late frames (epoch at or below the watermark) are counted and dropped;
the watermark only moves forward.

The merge itself runs on device as ONE jitted batched reduction over
the stacked per-node arrays — sum for CM tables / entropy histograms /
totals (psum-style), max for HLL register banks, and a join-semilattice
fold for the heavy-hitter candidate tables (ops/topk.py). Cluster
heavy-hitter counts are then the merged CMS queried at the UNION of
every node's candidates: a key whose traffic splits across nodes is
undercounted in any single candidate table but exact (up to CMS error)
in the summed tables.

Published families (docs/metrics.md): cluster-wide top flows,
per-tenant top flows, per-service cardinality, DDoS entropy, distinct
flows — all ``fleet_*``. Label-space growth is bounded by construction:
keyed gauges are cleared and re-published each epoch, capped at
``fleet_topk_k`` cluster series plus ``fleet_tenant_series_max`` series
per tenant across at most ``fleet_max_tenants`` tenants; when over
budget the LOWEST-priority tenants are shed first (PSketch-style
priority awareness, PAPERS.md) and the shed is counted.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from retina_tpu.devprog import device_entry
from retina_tpu.fleet.codec import (
    ROLLUP_TOPIC, FleetDecodeError, FleetSnapshot, decode_snapshot,
)
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.obs.recorder import get_recorder
from retina_tpu.ops.countmin import CountMinSketch
from retina_tpu.ops.entropy import EntropyWindow
from retina_tpu.ops.hyperloglog import HyperLogLog
from retina_tpu.ops.invertible import InvertibleSketch, decode_verified
from retina_tpu.ops.topk import TopKTable
from retina_tpu.pubsub import get_pubsub
from retina_tpu.utils import metric_names as mn

ENTROPY_DIMS = ("src_ip", "dst_ip", "dst_port")
_HH_FAMILIES = ("flow", "svc", "dns")

# Seed-generation reference history kept per aggregator: a live seed
# rotation is a handful of generations at most, and old generations'
# references are useless once every node has rotated past them.
_GEN_HISTORY = 8


def format_key(row: np.ndarray) -> str:
    """Stable label rendering of one candidate key row (C u32 columns)."""
    return "-".join(f"{int(c):08x}" for c in row)


class _EpochBucket:
    """Snapshots collected for one not-yet-closed epoch."""

    __slots__ = ("snaps", "first_t")

    def __init__(self, now: float) -> None:
        self.snaps: dict[str, FleetSnapshot] = {}
        self.first_t = now


class FleetAggregator:
    """Thread-safe; ``ingest`` runs on transport threads (pubsub pool /
    gRPC handlers), ``poll`` on the internal timer thread."""

    def __init__(self, cfg, supervisor=None, reship_transport=None) -> None:
        self.cfg = cfg
        self.log = logger("fleet.agg")
        self._supervisor = supervisor
        self._lock = threading.Lock()
        self._buckets: dict[int, _EpochBucket] = {}
        self._watermark = -1  # highest CLOSED epoch
        # Seed/shape references keyed by seed generation: a frame is
        # validated against ITS OWN generation's reference, so a rotated
        # node is never permanently quarantined — only a node whose
        # seeds disagree with its generation's reference is dropped
        # (``seed_mismatch``), which still catches real misconfig.
        self._gen_refs: dict[
            int, tuple[dict[str, int], dict[str, tuple]]
        ] = {}
        # Tier-2 re-ship: when configured, every merged epoch is
        # re-encoded as a (valid, tier=1) node snapshot and shipped to
        # the next rollup tier — the merge algebra is a semilattice, so
        # the root aggregator folds zone rollups exactly like node
        # frames. ``reship_transport`` injects a transport callable for
        # tests/harnesses; otherwise cfg.fleet_reship_addr dials gRPC.
        self._reshipper = None
        if reship_transport is not None or str(cfg.fleet_reship_addr):
            from retina_tpu.fleet.shipper import SnapshotShipper

            ship_cfg = dataclasses.replace(
                cfg, fleet_relay_addr=str(cfg.fleet_reship_addr)
            )
            self._reshipper = SnapshotShipper(
                ship_cfg, supervisor=supervisor,
                transport=reship_transport,
            )
            self._reshipper.tier = 1
        # jitted batched-merge executables keyed by (n_nodes, array
        # signature): re-lowering per epoch would dominate the merge.
        self._merge_cache: dict[Any, Any] = {}
        # Quorum-closed buckets awaiting merge when fleet_merge_async is
        # set: ingest only appends here (under the lock); the poll
        # thread drains it ahead of straggler checks.
        self._ready_q: deque[tuple[int, _EpochBucket]] = deque()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sub_id: str | None = None
        # Merged-epoch history ring (timetravel/ring.py RingProtocol):
        # the aggregator OWNS its epoch ring — each merged epoch's
        # arrays are retained as a slot, so range queries (node tier and
        # the fleet query plane) cover cluster history, not just this
        # node's. Created here, not by the daemon: the ring is part of
        # the aggregator's state, and it exposes the exact
        # select/span/stats surface of the engine's SnapshotRing.
        self.epoch_ring: Any = None
        if getattr(cfg, "timetravel_enabled", False):
            from retina_tpu.timetravel.ring import SnapshotRing

            self.epoch_ring = SnapshotRing(
                cfg.timetravel_ring_windows, name="fleet",
                supervisor=supervisor,
            )
        # Rolling window of recent rollups for tests/dryrun/debug vars.
        # The retention is a plain attribute so harnesses that score a
        # fixed epoch window (fleet/churn.py) can widen it.
        self.rollups: list[dict] = []
        self.rollups_keep = 64
        self.epochs_merged = 0
        # High-water mark of concurrently-open epoch buckets; staying
        # at or under cfg.fleet_epoch_history proves the overflow
        # eviction never had to force-close an epoch (dryrun asserts
        # this at 100-agent scale).
        self.open_buckets_max = 0

    # Back-compat alias: older wiring (daemon, tests) reached the ring
    # as ``timetravel_ring``; both names see the same object.
    @property
    def timetravel_ring(self) -> Any:
        return self.epoch_ring

    @timetravel_ring.setter
    def timetravel_ring(self, ring: Any) -> None:
        self.epoch_ring = ring

    # -- lifecycle -----------------------------------------------------
    def start(self, subscribe: bool = True) -> None:
        """Start the straggler-poll thread; optionally subscribe to the
        in-process FLEET_TOPIC (the co-located transport)."""
        if subscribe and self._sub_id is None:
            from retina_tpu.fleet.codec import FLEET_TOPIC

            self._sub_id = get_pubsub().subscribe(FLEET_TOPIC, self.ingest)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._poll_loop, name="fleet-agg", daemon=True
            )
            self._thread.start()
        if self._reshipper is not None:
            self._reshipper.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._reshipper is not None:
            self._reshipper.stop(timeout_s=timeout_s)
        if self._sub_id is not None:
            from retina_tpu.fleet.codec import FLEET_TOPIC

            try:
                get_pubsub().unsubscribe(FLEET_TOPIC, self._sub_id)
            except KeyError:  # noqa: RT101 — already unsubscribed; stop is idempotent
                pass
            self._sub_id = None
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            if self._supervisor is not None:
                self._supervisor.deregister("fleet-agg")
        self._thread = None

    def _poll_loop(self) -> None:  # runs-on: fleet-agg
        hb = None
        if self._supervisor is not None:
            hb = self._supervisor.register(
                "fleet-agg", self.cfg.watchdog_deadline_s
            )
        cadence = max(0.05, self.cfg.fleet_straggler_timeout_s / 4.0)
        while not self._stop.is_set():
            if hb is not None:
                hb.beat()
            try:
                self.poll()
            except Exception:
                get_metrics().fleet_merge_errors.inc()
                if rate_limited("fleet.poll"):
                    self.log.exception("fleet poll failed")
            if hb is not None:
                hb.park()
            self._stop.wait(cadence)

    # -- ingest --------------------------------------------------------
    def ingest(self, frame: bytes) -> bool:  # runs-on: pubsub*, grpc*  # hot-path: transport
        """Decode + bucket one wire frame. Returns True when accepted."""
        m = get_metrics()
        try:
            snap = decode_snapshot(frame)
        except FleetDecodeError as e:
            m.fleet_snapshots_dropped.labels(reason="decode").inc()
            if rate_limited("fleet.decode"):
                self.log.warning("fleet frame rejected: %s", e)
            return False
        ready = None
        with self._lock:
            if snap.epoch <= self._watermark:
                m.fleet_snapshots_dropped.labels(reason="late").inc()
                return False
            gen = int(snap.seed_gen)
            ref = self._gen_refs.get(gen)
            if ref is None:
                # First frame of this generation defines its reference;
                # bound the history so a node spraying bogus generations
                # cannot grow this dict unboundedly.
                while len(self._gen_refs) >= _GEN_HISTORY:
                    del self._gen_refs[min(self._gen_refs)]
                ref = (
                    dict(snap.seeds),
                    {k: v.shape for k, v in snap.arrays.items()},
                )
                self._gen_refs[gen] = ref
            ref_seeds, ref_shapes = ref
            if snap.seeds != ref_seeds:
                m.fleet_snapshots_dropped.labels(
                    reason="seed_mismatch"
                ).inc()
                return False
            shapes = {k: v.shape for k, v in snap.arrays.items()}
            if shapes != ref_shapes:
                m.fleet_snapshots_dropped.labels(
                    reason="shape_mismatch"
                ).inc()
                return False
            bucket = self._buckets.get(snap.epoch)
            if bucket is None:
                bucket = self._buckets[snap.epoch] = _EpochBucket(
                    time.monotonic()
                )
                if len(self._buckets) > self.open_buckets_max:
                    self.open_buckets_max = len(self._buckets)
            if snap.node in bucket.snaps:
                m.fleet_snapshots_dropped.labels(reason="duplicate").inc()
                return False
            bucket.snaps[snap.node] = snap
            m.fleet_snapshots_received.labels(node=snap.node).inc()
            expected = int(self.cfg.fleet_expected_nodes)
            if expected > 0 and len(bucket.snaps) >= expected:
                ready = [(snap.epoch, self._buckets.pop(snap.epoch))]
            else:
                ready = self._overflow_locked()
            if ready and self.cfg.fleet_merge_async:
                # Hand closed buckets to the poll thread: the transport
                # handler must not pay for the merge (or its compile).
                self._ready_q.extend(ready)
                ready = None
        for epoch, b in ready or ():
            try:
                self._merge_epoch(epoch, b, straggled=False)
            except Exception:
                m.fleet_merge_errors.inc()
                if rate_limited("fleet.merge"):
                    self.log.exception("fleet merge failed (epoch %d)", epoch)
        return True

    def _overflow_locked(self) -> list[tuple[int, _EpochBucket]]:
        """Bound open-epoch memory: keep at most fleet_epoch_history
        buckets, force-closing the oldest (counts as straggled)."""
        out = []
        limit = max(1, int(self.cfg.fleet_epoch_history))
        while len(self._buckets) > limit:
            oldest = min(self._buckets)
            out.append((oldest, self._buckets.pop(oldest)))
        return out

    def poll(self, now: float | None = None) -> int:
        """Close epochs whose straggler timeout has expired. Returns the
        number of epochs merged."""
        now = time.monotonic() if now is None else now
        timeout = self.cfg.fleet_straggler_timeout_s
        ready: list[tuple[int, _EpochBucket, bool]] = []
        with self._lock:
            # Quorum-closed buckets deferred by ingest (fleet_merge_async)
            # merge first — they are complete and older than any
            # still-open straggler.
            while self._ready_q:
                epoch, bucket = self._ready_q.popleft()
                ready.append((epoch, bucket, False))
            for epoch in sorted(self._buckets):
                if now - self._buckets[epoch].first_t >= timeout:
                    ready.append((epoch, self._buckets.pop(epoch), True))
        for epoch, bucket, straggled in ready:
            try:
                self._merge_epoch(epoch, bucket, straggled=straggled)
            except Exception:
                get_metrics().fleet_merge_errors.inc()
                if rate_limited("fleet.merge"):
                    self.log.exception("fleet merge failed (epoch %d)", epoch)
        return len(ready)

    # -- merge ---------------------------------------------------------
    @device_entry("fleet.merge", kind="jit")
    def _merge_fn(self, n: int, seeds: dict[str, int], names: tuple):
        key = (n, names, tuple(sorted(seeds.items())))
        fn = self._merge_cache.get(key)
        if fn is not None:
            return fn

        def merge(stacked: dict[str, jnp.ndarray]) -> dict[str, Any]:
            out: dict[str, Any] = {}
            for name in names:
                arr = stacked[name]
                if name.startswith("hll_"):
                    out[name] = jnp.max(arr, axis=0)
                elif name.endswith("_keys") or name.endswith("_counts"):
                    continue  # folded below as (keys, counts) pairs
                else:
                    out[name] = jnp.sum(arr, axis=0)
            for fam in _HH_FAMILIES:
                kname, cname = f"{fam}_keys", f"{fam}_counts"
                if kname not in stacked:  # noqa: RT212 — dict-key test, static per jit cache key
                    continue
                seed = int(seeds.get(fam, 0))
                t = TopKTable(
                    stacked[kname][0], stacked[cname][0], seed=seed
                )
                for i in range(1, n):
                    t = t.merge(TopKTable(
                        stacked[kname][i], stacked[cname][i], seed=seed,
                    ))
                out[kname], out[cname] = t.key_rows, t.counts
            return out

        # donate_argnums=(0,): `stacked` is built fresh per epoch in
        # _merge_epoch (jnp.asarray of a host stack) and never read
        # after this call — donating lets XLA fold the (n, ...) stacks
        # into the reduction outputs instead of holding both the stack
        # and the merged arrays live (RT302; found by the
        # device-program donation audit).
        fn = jax.jit(merge, donate_argnums=(0,))
        self._merge_cache[key] = fn
        return fn

    def _merge_epoch(  # may-block: device merge on the caller's thread — transport-lane reach is the sync cfg.fleet_merge_async=False mode (tests/bench); production fleets set it True and merge on the poll thread (windowed _ready_q handoff)
        self, epoch: int, bucket: _EpochBucket, straggled: bool
    ) -> None:
        t0 = time.monotonic()
        m = get_metrics()
        rec = get_recorder()
        span_t0 = rec.begin()
        snaps = sorted(bucket.snaps.values(), key=lambda s: s.node)
        if not snaps:
            return
        # Mid-rotation an epoch can hold frames from more than one seed
        # generation. Cross-generation sketches don't merge, so take the
        # dominant generation (ties break toward the NEWER one — the
        # rotation target) and count the minority as per-epoch skew
        # drops; those nodes re-admit next epoch, nothing is quarantined
        # permanently.
        by_gen: dict[int, list[FleetSnapshot]] = {}
        for s in snaps:
            by_gen.setdefault(int(s.seed_gen), []).append(s)
        gen = max(by_gen, key=lambda g: (len(by_gen[g]), g))
        if len(by_gen) > 1:
            skewed = len(snaps) - len(by_gen[gen])
            m.fleet_snapshots_dropped.labels(reason="gen_skew").inc(skewed)
            if rate_limited("fleet.gen_skew"):
                self.log.warning(
                    "fleet epoch %d: %d frame(s) outside dominant seed "
                    "generation %d dropped (rotation in flight)",
                    epoch, skewed, gen,
                )
            snaps = by_gen[gen]
        # Cross-process lineage: the shipped trace context carries the
        # window-epoch trace ID from the node's close path; frames from
        # trace-less (older) nodes fall back to the epoch itself, which
        # is the same value by construction.
        trace_id = next(
            (int(s.trace["tid"]) for s in snaps
             if s.trace is not None and "tid" in s.trace),
            int(epoch),
        )
        with self._lock:
            self._watermark = max(self._watermark, epoch)
        names = sorted(
            set.intersection(*(set(s.arrays) for s in snaps))
        )
        stacked = {
            name: jnp.asarray(
                np.stack([s.arrays[name] for s in snaps])
            )
            for name in names
        }
        seeds = snaps[0].seeds
        merged = self._merge_fn(len(snaps), seeds, tuple(names))(stacked)
        if self.timetravel_ring is not None:
            # Merged-epoch snapshot into the fleet ring: already a
            # valid fold operand (same algebra, same catalog), so
            # cluster-wide range queries are one more fold away. Host
            # readback here is fine — the poll thread does host work
            # for the rollup anyway.
            try:
                self.timetravel_ring.append_host(
                    epoch,
                    {k: np.asarray(v) for k, v in merged.items()},
                    float(snaps[0].window_s),
                    dict(seeds),
                )
            except Exception:
                if rate_limited("fleet.ttring"):
                    self.log.exception("timetravel ring append failed")
        if self._reshipper is not None:
            # Re-ship the merged epoch one tier up: the merged arrays
            # are themselves a valid node snapshot (same catalog, same
            # dtypes — the algebra is closed under merge), so the next
            # tier ingests this aggregator as if it were one big node.
            self._reshipper.offer(
                epoch,
                {k: np.asarray(v) for k, v in merged.items()},
                float(snaps[0].window_s),
                dict(seeds),
                seed_gen=gen,
            )
            m.fleet_rollups_reshipped.inc()
        rollup = self._rollup(epoch, snaps, merged, seeds)
        rollup["straggled"] = straggled
        rollup["seed_gen"] = gen
        rollup["merge_seconds"] = time.monotonic() - t0
        self._publish(rollup)
        rec.record(mn.STAGE_AGG_MERGE, span_t0, trace_id)
        m.fleet_windows_merged.inc()
        if straggled:
            m.fleet_windows_stragglers.inc()
        m.fleet_merge_seconds.set(rollup["merge_seconds"])
        with self._lock:
            self.epochs_merged += 1
            self.rollups.append(rollup)
            del self.rollups[:-self.rollups_keep]

    # -- rollup computation -------------------------------------------
    def _cluster_topk(
        self,
        fam: str,
        snaps: list[FleetSnapshot],
        merged: dict[str, Any],
        seeds: dict[str, int],
        k: int,
        candidates: np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-k of the candidate set, counted by the summed CMS (exact
        cross-node totals up to CMS overestimate). ``candidates``
        defaults to the union of every node's shipped candidate tables;
        with invertible snapshots the caller passes the keys DECODED
        from merged sketch state instead — no node shipped them."""
        if candidates is not None:
            cand = [candidates.astype(np.uint32).reshape(-1, 4)]
        else:
            cand = []
            for s in snaps:
                keys = s.arrays.get(f"{fam}_keys")
                counts = s.arrays.get(f"{fam}_counts")
                if keys is None or counts is None:
                    continue
                cand.append(keys[counts > 0])
        if not cand:
            return np.zeros((0, 0), np.uint32), np.zeros((0,), np.uint64)
        union = np.unique(np.concatenate(cand, axis=0), axis=0)
        if not len(union):
            return union, np.zeros((0,), np.uint64)
        cms = CountMinSketch(
            table=merged[f"{fam}_cms"],
            seed=int(seeds.get(fam, 0)),
        )
        key_cols = [jnp.asarray(union[:, c]) for c in range(union.shape[1])]
        est = np.asarray(cms.query(key_cols)).astype(np.uint64)
        order = np.argsort(est)[::-1][:k]
        sel = est[order] > 0
        return union[order][sel], est[order][sel]

    def _invertible_decode(
        self, merged: dict[str, Any], seeds: dict[str, int]
    ) -> dict[str, Any] | None:
        """Recover CLUSTER-WIDE heavy keys from the merged invertible
        arrays (ops/invertible.py), verified against the merged flow
        CMS. The arrays are pure sums, so the fleet-summed sketch
        decodes exactly like a single node's — keys that were too light
        to decode on any one node surface once their cluster-wide
        weight dominates a bucket, and no node shipped a raw key.
        Returns sorted-descending ``keys (N, 4)``, ``est (N,)``,
        ``tier (N,)`` (1 = priority region) plus per-source packet
        attribution ``sources = (src_ips, packets)`` for DDoS
        attribution; None when the epoch carried no invertible state."""
        if "inv_flow_planes" not in merged or "flow_cms" not in merged:
            return None
        cms = CountMinSketch(
            table=merged["flow_cms"], seed=int(seeds.get("flow", 0))
        )
        all_keys, all_est, all_tier = [], [], []
        for region, tier in (("inv_flow", 0), ("inv_hi", 1)):
            if f"{region}_planes" not in merged:
                continue
            inv = InvertibleSketch(
                planes=jnp.asarray(merged[f"{region}_planes"]),
                weights=jnp.asarray(merged[f"{region}_weights"]),
                seed=int(seeds.get(region, 0)),
            )
            cols, est, ok = decode_verified(inv, cms)
            okh = np.asarray(ok, bool)
            keys = np.stack([np.asarray(c) for c in cols], axis=1)[okh]
            all_keys.append(keys.astype(np.uint32))
            all_est.append(np.asarray(est)[okh].astype(np.uint64))
            all_tier.append(np.full(len(keys), tier, np.uint32))
        if not all_keys:
            return None
        keys = np.concatenate(all_keys)
        est = np.concatenate(all_est)
        tier = np.concatenate(all_tier)
        if len(keys):
            # A key decodes from up to depth buckets per region.
            uniq, idx = np.unique(keys, axis=0, return_index=True)
            keys, est, tier = uniq, est[idx], tier[idx]
            order = np.argsort(est)[::-1]
            keys, est, tier = keys[order], est[order], tier[order]
            srcs, sinv = np.unique(keys[:, 0], return_inverse=True)
            spk = np.zeros(len(srcs), np.uint64)
            np.add.at(spk, sinv, est)
            sorder = np.argsort(spk)[::-1]
            sources = (srcs[sorder], spk[sorder])
        else:
            sources = (
                np.zeros((0,), np.uint32), np.zeros((0,), np.uint64)
            )
        return {"keys": keys, "est": est, "tier": tier,
                "sources": sources}

    def _rollup(
        self,
        epoch: int,
        snaps: list[FleetSnapshot],
        merged: dict[str, Any],
        seeds: dict[str, int],
    ) -> dict:
        cfg = self.cfg
        k = int(cfg.fleet_topk_k)
        rollup: dict[str, Any] = {
            "epoch": epoch,
            "nodes": [s.node for s in snaps],
            "window_s": snaps[0].window_s,
        }
        inv = None
        if "inv_flow_planes" in merged:
            try:
                inv = self._invertible_decode(merged, seeds)
            except Exception:
                get_metrics().fleet_invertible_decode_failed.inc()
                if rate_limited("fleet.invdec"):
                    self.log.exception("fleet invertible decode failed")
        if inv is not None:
            rollup["invertible"] = inv
        # Cluster-wide heavy hitters per family. With invertible state
        # in the epoch, the flow candidate set is the keys decoded from
        # MERGED sketch arrays (nodes shipped no raw keys); otherwise
        # it is the union of per-node candidate tables.
        for fam in _HH_FAMILIES:
            if f"{fam}_cms" not in merged:
                continue
            cand = (
                inv["keys"]
                if fam == "flow" and inv is not None and len(inv["keys"])
                else None
            )
            keys, counts = self._cluster_topk(
                fam, snaps, merged, seeds, k, candidates=cand
            )
            rollup[f"top_{fam}"] = (keys, counts)
        # Per-service (per-pod) distinct-source cardinality.
        if "hll_src_per_pod" in merged:
            hll = HyperLogLog(
                registers=merged["hll_src_per_pod"],
                seed=int(seeds.get("hll_src_per_pod", 0)),
            )
            est = np.asarray(hll.estimate())
            top = np.argsort(est)[::-1][: int(cfg.fleet_service_top)]
            rollup["service_cardinality"] = [
                (int(i), float(est[i])) for i in top if est[i] >= 1.0
            ]
        if "hll_flows" in merged:
            hll = HyperLogLog(
                registers=merged["hll_flows"],
                seed=int(seeds.get("hll_flows", 0)),
            )
            rollup["distinct_flows"] = float(np.asarray(hll.estimate())[0])
        # Cluster DDoS entropy of the merged histograms: exactly the
        # single-node estimate of the union stream (ops/entropy.py).
        if "entropy" in merged:
            ent = EntropyWindow(
                counts=merged["entropy"],
                seed=int(seeds.get("entropy", 0)),
            )
            bits = np.asarray(ent.entropy_bits())
            rollup["entropy_bits"] = {
                dim: float(bits[i])
                for i, dim in enumerate(ENTROPY_DIMS)
                if i < len(bits)
            }
        if "totals" in merged:
            rollup["totals"] = np.asarray(merged["totals"])
        # Per-tenant heavy hitters under the cardinality guardrails.
        rollup["tenants"] = self._tenant_rollups(
            snaps, seeds,
            inv_keys=(
                inv["keys"]
                if inv is not None and len(inv["keys"]) else None
            ),
        )
        return rollup

    def _tenant_rollups(
        self,
        snaps: list[FleetSnapshot],
        seeds: dict[str, int],
        inv_keys: np.ndarray | None = None,
    ) -> dict[str, dict]:
        """Per-tenant flow top-k with the label-space guardrails: at
        most ``fleet_max_tenants`` tenants (lowest priority shed first),
        at most ``fleet_tenant_series_max`` series each."""
        cfg = self.cfg
        m = get_metrics()
        by_tenant: dict[str, list[FleetSnapshot]] = {}
        prio: dict[str, int] = {}
        for s in snaps:
            by_tenant.setdefault(s.tenant, []).append(s)
            prio[s.tenant] = max(prio.get(s.tenant, s.priority), s.priority)
        ranked = sorted(by_tenant, key=lambda t: (-prio[t], t))
        kept = ranked[: max(0, int(cfg.fleet_max_tenants))]
        for t in ranked[len(kept):]:
            m.fleet_tenants_shed.inc()
            if rate_limited("fleet.tenant_shed"):
                self.log.warning(
                    "fleet: tenant %s shed (priority %d, budget %d)",
                    t, prio[t], cfg.fleet_max_tenants,
                )
        cap = max(1, int(cfg.fleet_tenant_series_max))
        out: dict[str, dict] = {}
        for tenant in kept:
            group = by_tenant[tenant]
            tables = [
                s.arrays["flow_cms"] for s in group
                if "flow_cms" in s.arrays
            ]
            if not tables:
                continue
            merged_cms = {
                "flow_cms": jnp.sum(
                    jnp.asarray(np.stack(tables)), axis=0
                )
            }
            keys, counts = self._cluster_topk(
                "flow", group, merged_cms, seeds,
                min(int(cfg.fleet_topk_k), cap),
                candidates=inv_keys,
            )
            if len(keys) > cap:  # defense in depth; min() above caps
                m.fleet_series_capped.inc(len(keys) - cap)
                keys, counts = keys[:cap], counts[:cap]
            out[tenant] = {
                "priority": prio[tenant],
                "top_flows": (keys, counts),
                "nodes": [s.node for s in group],
            }
        return out

    # -- publication ---------------------------------------------------
    def _publish(self, rollup: dict) -> None:
        m = get_metrics()
        m.fleet_nodes_reporting.set(len(rollup["nodes"]))
        # Keyed gauges: clear-and-republish each epoch so the exported
        # label space never exceeds this epoch's (capped) series set —
        # the guardrail is structural, not advisory.
        m.fleet_top_flows.clear()
        m.fleet_tenant_top_flows.clear()
        m.fleet_service_cardinality.clear()
        m.fleet_tenant_series.clear()
        m.fleet_invertible_sources.clear()
        inv = rollup.get("invertible")
        if inv is not None:
            m.fleet_invertible_keys.set(float(len(inv["keys"])))
            srcs, spk = inv["sources"]
            cap = max(0, int(self.cfg.fleet_topk_k))
            for ip, pk in zip(srcs[:cap], spk[:cap]):
                m.fleet_invertible_sources.labels(
                    key=f"{int(ip):08x}"
                ).set(float(pk))
        for fam, gauge in (("flow", m.fleet_top_flows),):
            pair = rollup.get(f"top_{fam}")
            if pair is None:
                continue
            keys, counts = pair
            for row, count in zip(keys, counts):
                gauge.labels(key=format_key(row)).set(float(count))
        for idx, est in rollup.get("service_cardinality", ()):
            m.fleet_service_cardinality.labels(service=f"pod{idx}").set(est)
        for dim, bits in rollup.get("entropy_bits", {}).items():
            m.fleet_entropy_bits.labels(dimension=dim).set(bits)
        if "distinct_flows" in rollup:
            m.fleet_distinct_flows.set(rollup["distinct_flows"])
        for tenant, tr in rollup["tenants"].items():
            keys, counts = tr["top_flows"]
            for row, count in zip(keys, counts):
                m.fleet_tenant_top_flows.labels(
                    tenant=tenant, key=format_key(row)
                ).set(float(count))
            m.fleet_tenant_series.labels(tenant=tenant).set(len(keys))
        get_pubsub().publish(ROLLUP_TOPIC, rollup)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {
                "watermark": self._watermark,
                "open_epochs": sorted(self._buckets),
                "ready_q": len(self._ready_q),
                "epochs_merged": self.epochs_merged,
                "generations": sorted(self._gen_refs),
                "nodes_last": (
                    self.rollups[-1]["nodes"] if self.rollups else []
                ),
            }
        if self._reshipper is not None:
            out["reship"] = self._reshipper.stats()
        return out

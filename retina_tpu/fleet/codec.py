"""Versioned wire codec for fleet sketch snapshots.

One snapshot is the device-merged sketch state of one node for one
closed window: CM tables, heavy-hitter candidate tables, HLL register
banks, the entropy histograms, and the window totals. "Sketchy With a
Chance of Adoption" (PAPERS.md) is the design argument: the sketches
are the compressed, *mergeable* representation, so the fleet tier ships
them instead of samples and the operator merges losslessly.

Frame layout (little-endian throughout)::

    b"RFLT" | u8 version | u32 header_len | header (msgpack) | payload

The header carries node/tenant/priority/epoch/seq/window metadata, the
sketch seeds (hash-function identity — merging sketches built with
different seeds is meaningless and is refused at ingest), and an array
directory of ``{name, wire dtype, target dtype, shape}`` records; the
payload is the arrays' raw bytes concatenated in directory order.

HLL register banks hold values 0..33 by construction (rank of a 32-bit
hash) but live as uint32 on device for scatter-dtype uniformity; the
codec packs them to uint8 on the wire (4x smaller — at production
shapes the per-pod bank is the largest array in the frame) and restores
uint32 on decode, so round-trip is value-exact.
"""

from __future__ import annotations

import dataclasses
import struct

import msgpack
import numpy as np

MAGIC = b"RFLT"
VERSION = 1

# In-process pubsub topics (pubsub.py). Snapshot payloads are bytes —
# exactly what the gRPC Ship RPC carries, so in-process and relay
# transports are interchangeable.
FLEET_TOPIC = "fleet/snapshots"
ROLLUP_TOPIC = "fleet/rollups"

# v1 array catalog: name -> (device dtype, wire dtype). Encoders may
# ship any subset (the aggregator merges what every node in the epoch
# actually sent), but names outside the catalog are a decode error —
# the catalog IS the schema.
ARRAY_CATALOG: dict[str, tuple[str, str]] = {
    "flow_cms": ("uint32", "uint32"),
    "flow_keys": ("uint32", "uint32"),
    "flow_counts": ("uint32", "uint32"),
    "svc_cms": ("uint32", "uint32"),
    "svc_keys": ("uint32", "uint32"),
    "svc_counts": ("uint32", "uint32"),
    "dns_cms": ("uint32", "uint32"),
    "dns_keys": ("uint32", "uint32"),
    "dns_counts": ("uint32", "uint32"),
    "hll_flows": ("uint32", "uint8"),
    "hll_src_per_pod": ("uint32", "uint8"),
    "entropy": ("float32", "float32"),
    "totals": ("uint32", "uint32"),
    # Invertible sketch regions (ops/invertible.py): pure-sum bit-plane
    # counters — the aggregator decodes cluster-wide heavy keys from the
    # MERGED arrays, so no node ever ships raw keys.
    "inv_flow_planes": ("uint32", "uint32"),
    "inv_flow_weights": ("uint32", "uint32"),
    "inv_hi_planes": ("uint32", "uint32"),
    "inv_hi_weights": ("uint32", "uint32"),
}

# Sketch op class implementing each catalog array's merge — the RT225
# lint rule keys off this: every DISTINCT class named here must have a
# merge-associativity (and commutativity) property test under tests/,
# or the rollup silently stops being order-independent when someone
# edits a merge. ``None`` marks plain vector adds with no op class
# (associative by construction). The aggregator's _merge_fn mirrors
# these semantics via its name-pattern branches (hll_* -> max,
# *_keys/*_counts -> semilattice fold, else sum).
ARRAY_OP_CLASSES: dict[str, str | None] = {
    "flow_cms": "retina_tpu.ops.countmin.CountMinSketch",
    "flow_keys": "retina_tpu.ops.topk.TopKTable",
    "flow_counts": "retina_tpu.ops.topk.TopKTable",
    "svc_cms": "retina_tpu.ops.countmin.CountMinSketch",
    "svc_keys": "retina_tpu.ops.topk.TopKTable",
    "svc_counts": "retina_tpu.ops.topk.TopKTable",
    "dns_cms": "retina_tpu.ops.countmin.CountMinSketch",
    "dns_keys": "retina_tpu.ops.topk.TopKTable",
    "dns_counts": "retina_tpu.ops.topk.TopKTable",
    "hll_flows": "retina_tpu.ops.hyperloglog.HyperLogLog",
    "hll_src_per_pod": "retina_tpu.ops.hyperloglog.HyperLogLog",
    "entropy": "retina_tpu.ops.entropy.EntropyWindow",
    "totals": None,
    "inv_flow_planes": "retina_tpu.ops.invertible.InvertibleSketch",
    "inv_flow_weights": "retina_tpu.ops.invertible.InvertibleSketch",
    "inv_hi_planes": "retina_tpu.ops.invertible.InvertibleSketch",
    "inv_hi_weights": "retina_tpu.ops.invertible.InvertibleSketch",
}


class FleetDecodeError(ValueError):
    """Raised on any malformed fleet frame (bad magic/version/length,
    unknown array, dtype/shape mismatch). The aggregator counts these
    and drops the frame — a misbehaving node must never take down the
    rollup tier."""


@dataclasses.dataclass
class FleetSnapshot:
    """Decoded (or to-encode) snapshot: metadata + host arrays."""

    node: str
    tenant: str
    priority: int  # higher = more important; shed LAST
    epoch: int  # window epoch (aligned across nodes)
    seq: int  # per-node monotonic ship counter (duplicate detection)
    window_s: float
    seeds: dict[str, int]  # sketch hash seeds (merge identity)
    arrays: dict[str, np.ndarray]
    # Optional trace context (obs/recorder.py): the window-epoch trace
    # ID plus origin metadata, so the aggregator's merge span joins the
    # shipping node's span lineage. Absent on frames from older nodes
    # (and omitted from the wire when None), so the codec stays
    # compatible in both directions: old decoders ignore the unknown
    # msgpack key, this decoder tolerates its absence.
    trace: dict | None = None
    # Seed generation: bumped by a live seed rotation. Sketches only
    # merge within one generation; the aggregator quarantines
    # cross-generation frames per epoch instead of permanently
    # quarantining a rotated node. Same compatibility pattern as
    # ``trace``: omitted from the wire when 0, so pre-rotation frames
    # stay byte-identical and decode as generation 0.
    seed_gen: int = 0
    # Rollup tier of the ENCODER: 0 = node agent, 1 = zone aggregator
    # re-ship, 2+ = higher tiers. Informational (the merge algebra is
    # tier-blind — an aggregator's output is a valid node snapshot);
    # omitted from the wire when 0.
    tier: int = 0

    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays.values())


def encode_snapshot(snap: FleetSnapshot) -> bytes:
    """Serialize to one wire frame. Arrays are packed in sorted-name
    order so encoding is deterministic (byte-identical for equal
    snapshots)."""
    directory = []
    chunks = []
    for name in sorted(snap.arrays):
        if name not in ARRAY_CATALOG:
            raise ValueError(f"array {name!r} not in fleet catalog v1")
        target, wire = ARRAY_CATALOG[name]
        arr = np.asarray(snap.arrays[name])
        if arr.dtype != np.dtype(target):
            raise ValueError(
                f"array {name!r} must be {target}, got {arr.dtype}"
            )
        wired = np.ascontiguousarray(arr.astype(wire, copy=False))
        directory.append({
            "n": name, "d": wire, "t": target, "s": list(arr.shape),
        })
        chunks.append(wired.tobytes())
    hdr: dict = {
        "v": VERSION,
        "node": snap.node,
        "tenant": snap.tenant,
        "prio": int(snap.priority),
        "epoch": int(snap.epoch),
        "seq": int(snap.seq),
        "win_s": float(snap.window_s),
        "seeds": {k: int(v) for k, v in snap.seeds.items()},
        "arrays": directory,
    }
    if snap.trace is not None:
        # Optional trace context: omitted entirely when unset so frames
        # from trace-less encoders stay byte-identical to v1-as-shipped.
        hdr["trace"] = snap.trace
    if snap.seed_gen:
        # Optional like trace: generation 0 frames stay byte-identical
        # to pre-rotation v1 frames in both directions.
        hdr["sgen"] = int(snap.seed_gen)
    if snap.tier:
        hdr["tier"] = int(snap.tier)
    header = msgpack.packb(hdr, use_bin_type=True)
    return b"".join(
        [MAGIC, bytes([VERSION]), struct.pack("<I", len(header)), header]
        + chunks
    )


def decode_snapshot(frame: bytes) -> FleetSnapshot:
    """Parse + validate one wire frame (inverse of encode_snapshot)."""
    if len(frame) < 9 or frame[:4] != MAGIC:
        raise FleetDecodeError("bad magic")
    if frame[4] != VERSION:
        raise FleetDecodeError(f"unsupported fleet version {frame[4]}")
    (hlen,) = struct.unpack_from("<I", frame, 5)
    if 9 + hlen > len(frame):
        raise FleetDecodeError("truncated header")
    try:
        hdr = msgpack.unpackb(frame[9:9 + hlen], raw=False)
    except Exception as e:
        raise FleetDecodeError(f"header unpack failed: {e}") from e
    if not isinstance(hdr, dict) or hdr.get("v") != VERSION:
        raise FleetDecodeError("header version mismatch")
    arrays: dict[str, np.ndarray] = {}
    off = 9 + hlen
    for rec in hdr.get("arrays", ()):
        name = rec.get("n")
        if name not in ARRAY_CATALOG:
            raise FleetDecodeError(f"unknown array {name!r}")
        target, wire = ARRAY_CATALOG[name]
        if rec.get("d") != wire or rec.get("t") != target:
            raise FleetDecodeError(f"array {name!r} dtype mismatch")
        shape = tuple(int(x) for x in rec.get("s", ()))
        n = int(np.prod(shape)) if shape else 1
        nbytes = n * np.dtype(wire).itemsize
        if off + nbytes > len(frame):
            raise FleetDecodeError(f"array {name!r} truncated")
        buf = np.frombuffer(frame, dtype=wire, count=n, offset=off)
        arrays[name] = buf.reshape(shape).astype(target, copy=False)
        off += nbytes
    if off != len(frame):
        raise FleetDecodeError(
            f"{len(frame) - off} trailing bytes after payload"
        )
    try:
        return FleetSnapshot(
            node=str(hdr["node"]),
            tenant=str(hdr["tenant"]),
            priority=int(hdr["prio"]),
            epoch=int(hdr["epoch"]),
            seq=int(hdr["seq"]),
            window_s=float(hdr["win_s"]),
            seeds={str(k): int(v) for k, v in hdr["seeds"].items()},
            arrays=arrays,
            trace=(dict(hdr["trace"])
                   if isinstance(hdr.get("trace"), dict) else None),
            seed_gen=int(hdr.get("sgen", 0)),
            tier=int(hdr.get("tier", 0)),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise FleetDecodeError(f"bad header field: {e}") from e

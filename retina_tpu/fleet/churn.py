"""Multi-process fleet churn harness (``bench.py --churn-dryrun``).

The real topology, end to end, with real failures:

- N node agents as SEPARATE OS processes (fleet/node_agent.py, JAX-free)
  shipping RFLT frames over real ``retina.Fleet/Ship`` gRPC sockets;
- Z zone relays, each a :class:`HubbleServer` feeding a zone
  :class:`FleetAggregator` whose merged epochs RE-SHIP (tier 1) to a
  root relay + root aggregator — the two-level rollup;
- a scripted fault timeline: a rolling restart of ``churn_frac`` of
  the nodes, a node→relay partition (zone 0's relay goes away and
  comes back on the same port), a relay→root partition (zone 1's
  uplink refuses), and a live fleet-wide seed rotation.

Scorecard gates (the ISSUE-19 acceptance contract):

- root-tier top-k recall ≥ 0.95 every epoch, scored against EXACT
  per-flow counts of exactly the nodes each rollup merged (traffic is
  deterministic per (seed, node, epoch) — hostsketch.epoch_traffic —
  so the parent recomputes ground truth with zero IPC);
- partitions heal with spooled frames REPLAYED (child spools for the
  node→relay cut, the zone re-shipper's spool for the relay→root cut),
  and no frame is lost silently: every send attempt is accounted
  accepted-or-counted-drop on the receiving side;
- the seed rotation re-admits EVERY live node at the new generation;
- ``trace_lineage_ok`` across all three tiers: every root-merged epoch
  appears as a SHIP_SEND trace ID in some child, a SHIP_SEND in the
  parent (zone re-ship), and ≥2 AGG_MERGE spans (zone + root);
- operator scrape latency p99 stays bounded while all of this churns.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import Counter
from pathlib import Path
from typing import Any, Callable

import numpy as np

import retina_tpu
from retina_tpu.config import Config
from retina_tpu.fleet.aggregator import FleetAggregator
from retina_tpu.fleet.codec import FleetSnapshot, encode_snapshot
from retina_tpu.fleet.hostsketch import (
    exact_counter, rotated_seeds, sketch_arrays_np,
)
from retina_tpu.hubble.observer import FlowObserver
from retina_tpu.hubble.server import FleetShipClient, HubbleServer
from retina_tpu.metrics import get_exporter, get_metrics
from retina_tpu.obs.recorder import get_recorder
from retina_tpu.utils import metric_names as mn

_REPO_ROOT = Path(retina_tpu.__file__).resolve().parents[1]


class _Child:
    """One node-agent process + a stdout reader thread (deadline-based
    readiness — satellite: no fixed sleeps anywhere in this harness)."""

    def __init__(self, index: int, relay: str, *, interval: float,
                 heavy: int, light: int, seed: int, gen: int = 0):
        self.index = index
        self.node = f"node{index:03d}"
        self.ready = threading.Event()
        self.stats: dict | None = None
        self._stats_evt = threading.Event()
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            str(_REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
        )
        env.setdefault("JAX_PLATFORMS", "cpu")  # inert: child is JAX-free
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "retina_tpu.fleet.node_agent",
             "--node-index", str(index), "--relay", relay,
             "--interval", str(interval), "--heavy", str(heavy),
             "--light", str(light), "--seed", str(seed),
             "--gen", str(gen)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
            cwd=str(_REPO_ROOT),
        )
        self._reader = threading.Thread(
            target=self._read, name=f"churn-read-{index}", daemon=True
        )
        self._reader.start()

    def _read(self) -> None:
        import json

        for line in self.proc.stdout:
            if line.startswith("READY "):
                self.ready.set()
            elif line.startswith("STATS "):
                try:
                    self.stats = json.loads(line[len("STATS "):])
                except ValueError:
                    self.stats = None
                self._stats_evt.set()
        self._stats_evt.set()  # EOF without STATS (killed child)

    def send(self, line: str) -> None:
        try:
            self.proc.stdin.write(line + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, ValueError, OSError):  # noqa: RT101 - a dead child's pipe is expected mid-churn; its STATS collection accounts for it
            pass

    def stop(self, deadline_s: float = 15.0) -> dict | None:
        self.send("STOP")
        self._stats_evt.wait(deadline_s)
        try:
            self.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()
        return self.stats

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait()


class _ZoneUplink:
    """Zone→root transport with a partition switch. Counts every
    attempt so the scorecard can prove nothing vanished in transit."""

    def __init__(self, root_addr: str):
        self.root_addr = root_addr
        self.partitioned = False
        self.sent = 0
        self._client: FleetShipClient | None = None
        self._lock = threading.Lock()

    def __call__(self, frame: bytes) -> None:
        with self._lock:
            if self.partitioned:
                raise ConnectionError("relay->root partition (scripted)")
            if self._client is None:
                # Default (short) deadline on purpose: the root handler
                # merges inline, so a cold jit compile can outlive the
                # RPC — failing fast keeps the replay queue moving and
                # the frame that did land server-side just re-ships as
                # a counted duplicate (tolerated by the >= accounting).
                self._client = FleetShipClient(self.root_addr)
            client = self._client
        client.ship(frame)
        with self._lock:
            self.sent += 1

    def close(self) -> None:
        with self._lock:
            if self._client is not None:
                self._client.close()
                self._client = None


class _CountedIngest:
    """Wrap an aggregator's ingest with accept/reject accounting (the
    reject side is the aggregator's counted drop — late/dup/skew — so
    accepted + rejected == frames that arrived: no silent loss)."""

    def __init__(self, ingest: Callable[[bytes], bool]):
        self._ingest = ingest
        self.accepted = 0
        self.rejected = 0
        self._lock = threading.Lock()

    def __call__(self, frame: bytes) -> bool:
        ok = self._ingest(frame)
        with self._lock:
            if ok:
                self.accepted += 1
            else:
                self.rejected += 1
        return ok


def _wait(predicate: Callable[[], bool], deadline_s: float,
          poll_s: float = 0.05) -> bool:
    """Deadline-based condition wait (never a bare fixed sleep)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return predicate()


def _sleep_until_epoch(interval: float, epoch: int) -> None:
    """Sleep until wall-clock window ``epoch`` begins."""
    target = epoch * interval
    while True:
        dt = target - time.time()
        if dt <= 0:
            return
        time.sleep(min(dt, 0.2))


def run_churn_dryrun(
    nodes: int = 64,
    zones: int = 4,
    heavy_flows: int = 40,
    light_flows: int = 64,
    seed: int = 0,
    interval_s: float = 1.0,
    churn_frac: float = 0.10,
    scrape_p99_budget_s: float = 0.5,
    log: Callable[[str], None] = lambda s: None,
) -> dict[str, Any]:
    """Run the full churn timeline; returns the scorecard dict."""
    assert nodes >= zones >= 2 and nodes % zones == 0
    per_zone = nodes // zones
    k = 32
    rotation_gen = 1

    # -- root tier -----------------------------------------------------
    root_cfg = Config(
        fleet_enabled=True, fleet_aggregator=True,
        fleet_expected_nodes=zones,
        fleet_straggler_timeout_s=2.5 * interval_s,
        fleet_topk_k=k, fleet_node_name="root",
        fleet_max_tenants=8,
        fleet_merge_async=True,
    )
    root = FleetAggregator(root_cfg)
    # The default rollup retention (64) can evict scored-window epochs
    # while post-timeline merges drain; keep the whole run.
    root.rollups_keep = 512
    root_ingest = _CountedIngest(root.ingest)
    root_server = HubbleServer(
        FlowObserver(), "127.0.0.1:0", fleet_ingest=root_ingest
    )
    root_server.start()
    root_addr = f"127.0.0.1:{root_server.port}"
    # NB: root.start() is deferred until after the prewarm below — its
    # poll thread straggler-closes buckets, and the prewarm's zone
    # merges arrive seconds apart (cold compiles), which would close
    # the warm epoch at n=1 and leave the full-quorum path cold.

    # -- zone tier -----------------------------------------------------
    zone_aggs: list[FleetAggregator] = []
    zone_uplinks: list[_ZoneUplink] = []
    zone_ingests: list[_CountedIngest] = []
    zone_servers: list[HubbleServer | None] = []
    zone_addrs: list[str] = []
    for z in range(zones):
        up = _ZoneUplink(root_addr)
        zcfg = Config(
            fleet_enabled=True, fleet_aggregator=True,
            fleet_expected_nodes=per_zone,
            fleet_straggler_timeout_s=1.5 * interval_s,
            fleet_topk_k=k, fleet_node_name=f"zone{z}",
            fleet_reship_addr=root_addr,  # transport below overrides
            fleet_ship_spool=64,
            fleet_ship_backoff_base_s=0.05,
            fleet_ship_backoff_max_s=0.5,
            fleet_max_tenants=8,
            fleet_merge_async=True,
        )
        agg = FleetAggregator(zcfg, reship_transport=up)
        agg.rollups_keep = 512
        ing = _CountedIngest(agg.ingest)
        srv = HubbleServer(FlowObserver(), "127.0.0.1:0", fleet_ingest=ing)
        srv.start()
        agg.start(subscribe=False)
        zone_aggs.append(agg)
        zone_uplinks.append(up)
        zone_ingests.append(ing)
        zone_servers.append(srv)
        zone_addrs.append(f"127.0.0.1:{srv.port}")

    # -- compile prewarm ----------------------------------------------
    # The merge/rollup jit caches key on (batch size, seeds), so a live
    # seed rotation would otherwise trigger a fleet-wide compile storm
    # INSIDE the gRPC handlers (merges run inline on quorum close) —
    # uplink RPCs time out, replays pile up, and the root closes
    # partial buckets right when the rotation gate is scored. Warm the
    # full-quorum merge path for BOTH generations through the real
    # pipeline: synthetic zero-traffic epochs 1 (gen 0) and 2 (gen 1)
    # ingested at every zone; the re-ship cascade warms the root. Real
    # window epochs are ~1e9, so the warm epochs never collide.
    log(f"churn: prewarming merge compiles for gens 0/{rotation_gen}")
    for warm_epoch, warm_gen in ((1, 0), (2, rotation_gen)):
        wseeds = rotated_seeds(warm_gen)
        warrays = sketch_arrays_np(
            np.zeros((0, 4), np.uint32), np.zeros(0, np.uint32), wseeds
        )
        for z, agg in enumerate(zone_aggs):
            for n in range(per_zone):
                agg.ingest(encode_snapshot(FleetSnapshot(
                    node=f"warm{n:03d}", tenant="warm", priority=0,
                    epoch=warm_epoch, seq=warm_epoch, window_s=interval_s,
                    seeds=dict(wseeds),
                    arrays={k: v.copy() for k, v in warrays.items()},
                    seed_gen=warm_gen,
                )))
    def _warm_done() -> bool:
        # The root's poll thread isn't running yet (started below) —
        # drive its deferred-merge queue here. now=0.0 makes every
        # straggler check negative, so only quorum-complete warm
        # buckets merge; a partially-arrived one keeps waiting.
        root.poll(now=0.0)
        return {1, 2} <= {r["epoch"] for r in root.rollups}

    warm_ok = _wait(_warm_done, deadline_s=180.0, poll_s=0.1)
    log(f"churn: prewarm done (root warmed across tiers: {warm_ok})")
    root.start(subscribe=False)

    # -- scrape-latency probe (operator view under fan-in) -------------
    scrape_times: list[float] = []
    scrape_stop = threading.Event()

    def scraper() -> None:
        exp = get_exporter()
        while not scrape_stop.is_set():
            t0 = time.monotonic()
            exp.gather_text()
            scrape_times.append(time.monotonic() - t0)
            scrape_stop.wait(0.05)

    scrape_thread = threading.Thread(
        target=scraper, name="churn-scrape", daemon=True
    )
    scrape_thread.start()

    # -- node tier: real child processes -------------------------------
    def spawn(i: int, gen: int = 0) -> _Child:
        return _Child(
            i, zone_addrs[i % zones], interval=interval_s,
            heavy=heavy_flows, light=light_flows, seed=seed, gen=gen,
        )

    children: dict[int, _Child] = {i: spawn(i) for i in range(nodes)}
    ready_ok = _wait(
        lambda: all(c.ready.is_set() for c in children.values()),
        deadline_s=60.0,
    )
    events: list[str] = []
    if not ready_ok:
        missing = [c.node for c in children.values() if not c.ready.is_set()]
        events.append(f"READY timeout: {missing}")

    def mark(msg: str) -> None:
        # Stamp every fault event with the ACTUAL epoch offset it fired
        # at — on a loaded host a deadline wait can push an event past
        # its scripted slot, and scorecard triage needs the real times.
        events.append(f"[e+{int(time.time() // interval_s) - e0}] {msg}")
        log(f"churn: {events[-1]}")
    # First fully-observed epoch: the next wall-clock window boundary.
    e0 = int(time.time() // interval_s) + 1
    log(f"churn: {nodes} children ready across {zones} zones; "
        f"timeline starts at epoch {e0}")

    # -- fault timeline (wall-clock epochs, e = offset from e0) --------
    churn_n = max(1, int(round(nodes * churn_frac)))
    # Evenly spread victims across the fleet (distinct by construction:
    # i*nodes//churn_n is strictly increasing for churn_n <= nodes).
    restart_ids = sorted(i * nodes // churn_n for i in range(churn_n))
    total_epochs = 14

    _sleep_until_epoch(interval_s, e0 + 3)
    for i in restart_ids:  # rolling restart, 10% of the fleet
        old = children[i]
        old.kill()
        children[i] = spawn(i)
        mark(f"restarted {old.node}")
    _wait(lambda: all(
        children[i].ready.is_set() for i in restart_ids
    ), deadline_s=30.0)

    _sleep_until_epoch(interval_s, e0 + 5)
    # node→relay partition: zone 0's relay disappears mid-epoch...
    z0_port = zone_servers[0].port
    zone_servers[0].stop(grace=0)
    zone_servers[0] = None
    mark("zone0 relay down")
    _sleep_until_epoch(interval_s, e0 + 6)
    time.sleep(interval_s / 2.0)
    # ...and comes back on the SAME port: children re-dial and replay.
    # Deadline-based rebind (the dead server's socket can linger for a
    # beat; add_insecure_port reports failure as port 0).
    rebind_deadline = time.monotonic() + 15.0
    srv = None
    while srv is None:
        cand = HubbleServer(
            FlowObserver(), f"127.0.0.1:{z0_port}",
            fleet_ingest=zone_ingests[0],
        )
        if cand.port == z0_port:
            srv = cand
        else:
            cand.stop(grace=0)
            if time.monotonic() > rebind_deadline:
                raise RuntimeError(
                    f"zone0 relay could not rebind port {z0_port}"
                )
            time.sleep(0.2)
    srv.start()
    zone_servers[0] = srv
    mark("zone0 relay back")

    _sleep_until_epoch(interval_s, e0 + 7)
    zone_uplinks[1].partitioned = True  # relay→root partition
    mark("zone1 uplink partitioned")
    # Heal only once the cut has provably bitten: at least one merged
    # epoch must land in zone1's re-ship spool first. On a loaded host
    # the zone's poll-thread merge of e+7 can lag past a fixed heal
    # point — and a partition nothing tried to cross exercises nothing.
    spool_armed = _wait(
        lambda: zone_aggs[1].stats().get("reship", {}).get(
            "spool_depth", 0) > 0,
        deadline_s=4 * interval_s + 15.0,
    )
    _sleep_until_epoch(interval_s, e0 + 8)
    time.sleep(interval_s / 2.0)
    zone_uplinks[1].partitioned = False
    mark(f"zone1 uplink healed (spool_armed={spool_armed})")

    _sleep_until_epoch(interval_s, e0 + 9)
    # Live fleet-wide seed rotation. The deadline waits above can push
    # the clock past the scripted e+9 slot, so the rotation's effective
    # epoch is whatever boundary comes NEXT (children flip generation
    # at their next window build) — and the scored window extends to
    # keep ≥5 observable post-rotation epochs no matter how far the
    # timeline slipped.
    rot_e = int(time.time() // interval_s) + 1
    for c in children.values():
        c.send(f"ROTATE {rotation_gen}")
    mark(f"rotation to gen {rotation_gen} (effective e+{rot_e - e0})")
    total_epochs = max(total_epochs, rot_e - e0 + 5)

    # Give the last scored epoch one full extra window to ship, then
    # stop the children FIRST: on a loaded host the merge backlog can
    # only drain once the fleet stops competing for the cores, and a
    # child shipping epochs past the scored window adds nothing.
    last_scored = e0 + total_epochs - 1
    _sleep_until_epoch(interval_s, e0 + total_epochs + 1)

    # -- teardown + collection -----------------------------------------
    child_stats: dict[int, dict | None] = {}
    stoppers = []
    for i, c in children.items():
        t = threading.Thread(
            target=lambda i=i, c=c: child_stats.__setitem__(i, c.stop()),
            daemon=True,
        )
        t.start()
        stoppers.append(t)
    for t in stoppers:
        t.join(timeout=30.0)
    # Now let stragglers close and the root work through its deferred
    # merge queue up to the end of the scored window.
    _wait(
        lambda: any(
            r["epoch"] >= last_scored for r in root.rollups
        ),
        deadline_s=60.0,
    )
    # Zone re-ship spools drain on their own retry timers post-heal.
    _wait(lambda: all(
        a.stats().get("reship", {}).get("spool_depth", 0) == 0
        for a in zone_aggs
    ), deadline_s=15.0)
    # Capture live aggregator state BEFORE stop(): open buckets and the
    # deferred-merge queue are exactly what a stalled tier leaves behind.
    root_stats_end = root.stats()
    drop_reasons: dict[str, int] = {}
    for metric in get_metrics().fleet_snapshots_dropped.collect():
        for s in metric.samples:
            if s.name.endswith("_total") and s.value:
                drop_reasons[s.labels.get("reason", "?")] = int(s.value)
    scrape_stop.set()
    scrape_thread.join(timeout=5.0)
    for a in zone_aggs:
        a.stop()
    root.stop()
    for s in zone_servers:
        if s is not None:
            s.stop(grace=0)
    root_server.stop(grace=0)
    for up in zone_uplinks:
        up.close()

    # -- scorecard -----------------------------------------------------
    # A frame replayed after its epoch already merged can open a second
    # bucket and publish a second, smaller rollup for the same epoch
    # (the recovery path doing its job). Pairing across tiers must be
    # FIRST-wins on both: a root bucket dedupes per zone name keeping
    # the first-arriving frame, and the re-shipper is FIFO (spool
    # replays oldest-first), so the root's sketch content for an epoch
    # is exactly the FIRST rollup each zone published for it — scoring
    # against any other instance compares the wrong ground truth.
    zone_rollups: list[dict[int, dict]] = []
    for a in zone_aggs:
        first: dict[int, dict] = {}
        for r in a.rollups:
            first.setdefault(r["epoch"], r)
        zone_rollups.append(first)

    root_first: dict[int, dict] = {}
    for r in root.rollups:
        root_first.setdefault(r["epoch"], r)
    recalls: dict[int, float] = {}
    for r in root_first.values():
        e = r["epoch"]
        if e < e0 or e > last_scored:
            continue
        merged_exact: Counter = Counter()
        for zname in r["nodes"]:
            zr = zone_rollups[int(zname[4:])].get(e)
            if zr is None:
                continue
            for node in zr["nodes"]:
                merged_exact.update(exact_counter(
                    seed, int(node[4:]), e, heavy_flows, light_flows
                ))
        if not merged_exact:
            continue
        exact_top = [kk for kk, _ in merged_exact.most_common(k)]
        got = {tuple(int(x) for x in row) for row in r["top_flow"][0]}
        recalls[e] = (
            sum(1 for kk in exact_top if kk in got) / len(exact_top)
        )
    recall_min = min(recalls.values()) if recalls else 0.0

    # Spool/replay evidence: the node→relay cut must show child-side
    # replay (zone-0 children), the relay→root cut re-ship replay.
    zone0_children = [
        s for i, s in child_stats.items()
        if s is not None and i % zones == 0
    ]
    child_replayed = sum(s["spool_replayed"] for s in zone0_children)
    child_evicted = sum(
        s["spool_evicted"] for s in child_stats.values() if s is not None
    )
    reship_stats = [a.stats().get("reship", {}) for a in zone_aggs]
    reship_replayed = sum(
        int(s.get("spool_replayed", 0)) for s in reship_stats
    )
    reship_spool_left = sum(
        int(s.get("spool_depth", 0)) for s in reship_stats
    )
    # Frame accounting, node tier: every frame a graceful child queued
    # was either shipped or is an explicitly counted eviction; and at
    # the relays, every arrived frame was accepted or counted-dropped.
    child_acct_ok = all(
        s["shipped"] + s["spool_evicted"] + s["spool_depth"]
        == sum(1 for o in s["offered"] if o["queued"])
        for s in child_stats.values() if s is not None
    )
    # Direction matters: a send the uplink believes delivered must have
    # arrived (accepted or counted-drop). Arrivals can EXCEED counted
    # sends — an RPC that times out after server-side processing is a
    # counted failure on the sender and a counted duplicate on replay —
    # so >= is the no-silent-loss invariant, not ==.
    uplink_sent = sum(u.sent for u in zone_uplinks)
    root_acct_ok = (
        root_ingest.accepted + root_ingest.rejected >= uplink_sent
    )
    no_silent_loss = bool(
        child_acct_ok and root_acct_ok and reship_spool_left == 0
    )

    # Rotation re-admission: some scored epoch at the new generation
    # must merge EVERY zone at the root and EVERY live node in every
    # zone (live = all of them; restarts completed long before).
    readmit_epochs = [
        r["epoch"] for r in root.rollups
        if r.get("seed_gen") == rotation_gen
        and len(r["nodes"]) == zones
        # Post-rotation scored window only: the gen-1 PREWARM epoch is
        # also a full-quorum gen-1 rollup and must not satisfy this.
        and rot_e <= r["epoch"] <= last_scored
        and all(
            len(zone_rollups[int(z[4:])].get(r["epoch"], {}).get(
                "nodes", ())) == per_zone
            for z in r["nodes"]
        )
    ]
    rotation_ok = bool(readmit_epochs)
    # Post-rotation tail diagnostics (what merged, at which generation,
    # with how many nodes per zone) — the first thing to read when the
    # re-admission gate fails.
    rotation_tail = [
        {
            "e": r["epoch"] - e0,
            "gen": r.get("seed_gen"),
            "zones": list(r["nodes"]),
            "zone_nodes": {
                z: len(zone_rollups[int(z[4:])].get(
                    r["epoch"], {}).get("nodes", ()))
                for z in r["nodes"]
            },
        }
        for r in root.rollups if r["epoch"] >= rot_e
    ]

    # Three-tier trace lineage over the window-epoch trace ID.
    spans = get_recorder().spans()
    parent_ship_tids = {
        s["trace_id"] for s in spans if s["stage"] == mn.STAGE_SHIP_SEND
    }
    merge_tid_counts = Counter(
        s["trace_id"] for s in spans if s["stage"] == mn.STAGE_AGG_MERGE
    )
    child_ship_tids: set[int] = set()
    for s in child_stats.values():
        if s is not None:
            child_ship_tids.update(int(t) for t in s["ship_tids"])
    root_epochs = {
        r["epoch"] for r in root.rollups
        if e0 <= r["epoch"] <= last_scored
    }
    lineage_ok = bool(root_epochs) and all(
        e in child_ship_tids
        and e in parent_ship_tids
        and merge_tid_counts.get(e, 0) >= 2
        for e in root_epochs
    )

    scrape_p99 = (
        float(np.quantile(np.array(scrape_times), 0.99))
        if scrape_times else float("inf")
    )

    res: dict[str, Any] = {
        "nodes": nodes,
        "zones": zones,
        "per_zone": per_zone,
        "epochs_scored": len(recalls),
        "root_epochs_merged": root.epochs_merged,
        "zone_epochs_merged": [a.epochs_merged for a in zone_aggs],
        # Triage aid: WHERE the root's merges actually landed relative
        # to the scored window. A healthy run is all "in"; "above"
        # means merges drained after the window closed (host overload),
        # "below" is warm/prewarm traffic.
        "root_state_at_teardown": {
            "watermark_offset": root_stats_end["watermark"] - e0,
            "open_epoch_offsets": [
                e - e0 for e in root_stats_end["open_epochs"]
            ][:32],
            "ready_q": root_stats_end.get("ready_q", 0),
        },
        # In-process drop accounting by reason (all tiers share the
        # process-global counter; zone + root combined).
        "frames_dropped_by_reason": drop_reasons,
        "root_epoch_dist": {
            "below": sum(1 for r in root.rollups if r["epoch"] < e0),
            "in": sum(
                1 for r in root.rollups
                if e0 <= r["epoch"] <= last_scored
            ),
            "above": sum(
                1 for r in root.rollups if r["epoch"] > last_scored
            ),
            "offsets": sorted(
                {r["epoch"] - e0 for r in root.rollups}
            )[:64],
        },
        "recall_min": round(recall_min, 4),
        "recall_per_epoch": {
            e - e0: round(v, 4) for e, v in sorted(recalls.items())
        },
        "restarted": [children[i].node for i in restart_ids],
        "child_spool_replayed": child_replayed,
        "child_spool_evicted": child_evicted,
        "reship_spool_replayed": reship_replayed,
        "uplink_frames_sent": uplink_sent,
        "root_frames_accepted": root_ingest.accepted,
        "root_frames_rejected_counted": root_ingest.rejected,
        "no_silent_frame_loss": no_silent_loss,
        "rotation_gen": rotation_gen,
        "rotation_readmitted_all": rotation_ok,
        "rotation_readmit_epochs": [e - e0 for e in readmit_epochs],
        "rotation_tail": rotation_tail,
        "child_summary": {
            (s["node"] if s else f"node{i:03d}"): (
                [s["n_offered"], s["shipped"], s["spool_replayed"],
                 s["spool_evicted"], s["seed_gen"]]
                if s else "no-stats"
            )
            for i, s in sorted(child_stats.items())
        },
        "zone_nodes_by_epoch": [
            {
                e - e0: sorted(zr[e]["nodes"])
                for e in sorted(zr) if e0 <= e <= last_scored
            }
            for zr in zone_rollups
        ],
        "trace_lineage_ok": lineage_ok,
        "scrape_p99_s": round(scrape_p99, 4),
        "scrape_samples": len(scrape_times),
        "events": events,
        "ok": bool(
            len(recalls) >= total_epochs - 4
            and recall_min >= 0.95
            and child_replayed > 0
            and reship_replayed > 0
            and no_silent_loss
            and rotation_ok
            and lineage_ok
            and scrape_p99 <= scrape_p99_budget_s
        ),
    }
    log(
        f"churn dryrun: {nodes} procs/{zones} zones, "
        f"{len(recalls)} epochs scored, min recall {recall_min:.3f}, "
        f"child replay {child_replayed}, reship replay {reship_replayed}, "
        f"rotation re-admitted={rotation_ok}, lineage={lineage_ok}, "
        f"scrape p99 {scrape_p99 * 1e3:.1f}ms"
    )
    return res

"""Node-side snapshot shipper: window close -> wire frame -> relay.

At each window close the engine dispatches the on-device fleet export
(one psum/pmax/all_gather pass over the mesh, parallel/telemetry.py)
and hands the resulting device dict to this shipper's bounded queue.
The worker thread does everything slow OFF the device proxy: readback
(fetch_on_device per leaf — polls readiness, never parks the proxy),
encode (fleet/codec.py), and the transport send.

Backpressure contract (the repo-wide rule): never block the close path
— a full queue drops the snapshot and counts it. Overload contract:
under SHEDDING and above, the shipper backs off to shipping 1 window in
``fleet_shed_ship_every`` (the rollup is the cheapest remote work to
lose; local scrape metrics stay complete).

Delivery contract: a transport failure opens the send circuit and the
frame goes to a bounded in-memory spool (oldest-evicted, both counted)
instead of being lost. The worker retries with jittered exponential
backoff — recreating the gRPC channel on each retry so a bounced relay
is re-dialed fresh — and on heal replays the spool oldest-first before
new frames, so a transient relay outage costs latency, not data. The
circuit state is exported as a gauge (fleet_ship_circuit_open) and in
:meth:`stats` — the node-local health signal operators alert on.

Transport is pluggable: default is the in-process pubsub bus
(FLEET_TOPIC — the aggregator subscribes when co-located); when
``fleet_relay_addr`` is set, frames go over the hubble relay's
"retina.Fleet" Ship RPC instead (hubble/server.py).
"""

from __future__ import annotations

import os
import queue as queue_mod
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from retina_tpu.fleet.codec import FLEET_TOPIC, FleetSnapshot, encode_snapshot
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.obs.recorder import get_recorder
from retina_tpu.pubsub import get_pubsub
from retina_tpu.runtime.overload import SHEDDING
from retina_tpu.utils import metric_names as mn
from retina_tpu.utils.device_proxy import fetch_on_device

# Worker wake sentinel: a retry-timer tick, not a frame.
_TICK = object()


class SnapshotShipper:
    """Owns the ship queue + worker thread for one node agent."""

    def __init__(
        self,
        cfg,
        overload=None,  # OverloadController (state read only)
        supervisor=None,  # runtime/supervisor.py Supervisor
        transport: Optional[Callable[[bytes], None]] = None,
    ) -> None:
        self.cfg = cfg
        self.log = logger("fleet.shipper")
        self.node = cfg.fleet_node_name or cfg.node_name or (
            f"node-{os.getpid()}"
        )
        self.tenant = cfg.fleet_tenant
        self.priority = int(cfg.fleet_priority)
        # Live seed generation: rotated by set_seed_generation (or per
        # offer); tags every frame so the aggregator can tell a rotated
        # node from a misconfigured one.
        self.seed_gen = int(cfg.fleet_seed_generation)
        # Tier stamped on outgoing frames (0 = node agent; the
        # aggregator's re-shipper sets 1).
        self.tier = 0
        self._overload = overload
        self._supervisor = supervisor
        self._transport = transport
        self._grpc_client: Any = None
        self._q: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, int(cfg.fleet_ship_queue))
        )
        self._seq = 0
        self._win_count = 0  # windows offered (shed-backoff modulus)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.shipped = 0  # frames actually sent (tests/dryrun)
        # -- spool / circuit state (worker thread only, read by stats) --
        self._spool: deque[bytes] = deque()
        self._spool_cap = max(0, int(cfg.fleet_ship_spool))
        self.circuit_open = False
        self._fail_streak = 0
        self._next_retry_t = 0.0
        self.spooled = 0
        self.spool_evicted = 0
        self.spool_replayed = 0
        self.reconnects = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-ship-{self.node}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._q.put(None)  # wake the worker
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        if self._supervisor is not None and self._thread is not None:
            self._supervisor.deregister(f"fleet-ship-{self.node}")
        self._thread = None

    def set_seed_generation(self, gen: int) -> None:
        """Rotate the live seed generation (tags frames from the NEXT
        offer on; in-flight frames keep the generation they were built
        under). Single int write — safe from any thread."""
        self.seed_gen = int(gen)

    # -- close-path entry (device-proxy thread; must never block) ------
    def offer(  # hot-path: close
        self,
        epoch: int,
        arrays: dict[str, Any],
        window_s: float,
        seeds: dict[str, int],
        seed_gen: int | None = None,
    ) -> bool:  # runs-on: device-proxy
        """Enqueue one window's export for shipping. ``arrays`` values
        may be device arrays (fetched on the worker) or host numpy.
        Returns False when deferred (overload backoff) or dropped
        (queue full / stopped)."""
        if self._stop.is_set():
            return False
        m = get_metrics()
        with self._lock:
            self._win_count += 1
            count = self._win_count
        ov = self._overload
        if ov is not None and ov.state >= SHEDDING:
            every = max(1, int(self.cfg.fleet_shed_ship_every))
            if count % every != 0:
                m.fleet_ship_deferred.inc()
                return False
        gen = self.seed_gen if seed_gen is None else int(seed_gen)
        try:
            self._q.put_nowait((epoch, arrays, window_s, seeds, gen))
            return True
        except queue_mod.Full:
            m.fleet_ship_dropped.inc()
            if rate_limited("fleet.ship_queue_full"):
                self.log.warning(
                    "fleet ship queue full; dropping epoch %d", epoch
                )
            return False

    # -- worker --------------------------------------------------------
    def _run(self) -> None:  # runs-on: fleet-ship
        hb = None
        if self._supervisor is not None:
            hb = self._supervisor.register(
                f"fleet-ship-{self.node}", self.cfg.watchdog_deadline_s
            )
        while not self._stop.is_set():
            if hb is not None:
                hb.park()
            # With frames waiting in the spool, wake at the next retry
            # time even if the queue stays empty — the replay must not
            # depend on new window closes arriving.
            timeout = None
            if self._spool:
                timeout = max(
                    0.01, self._next_retry_t - time.monotonic()
                )
            try:
                item = self._q.get(timeout=timeout)
            except queue_mod.Empty:
                item = _TICK
            if item is None or self._stop.is_set():
                break
            if hb is not None:
                hb.beat()
            try:
                if item is _TICK:
                    self._try_drain()
                else:
                    self._ship_one(*item)
            except Exception:
                get_metrics().fleet_ship_errors.inc()
                if rate_limited("fleet.ship"):
                    self.log.exception("fleet snapshot ship failed")

    def _ship_one(
        self,
        epoch: int,
        arrays: dict[str, Any],
        window_s: float,
        seeds: dict[str, int],
        seed_gen: int = 0,
    ) -> None:
        rec = get_recorder()
        t0 = rec.begin()
        host: dict[str, np.ndarray] = {}
        for name, arr in arrays.items():
            if isinstance(arr, np.ndarray):
                host[name] = arr
            else:
                host[name] = fetch_on_device(arr)
        rec.record(mn.STAGE_SHIP_READBACK, t0, int(epoch))
        with self._lock:
            seq = self._seq
            self._seq += 1
        snap = FleetSnapshot(
            node=self.node, tenant=self.tenant, priority=self.priority,
            epoch=int(epoch), seq=seq, window_s=float(window_s),
            seeds=seeds, arrays=host,
            # Trace context: the window epoch IS the trace ID; the
            # aggregator's merge span joins this lineage across the
            # process boundary (docs/observability.md).
            trace={"tid": int(epoch), "node": self.node},
            seed_gen=int(seed_gen),
            tier=int(self.tier),
        )
        t0 = rec.begin()
        frame = encode_snapshot(snap)
        rec.record(mn.STAGE_SHIP_ENCODE, t0, int(epoch))
        t0 = rec.begin()
        self._deliver(frame)
        rec.record(mn.STAGE_SHIP_SEND, t0, int(epoch))

    # -- delivery: circuit + spool + backoff ---------------------------
    def _deliver(self, frame: bytes) -> None:
        """Send one fresh frame, preserving epoch order: with frames
        already spooled the new frame queues BEHIND them (and a drain is
        attempted if the retry timer expired); otherwise it is sent
        directly and spooled on failure."""
        if self._spool:
            self._spool_frame(frame)
            self._try_drain()
            return
        try:
            self._send(frame)
        except Exception:
            self._note_send_failure(frame_lost=False)
            self._spool_frame(frame)
            return
        self._note_send_ok(len(frame))

    def _try_drain(self) -> None:
        """Replay the spool oldest-first once the backoff timer allows;
        a failure re-arms the timer and keeps the remaining frames."""
        if not self._spool or time.monotonic() < self._next_retry_t:
            return
        while self._spool:
            frame = self._spool[0]
            try:
                self._send(frame)
            except Exception:
                self._note_send_failure(frame_lost=False)
                return
            self._spool.popleft()
            self.spool_replayed += 1
            get_metrics().fleet_ship_spool_replayed.inc()
            self._note_send_ok(len(frame))

    def _spool_frame(self, frame: bytes) -> None:
        m = get_metrics()
        if self._spool_cap <= 0:
            # Spooling disabled: the legacy drop-on-error behavior
            # (the failure itself was already counted as a ship error).
            return
        while len(self._spool) >= self._spool_cap:
            self._spool.popleft()  # oldest-evicted
            self.spool_evicted += 1
            m.fleet_ship_spool_evicted.inc()
        self._spool.append(frame)
        self.spooled += 1
        m.fleet_ship_spooled.inc()

    def _note_send_ok(self, nbytes: int) -> None:
        m = get_metrics()
        m.fleet_snapshots_shipped.inc()
        m.fleet_ship_bytes.inc(nbytes)
        self.shipped += 1
        if self.circuit_open:
            self.log.info(
                "fleet ship circuit closed after %d failures "
                "(%d frames spooled)", self._fail_streak, len(self._spool),
            )
        self.circuit_open = False
        self._fail_streak = 0
        m.fleet_ship_circuit_open.set(0.0)

    def _note_send_failure(self, frame_lost: bool) -> None:
        m = get_metrics()
        m.fleet_ship_errors.inc()
        self._fail_streak += 1
        self.circuit_open = True
        m.fleet_ship_circuit_open.set(1.0)
        # Jittered exponential backoff: full-jitter style (uniform in
        # [base/2, backoff]) so a fleet of nodes cut off by one relay
        # outage does not re-dial in lockstep on heal.
        base = max(1e-3, float(self.cfg.fleet_ship_backoff_base_s))
        cap = max(base, float(self.cfg.fleet_ship_backoff_max_s))
        backoff = min(cap, base * (2.0 ** min(self._fail_streak - 1, 16)))
        delay = random.uniform(base / 2.0, backoff)
        self._next_retry_t = time.monotonic() + delay
        # A failed gRPC channel is torn down so the next attempt
        # re-dials (the relay may have restarted on the same address
        # with a new socket).
        if self._grpc_client is not None:
            try:
                self._grpc_client.close()
            except Exception:  # noqa: RT101 — best-effort channel teardown
                pass
            self._grpc_client = None
        if rate_limited("fleet.ship_circuit"):
            self.log.warning(
                "fleet ship failed (streak %d); retry in %.3fs, "
                "%d frames spooled", self._fail_streak, delay,
                len(self._spool),
            )

    def _send(self, frame: bytes) -> None:
        if self._transport is not None:
            self._transport(frame)
            return
        addr = self.cfg.fleet_relay_addr
        if addr:
            if self._grpc_client is None:
                # Lazy import: grpc is optional at module import time
                # (same gating as hubble/server.py).
                from retina_tpu.hubble.server import FleetShipClient

                if self._fail_streak:
                    self.reconnects += 1
                    get_metrics().fleet_ship_reconnects.inc()
                self._grpc_client = FleetShipClient(addr)
            self._grpc_client.ship(frame)
            return
        get_pubsub().publish(FLEET_TOPIC, frame)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        return {
            "node": self.node,
            "tenant": self.tenant,
            "seq": self._seq,
            "shipped": self.shipped,
            "queue_depth": self._q.qsize(),
            "seed_gen": self.seed_gen,
            "circuit_open": self.circuit_open,
            "spool_depth": len(self._spool),
            "spooled": self.spooled,
            "spool_evicted": self.spool_evicted,
            "spool_replayed": self.spool_replayed,
            "reconnects": self.reconnects,
        }


def window_epoch(window_s: float, now: float | None = None) -> int:
    """Wall-clock window epoch — aligned across nodes whose clocks are
    NTP-close (a skew below window_s/2 lands in the right bucket; the
    aggregator's straggler timeout absorbs the rest)."""
    now = time.time() if now is None else now
    return int(now // max(window_s, 1e-6))

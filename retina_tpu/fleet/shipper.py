"""Node-side snapshot shipper: window close -> wire frame -> relay.

At each window close the engine dispatches the on-device fleet export
(one psum/pmax/all_gather pass over the mesh, parallel/telemetry.py)
and hands the resulting device dict to this shipper's bounded queue.
The worker thread does everything slow OFF the device proxy: readback
(fetch_on_device per leaf — polls readiness, never parks the proxy),
encode (fleet/codec.py), and the transport send.

Backpressure contract (the repo-wide rule): never block the close path
— a full queue drops the snapshot and counts it. Overload contract:
under SHEDDING and above, the shipper backs off to shipping 1 window in
``fleet_shed_ship_every`` (the rollup is the cheapest remote work to
lose; local scrape metrics stay complete).

Transport is pluggable: default is the in-process pubsub bus
(FLEET_TOPIC — the aggregator subscribes when co-located); when
``fleet_relay_addr`` is set, frames go over the hubble relay's
"retina.Fleet" Ship RPC instead (hubble/server.py).
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from retina_tpu.fleet.codec import FLEET_TOPIC, FleetSnapshot, encode_snapshot
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.obs.recorder import get_recorder
from retina_tpu.pubsub import get_pubsub
from retina_tpu.runtime.overload import SHEDDING
from retina_tpu.utils import metric_names as mn
from retina_tpu.utils.device_proxy import fetch_on_device


class SnapshotShipper:
    """Owns the ship queue + worker thread for one node agent."""

    def __init__(
        self,
        cfg,
        overload=None,  # OverloadController (state read only)
        supervisor=None,  # runtime/supervisor.py Supervisor
        transport: Optional[Callable[[bytes], None]] = None,
    ) -> None:
        self.cfg = cfg
        self.log = logger("fleet.shipper")
        self.node = cfg.fleet_node_name or cfg.node_name or (
            f"node-{os.getpid()}"
        )
        self.tenant = cfg.fleet_tenant
        self.priority = int(cfg.fleet_priority)
        self._overload = overload
        self._supervisor = supervisor
        self._transport = transport
        self._grpc_client: Any = None
        self._q: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, int(cfg.fleet_ship_queue))
        )
        self._seq = 0
        self._win_count = 0  # windows offered (shed-backoff modulus)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.shipped = 0  # frames actually sent (tests/dryrun)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-ship-{self.node}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._q.put(None)  # wake the worker
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        if self._supervisor is not None and self._thread is not None:
            self._supervisor.deregister(f"fleet-ship-{self.node}")
        self._thread = None

    # -- close-path entry (device-proxy thread; must never block) ------
    def offer(
        self,
        epoch: int,
        arrays: dict[str, Any],
        window_s: float,
        seeds: dict[str, int],
    ) -> bool:  # runs-on: device-proxy
        """Enqueue one window's export for shipping. ``arrays`` values
        may be device arrays (fetched on the worker) or host numpy.
        Returns False when deferred (overload backoff) or dropped
        (queue full / stopped)."""
        if self._stop.is_set():
            return False
        m = get_metrics()
        with self._lock:
            self._win_count += 1
            count = self._win_count
        ov = self._overload
        if ov is not None and ov.state >= SHEDDING:
            every = max(1, int(self.cfg.fleet_shed_ship_every))
            if count % every != 0:
                m.fleet_ship_deferred.inc()
                return False
        try:
            self._q.put_nowait((epoch, arrays, window_s, seeds))
            return True
        except queue_mod.Full:
            m.fleet_ship_dropped.inc()
            if rate_limited("fleet.ship_queue_full"):
                self.log.warning(
                    "fleet ship queue full; dropping epoch %d", epoch
                )
            return False

    # -- worker --------------------------------------------------------
    def _run(self) -> None:  # runs-on: fleet-ship
        hb = None
        if self._supervisor is not None:
            hb = self._supervisor.register(
                f"fleet-ship-{self.node}", self.cfg.watchdog_deadline_s
            )
        while not self._stop.is_set():
            if hb is not None:
                hb.park()
            item = self._q.get()
            if item is None or self._stop.is_set():
                break
            if hb is not None:
                hb.beat()
            try:
                self._ship_one(*item)
            except Exception:
                get_metrics().fleet_ship_errors.inc()
                if rate_limited("fleet.ship"):
                    self.log.exception("fleet snapshot ship failed")

    def _ship_one(
        self,
        epoch: int,
        arrays: dict[str, Any],
        window_s: float,
        seeds: dict[str, int],
    ) -> None:
        rec = get_recorder()
        t0 = rec.begin()
        host: dict[str, np.ndarray] = {}
        for name, arr in arrays.items():
            if isinstance(arr, np.ndarray):
                host[name] = arr
            else:
                host[name] = fetch_on_device(arr)
        rec.record(mn.STAGE_SHIP_READBACK, t0, int(epoch))
        with self._lock:
            seq = self._seq
            self._seq += 1
        snap = FleetSnapshot(
            node=self.node, tenant=self.tenant, priority=self.priority,
            epoch=int(epoch), seq=seq, window_s=float(window_s),
            seeds=seeds, arrays=host,
            # Trace context: the window epoch IS the trace ID; the
            # aggregator's merge span joins this lineage across the
            # process boundary (docs/observability.md).
            trace={"tid": int(epoch), "node": self.node},
        )
        t0 = rec.begin()
        frame = encode_snapshot(snap)
        rec.record(mn.STAGE_SHIP_ENCODE, t0, int(epoch))
        t0 = rec.begin()
        self._send(frame)
        rec.record(mn.STAGE_SHIP_SEND, t0, int(epoch))
        m = get_metrics()
        m.fleet_snapshots_shipped.inc()
        m.fleet_ship_bytes.inc(len(frame))
        self.shipped += 1

    def _send(self, frame: bytes) -> None:
        if self._transport is not None:
            self._transport(frame)
            return
        addr = self.cfg.fleet_relay_addr
        if addr:
            if self._grpc_client is None:
                # Lazy import: grpc is optional at module import time
                # (same gating as hubble/server.py).
                from retina_tpu.hubble.server import FleetShipClient

                self._grpc_client = FleetShipClient(addr)
            self._grpc_client.ship(frame)
            return
        get_pubsub().publish(FLEET_TOPIC, frame)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        return {
            "node": self.node,
            "tenant": self.tenant,
            "seq": self._seq,
            "shipped": self.shipped,
            "queue_depth": self._q.qsize(),
        }


def window_epoch(window_s: float, now: float | None = None) -> int:
    """Wall-clock window epoch — aligned across nodes whose clocks are
    NTP-close (a skew below window_s/2 lands in the right bucket; the
    aggregator's straggler timeout absorbs the rest)."""
    now = time.time() if now is None else now
    return int(now // max(window_s, 1e-6))

"""Fleet rollup tier: cluster-wide sketch aggregation over the relay.

Node agents ship compact, versioned sketch snapshots (NOT raw samples)
at every window close; an operator-level aggregator aligns them by
window epoch, merges them on device with batched psum-style reductions,
and publishes cluster-wide heavy hitters, per-service cardinality, and
DDoS entropy under the ``fleet_*`` Prometheus families — with
per-tenant cardinality guardrails (docs/fleet.md).
"""

from retina_tpu.fleet.codec import (  # noqa: F401
    FLEET_TOPIC, ROLLUP_TOPIC, FleetDecodeError, FleetSnapshot,
    decode_snapshot, encode_snapshot,
)
from retina_tpu.fleet.shipper import SnapshotShipper  # noqa: F401
from retina_tpu.fleet.aggregator import FleetAggregator  # noqa: F401

"""Fleet rollup tier: cluster-wide sketch aggregation over the relay.

Node agents ship compact, versioned sketch snapshots (NOT raw samples)
at every window close; an operator-level aggregator aligns them by
window epoch, merges them on device with batched psum-style reductions,
and publishes cluster-wide heavy hitters, per-service cardinality, and
DDoS entropy under the ``fleet_*`` Prometheus families — with
per-tenant cardinality guardrails (docs/fleet.md).
"""

from retina_tpu.fleet.codec import (  # noqa: F401
    FLEET_TOPIC, ROLLUP_TOPIC, FleetDecodeError, FleetSnapshot,
    decode_snapshot, encode_snapshot,
)
from retina_tpu.fleet.shipper import SnapshotShipper  # noqa: F401

__all__ = [
    "FLEET_TOPIC", "ROLLUP_TOPIC", "FleetDecodeError", "FleetSnapshot",
    "decode_snapshot", "encode_snapshot", "SnapshotShipper",
    "FleetAggregator",
]


def __getattr__(name: str):
    # Lazy: the aggregator pulls in JAX, and the JAX-free half of this
    # package (codec/shipper/hostsketch/node_agent) is exactly what the
    # churn harness's 64+ child processes import — eager aggregator
    # import would cost every child the full JAX startup.
    if name == "FleetAggregator":
        from retina_tpu.fleet.aggregator import FleetAggregator

        return FleetAggregator
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

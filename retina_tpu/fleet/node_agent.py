"""Standalone node-agent child process (``python -m retina_tpu.fleet.node_agent``).

One real OS process per simulated node: builds numpy-only sketch
windows (fleet/hostsketch.py — no JAX import, so 64+ children start in
seconds), and ships real RFLT frames through a real
:class:`SnapshotShipper` over the relay's ``retina.Fleet/Ship`` gRPC
socket. This is the worker half of the churn harness
(fleet/churn.py); nothing here is test-only — the shipper, codec, and
transport are the production paths.

Protocol (line-oriented, parent <-> child):

- stdout ``READY node=<name> pid=<pid>`` once the shipper is running —
  the parent's deadline-based readiness signal (no fixed sleeps).
- stdin ``ROTATE <gen>``: live seed rotation — the NEXT epoch is built
  and tagged under generation <gen> (hostsketch.rotated_seeds).
- stdin ``STOP`` (or EOF — an orphaned child must not outlive its
  parent): drain the ship spool within the deadline, emit one stdout
  ``STATS <json>`` line (shipper stats + offered epochs + SHIP_SEND
  trace IDs for the cross-process lineage check), and exit 0.

Traffic is derived deterministically from (run seed, node index,
epoch), so the parent scores exact recall without any data channel and
a restarted replacement process regenerates the identical stream.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from retina_tpu.config import Config
from retina_tpu.fleet.hostsketch import (
    epoch_traffic, rotated_seeds, sketch_arrays_np,
)
from retina_tpu.fleet.shipper import SnapshotShipper, window_epoch
from retina_tpu.obs.recorder import get_recorder
from retina_tpu.utils import metric_names as mn


def _parse_args(argv: list[str] | None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(prog="retina-node-agent")
    ap.add_argument("--node-index", type=int, required=True)
    ap.add_argument("--relay", required=True, help="zone relay addr host:port")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--heavy", type=int, default=40)
    ap.add_argument("--light", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gen", type=int, default=0)
    ap.add_argument("--tenant-mod", type=int, default=4)
    ap.add_argument("--spool", type=int, default=256)
    ap.add_argument("--backoff-base", type=float, default=0.05)
    ap.add_argument("--backoff-max", type=float, default=1.0)
    ap.add_argument(
        "--max-epochs", type=int, default=600,
        help="hard exit after this many shipped epochs (orphan guard)",
    )
    ap.add_argument(
        "--drain-timeout", type=float, default=10.0,
        help="max seconds to wait for queue+spool drain on STOP",
    )
    return ap.parse_args(argv)


def main(argv: list[str] | None = None) -> int:
    args = _parse_args(argv)
    idx = int(args.node_index)
    node = f"node{idx:03d}"
    cfg = Config(
        fleet_enabled=True,
        fleet_node_name=node,
        fleet_tenant=f"tenant{idx % max(1, args.tenant_mod)}",
        fleet_priority=idx % 4,
        fleet_relay_addr=args.relay,
        fleet_seed_generation=int(args.gen),
        fleet_ship_spool=int(args.spool),
        fleet_ship_backoff_base_s=float(args.backoff_base),
        fleet_ship_backoff_max_s=float(args.backoff_max),
    )
    ship = SnapshotShipper(cfg)
    ship.start()

    stop = threading.Event()
    # Written by the control thread, read at each epoch build; a plain
    # int attribute via a 1-slot list keeps this lock-free (GIL-atomic).
    gen_box = [int(args.gen)]

    def control() -> None:  # runs-on: na-control
        for line in sys.stdin:
            parts = line.strip().split()
            if not parts:
                continue
            if parts[0] == "ROTATE" and len(parts) > 1:
                gen_box[0] = int(parts[1])
            elif parts[0] == "STOP":
                stop.set()
                return
        stop.set()  # EOF: parent is gone

    threading.Thread(target=control, name="na-control", daemon=True).start()

    print(f"READY node={node} pid={os.getpid()}", flush=True)

    offered: list[dict] = []
    last_epoch = -1
    interval = max(0.05, float(args.interval))
    while not stop.is_set() and len(offered) < args.max_epochs:
        epoch = window_epoch(interval)
        if epoch != last_epoch:
            last_epoch = epoch
            gen = gen_box[0]
            if gen != ship.seed_gen:
                ship.set_seed_generation(gen)
            keys, w = epoch_traffic(
                args.seed, idx, epoch, args.heavy, args.light
            )
            seeds = rotated_seeds(gen)
            arrays = sketch_arrays_np(keys, w, seeds)
            ok = ship.offer(epoch, arrays, interval, seeds, seed_gen=gen)
            offered.append(
                {"epoch": int(epoch), "gen": int(gen), "queued": bool(ok)}
            )
        # Wake early enough to catch the next boundary and to let the
        # spool retry timer run between epochs.
        stop.wait(interval / 20.0)

    # Drain: give the worker time to replay any spooled frames before
    # reporting — a healed partition must end with an empty spool. The
    # third condition closes the race where the worker popped the last
    # frame (queue shows empty) but hasn't finished sending it: every
    # queued frame must be accounted shipped-or-evicted before STATS.
    n_queued = sum(1 for o in offered if o["queued"])
    deadline = time.monotonic() + float(args.drain_timeout)
    while time.monotonic() < deadline:
        st = ship.stats()
        if (st["queue_depth"] == 0 and st["spool_depth"] == 0
                and st["shipped"] + st["spool_evicted"] >= n_queued):
            break
        time.sleep(0.05)

    st = ship.stats()
    ship_tids = sorted({
        int(s["trace_id"]) for s in get_recorder().spans()
        if s["stage"] == mn.STAGE_SHIP_SEND
    })
    st.update({
        "offered": offered,
        "n_offered": len(offered),
        "ship_tids": ship_tids,
    })
    print("STATS " + json.dumps(st), flush=True)
    ship.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

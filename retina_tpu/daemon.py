"""Standard agent daemon: the boot sequence.

Reference analog: cmd/standard/daemon.go:80-323 — Daemon.Start loads
config, sets up zap + telemetry + metrics, builds the controller-runtime
manager, wires pubsub/cache/enricher/filtermanager/metrics-module when
pod-level is on (:239-295), then runs the controller manager until SIGTERM
cancels the context and the Stop cascade runs.

Here: config → logging → ControllerManager (server + engine + plugins +
watchers) → MetricsModule (pod-level) → signal-driven stop event. The
driver-facing entry is :func:`run_agent`; ``python -m retina_tpu`` calls
it via the CLI.
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Optional

from retina_tpu.config import Config, enable_compilation_cache, load_config
from retina_tpu.crd.types import MetricsConfiguration
from retina_tpu.log import logger, setup_logger
from retina_tpu.managers.controllermanager import ControllerManager
from retina_tpu.module.metrics_module import MetricsModule


class Daemon:
    def __init__(self, cfg: Config, apiserver_host: str = ""):
        self.cfg = cfg
        self.log = logger("daemon")
        if cfg.device_platform:
            # Must land before the first device use in this process;
            # jax.config is a no-op once a backend is initialized.
            import jax

            jax.config.update("jax_platforms", cfg.device_platform)
            self.log.info("device platform forced: %s",
                          cfg.device_platform)
        if enable_compilation_cache(cfg.compilation_cache_dir):
            self.log.info("XLA compilation cache at %s",
                          cfg.compilation_cache_dir)
        if cfg.fault_spec:
            # Deterministic fault injection (chaos testing): armed only
            # when explicitly configured (RETINA_FAULT_SPEC / config).
            from retina_tpu.runtime import faults

            faults.configure(cfg.fault_spec)
            self.log.warning("fault injection armed: %s", cfg.fault_spec)
        self.cm = ControllerManager(cfg, apiserver_host=apiserver_host)
        # Identity from a real cluster (pkg/k8s watcher analog): core/v1
        # pods/services/nodes land in the same cache the CRD-store path
        # feeds, so enrichment works without our operator running.
        # Selected by an explicit kubeconfig OR automatically when running
        # in-cluster with a service account (the daemonset deployment).
        self.kubewatch = None
        self.ciliumwatch = None
        from retina_tpu.operator.kubeclient import in_cluster_available

        if cfg.kubeconfig or in_cluster_available():
            from retina_tpu.operator.kubewatch import CoreWatcher

            use_cilium = cfg.identity_source == "cilium"
            self.kubewatch = CoreWatcher(
                self.cm.cache, cfg.kubeconfig,
                namespace=cfg.kube_namespace,
                include_pods=not use_cilium,
                include_namespaces=cfg.enable_annotations,
            )
            if use_cilium:
                # Identity from the foreign CNI's objects (cilium-crds
                # interop): CEPs instead of core/v1 pods.
                if cfg.enable_annotations:
                    # CEPs carry identity labels, not pod annotations:
                    # per-POD retina.sh=observe opt-in cannot work in
                    # this mode; namespace-level opt-in still does.
                    self.log.warning(
                        "identity_source=cilium: per-pod observe "
                        "annotations are invisible (CiliumEndpoints "
                        "carry no pod annotations); use the namespace "
                        "annotation instead"
                    )
                from retina_tpu.operator.cilium import CiliumWatcher

                self.ciliumwatch = CiliumWatcher(
                    self.cm.cache, cfg.kubeconfig,
                    namespace=cfg.kube_namespace,
                )
        self.metrics_module: Optional[MetricsModule] = None
        self._mm_thread: Optional[threading.Thread] = None
        self.hubble = None
        self.monitoragent = None
        # Fleet rollup tier (fleet/): the aggregator role is explicit
        # config, not inferred — one operator-side process merges the
        # cluster's shipped sketch snapshots. Built before the relay so
        # the relay can front its ingest (retina.Fleet/Ship).
        self.fleet_aggregator = None
        if cfg.fleet_aggregator:
            from retina_tpu.fleet import FleetAggregator

            self.fleet_aggregator = FleetAggregator(
                cfg, supervisor=self.cm.supervisor
            )
        # Time-travel query tier (timetravel/): one QueryService owns
        # the jitted fold cache and every ring in this process — the
        # engine's per-window ring, plus a merged-epoch ring when the
        # aggregator role is on. The closed loop (autocapture) rides
        # the same service.
        self.query_service = None
        self.autocapture = None
        if cfg.timetravel_enabled:
            from retina_tpu.timetravel.query import QueryService

            self.query_service = QueryService(
                cfg, overload=self.cm.engine._overload
            )
            if self.cm.engine.timetravel_ring is not None:
                self.query_service.add_ring(
                    self.cm.engine.timetravel_ring
                )
            if (
                self.fleet_aggregator is not None
                and self.fleet_aggregator.epoch_ring is not None
            ):
                # The aggregator owns its merged-epoch ring; the query
                # tier just folds over it (RingProtocol).
                self.query_service.add_ring(
                    self.fleet_aggregator.epoch_ring
                )
            if cfg.autocapture_enabled:
                from retina_tpu.timetravel.autocapture import AutoCapture

                self.autocapture = AutoCapture(
                    cfg, self.query_service, ring_name="engine",
                    engine=self.cm.engine,
                    supervisor=self.cm.supervisor,
                )
                self.cm.engine.anomaly_hook = self.autocapture.notify
        # Detector bank (detect/): every registered detector judged at
        # window close over the engine's record tap; accepted firings
        # land in the same closed loop as the entropy hook
        # (AutoCapture.notify) when autocapture is on.
        self.detector_bank = None
        if cfg.detectors_enabled:
            from retina_tpu.detect import build_default_bank
            from retina_tpu.fleet.shipper import window_epoch

            sink = (
                self.autocapture.notify
                if self.autocapture is not None else None
            )
            self.detector_bank = build_default_bank(cfg, sink=sink)

            def _record_tap(
                records, now_s,
                _bank=self.detector_bank, _win=cfg.window_seconds,
            ):
                _bank.observe(
                    window_epoch(_win), records, now_s=float(now_s)
                )

            self.cm.engine.record_hook = _record_tap
        # Fleet query plane (fleetquery/): cluster-wide range answers
        # over whatever fleet sources this process has — the merged
        # epoch ring when the aggregator role is on, plus any node
        # clients the operator registers.
        self.fleetquery = None
        if cfg.fleetquery_enabled:
            from retina_tpu.fleetquery import FleetQueryService

            self.fleetquery = FleetQueryService(
                cfg, overload=self.cm.engine._overload
            )
            if (
                self.fleet_aggregator is not None
                and self.fleet_aggregator.epoch_ring is not None
            ):
                self.fleetquery.add_ring(
                    self.fleet_aggregator.epoch_ring
                )
        if cfg.enable_hubble:
            # Hubble CP rides alongside (cmd/hubble cell graph analog):
            # plugins mirror events into the external channel; the monitor
            # agent fans them out to the flow observer; the gRPC relay
            # serves GetFlows (SURVEY.md §3.5).
            from retina_tpu.hubble import (
                FlowObserver,
                HubbleServer,
                MonitorAgent,
            )

            self.monitoragent = MonitorAgent()
            dns_plugin = self.cm.pluginmanager.plugins.get("dns")
            self.observer = FlowObserver(
                capacity=cfg.hubble_ring_capacity,
                cache=self.cm.cache,
                dns_resolver=(dns_plugin.resolve if dns_plugin else None),
            )
            self.monitoragent.register_consumer(self.observer.consume)
            self.cm.pluginmanager.setup_channel(self.monitoragent.channel)
            # Peer set = static config peers + the node store (nodes the
            # operator publishes land in the cache; the peer service then
            # reflects live cluster membership, not boot-time config).
            def _peers() -> list[dict[str, str]]:
                # Peers serve on the same configured hubble port; with an
                # ephemeral bind (tests) fall back to our bound port.
                port = cfg.hubble_addr.rsplit(":", 1)[1]
                if port == "0" and self.hubble is not None:
                    port = str(self.hubble.port)
                out = [dict(p) for p in cfg.hubble_peers]
                seen = {p.get("address") for p in out}
                for n in self.cm.cache.list_nodes():
                    if n.ip and n.name != cfg.node_name:
                        addr = f"{n.ip}:{port}"
                        if addr not in seen:
                            out.append({"name": n.name, "address": addr})
                return out

            self.hubble = HubbleServer(
                self.observer,
                addr=cfg.hubble_addr,
                peers=_peers,
                node_name=cfg.node_name,
                tls_cert=cfg.hubble_tls_cert,
                tls_key=cfg.hubble_tls_key,
                tls_client_ca=cfg.hubble_tls_client_ca,
                unix_socket=cfg.hubble_sock_path,
                fleet_ingest=(
                    self.fleet_aggregator.ingest
                    if self.fleet_aggregator is not None else None
                ),
            )
            self.hubble_metrics_server = None
            if cfg.hubble_metrics_addr:
                # Dedicated hubble metrics mux (:9965 analog): serves ONLY
                # the hubble registry so scraping both muxes never
                # double-ingests the node/pod families.
                from retina_tpu.exporter import get_exporter
                from retina_tpu.server import Server

                self.hubble_metrics_server = Server(
                    cfg.hubble_metrics_addr,
                    gather=get_exporter().gather_hubble_text,
                    metrics_cache_ttl_s=cfg.metrics_cache_ttl_s,
                )
        if cfg.enable_pod_level:
            dns_plugin = self.cm.pluginmanager.plugins.get("dns")
            self.metrics_module = MetricsModule(
                cfg,
                engine=self.cm.engine,
                cache=self.cm.cache,
                filtermanager=self.cm.filtermanager,
                pubsub=self.cm.pubsub,
                dns_resolver=(dns_plugin.resolve if dns_plugin else None),
            )
        # Per-flow trace sampling off the record stream (module/traces):
        # idle until a TracesConfiguration reconcile names targets,
        # queried via /debug/vars -> CLI `retina-tpu trace`.
        from retina_tpu.module.traces import TracesModule

        self.traces_module = TracesModule()
        self.traces_module.attach(self.cm.engine)
        # Agent-side CRD reconcile (the reference daemon watches its
        # module CRDs itself, pkg/controllers/daemon): a list+watch
        # bridge feeds a local store whose watches drive the metrics +
        # traces modules — without this, only the OPERATOR process would
        # see the CRs and the agent's modules would never reconcile.
        self.crd_bridge = None
        if cfg.kubeconfig or in_cluster_available():
            try:
                from retina_tpu.operator.bridge import KubeBridge
                from retina_tpu.operator.store import CRDStore

                crd_store = CRDStore()
                crd_store.watch(
                    "MetricsConfiguration", self._on_metrics_crd
                )
                crd_store.watch(
                    "TracesConfiguration", self._on_traces_crd
                )
                self.crd_bridge = KubeBridge(
                    crd_store, cfg.kubeconfig,
                    namespace=cfg.kube_namespace,
                    # Only the module CRs: Captures are the operator's
                    # business, and N agents each LISTing every Capture
                    # is pure apiserver load.
                    kinds=["MetricsConfiguration",
                           "TracesConfiguration"],
                )
            except Exception as e:
                self.log.warning("agent CRD bridge unavailable: %s", e)

    # -- module CRD reconciles (agent side) ---------------------------
    def _on_metrics_crd(self, event: str, conf: Any) -> None:
        if self.metrics_module is None:
            return
        try:
            if event == "deleted":
                self.metrics_module.reconcile(
                    MetricsConfiguration.default()
                )
            elif event == "applied":
                self.metrics_module.reconcile(conf)
        except Exception:
            self.log.exception("metrics CRD reconcile failed")

    def _on_traces_crd(self, event: str, conf: Any) -> None:
        from retina_tpu.crd.types import TracesConfiguration

        try:
            if event == "deleted":
                self.traces_module.reconcile(TracesConfiguration())
            elif event == "applied":
                self.traces_module.reconcile(conf)
        except Exception:
            self.log.exception("traces CRD reconcile failed")

    def start(self, stop: threading.Event) -> None:
        self.log.info(
            "starting retina-tpu agent: plugins=%s source=%s pod_level=%s",
            self.cfg.enabled_plugins, self.cfg.event_source,
            self.cfg.enable_pod_level,
        )
        self.cm.init()
        if self.cm.server is not None:
            from retina_tpu.module.traces import MAX_EVENTS_PER_TARGET

            self.cm.server.expose_var(
                "traces",
                lambda: self.traces_module.traces(
                    limit=MAX_EVENTS_PER_TARGET
                ),
            )
            self.cm.server.expose_var(
                "traces_stats", self.traces_module.stats
            )
        if self.query_service is not None and self.cm.server is not None:
            # /timetravel/query + the ring debug var ride the existing
            # agent mux; registration is a dict insert, safe while the
            # server serves.
            self.query_service.attach(self.cm.server)
        if self.fleetquery is not None and self.cm.server is not None:
            # /fleet/query + the fleetquery debug var, same shape.
            self.fleetquery.attach(self.cm.server)
        if self.cm.server is not None:
            # Flight-recorder debug API (obs/debug.py): GET /debug/trace
            # + POST /debug/profile, same attach shape as the query
            # service; SHEDDING-aware via the engine's controller.
            from retina_tpu.obs.debug import DebugObservability

            DebugObservability(
                self.cfg, overload=self.cm.engine._overload
            ).attach(self.cm.server)
        if self.autocapture is not None:
            self.autocapture.start()
        if self.monitoragent is not None:
            self.monitoragent.start(stop)
        if self.fleet_aggregator is not None:
            self.fleet_aggregator.start()
        if self.hubble is not None:
            self.hubble.start()
            if getattr(self, "hubble_metrics_server", None) is not None:
                self.hubble_metrics_server.start()
        if self.metrics_module is not None:
            self.metrics_module.reconcile(MetricsConfiguration.default())
            self._mm_thread = threading.Thread(
                target=self.metrics_module.start, args=(stop,),
                name="metricsmodule", daemon=True,
            )
            self._mm_thread.start()
        if self.cfg.snapshot_dir:
            import os

            path = os.path.join(self.cfg.snapshot_dir, "sketch_state.npz")
            if os.path.exists(path):
                # Crash-only contract: load_state never raises — an
                # unreadable checkpoint (stale fingerprint, corrupt or
                # truncated npz) is quarantined to .bad inside
                # checkpoint.load_state and we cold-start.
                if self.cm.engine.load_snapshot_state(path):
                    self.log.info("resumed sketch state from %s", path)
                else:
                    self.log.warning(
                        "checkpoint at %s unusable; cold-starting", path
                    )
        if self.kubewatch is not None:
            self.kubewatch.start()
        if self.ciliumwatch is not None:
            self.ciliumwatch.start()
        if self.crd_bridge is not None:
            self.crd_bridge.start()
        try:
            self.cm.start(stop)  # blocks until stop fires; runs shutdown
        finally:
            if self.crd_bridge is not None:
                self.crd_bridge.stop()
            if self.ciliumwatch is not None:
                self.ciliumwatch.stop()
            if self.kubewatch is not None:
                self.kubewatch.stop()
            if self.hubble is not None:
                self.hubble.stop()
                if getattr(self, "hubble_metrics_server", None) is not None:
                    self.hubble_metrics_server.stop()
            if self.fleet_aggregator is not None:
                self.fleet_aggregator.stop()
                ring = self.fleet_aggregator.timetravel_ring
                if ring is not None:
                    ring.stop()
            if self.autocapture is not None:
                self.autocapture.stop()
            if self.detector_bank is not None:
                # Judge the in-progress window before the loop dies.
                self.detector_bank.flush()
            if self.fleetquery is not None:
                self.fleetquery.close()


def run_agent(
    config_path: str | None = None,
    overrides: dict[str, Any] | None = None,
    apiserver_host: str = "",
    install_signals: bool = True,
) -> Daemon:
    """Build + run the agent (blocking). SIGTERM/SIGINT → clean stop."""
    cfg = load_config(config_path, overrides=overrides)
    setup_logger(cfg.log_level, cfg.log_file)
    if cfg.distributed_coordinator:
        # Multi-host mesh: must run before any backend use so every
        # process sees the global device set (jax.devices() spans hosts;
        # shard_map collectives then ride ICI within a slice and DCN
        # across hosts — no hand-written NCCL/MPI analog).
        import jax

        jax.distributed.initialize(
            coordinator_address=cfg.distributed_coordinator,
            num_processes=cfg.distributed_num_processes,
            process_id=cfg.distributed_process_id,
        )
    stop = threading.Event()
    if install_signals:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: stop.set())
    d = Daemon(cfg, apiserver_host=apiserver_host)
    d.start(stop)
    return d

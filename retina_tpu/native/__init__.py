"""Native component loader: compile-on-demand + ctypes bindings.

Reference analog: pkg/loader/compile.go — the reference shells out to
clang at plugin-reconcile time to build its eBPF objects; here the loader
invokes ``make`` (g++) once per checkout and caches the shared library
next to the sources. Every consumer degrades gracefully to the pure
Python/numpy implementation when the toolchain is unavailable
(``native_available()`` gates the fast paths).

Exposes:
- :func:`decode_pcap_native` — C++ pcap→records decoder (decoder.cpp),
  bit-identical to sources/pcapdecode.decode_pcap_bytes.
- :class:`NativeRing` — shared-memory SPSC record ring (ring.cpp) usable
  across processes via an mmap'd file.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from retina_tpu.events.schema import NUM_FIELDS
from retina_tpu.log import logger

_log = logger("native")
_dir = os.path.dirname(os.path.abspath(__file__))
_so_path = os.path.join(_dir, "libretina_native.so")
_lib: Optional[ctypes.CDLL] = None
_lock = threading.Lock()
_build_failed = False

# Expected ABI of libretina_native.so (ring.cpp rt_abi_version — the
# single source of truth on the C++ side). The loader refuses a library
# reporting anything else: a stale prebuilt .so (wrong checkout, wrong
# arch cache) would otherwise misparse the dense wire bitstream or the
# striped-combine arguments silently. Bump BOTH sides together.
NATIVE_ABI_VERSION = 2


def _build(force: bool = False) -> bool:
    try:
        cmd = ["make", "-C", _dir, "-s"]
        if force:
            cmd.append("-B")
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        detail = getattr(e, "stderr", b"") or b""
        _log.warning("native build failed (%s); using Python fallbacks: %s",
                     e, detail.decode(errors="replace")[:500])
        return False


def _loaded_abi(lib: ctypes.CDLL) -> int:
    """ABI version a loaded library reports (0 = pre-versioning v1-era
    binary with no rt_abi_version export)."""
    try:
        fn = lib.rt_abi_version
    except AttributeError:
        return 0
    fn.restype = ctypes.c_uint32
    fn.argtypes = []
    return int(fn())


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        src_mtime = max(
            os.path.getmtime(os.path.join(_dir, f))
            for f in ("decoder.cpp", "ring.cpp", "combine.cpp",
                      "afpacket.cpp", "flowdict.cpp", "pack.cpp")
        )
        if (not os.path.exists(_so_path)
                or os.path.getmtime(_so_path) < src_mtime):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_so_path)
        except OSError as e:
            _log.warning("native library load failed: %s", e)
            _build_failed = True
            return None
        # ABI gate: an .so that predates (or postdates) this checkout's
        # bindings gets one forced rebuild from source; if the toolchain
        # can't produce a matching binary, fall back to Python rather
        # than call through a mismatched ABI.
        abi = _loaded_abi(lib)
        if abi != NATIVE_ABI_VERSION:
            _log.warning(
                "native library ABI %d != expected %d; rebuilding",
                abi, NATIVE_ABI_VERSION,
            )
            if not _build(force=True):
                _build_failed = True
                return None
            lib = ctypes.CDLL(_so_path)
            abi = _loaded_abi(lib)
            if abi != NATIVE_ABI_VERSION:
                _log.warning(
                    "native library ABI still %d after rebuild; "
                    "using Python fallbacks", abi,
                )
                _build_failed = True
                return None
        lib.rt_decode_pcap.restype = ctypes.c_long
        lib.rt_decode_pcap.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.rt_combine.restype = ctypes.c_long
        lib.rt_combine.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rt_combine_hint.restype = ctypes.c_long
        lib.rt_combine_hint.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
        ]
        lib.rt_combine_mt.restype = ctypes.c_long
        lib.rt_combine_mt.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.c_uint,
        ]
        lib.rt_combine_multi.restype = ctypes.c_long
        lib.rt_combine_multi.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
        ]
        lib.rt_combine_stripe.restype = ctypes.c_long
        lib.rt_combine_stripe.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.c_uint32, ctypes.c_uint32,
        ]
        lib.rt_flowdict_new.restype = ctypes.c_void_p
        lib.rt_flowdict_new.argtypes = [ctypes.c_uint32]
        lib.rt_flowdict_free.restype = None
        lib.rt_flowdict_free.argtypes = [ctypes.c_void_p]
        lib.rt_flowdict_clear.restype = None
        lib.rt_flowdict_clear.argtypes = [ctypes.c_void_p]
        lib.rt_flowdict_len.restype = ctypes.c_uint32
        lib.rt_flowdict_len.argtypes = [ctypes.c_void_p]
        lib.rt_flowdict_generation.restype = ctypes.c_uint32
        lib.rt_flowdict_generation.argtypes = [ctypes.c_void_p]
        lib.rt_flowdict_assign.restype = ctypes.c_uint32
        lib.rt_flowdict_assign.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.rt_ts_base.restype = ctypes.c_uint64
        lib.rt_ts_base.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
        ]
        lib.rt_pack.restype = None
        lib.rt_pack.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rt_flowwire.restype = ctypes.c_long
        lib.rt_flowwire.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rt_flowwire_dense.restype = ctypes.c_long
        lib.rt_flowwire_dense.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rt_afp_open.restype = ctypes.c_void_p
        lib.rt_afp_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
        ]
        lib.rt_afp_poll.restype = ctypes.c_long
        lib.rt_afp_poll.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.rt_afp_drops.restype = ctypes.c_uint64
        lib.rt_afp_drops.argtypes = [ctypes.c_void_p]
        lib.rt_afp_close.restype = None
        lib.rt_afp_close.argtypes = [ctypes.c_void_p]
        lib.rt_ring_bytes.restype = ctypes.c_size_t
        lib.rt_ring_bytes.argtypes = [ctypes.c_uint64, ctypes.c_uint32]
        lib.rt_ring_init.restype = ctypes.c_int
        lib.rt_ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                     ctypes.c_uint32]
        lib.rt_ring_check.restype = ctypes.c_int
        lib.rt_ring_check.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        for fn, nargs in (("rt_ring_push", 3), ("rt_ring_pop", 3),
                          ("rt_ring_size", 1), ("rt_ring_dropped", 1)):
            f = getattr(lib, fn)
            f.restype = ctypes.c_uint64
            f.argtypes = [ctypes.c_void_p] + (
                [ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64]
                if nargs == 3 else []
            )
        _lib = lib
        _log.info("native library loaded: %s", _so_path)
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def decode_pcap_native(data: bytes, obs_point: int = 2) -> Optional[tuple]:
    """C++ decode. Returns (records (N,16) u32, n_packets_total) or None
    when the library is unavailable. DNS names are NOT extracted here
    (strings stay host-Python; see sources/pcapdecode for the name pass)
    but DNS qtype/rcode/qname-hash fields are filled identically."""
    lib = get_lib()
    if lib is None:
        return None
    # Generous upper bound: every record is ≥ 16B header + 54B packet.
    max_records = max(len(data) // 70 + 64, 1024)
    while True:
        out = np.zeros((max_records, NUM_FIELDS), np.uint32)
        total = ctypes.c_size_t(0)
        n = lib.rt_decode_pcap(
            data, len(data), obs_point,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            max_records, ctypes.byref(total),
        )
        if n == -1:
            raise ValueError("not a pcap file")
        if n == -2:
            max_records *= 2
            continue
        return out[:n], int(total.value)


# Distinct-group count of the previous combine: flush-over-flush flow
# diversity is stable, so sizing the next probe table from it keeps the
# table cache-resident (combine.cpp rt_combine_hint grows it when the
# hint undershoots — identical results either way). Plain int store:
# only the engine feed thread writes it, and a stale read only costs a
# suboptimal table size.
_combine_hint_groups = 0


def _default_combine_threads() -> int:
    """RETINA_COMBINE_THREADS, else cores-1 capped at 4 (the combiner
    shares the host with the agent's feed/proxy/server threads). On the
    1-core bench host this resolves to 1 — the single-threaded pass."""
    env = os.environ.get("RETINA_COMBINE_THREADS", "")
    if env.isdigit():
        return max(1, int(env))
    return max(1, min(4, (os.cpu_count() or 1) - 1))


_combine_threads = _default_combine_threads()


def get_combine_threads() -> int:
    """Current combiner thread count (combine_blocks routes multi-core
    quanta through the MT concat path instead of the single-thread
    multi-block pass)."""
    return _combine_threads


def set_combine_threads(n: int) -> None:
    """Engine/config hook (host_combine_threads). PROCESS-WIDE: the
    combiner is shared library state, so with several engines in one
    process the last setter wins (the daemon runs one engine). 0
    restores the auto default."""
    global _combine_threads
    _combine_threads = int(n) if n > 0 else _default_combine_threads()


def combine_native(records: np.ndarray) -> Optional[np.ndarray]:
    """C++ descriptor-RLE combine (combine.cpp). Returns the combined
    (G, 16) array, or None when the library is unavailable. Semantics
    match parallel.combine.combine_records_numpy; the ctypes call
    releases the GIL, so combining overlaps device transfers running on
    another thread."""
    global _combine_hint_groups
    lib = get_lib()
    if lib is None:
        return None
    n = len(records)
    if n <= 1:
        return records
    if not records.flags.c_contiguous:
        records = np.ascontiguousarray(records)
    out = np.empty_like(records)
    # Target load factor <= 0.25 at the remembered group count so the
    # common case never pays the grow-and-rehash.
    g = lib.rt_combine_mt(
        records.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        4 * _combine_hint_groups,
        _combine_threads,
    )
    if g < 0:
        return None
    _combine_hint_groups = int(g)
    if g == n:
        return records
    return out[:g]


def combine_native_blocks(
    blocks: list,
) -> Optional[np.ndarray]:
    """C++ multi-block combine (combine.cpp rt_combine_multi): one pass
    over a LIST of (n_i, 16) u32 blocks, skipping the concatenation
    copy the single-array path needs (~40% of the combine stage at
    production quanta). Output is bit-identical to
    ``combine_native(np.concatenate(blocks))``. Returns None when the
    library is unavailable or any block isn't a plain (N, 16) u32
    array — callers fall back to concat + combine."""
    global _combine_hint_groups
    lib = get_lib()
    if lib is None or not blocks:
        return None
    total = 0
    for b in blocks:
        if (b.ndim != 2 or b.shape[1] != 16 or b.dtype != np.uint32
                or not b.flags.c_contiguous):
            return None
        total += len(b)
    if total == 0:
        return blocks[0][:0]
    ptrs = (ctypes.POINTER(ctypes.c_uint32) * len(blocks))(
        *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
          for b in blocks]
    )
    ns = (ctypes.c_size_t * len(blocks))(*[len(b) for b in blocks])
    out = np.empty((total, 16), np.uint32)
    g = lib.rt_combine_multi(
        ptrs, ns, len(blocks),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        4 * _combine_hint_groups,
    )
    if g < 0:
        return None
    _combine_hint_groups = int(g)
    return out[:g]


def native_abi_version() -> Optional[int]:
    """ABI version of the loaded native library (None when unavailable).
    get_lib() already enforces == NATIVE_ABI_VERSION; this exists for
    the tier-1 ABI check and diagnostics."""
    lib = get_lib()
    if lib is None:
        return None
    return _loaded_abi(lib)


def combine_native_blocks_striped(
    blocks: list, n_stripes: int,
) -> Optional[np.ndarray]:
    """Multi-consumer combine crew (combine.cpp rt_combine_stripe): T
    Python threads each combine ONE key-hash stripe of the same block
    list into a private output buffer — the ctypes calls release the
    GIL, the key partition makes the flow sets disjoint, so there is no
    merge pass and no shared mutable state (per-worker partitioned
    combine). Output concatenates the stripes; row order therefore
    differs from the single-pass combine (consumers treat order as
    arbitrary), but the key -> (packets, bytes, latest-ts) map is
    identical — cross-checked by tests/test_combine_scaling.py.
    Returns None when the library is unavailable or any block isn't a
    plain (N, 16) u32 array — callers fall back."""
    global _combine_hint_groups
    lib = get_lib()
    if lib is None or not blocks or n_stripes < 2:
        return None
    total = 0
    for b in blocks:
        if (b.ndim != 2 or b.shape[1] != 16 or b.dtype != np.uint32
                or not b.flags.c_contiguous):
            return None
        total += len(b)
    if total == 0:
        return blocks[0][:0]
    n_stripes = min(int(n_stripes), 16)
    ptrs = (ctypes.POINTER(ctypes.c_uint32) * len(blocks))(
        *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
          for b in blocks]
    )
    ns = (ctypes.c_size_t * len(blocks))(*[len(b) for b in blocks])
    # Per-stripe buffers sized for the worst case (all rows one stripe):
    # np.empty is a virtual allocation, so untouched pages of the slack
    # cost address space, not RAM.
    outs = [np.empty((total, 16), np.uint32) for _ in range(n_stripes)]
    counts = [0] * n_stripes
    hint = (4 * _combine_hint_groups) // n_stripes

    def run(s: int) -> None:
        counts[s] = lib.rt_combine_stripe(
            ptrs, ns, len(blocks),
            outs[s].ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            hint, s, n_stripes,
        )

    workers = [
        threading.Thread(target=run, args=(s,), daemon=True)
        for s in range(1, n_stripes)
    ]
    try:
        for w in workers:
            w.start()
    except RuntimeError:  # noqa: RT101 — not swallowed: a stripe whose
        # thread never spawned (pid pressure) is detected below by
        # w.ident is None and re-run sequentially on this thread, so
        # the result is identical either way; nothing to count.
        pass
    run(0)
    for w in workers:
        if w.ident is not None:
            w.join()
        else:
            run(workers.index(w) + 1)
    if any(c < 0 for c in counts):
        return None
    g = sum(int(c) for c in counts)
    _combine_hint_groups = g
    return np.concatenate(
        [outs[s][: int(counts[s])] for s in range(n_stripes)], axis=0
    )


def flowwire_native(
    rows: np.ndarray, ids: np.ndarray, sel_new: np.ndarray,
    base: int, id_bits: int, new_out: np.ndarray,
    known_out: np.ndarray,
) -> Optional[int]:
    """C++ v3 flow-dict wire build (pack.cpp rt_flowwire): one pass
    splits ``rows`` by ``sel_new`` into the new wire (id + 12 packed
    lanes, written to ``new_out``) and the known wire (id|pk<<id_bits,
    bytes -> ``known_out``). Returns the new-row count, or None when
    the library is unavailable / inputs don't match the fast-path
    layout (caller falls back to the numpy build). Semantics are
    cross-checked against the numpy path by tests/test_native.py."""
    lib = get_lib()
    n = len(rows)
    if (lib is None or rows.ndim != 2 or rows.shape[1] != NUM_FIELDS
            or rows.dtype != np.uint32 or not rows.flags.c_contiguous
            or ids.dtype != np.uint32 or not ids.flags.c_contiguous
            or sel_new.dtype != np.uint8
            or not sel_new.flags.c_contiguous
            or len(ids) != n or len(sel_new) != n
            or new_out.dtype != np.uint32 or known_out.dtype != np.uint32
            or not new_out.flags.c_contiguous
            or not known_out.flags.c_contiguous
            or new_out.ndim != 2 or new_out.shape[1] != 13
            or known_out.ndim != 2 or known_out.shape[1] != 2):
        return None
    # Capacity guard: the C++ side writes n_new*13 + n_known*2 words
    # unchecked — an undersized buffer must fall back, not corrupt.
    n_sel = int(sel_new.sum())
    if len(new_out) < n_sel or len(known_out) < n - n_sel:
        return None
    u32p = ctypes.POINTER(ctypes.c_uint32)
    return int(lib.rt_flowwire(
        rows.ctypes.data_as(u32p), n, ids.ctypes.data_as(u32p),
        sel_new.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(int(base)), ctypes.c_uint32(int(id_bits)),
        new_out.ctypes.data_as(u32p), known_out.ctypes.data_as(u32p),
    ))


def flowwire_dense_native(
    rows: np.ndarray, ids: np.ndarray, sel_new: np.ndarray,
    base: int, id_bits: int, pk_bits: int, by_bits: int,
    new_out: np.ndarray, known_words: np.ndarray,
) -> Optional[int]:
    """C++ v4 dense flow-dict wire build (pack.cpp rt_flowwire_dense):
    like flowwire_native but known rows land in the ZEROED 1-D
    ``known_words`` bitstream at (id_bits + pk_bits + by_bits) bits per
    row (parallel/wire.py dense_known_rows is the numpy twin). Returns
    the new-row count, or None when the library is unavailable / the
    inputs don't match the fast-path layout."""
    lib = get_lib()
    n = len(rows)
    row_bits = int(id_bits) + int(pk_bits) + int(by_bits)
    if (lib is None or row_bits > 64
            or rows.ndim != 2 or rows.shape[1] != NUM_FIELDS
            or rows.dtype != np.uint32 or not rows.flags.c_contiguous
            or ids.dtype != np.uint32 or not ids.flags.c_contiguous
            or sel_new.dtype != np.uint8
            or not sel_new.flags.c_contiguous
            or len(ids) != n or len(sel_new) != n
            or new_out.dtype != np.uint32
            or known_words.dtype != np.uint32
            or not new_out.flags.c_contiguous
            or not known_words.flags.c_contiguous
            or new_out.ndim != 2 or new_out.shape[1] != 13
            or known_words.ndim != 1):
        return None
    # Capacity guard: n_new*13 words on the new side, the dense stream
    # plus one pad word on the known side — undersized must fall back,
    # not corrupt.
    n_sel = int(sel_new.sum())
    need = ((n - n_sel) * row_bits + 31) // 32 + 1
    if len(new_out) < n_sel or len(known_words) < need:
        return None
    u32p = ctypes.POINTER(ctypes.c_uint32)
    return int(lib.rt_flowwire_dense(
        rows.ctypes.data_as(u32p), n, ids.ctypes.data_as(u32p),
        sel_new.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(int(base)), ctypes.c_uint32(int(id_bits)),
        ctypes.c_uint32(int(pk_bits)), ctypes.c_uint32(int(by_bits)),
        new_out.ctypes.data_as(u32p), known_words.ctypes.data_as(u32p),
    ))


def pack_native(
    records: np.ndarray, base: Optional[int] = None
) -> Optional[tuple]:
    """C++ wire packer (pack.cpp): (n, 16) u32 -> ((n, 12) u32, base).
    Returns None when the native library is unavailable or the input is
    not a 2-D schema array (callers fall back to the numpy path).
    Semantics match parallel.wire.pack_records — cross-checked by
    tests/test_native.py."""
    lib = get_lib()
    if (lib is None or records.ndim != 2 or records.dtype != np.uint32
            or records.shape[1] != NUM_FIELDS):
        return None
    if not records.flags.c_contiguous:
        records = np.ascontiguousarray(records)
    n = len(records)
    rows = records.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
    if base is None:
        base = int(lib.rt_ts_base(rows, n)) if n else 0
    out = np.empty((n, 12), np.uint32)
    if n:
        lib.rt_pack(
            rows, n, ctypes.c_uint64(base),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
    return out, base


class NativeFlowDict:
    """Persistent descriptor->id dictionary (flowdict.cpp) — the
    GIL-released twin of parallel.flowdict.HostFlowDict (same contract,
    cross-checked by tests). Raises RuntimeError if the native library
    is unavailable; callers fall back to the Python dict."""

    def __init__(self, capacity: int = 1 << 18):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.capacity = int(capacity)
        self._h = lib.rt_flowdict_new(self.capacity)
        if not self._h:
            raise RuntimeError("flowdict allocation failed")

    @property
    def generation(self) -> int:
        return int(self._lib.rt_flowdict_generation(self._h))

    def __len__(self) -> int:
        return int(self._lib.rt_flowdict_len(self._h))

    def clear(self) -> None:
        self._lib.rt_flowdict_clear(self._h)

    def lookup_or_assign(self, records: np.ndarray):
        n = len(records)
        ids = np.zeros(n, np.uint32)
        is_new = np.zeros(n, np.uint8)
        if n:
            # Same contract as HostFlowDict: accept (N, >=16) of any int
            # dtype — rt_flowdict_assign reads row-major (n,16) u32, so
            # anything wider/non-u32 must be sliced+cast first or the C++
            # side would misread the rows.
            if records.ndim != 2 or records.shape[1] < NUM_FIELDS:
                raise ValueError(
                    f"expected (N, >={NUM_FIELDS}) records, got "
                    f"{records.shape}"
                )
            if (records.dtype != np.uint32
                    or records.shape[1] != NUM_FIELDS):
                records = records[:, :NUM_FIELDS].astype(np.uint32)
            if not records.flags.c_contiguous:
                records = np.ascontiguousarray(records)
            self._lib.rt_flowdict_assign(
                self._h,
                records.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                n,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                is_new.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        return ids, is_new.astype(bool)

    def close(self) -> None:
        if self._h:
            self._lib.rt_flowdict_free(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # noqa: RT101 — __del__ must never raise; close() is the real API
            pass


class AfPacketRing:
    """TPACKET_V3 live capture (afpacket.cpp) — the perf-ring analog.

    ``poll(timeout_ms)`` returns ((N, 16) records, frames_seen); kernel
    drops surface via ``drops()`` as a monotonic counter. Raises
    RuntimeError when the ring cannot open (no CAP_NET_RAW, non-Linux,
    unknown interface) — callers fall back to the Python socket loop.
    """

    # A 1 MiB TPACKET_V3 block holds at most ~11k minimum-size frames;
    # polling with capacity for two full blocks means the mid-block
    # resume path is the exception, not the rule.
    POLL_RECORDS = 1 << 15

    DNS_BUF_BYTES = 1 << 16

    def __init__(self, iface: str = "", block_size: int = 1 << 20,
                 block_nr: int = 32, obs_point: int = 2):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.obs_point = obs_point
        self._h = lib.rt_afp_open(iface.encode(), block_size, block_nr)
        if not self._h:
            raise RuntimeError(
                f"AF_PACKET TPACKET_V3 ring open failed (iface={iface!r}; "
                "needs Linux + CAP_NET_RAW)"
            )
        self._buf = np.empty((self.POLL_RECORDS, NUM_FIELDS), np.uint32)
        self._dns_buf = (ctypes.c_uint8 * self.DNS_BUF_BYTES)()

    def poll(self, timeout_ms: int = 100):
        """Returns (records (N, 16), frames_seen, dns_frames bytes) —
        dns_frames is a [u16 len][frame] blob of the DNS packets in this
        batch, for the host-side qname string pass."""
        if self._h is None:
            raise RuntimeError("AF_PACKET ring is closed")
        seen = ctypes.c_uint64(0)
        dns_used = ctypes.c_size_t(0)
        n = self._lib.rt_afp_poll(
            self._h, timeout_ms, self.obs_point,
            self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            self.POLL_RECORDS, ctypes.byref(seen),
            self._dns_buf, self.DNS_BUF_BYTES, ctypes.byref(dns_used),
        )
        if n < 0:
            raise RuntimeError("AF_PACKET poll failed")
        return (
            self._buf[:n].copy(),
            int(seen.value),
            bytes(self._dns_buf[: dns_used.value]),
        )

    def drops(self) -> int:
        if self._h is None:
            raise RuntimeError("AF_PACKET ring is closed")
        return int(self._lib.rt_afp_drops(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.rt_afp_close(self._h)
            self._h = None


class NativeRing:
    """SPSC record ring over private memory or an mmap'd shm file."""

    def __init__(self, capacity: int = 1 << 14,
                 path: Optional[str] = None, create: bool = True):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.capacity = capacity
        nbytes = lib.rt_ring_bytes(capacity, NUM_FIELDS)
        self._file = None
        if path is None:
            self._mm = mmap.mmap(-1, nbytes)
        else:
            mode = "r+b" if (os.path.exists(path) and not create) else "w+b"
            self._file = open(path, mode)
            if create or os.path.getsize(path) < nbytes:
                self._file.truncate(nbytes)
            self._mm = mmap.mmap(self._file.fileno(), nbytes)
        self._buf = ctypes.c_char.from_buffer(self._mm)
        self._addr = ctypes.addressof(self._buf)
        if create:
            if lib.rt_ring_init(self._addr, capacity, NUM_FIELDS) != 0:
                raise ValueError("capacity must be a power of two")
        elif lib.rt_ring_check(self._addr, NUM_FIELDS) != 0:
            raise ValueError(f"not a retina ring: {path}")

    def push(self, records: np.ndarray) -> int:
        rec = np.ascontiguousarray(records, np.uint32)
        assert rec.ndim == 2 and rec.shape[1] == NUM_FIELDS
        return int(self._lib.rt_ring_push(
            self._addr,
            rec.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(rec),
        ))

    def pop(self, max_records: int = 8192) -> np.ndarray:
        out = np.empty((max_records, NUM_FIELDS), np.uint32)
        n = int(self._lib.rt_ring_pop(
            self._addr,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            max_records,
        ))
        return out[:n]

    def __len__(self) -> int:
        return int(self._lib.rt_ring_size(self._addr))

    @property
    def dropped(self) -> int:
        return int(self._lib.rt_ring_dropped(self._addr))

    def close(self) -> None:
        # Release the exported buffer before closing the mmap.
        del self._buf
        self._mm.close()
        if self._file is not None:
            self._file.close()

// Shared-memory SPSC event ring — the perf-ring analog, in C++.
//
// Reference analog: the kernel→user perf event array
// (packetparser.c:19-21, 16,384 entries; read loop
// packetparser_linux.go:669-698): a bounded, never-blocking ring where
// overflow drops are counted, not waited on. This ring lives in a caller-
// provided memory region (heap or mmap'd shm file), so a C++/Go producer
// process can feed the Python agent — or plugin threads can bypass the
// GIL'd queue — with zero copies beyond the record write.
//
// Single-producer/single-consumer, acquire/release atomics, fixed-width
// records (NUM_FIELDS u32 = 64 B, cacheline-sized like the reference's
// perf records). C ABI via ctypes. Build: make -C retina_tpu/native
#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t kMagic = 0x52544E52;  // "RTNR"

struct alignas(64) Header {
  uint32_t magic;
  uint32_t record_words;  // u32 lanes per record
  uint64_t capacity;      // record slots (power of two)
  alignas(64) std::atomic<uint64_t> head;     // writer position
  alignas(64) std::atomic<uint64_t> tail;     // reader position
  alignas(64) std::atomic<uint64_t> dropped;  // producer-side losses
};

inline Header* hdr(void* mem) { return static_cast<Header*>(mem); }
inline uint32_t* slots(void* mem) {
  return reinterpret_cast<uint32_t*>(static_cast<uint8_t*>(mem) +
                                     sizeof(Header));
}

}  // namespace

extern "C" {

// Bytes needed for a ring of `capacity` records (capacity: power of two).
size_t rt_ring_bytes(uint64_t capacity, uint32_t record_words) {
  return sizeof(Header) + capacity * record_words * sizeof(uint32_t);
}

// Initialize a ring in caller-provided zeroed memory. Returns 0 on
// success, -1 on bad capacity (not a power of two).
int rt_ring_init(void* mem, uint64_t capacity, uint32_t record_words) {
  if (capacity == 0 || (capacity & (capacity - 1))) return -1;
  Header* h = hdr(mem);
  h->magic = kMagic;
  h->record_words = record_words;
  h->capacity = capacity;
  h->head.store(0, std::memory_order_relaxed);
  h->tail.store(0, std::memory_order_relaxed);
  h->dropped.store(0, std::memory_order_relaxed);
  return 0;
}

// Validate an existing ring (attach from another process). 0 = ok.
int rt_ring_check(void* mem, uint32_t record_words) {
  Header* h = hdr(mem);
  if (h->magic != kMagic || h->record_words != record_words) return -1;
  return 0;
}

// Push n records; returns how many were accepted (rest dropped+counted —
// the never-block rule, packetparser_linux.go:692-697).
uint64_t rt_ring_push(void* mem, const uint32_t* records, uint64_t n) {
  Header* h = hdr(mem);
  const uint64_t cap = h->capacity;
  const uint32_t w = h->record_words;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  const uint64_t tail = h->tail.load(std::memory_order_acquire);
  uint64_t free_slots = cap - (head - tail);
  uint64_t take = n < free_slots ? n : free_slots;
  uint32_t* base = slots(mem);
  // At most two contiguous spans (pre/post wrap): one memcpy per span
  // instead of one per record — the per-record call overhead dominated
  // at staged-block sizes (thousands of 64 B records per push).
  uint64_t start = head & (cap - 1);
  uint64_t first = take < cap - start ? take : cap - start;
  std::memcpy(base + start * w, records, first * w * sizeof(uint32_t));
  if (take > first)
    std::memcpy(base, records + first * w,
                (take - first) * w * sizeof(uint32_t));
  h->head.store(head + take, std::memory_order_release);
  if (take < n)
    h->dropped.fetch_add(n - take, std::memory_order_relaxed);
  return take;
}

// Pop up to max records into out; returns how many were read.
uint64_t rt_ring_pop(void* mem, uint32_t* out, uint64_t max) {
  Header* h = hdr(mem);
  const uint64_t cap = h->capacity;
  const uint32_t w = h->record_words;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  const uint64_t head = h->head.load(std::memory_order_acquire);
  uint64_t avail = head - tail;
  uint64_t take = max < avail ? max : avail;
  uint32_t* base = slots(mem);
  // Mirror of the push path: at most two span memcpys per pop.
  uint64_t start = tail & (cap - 1);
  uint64_t first = take < cap - start ? take : cap - start;
  std::memcpy(out, base + start * w, first * w * sizeof(uint32_t));
  if (take > first)
    std::memcpy(out + first * w, base,
                (take - first) * w * sizeof(uint32_t));
  h->tail.store(tail + take, std::memory_order_release);
  return take;
}

uint64_t rt_ring_size(void* mem) {
  Header* h = hdr(mem);
  return h->head.load(std::memory_order_acquire) -
         h->tail.load(std::memory_order_acquire);
}

uint64_t rt_ring_dropped(void* mem) {
  return hdr(mem)->dropped.load(std::memory_order_relaxed);
}

}  // extern "C"

// Host-side record combiner: RLE of identical flow descriptors.
//
// The C++ twin of retina_tpu/parallel/combine.py (see that module for the
// losslessness contract and the eBPF-map analogy). One pass, open
// addressing: hash the 12 descriptor columns, probe, and either claim an
// output row or accumulate PACKETS/BYTES (saturating) and take the later
// timestamp. Order of first appearance is preserved, which the Python
// fallback does NOT guarantee (it sorts); consumers treat row order as
// arbitrary.
//
// Must stay semantically identical to combine_records_numpy — the test
// suite cross-checks the two on random batches.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>

namespace {

constexpr int NUM_FIELDS = 16;
// Field indices (retina_tpu/events/schema.py).
constexpr int F_TS_LO = 0, F_TS_HI = 1, F_BYTES = 6, F_PACKETS = 7;
// Descriptor columns: everything except TS_LO/TS_HI/BYTES/PACKETS.
constexpr int KEY_COLS[12] = {2, 3, 4, 5, 8, 9, 10, 11, 12, 13, 14, 15};

// The 12 key columns form two contiguous spans (2..5 and 8..15):
// hashing/comparing them as six unaligned u64 words halves the per-row
// mix rounds vs the per-column loop — this pass is the host feed path's
// single largest cost at production quanta.
inline uint64_t hash_row(const uint32_t* row) {
  uint64_t h = 0x9E3779B97F4A7C15ull, v;
  const char* p = (const char*)(row + 2);
  for (int i = 0; i < 2; i++) {
    memcpy(&v, p + 8 * i, 8);
    h ^= v;
    h *= 0xFF51AFD7ED558CCDull;
    h ^= h >> 33;
  }
  p = (const char*)(row + 8);
  for (int i = 0; i < 4; i++) {
    memcpy(&v, p + 8 * i, 8);
    h ^= v;
    h *= 0xFF51AFD7ED558CCDull;
    h ^= h >> 33;
  }
  return h;
}

inline bool keys_equal(const uint32_t* a, const uint32_t* b) {
  return memcmp(a + 2, b + 2, 4 * sizeof(uint32_t)) == 0 &&
         memcmp(a + 8, b + 8, 8 * sizeof(uint32_t)) == 0;
}

inline uint32_t sat_add_u32(uint32_t a, uint32_t b) {
  uint64_t s = (uint64_t)a + b;
  return s > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)s;
}

// Which of n_stripes key-partitions a row belongs to. Uses the UPPER
// hash bits (the table index burns the lower ones — reusing them would
// collapse each stripe's slot distribution) via a multiply-shift range
// map, so any n_stripes works without a per-row divide.
inline uint32_t stripe_of(uint64_t h, uint32_t n_stripes) {
  return (uint32_t)(((uint64_t)(uint32_t)(h >> 32) * n_stripes) >> 32);
}

}  // namespace

extern "C" {

// rows: (n, 16) u32 row-major. out: caller buffer with room for n rows.
// Returns the number of combined rows written to out, or -1 on alloc
// failure. out may alias nothing (distinct buffer required).
//
// hint_slots (rt_combine_hint): expected table size from the caller's
// previous quantum — distinct-flow counts are stable flush over flush,
// and a table sized to the distinct count stays cache-resident where
// the worst-case 2n sizing (16 MB at production quanta) probes cold
// RAM. 0 means no hint (worst-case sizing, the old behavior). When a
// hint undershoots, the table doubles and re-inserts the g combined
// rows seen so far (cheap: g << n), so results are identical for any
// hint.
long rt_combine_multi(const uint32_t* const* blocks, const size_t* ns,
                      size_t nblocks, uint32_t* out, size_t hint_slots);

long rt_combine_hint(const uint32_t* rows, size_t n, uint32_t* out,
                     size_t hint_slots) {
  // One-block view of the multi-block core (single table body lives
  // in rt_combine_multi so a fix can never diverge between them).
  const uint32_t* blocks[1] = {rows};
  size_t ns[1] = {n};
  return rt_combine_multi(blocks, ns, 1, out, hint_slots);
}

// Multi-threaded combine for multi-core hosts: T contiguous chunks
// combined independently (each with its own table), then one
// sequential merge pass over the concatenated partials (G_total rows,
// ~n/ratio — cheap). Row order differs from the single-thread pass
// (chunk-major first-appearance); consumers treat order as arbitrary
// (see header). nthreads <= 1, tiny inputs, or any allocation failure
// fall back to the single-threaded pass — results are equivalent
// either way (cross-checked as key -> value maps by the test suite).
long rt_combine_mt(const uint32_t* rows, size_t n, uint32_t* out,
                   size_t hint_slots, unsigned nthreads) {
  constexpr size_t kMinPerThread = 1 << 15;
  if (nthreads > 16) nthreads = 16;
  if (nthreads <= 1 || n < 2 * kMinPerThread)
    return rt_combine_hint(rows, n, out, hint_slots);
  if ((size_t)nthreads > n / kMinPerThread)
    nthreads = (unsigned)(n / kMinPerThread);

  uint32_t* scratch =
      (uint32_t*)malloc(n * NUM_FIELDS * sizeof(uint32_t));
  if (!scratch) return rt_combine_hint(rows, n, out, hint_slots);
  long* counts = (long*)malloc(nthreads * sizeof(long));
  if (!counts) {
    free(scratch);
    return rt_combine_hint(rows, n, out, hint_slots);
  }

  size_t chunk = n / nthreads;
  size_t per_hint = hint_slots ? hint_slots / nthreads : 0;
  // Spawn-per-call is fine at these sizes: threading only engages at
  // >= 64k rows, where create+join (tens of us) is <0.1% of the pass.
  // std::thread construction can throw (EAGAIN under pid-limit
  // pressure) — that must become the single-threaded fallback, never
  // an exception across the extern "C" boundary (std::terminate).
  std::thread workers[16];
  unsigned spawned = 0;
  try {
    for (unsigned t = 0; t < nthreads; t++) {
      size_t lo = t * chunk;
      size_t hi = (t == nthreads - 1) ? n : lo + chunk;
      workers[t] = std::thread([=]() {
        counts[t] = rt_combine_hint(rows + lo * NUM_FIELDS, hi - lo,
                                    scratch + lo * NUM_FIELDS, per_hint);
      });
      spawned++;
    }
  } catch (...) {
    for (unsigned t = 0; t < spawned; t++) workers[t].join();
    free(counts);
    free(scratch);
    return rt_combine_hint(rows, n, out, hint_slots);
  }
  for (unsigned t = 0; t < nthreads; t++) workers[t].join();

  bool failed = false;
  size_t total = 0;
  for (unsigned t = 0; t < nthreads; t++) {
    if (counts[t] < 0) failed = true;
    else total += (size_t)counts[t];
  }
  long g = -1;
  if (!failed) {
    // Compact the partials to one contiguous run, then merge. The
    // compaction reuses scratch in place (partials are in ascending
    // offsets, so memmove is safe front to back).
    size_t off = 0;
    for (unsigned t = 0; t < nthreads; t++) {
      size_t lo = t * chunk;
      size_t cnt = (size_t)counts[t];
      if (off != lo && cnt)
        memmove(scratch + off * NUM_FIELDS, scratch + lo * NUM_FIELDS,
                cnt * NUM_FIELDS * sizeof(uint32_t));
      off += cnt;
    }
    g = rt_combine_hint(scratch, total, out, hint_slots);
  }
  free(counts);
  free(scratch);
  if (g < 0) return rt_combine_hint(rows, n, out, hint_slots);
  return g;
}

long rt_combine(const uint32_t* rows, size_t n, uint32_t* out) {
  return rt_combine_hint(rows, n, out, 0);
}

// The one table body behind every combine entry point (single-block,
// multi-block, striped) — a fix can never diverge between them.
// stripe/n_stripes: with n_stripes > 1, only rows whose key hashes into
// the given stripe (stripe_of) are combined; the rest are skipped. Key
// partitioning makes concurrent striped calls over the SAME blocks
// write disjoint flow sets — the multi-consumer combine crew needs no
// cross-worker merge pass and no locks (each worker owns its out
// buffer; the input blocks are read-only).
static long combine_core(const uint32_t* const* blocks, const size_t* ns,
                         size_t nblocks, uint32_t* out, size_t hint_slots,
                         uint32_t stripe, uint32_t n_stripes) {
  size_t n = 0;
  for (size_t b = 0; b < nblocks; b++) n += ns[b];
  if (n == 0) return 0;
  size_t worst = 16;
  while (worst < 2 * n) worst <<= 1;
  size_t slots = worst;
  if (hint_slots) {
    slots = 1024;
    while (slots < hint_slots && slots < worst) slots <<= 1;
    if (slots > worst) slots = worst;
  }
  uint32_t* table = (uint32_t*)malloc(slots * sizeof(uint32_t));
  if (!table) return -1;
  memset(table, 0xFF, slots * sizeof(uint32_t));
  size_t mask = slots - 1;
  size_t g = 0;
  for (size_t b = 0; b < nblocks; b++) {
    const uint32_t* rows = blocks[b];
    size_t nb = ns[b];
    // Per-block prefetch pipeline (blocks are thousands of rows; the
    // ~kAhead ramp cost per boundary is noise).
    constexpr size_t kAhead = 8;
    size_t next_hashes[kAhead];
    for (size_t i = 0; i < nb && i < kAhead; i++) {
      next_hashes[i] = hash_row(rows + i * NUM_FIELDS);
      __builtin_prefetch(&table[next_hashes[i] & mask]);
    }
    for (size_t i = 0; i < nb; i++) {
      const uint32_t* row = rows + i * NUM_FIELDS;
      size_t h_i = next_hashes[i % kAhead];
      size_t slot = h_i & mask;
      if (i + kAhead < nb) {
        size_t h = hash_row(rows + (i + kAhead) * NUM_FIELDS);
        next_hashes[(i + kAhead) % kAhead] = h;
        __builtin_prefetch(&table[h & mask]);
      }
      if (n_stripes > 1 && stripe_of(h_i, n_stripes) != stripe) continue;
      if (2 * g >= slots && slots < worst) {
        size_t nslots = slots << 1;
        uint32_t* ntable = (uint32_t*)malloc(nslots * sizeof(uint32_t));
        if (!ntable) {
          free(table);
          return -1;
        }
        memset(ntable, 0xFF, nslots * sizeof(uint32_t));
        size_t nmask = nslots - 1;
        for (size_t j = 0; j < g; j++) {
          size_t s = hash_row(out + j * NUM_FIELDS) & nmask;
          while (ntable[s] != 0xFFFFFFFFu) s = (s + 1) & nmask;
          ntable[s] = (uint32_t)j;
        }
        free(table);
        table = ntable;
        slots = nslots;
        mask = nmask;
        slot = hash_row(row) & mask;
      }
      for (;;) {
        uint32_t gid = table[slot];
        if (gid == 0xFFFFFFFFu) {
          table[slot] = (uint32_t)g;
          memcpy(out + g * NUM_FIELDS, row,
                 NUM_FIELDS * sizeof(uint32_t));
          g++;
          break;
        }
        uint32_t* orow = out + (size_t)gid * NUM_FIELDS;
        if (keys_equal(orow, row)) {
          orow[F_PACKETS] = sat_add_u32(orow[F_PACKETS], row[F_PACKETS]);
          orow[F_BYTES] = sat_add_u32(orow[F_BYTES], row[F_BYTES]);
          uint64_t ots =
              ((uint64_t)orow[F_TS_HI] << 32) | orow[F_TS_LO];
          uint64_t nts = ((uint64_t)row[F_TS_HI] << 32) | row[F_TS_LO];
          if (nts > ots) {
            orow[F_TS_LO] = row[F_TS_LO];
            orow[F_TS_HI] = row[F_TS_HI];
          }
          break;
        }
        slot = (slot + 1) & mask;
      }
    }
  }
  free(table);
  return (long)g;
}

// Multi-block combine: same single-pass table as rt_combine_hint but
// consuming a LIST of row blocks — the feed loop's flush quantum is a
// list of sink blocks, and concatenating them first costs a full
// row-copy pass (~40% of the combine stage at production quanta).
// First-appearance output order matches exactly what rt_combine_hint
// would produce on the concatenation, so results are bit-identical
// (cross-checked by the test suite).
long rt_combine_multi(const uint32_t* const* blocks, const size_t* ns,
                      size_t nblocks, uint32_t* out, size_t hint_slots) {
  return combine_core(blocks, ns, nblocks, out, hint_slots, 0, 1);
}

// Striped multi-consumer combine: combine ONLY the rows of one key
// partition (stripe of n_stripes, see stripe_of). T concurrent callers
// over the same blocks with stripes 0..T-1 produce disjoint flow sets
// whose concatenation equals rt_combine_multi's output as a key->value
// map (first-appearance order is per-stripe). This is the per-worker
// partitioned combine of the feed pool's combine crew: unlike
// rt_combine_mt's chunk+sequential-merge, there is NO merge pass and no
// shared mutable state — each worker scans all rows but hashes/probes
// only its own stripe's, so the expensive part (table writes, output
// row copies) parallelizes perfectly.
long rt_combine_stripe(const uint32_t* const* blocks, const size_t* ns,
                       size_t nblocks, uint32_t* out, size_t hint_slots,
                       uint32_t stripe, uint32_t n_stripes) {
  if (n_stripes <= 1)
    return combine_core(blocks, ns, nblocks, out, hint_slots, 0, 1);
  if (stripe >= n_stripes) return 0;
  return combine_core(blocks, ns, nblocks, out, hint_slots, stripe,
                      n_stripes);
}

}  // extern "C"

// Host-side wire packer: (n, 16) schema rows -> (n, 12) packed lanes.
//
// The C++ twin of retina_tpu/parallel/wire.py pack_records (see that
// module for the lane layout and saturation bounds). Packing runs on
// every flush quantum right before the host->device transfer, so its
// cost lands on the feed path's critical section; the numpy version
// spends ~19% of the host path in strided column copies + u64
// timestamp math, this single pass is memory-bound.
//
// Must stay semantically identical to pack_records' numpy math — the
// test suite cross-checks the two on random batches (including zero
// timestamps, values past every saturation bound, and ts < base
// wraparound).

#include <cstdint>
#include <cstring>

namespace {

constexpr int NUM_FIELDS = 16;
constexpr int PACKED_FIELDS = 12;
// Field indices (retina_tpu/events/schema.py).
constexpr int F_TS_LO = 0, F_TS_HI = 1, F_SRC_IP = 2, F_DST_IP = 3,
              F_PORTS = 4, F_META = 5, F_BYTES = 6, F_PACKETS = 7,
              F_VERDICT = 8, F_DROP_REASON = 9, F_TSVAL = 10,
              F_TSECR = 11, F_DNS = 12, F_DNS_QHASH = 13,
              F_EVENT_TYPE = 14, F_IFINDEX = 15;

inline uint32_t min_u32(uint32_t a, uint32_t b) { return a < b ? a : b; }

// One packed wire row (the body shared by rt_pack and rt_flowwire —
// must stay semantically identical to pack_records' numpy math).
inline void pack_row(const uint32_t* r, uint32_t* o, uint64_t base) {
  constexpr uint64_t U32 = 0xFFFFFFFFull;
  uint64_t ts = ((uint64_t)r[F_TS_HI] << 32) | r[F_TS_LO];
  uint64_t diff = ts - base;  // wraps when ts < base, like numpy u64
  o[0] = ts > 0 ? (uint32_t)((diff < U32 - 1 ? diff : U32 - 1) + 1) : 0;
  o[1] = r[F_SRC_IP];
  o[2] = r[F_DST_IP];
  o[3] = r[F_PORTS];
  o[4] = r[F_META];
  o[5] = r[F_BYTES];
  o[6] = r[F_PACKETS];
  o[7] = (min_u32(r[F_VERDICT], 7) << 29)
       | (min_u32(r[F_DROP_REASON], 255) << 21)
       | (min_u32(r[F_EVENT_TYPE], 15) << 17)
       | min_u32(r[F_IFINDEX], 0x1FFFF);
  o[8] = r[F_TSVAL];
  o[9] = r[F_TSECR];
  o[10] = r[F_DNS];
  o[11] = r[F_DNS_QHASH];
}

}  // namespace

extern "C" {

// Minimum nonzero 64-bit timestamp over rows (0 if none) — the TS_REL
// base shared by every wire array cut from one flush (wire.py
// batch_ts_base).
uint64_t rt_ts_base(const uint32_t* rows, size_t n) {
  uint64_t base = UINT64_MAX;
  for (size_t i = 0; i < n; i++) {
    const uint32_t* r = rows + i * NUM_FIELDS;
    uint64_t ts = ((uint64_t)r[F_TS_HI] << 32) | r[F_TS_LO];
    if (ts > 0 && ts < base) base = ts;
  }
  return base == UINT64_MAX ? 0 : base;
}

// rows: (n, 16) u32 row-major -> out: (n, 12) u32 row-major.
// Matches pack_records' numpy semantics exactly, including the
// unsigned wrap for ts < base (numpy u64 subtraction wraps, then the
// min() clamp saturates the relative timestamp).
void rt_pack(const uint32_t* rows, size_t n, uint64_t base,
             uint32_t* out) {
  for (size_t i = 0; i < n; i++)
    pack_row(rows + i * NUM_FIELDS, out + i * PACKED_FIELDS, base);
}

// v3 flow-dict wire build: ONE pass splits a device's rows into the
// new-descriptor wire ([table_id | 12 packed lanes], 13 u32/row) and
// the known wire ([id | packets << id_bits, bytes], 2 u32/row) by the
// caller-computed escalation mask (engine._dispatch_flowdict computes
// it in numpy: is_new | pk overflow | TSval/TSecr | unstamped). The
// numpy equivalent needed two fancy-indexed row copies + a pack pass +
// two bit-pack passes per flush — this is the dispatch worker's
// largest remaining cost at production quanta.
// new_out must hold at least (popcount(sel), 13); known_out at least
// (n - popcount, 2). Returns n_new.
long rt_flowwire(const uint32_t* rows, size_t n, const uint32_t* ids,
                 const uint8_t* sel_new, uint64_t base,
                 uint32_t id_bits, uint32_t* new_out,
                 uint32_t* known_out) {
  size_t n_new = 0, n_known = 0;
  for (size_t i = 0; i < n; i++) {
    const uint32_t* r = rows + i * NUM_FIELDS;
    if (sel_new[i]) {
      uint32_t* o = new_out + n_new * 13;
      o[0] = ids[i];
      pack_row(r, o + 1, base);
      n_new++;
    } else {
      uint32_t* o = known_out + n_known * 2;
      o[0] = ids[i] | (r[F_PACKETS] << id_bits);
      o[1] = r[F_BYTES];
      n_known++;
    }
  }
  return (long)n_new;
}

// v4 dense flow-dict wire build: like rt_flowwire, but known rows go
// into a CONTIGUOUS BITSTREAM of (id_bits + pk_bits + by_bits)-bit
// rows instead of two full u32 lanes — at the default 18-bit dict and
// 10/22-bit packet/byte lanes that is 6.25 B/row vs 8, and the row
// width shrinks further as deployments tune the dict smaller. The
// caller's escalation mask must already route rows whose PACKETS or
// BYTES overflow their lane to the new/full side (engine adds the
// `bytes >= 1 << by_bits` term for this path), so the stream stores
// every surviving row exactly.
//
// known_out must be ZEROED by the caller and hold at least
// ceil(n_known * row_bits / 32) + 1 u32 words (the +1 pad word keeps
// the device unpack's two-word gather in bounds for the last row).
// Rows are appended in input order through a 128-bit accumulator; bits
// beyond the last row stay zero, which the device side masks off via
// the per-device validity count. row_bits = id_bits + pk_bits +
// by_bits must be <= 64 (id_bits <= 32 always satisfies this at the
// shipped 10/22 lane widths). Returns n_new.
long rt_flowwire_dense(const uint32_t* rows, size_t n,
                       const uint32_t* ids, const uint8_t* sel_new,
                       uint64_t base, uint32_t id_bits, uint32_t pk_bits,
                       uint32_t by_bits, uint32_t* new_out,
                       uint32_t* known_out) {
  const unsigned row_bits = id_bits + pk_bits + by_bits;
  size_t n_new = 0, w = 0;
  unsigned __int128 acc = 0;
  unsigned acc_bits = 0;
  for (size_t i = 0; i < n; i++) {
    const uint32_t* r = rows + i * NUM_FIELDS;
    if (sel_new[i]) {
      uint32_t* o = new_out + n_new * 13;
      o[0] = ids[i];
      pack_row(r, o + 1, base);
      n_new++;
    } else {
      uint64_t v = (uint64_t)ids[i] |
                   ((uint64_t)r[F_PACKETS] << id_bits) |
                   ((uint64_t)r[F_BYTES] << (id_bits + pk_bits));
      acc |= (unsigned __int128)v << acc_bits;
      acc_bits += row_bits;
      while (acc_bits >= 32) {
        known_out[w++] = (uint32_t)acc;
        acc >>= 32;
        acc_bits -= 32;
      }
    }
  }
  if (acc_bits) known_out[w] = (uint32_t)acc;
  return (long)n_new;
}

}  // extern "C"

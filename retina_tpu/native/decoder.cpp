// Native event decoder — the kernel-parse analog, in C++.
//
// Reference analog: pkg/plugin/packetparser/_cprog/packetparser.c — the
// eBPF parse() path (:118-227) and its TCP timestamp-option walker
// (:42-115). This library is the hot host-side equivalent: pcap bytes →
// fixed-width (N, 16) uint32 event records (retina_tpu/events/schema.py),
// one linear pass, no allocation. Bit-identical output to the Python/numpy
// reference decoder (sources/pcapdecode.py), which remains the fallback
// when this library is not built.
//
// C ABI only (consumed via ctypes). Build: make -C retina_tpu/native
#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

// Record field indices — must match retina_tpu/events/schema.py F.
enum Field {
  TS_LO = 0, TS_HI, SRC_IP, DST_IP, PORTS, META, BYTES, PACKETS,
  VERDICT, DROP_REASON, TSVAL, TSECR, DNS, DNS_QHASH, EVENT_TYPE, IFINDEX,
  NUM_FIELDS
};

constexpr uint32_t kVerdictForwarded = 1;
constexpr uint32_t kEvForward = 0, kEvDnsReq = 2, kEvDnsResp = 3;
constexpr uint32_t kProtoTcp = 6, kProtoUdp = 17;

inline uint16_t be16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) << 8 | p[1];
}
inline uint32_t be32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) << 24 | static_cast<uint32_t>(p[1]) << 16 |
         static_cast<uint32_t>(p[2]) << 8 | p[3];
}
inline uint32_t le32(const uint8_t* p) {
  return static_cast<uint32_t>(p[3]) << 24 | static_cast<uint32_t>(p[2]) << 16 |
         static_cast<uint32_t>(p[1]) << 8 | p[0];
}

// CRC-32 (IEEE, zlib-compatible) for DNS qname hashes — must match
// zlib.crc32 so host string tables key identically across both decoders.
uint32_t crc32_ieee(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// Parse the first DNS question's lowercased name into qhash; returns true
// on success. Mirrors pcapdecode._parse_dns + dns_qname_hash.
bool parse_dns(const uint8_t* data, size_t off, size_t end, uint32_t* qhash,
               uint32_t* qtype, uint32_t* rcode, bool* is_resp) {
  if (end - off < 12) return false;
  uint16_t flags = be16(data + off + 2);
  uint16_t qdcount = be16(data + off + 4);
  if (qdcount < 1) return false;
  *is_resp = (flags & 0x8000u) != 0;
  *rcode = flags & 0xF;
  uint8_t name[256];
  size_t nlen = 0;
  size_t p = off + 12;
  for (int i = 0; i < 64; i++) {
    if (p >= end) return false;
    uint8_t ln = data[p];
    if (ln == 0) { p += 1; break; }
    if (ln >= 0xC0) { p += 2; break; }
    if (p + 1 + ln > end || nlen + ln + 1 > sizeof(name)) return false;
    if (nlen) name[nlen++] = '.';
    for (size_t j = 0; j < ln; j++) {
      uint8_t ch = data[p + 1 + j];
      if (ch >= 'A' && ch <= 'Z') ch += 32;  // lowercase, like Python
      name[nlen++] = ch;
    }
    p += 1 + static_cast<size_t>(ln);
  }
  if (p + 4 > end) return false;
  *qtype = be16(data + p);
  *qhash = crc32_ieee(name, nlen);
  return true;
}

}  // namespace

extern "C" {

// One Ethernet frame -> one 16-lane record (shared by the pcap decoder
// and the TPACKET_V3 live ring reader, afpacket.cpp). Returns false for
// frames outside the parse set (non-IPv4, non-TCP/UDP, truncated) —
// exactly the packetparser.c parse() admission rule.
bool rt_decode_eth_frame(const uint8_t* pkt, size_t caplen, uint64_t ts_ns,
                         uint32_t obs_point, uint32_t direction,
                         uint32_t* r) {
  // --- Ethernet + IPv4 (packetparser.c parse() IPv4 block) ---
  if (caplen < 14 + 20) return false;
  if (be16(pkt + 12) != 0x0800) return false;
  const uint8_t* ip = pkt + 14;
  if ((ip[0] >> 4) != 4) return false;
  size_t ihl = static_cast<size_t>(ip[0] & 0xF) * 4;
  uint32_t proto = ip[9];
  if (proto != kProtoTcp && proto != kProtoUdp) return false;
  size_t l4_need = (proto == kProtoTcp) ? 20 : 8;
  if (caplen < 14 + ihl + l4_need) return false;
  const uint8_t* l4 = ip + ihl;

  uint32_t sport = be16(l4), dport = be16(l4 + 2);
  uint32_t tcp_flags = 0, tsval = 0, tsecr = 0;
  if (proto == kProtoTcp) {
    tcp_flags = l4[13];
    size_t doff = static_cast<size_t>(l4[12] >> 4) * 4;
    // --- TCP timestamp option walk (packetparser.c:42-115) ---
    if (doff > 20 && caplen >= 14 + ihl + doff) {
      const uint8_t* opt = l4 + 20;
      size_t opt_len = doff - 20, p = 0;
      while (p < opt_len) {
        uint8_t kind = opt[p];
        if (kind == 0) break;
        if (kind == 1) { p += 1; continue; }
        if (p + 1 >= opt_len) break;
        uint8_t olen = opt[p + 1] < 2 ? 2 : opt[p + 1];
        if (kind == 8 && p + 10 <= opt_len) {
          tsval = be32(opt + p + 2);
          tsecr = be32(opt + p + 6);
          break;
        }
        p += olen;
      }
    }
  }

  std::memset(r, 0, NUM_FIELDS * sizeof(uint32_t));
  r[TS_LO] = static_cast<uint32_t>(ts_ns);
  r[TS_HI] = static_cast<uint32_t>(ts_ns >> 32);
  r[SRC_IP] = be32(ip + 12);
  r[DST_IP] = be32(ip + 16);
  r[PORTS] = sport << 16 | dport;
  r[META] = proto << 24 | tcp_flags << 16 | obs_point << 8 | direction << 4;
  r[BYTES] = be16(ip + 2);
  r[PACKETS] = 1;
  r[VERDICT] = kVerdictForwarded;
  r[TSVAL] = tsval;
  r[TSECR] = tsecr;
  r[EVENT_TYPE] = kEvForward;

  // --- DNS (UDP :53) ---
  if (proto == kProtoUdp && (sport == 53 || dport == 53)) {
    size_t pay = 14 + ihl + 8;
    uint32_t qhash, qtype, rcode;
    bool is_resp;
    if (caplen > pay &&
        parse_dns(pkt, pay, caplen, &qhash, &qtype, &rcode, &is_resp)) {
      r[DNS] = (qtype & 0xFFFFu) << 16 | (rcode & 0xFFu) << 8 |
               (is_resp ? 2u : 1u);
      r[DNS_QHASH] = qhash;
      r[EVENT_TYPE] = is_resp ? kEvDnsResp : kEvDnsReq;
    }
  }
  return true;
}

// Decode pcap bytes into out[max_records][NUM_FIELDS] (uint32).
// Returns the number of decoded records (>= 0), or:
//   -1  not a pcap; -2  out buffer too small (records written up to max).
// n_packets_total receives the total packet count in the capture.
long rt_decode_pcap(const uint8_t* data, size_t len, uint32_t obs_point,
                    uint32_t* out, size_t max_records,
                    size_t* n_packets_total) {
  *n_packets_total = 0;
  if (len < 24) return 0;
  uint32_t magic = le32(data);
  bool swap = false, ns = false;
  if (magic == 0xA1B2C3D4u) { ns = false; }
  else if (magic == 0xA1B23C4Du) { ns = true; }
  else {
    uint32_t magic_be = be32(data);
    if (magic_be == 0xA1B2C3D4u) { swap = true; ns = false; }
    else if (magic_be == 0xA1B23C4Du) { swap = true; ns = true; }
    else return -1;
  }
  const uint32_t direction = (obs_point == 1 || obs_point == 2) ? 1u : 2u;
  size_t off = 24;
  size_t n = 0;
  bool overflow = false;
  while (off + 16 <= len) {
    uint32_t ts_sec = swap ? be32(data + off) : le32(data + off);
    uint32_t ts_frac = swap ? be32(data + off + 4) : le32(data + off + 4);
    uint32_t incl = swap ? be32(data + off + 8) : le32(data + off + 8);
    if (off + 16 + incl > len) break;
    const uint8_t* pkt = data + off + 16;
    size_t caplen = incl;
    off += 16 + incl;
    (*n_packets_total)++;

    if (n >= max_records) { overflow = true; break; }
    uint64_t ts_ns = static_cast<uint64_t>(ts_sec) * 1000000000ull +
                     static_cast<uint64_t>(ts_frac) * (ns ? 1ull : 1000ull);
    if (rt_decode_eth_frame(pkt, caplen, ts_ns, obs_point, direction,
                            out + n * NUM_FIELDS)) {
      n++;
    }
  }
  if (overflow) return -2;
  return static_cast<long>(n);
}

// ABI version of libretina_native.so. Bump on ANY exported-signature or
// wire-layout change; the Python loader (native/__init__.py
// NATIVE_ABI_VERSION) refuses a mismatched binary and rebuilds from
// source, so a stale .so from another checkout can never silently
// misparse the wire.
//   v1: rt_combine/rt_combine_mt/rt_flowwire era
//   v2: + rt_combine_stripe (striped multi-consumer combine) and
//       rt_flowwire_dense (v4 dense known-row bitstream)
uint32_t rt_abi_version(void) { return 2; }

}  // extern "C"

"""Bounded-latency range-query service over snapshot rings.

Served as ``GET /timetravel/query`` on the agent HTTP server
(server.py ``register_route``). Query params:

- ``ring``: which ring (``engine`` default, ``fleet`` when the
  aggregator runs);
- ``t0``/``t1``: window-epoch range ``[t0, t1)`` (shipper
  ``window_epoch`` units), or ``last=N`` for the newest N windows;
- ``k``: top-k size (default ``cfg.timetravel_query_topk``);
- ``fam``: heavy-hitter family (flow/svc/dns, default flow).

Latency contract (the thing the p99 test pins): scrape threads NEVER
queue behind a fold. One fold runs at a time (non-blocking
single-flight); every other concurrent request is served from the TTL
result cache — stale if need be — or answered ``busy`` immediately.
Under SHEDDING the TTL is ignored entirely (any cached result serves),
so the query tier sheds exactly like the metrics path: bounded work,
degraded freshness, never an unbounded queue.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any

from retina_tpu.fleet.aggregator import format_key
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.runtime.overload import SHEDDING
from retina_tpu.timetravel.fold import (
    RangeFold, range_decode, range_extract, range_topk, set_aot_cache_dir,
)
from retina_tpu.timetravel.ring import SnapshotRing

_JSON = "application/json"


def _reply(code: int, doc: dict) -> tuple[int, bytes, str]:
    return code, json.dumps(doc, default=str).encode(), _JSON


class QueryService:
    """One per daemon; owns the fold jit cache and the result cache."""

    def __init__(self, cfg, overload=None, fold: RangeFold | None = None):
        self.cfg = cfg
        self.log = logger("timetravel.query")
        self._overload = overload
        # Query programs share the engine's AOT disk cache — without
        # this, every restart re-lowers fold/extract/decode from
        # scratch (the BENCH_r06 hits=1/misses=26 regression).
        set_aot_cache_dir(getattr(cfg, "aot_cache_dir", ""))
        self.fold = fold or RangeFold()
        self.rings: dict[str, SnapshotRing] = {}
        # (ring, e0, e1, k, fam, appended) -> (monotonic_t, result doc)
        self._cache: dict[Any, tuple[float, dict]] = {}
        self._cache_lock = threading.Lock()
        self._flight = threading.Lock()
        self.queries = 0

    # -- wiring --------------------------------------------------------
    def add_ring(self, ring: SnapshotRing) -> None:
        self.rings[ring.name] = ring

    def attach(self, server) -> None:
        server.register_route("/timetravel/query", self.handle)
        server.expose_var(
            "timetravel",
            lambda: {n: r.stats() for n, r in self.rings.items()},
        )

    # -- HTTP entry (handler threads; must bound latency) --------------
    def handle(self, q: dict) -> tuple[int, bytes, str]:  # hot-path: query
        m = get_metrics()
        t0 = time.monotonic()
        status = "error"
        try:
            code, doc, status = self._handle(q)
            return _reply(code, doc)
        except Exception:
            if rate_limited("timetravel.query"):
                self.log.exception("range query failed")
            return _reply(500, {"error": "internal"})
        finally:
            m.timetravel_query_seconds.observe(time.monotonic() - t0)
            m.timetravel_queries.labels(status=status).inc()
            self.queries += 1

    def _handle(self, q: dict) -> tuple[int, dict, str]:
        ring_name = q.get("ring", ["engine"])[0]
        ring = self.rings.get(ring_name)
        if ring is None:
            return 404, {"error": f"unknown ring {ring_name!r}",
                         "rings": sorted(self.rings)}, "bad_request"
        oldest, newest = ring.span()
        if newest < 0:
            return 200, {"ring": ring_name, "windows": 0,
                         "empty": True}, "empty"
        if "last" in q:
            n = max(1, int(q["last"][0]))
            e0, e1 = newest - n + 1, newest + 1
        else:
            try:
                e0 = int(q["t0"][0])
                e1 = int(q["t1"][0])
            except (KeyError, ValueError, IndexError):
                return 400, {"error": "need t0+t1 (window epochs) "
                             "or last=N"}, "bad_request"
        if e1 <= e0:
            return 400, {"error": "empty range: t1 <= t0"}, "bad_request"
        k = int(q.get("k", [self.cfg.timetravel_query_topk])[0])
        fam = q.get("fam", ["flow"])[0]
        return self._query_cached(ring, e0, e1, k, fam)

    # -- cached + single-flight fold -----------------------------------
    def _query_cached(
        self, ring: SnapshotRing, e0: int, e1: int, k: int, fam: str
    ) -> tuple[int, dict, str]:
        ov = self._overload
        shedding = ov is not None and ov.state >= SHEDDING
        # Ranges ending before the newest slot are immutable (nothing
        # can append into them), so appended-count only keys ranges
        # that include the live edge.
        _, newest = ring.span()
        edge = ring.appended if e1 > newest else 0
        key = (ring.name, e0, e1, k, fam, edge)
        ttl = float(self.cfg.timetravel_query_cache_ttl_s)
        now = time.monotonic()
        with self._cache_lock:
            hit = self._cache.get(key)
        if hit is not None and (shedding or now - hit[0] < ttl):
            doc = dict(hit[1])
            if shedding and now - hit[0] >= ttl:
                doc["stale"] = True
            return 200, doc, "stale" if doc.get("stale") else "ok"
        if not self._flight.acquire(blocking=False):
            # A fold is already running: serve whatever we have rather
            # than queue the handler thread behind device work.
            if hit is not None:
                doc = dict(hit[1])
                doc["stale"] = True
                return 200, doc, "stale"
            return 503, {"error": "busy", "retry": True}, "busy"
        try:
            doc = self._query(ring, e0, e1, k, fam)
            with self._cache_lock:
                self._cache[key] = (time.monotonic(), doc)
                # Bounded cache: drop oldest entries past 128 keys.
                while len(self._cache) > 128:
                    self._cache.pop(next(iter(self._cache)))
            return 200, doc, "ok"
        finally:
            self._flight.release()

    # -- the actual range query (single flight) ------------------------
    def _query(
        self, ring: SnapshotRing, e0: int, e1: int, k: int, fam: str
    ) -> dict:
        slots = ring.select(e0, e1)
        get_metrics().timetravel_query_windows.set(len(slots))
        doc: dict[str, Any] = {
            "ring": ring.name, "t0": e0, "t1": e1,
            "windows": len(slots),
            "epochs": [s[0] for s in slots],
        }
        if not slots:
            doc["empty"] = True
            return doc
        seeds = slots[0][3]
        merged = self.fold.fold([s[1] for s in slots], seeds)
        extras = range_extract(merged, seeds)
        dec = range_decode(merged, seeds)
        keys, counts = range_topk(merged, seeds, fam=fam, k=k,
                                  est=extras.get(f"{fam}_est"))
        doc["topk"] = {
            "family": fam,
            "keys": [
                {"key": format_key(row), "count": int(c)}
                for row, c in zip(keys, counts)
            ],
        }
        doc["cardinality"] = extras.get("cardinality", 0.0)
        doc["entropy_bits"] = extras.get("entropy_bits", {})
        if dec is not None:
            srcs, pkts = dec["sources"]
            doc["decode"] = {
                "n_keys": int(len(dec["keys"])),
                "keys": [format_key(row) for row in dec["keys"][:k]],
                "est": [int(x) for x in dec["est"][:k]],
                "sources": [
                    {"src_ip": int(s), "packets": int(p)}
                    for s, p in zip(srcs[:k], pkts[:k])
                ],
            }
        return doc

    # -- direct (non-HTTP) query for the autocapture loop --------------
    def query_range(
        self, ring_name: str, e0: int, e1: int
    ) -> dict[str, Any] | None:
        """Fold + decode for in-process callers. Takes the flight lock
        BLOCKING (the autocapture thread may wait; scrapes may not)."""
        ring = self.rings.get(ring_name)
        if ring is None:
            return None
        slots = ring.select(e0, e1)
        if not slots:
            return None
        seeds = slots[0][3]
        with self._flight:
            merged = self.fold.fold([s[1] for s in slots], seeds)
        return {
            "merged": merged, "seeds": seeds,
            "windows": len(slots),
            "decode": range_decode(merged, seeds),
        }

"""Closed loop: detection → attribution → evidence, no human involved.

The reference closes this loop with a human in it — an operator sees
the DDoS dashboard, writes a Capture CRD, kubectl-waits for the job
(PAPER.md L3/L6). Here the whole arc is automatic: the entropy burst
detector fires at window close (ops/entropy.py AnomalyEWMA via the
engine publish path), ``notify`` enqueues the burst epoch without
blocking the harvest thread, and the worker pivots the query ring to
``[W - lookback, W + lookahead + 1)``, waits for the lookahead windows
to land, attributes source keys via the span-summed invertible decode
(fold.range_decode), and records a targeted capture — full rows for
ONLY the attributed sources through the existing capture subsystem
(ReplayProvider + synthesize_filter), a few MB of evidence instead of
a firehose.

Trigger storms are damped two ways: a cooldown
(``autocapture_cooldown_s``) absorbs the detector re-firing across
consecutive burst windows, and the 1-deep trigger queue drops (and
counts) bursts that arrive while a capture is in flight.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Any

import numpy as np

from retina_tpu.capture.manager import CaptureManager
from retina_tpu.capture.providers import ReplayProvider
from retina_tpu.capture.translator import CaptureJob, synthesize_filter
from retina_tpu.events.schema import u32_to_ip
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.timetravel.query import QueryService


class AutoCapture:
    """One per daemon; owns the trigger queue + capture worker."""

    def __init__(
        self,
        cfg,
        query: QueryService,
        ring_name: str = "engine",
        engine=None,
        manager: CaptureManager | None = None,
        supervisor=None,
    ) -> None:
        self.cfg = cfg
        self.log = logger("timetravel.autocapture")
        self._query = query
        self._ring_name = ring_name
        self._engine = engine
        if manager is None:
            provider = (
                ReplayProvider(engine=engine)
                if engine is not None else None
            )
            manager = CaptureManager(provider=provider)
        self._manager = manager
        self._supervisor = supervisor
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        self._lock = threading.Lock()
        self._last_trigger = -float("inf")  # monotonic; cooldown base
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Last few completed capture records (tests/dryrun/debug vars).
        self.captures: list[dict] = []

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="autocapture", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        self._q.put(None)  # wake the worker
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        if self._supervisor is not None and self._thread is not None:
            self._supervisor.deregister("autocapture")
        self._thread = None

    # -- detector entry (harvest thread; must never block) -------------
    def notify(self, epoch: int, dims: list[str]) -> bool:
        """Entropy burst at window-epoch ``epoch`` on dimensions
        ``dims``. Returns True when a capture was actually enqueued."""
        m = get_metrics()
        now = time.monotonic()
        with self._lock:
            cool = now - self._last_trigger
            if cool < float(self.cfg.autocapture_cooldown_s):
                m.autocapture_suppressed.labels(reason="cooldown").inc()
                return False
            self._last_trigger = now
        try:
            self._q.put_nowait((int(epoch), list(dims)))
        except queue_mod.Full:
            m.autocapture_suppressed.labels(reason="busy").inc()
            return False
        m.autocapture_triggered.inc()
        self.log.warning(
            "entropy burst on %s at epoch %d: autocapture queued",
            ",".join(dims), epoch,
        )
        return True

    # -- worker --------------------------------------------------------
    def _run(self) -> None:  # runs-on: autocapture
        hb = None
        if self._supervisor is not None:
            hb = self._supervisor.register("autocapture", 120.0)
        while not self._stop.is_set():
            if hb is not None:
                hb.park()
            item = self._q.get()
            if item is None or self._stop.is_set():
                break
            if hb is not None:
                hb.beat()
            epoch, dims = item
            try:
                self._capture_one(epoch, dims)
            except Exception:
                get_metrics().autocapture_failed.inc()
                if rate_limited("timetravel.autocapture"):
                    self.log.exception(
                        "autocapture for epoch %d failed", epoch
                    )

    def _await_lookahead(self, want_epoch: int) -> None:
        """Wait (bounded) for the lookahead windows to land in the ring
        so the query range covers traffic AFTER the burst fired too."""
        ring = self._query.rings.get(self._ring_name)
        if ring is None:
            return
        window_s = float(getattr(self.cfg, "window_seconds", 1.0))
        lookahead = int(self.cfg.autocapture_lookahead_windows)
        deadline = time.monotonic() + max(
            2.0 * (lookahead + 1) * window_s, 1.0
        )
        while not self._stop.is_set() and time.monotonic() < deadline:
            if ring.span()[1] >= want_epoch:
                return
            self._stop.wait(0.05)

    def _capture_one(self, epoch: int, dims: list[str]) -> None:
        cfg = self.cfg
        m = get_metrics()
        e0 = epoch - int(cfg.autocapture_lookback_windows)
        e1 = epoch + int(cfg.autocapture_lookahead_windows) + 1
        self._await_lookahead(e1 - 1)
        res = self._query.query_range(self._ring_name, e0, e1)
        dec = (res or {}).get("decode")
        if dec is None or not len(dec["keys"]):
            m.autocapture_suppressed.labels(reason="no_keys").inc()
            self.log.warning(
                "burst at epoch %d: nothing attributable in [%d, %d)",
                epoch, e0, e1,
            )
            return
        srcs, pkts = dec["sources"]
        n_src = int(cfg.autocapture_max_sources)
        ips = [u32_to_ip(int(s)) for s in srcs[:n_src]]
        filt = synthesize_filter(ips)
        out_dir = cfg.autocapture_output_dir or "/tmp/retina-autocapture"
        os.makedirs(out_dir, exist_ok=True)
        job = CaptureJob(
            capture_name=f"auto-{epoch}",
            namespace="retina",
            node_name=cfg.node_name or "local",
            filter_expr=filt,
            duration_s=int(cfg.autocapture_duration_s),
            max_size_mb=int(cfg.autocapture_max_size_mb),
            packet_size_bytes=0,
            output={"host_path": out_dir},
            include_metadata=False,
        )
        t0 = time.monotonic()
        artifacts = self._manager.run_job(job)
        size = sum(
            os.path.getsize(a) for a in artifacts if os.path.isfile(a)
        )
        record: dict[str, Any] = {
            "epoch": epoch,
            "dims": dims,
            "range": (e0, e1),
            "windows": int((res or {}).get("windows", 0)),
            "attributed_keys": int(len(dec["keys"])),
            "sources": [
                (u32_to_ip(int(s)), int(p))
                for s, p in zip(srcs[:n_src], np.asarray(pkts)[:n_src])
            ],
            "filter": filt,
            "artifacts": artifacts,
            "artifact_bytes": int(size),
            "capture_seconds": time.monotonic() - t0,
        }
        with self._lock:
            self.captures.append(record)
            del self.captures[:-8]
        m.autocapture_completed.inc()
        m.autocapture_attributed_keys.set(len(dec["keys"]))
        m.autocapture_artifact_bytes.set(size)
        m.autocapture_last_epoch.set(epoch)
        self.log.warning(
            "autocapture complete: epoch %d, %d keys, %d sources, "
            "%d bytes -> %s",
            epoch, len(dec["keys"]), len(ips), size, artifacts,
        )

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "captures": len(self.captures),
                "last": self.captures[-1] if self.captures else None,
            }

"""Bounded ring of per-window sketch snapshots.

One ring instance holds the last N windows of sketch state for one
producer — the engine (per window close) or the fleet aggregator (per
merged epoch). Slots follow the fleet array catalog (fleet/codec.py),
so any contiguous run of slots is a valid operand set for the
``timetravel.range_fold`` program and any slot is RFLT-encodable
as-is.

Close-lane contract (the repo-wide rule): ``offer`` runs on the device
proxy inside the window-close dispatch and must never block — it
enqueues and returns; a worker thread does the device readback
(fetch_on_device per leaf) OFF the proxy and appends to the ring. A
full queue drops the snapshot and counts it. Producers that already
hold host arrays (the aggregator) append directly with
``append_host`` — O(1), no thread hop.

Memory bound: ``capacity`` slots × the per-window export size (the
same arrays the fleet shipper puts on the wire). Eviction is implicit
— the deque's maxlen drops the oldest slot on append.
"""

from __future__ import annotations

import collections
import queue as queue_mod
import threading
from typing import Any, Protocol, runtime_checkable

import numpy as np

from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.utils.device_proxy import fetch_on_device


@runtime_checkable
class RingProtocol(Protocol):
    """The read surface every snapshot-history provider exposes.

    Both the engine's per-window ring and the fleet aggregator's
    merged-epoch ring (``FleetAggregator.epoch_ring``) satisfy this, so
    the node query tier (timetravel/query.py) and the fleet query plane
    (fleetquery/service.py) fold over either interchangeably. Slots are
    ``(epoch, arrays, window_s, seeds)`` tuples in the fleet array
    catalog.
    """

    name: str
    appended: int

    def select(
        self, e0: int, e1: int
    ) -> list[tuple[int, dict[str, np.ndarray], float, dict[str, int]]]:
        ...

    def span(self) -> tuple[int, int]:
        ...

    def stats(self) -> dict:
        ...


class SnapshotRing:
    """Thread-safe bounded window-snapshot history for one producer."""

    def __init__(
        self,
        capacity: int,
        name: str = "engine",
        overload=None,  # OverloadController (state read only)
        supervisor=None,  # runtime/supervisor.py Supervisor
        queue_size: int = 4,
    ) -> None:
        self.name = name
        self.capacity = max(1, int(capacity))
        self.log = logger(f"timetravel.ring.{name}")
        self._overload = overload
        self._supervisor = supervisor
        # deque(maxlen) gives O(1) append WITH implicit oldest-slot
        # eviction; slots stay epoch-sorted because producers append in
        # close order.
        self._slots: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._lock = threading.Lock()
        self._q: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, int(queue_size))
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.appended = 0  # slots landed (tests/dryrun)
        self.evicted = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"tt-ring-{self.name}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._q.put(None)  # wake the worker
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        if self._supervisor is not None and self._thread is not None:
            self._supervisor.deregister(f"tt-ring-{self.name}")
        self._thread = None

    # -- close-path entry (device-proxy thread; must never block) ------
    def offer(  # hot-path: close
        self,
        epoch: int,
        arrays: dict[str, Any],
        window_s: float,
        seeds: dict[str, int],
    ) -> bool:  # runs-on: device-proxy
        """Enqueue one window's export for ring retention. ``arrays``
        values may be device arrays (fetched on the worker) or host
        numpy. Returns False when dropped (queue full / stopped).

        No SHEDDING backoff here on purpose: the ring is the evidence
        trail the autocapture loop pivots to when the system is under
        attack — exactly when overload states fire — and retention is
        local memory, not wire traffic. Overload protection is the
        bounded queue itself.
        """
        if self._stop.is_set():
            return False
        try:
            self._q.put_nowait((epoch, arrays, window_s, seeds))
            return True
        except queue_mod.Full:
            m = get_metrics()
            m.timetravel_ring_dropped.labels(ring=self.name).inc()
            if rate_limited("timetravel.ring_full"):
                self.log.warning(
                    "ring readback queue full; dropping epoch %d", epoch
                )
            return False

    def append_host(
        self,
        epoch: int,
        arrays: dict[str, np.ndarray],
        window_s: float,
        seeds: dict[str, int],
    ) -> None:
        """Direct O(1) append of already-host arrays (aggregator path,
        tests). Safe from any thread."""
        with self._lock:
            if len(self._slots) == self._slots.maxlen:
                self.evicted += 1
            self._slots.append(
                (int(epoch), arrays, float(window_s), dict(seeds))
            )
            self.appended += 1
            depth = len(self._slots)
        m = get_metrics()
        m.timetravel_ring_appended.labels(ring=self.name).inc()
        m.timetravel_ring_depth.labels(ring=self.name).set(depth)

    # -- worker --------------------------------------------------------
    def _run(self) -> None:  # runs-on: tt-ring
        hb = None
        if self._supervisor is not None:
            hb = self._supervisor.register(
                f"tt-ring-{self.name}", 60.0
            )
        while not self._stop.is_set():
            if hb is not None:
                hb.park()
            item = self._q.get()
            if item is None or self._stop.is_set():
                break
            if hb is not None:
                hb.beat()
            try:
                epoch, arrays, window_s, seeds = item
                host: dict[str, np.ndarray] = {}
                for name, arr in arrays.items():
                    if isinstance(arr, np.ndarray):
                        host[name] = arr
                    else:
                        host[name] = fetch_on_device(arr)
                self.append_host(epoch, host, window_s, seeds)
            except Exception:
                get_metrics().timetravel_ring_dropped.labels(
                    ring=self.name
                ).inc()
                if rate_limited("timetravel.ring_readback"):
                    self.log.exception("ring snapshot readback failed")

    # -- queries -------------------------------------------------------
    def select(
        self, e0: int, e1: int
    ) -> list[tuple[int, dict[str, np.ndarray], float, dict[str, int]]]:
        """Slots with epoch in ``[e0, e1)``, oldest first. Returns
        copies of the slot tuples (the arrays themselves are shared,
        immutable-by-convention host buffers)."""
        with self._lock:
            return [s for s in self._slots if e0 <= s[0] < e1]

    def span(self) -> tuple[int, int]:
        """(oldest_epoch, newest_epoch) currently retained, or
        (-1, -1) when empty."""
        with self._lock:
            if not self._slots:
                return (-1, -1)
            return (self._slots[0][0], self._slots[-1][0])

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            depth = len(self._slots)
            oldest = self._slots[0][0] if depth else -1
            newest = self._slots[-1][0] if depth else -1
        return {
            "ring": self.name,
            "capacity": self.capacity,
            "depth": depth,
            "oldest_epoch": oldest,
            "newest_epoch": newest,
            "appended": self.appended,
            "evicted": self.evicted,
            "queue_depth": self._q.qsize(),
        }

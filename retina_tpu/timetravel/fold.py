"""Jitted semilattice fold over ring slots + host-side range queries.

One range query ``[e0, e1)`` = stack the selected ring slots and run
the SAME batched reduction the fleet aggregator runs across nodes
(fleet/aggregator.py ``fleet.merge``): sum for CM tables / entropy
histograms / totals / invertible planes, max for HLL register banks,
join-semilattice fold for the heavy-hitter candidate tables. Because
every per-array op is associative and commutative (RT300 proves it for
the registered program), a 7-window query is exactly the sketch the
engine WOULD have built had the window been 7× longer — time is just
another merge axis.

The fold is cached per ``(n_slots, array signature, seeds)`` like the
fleet merge cache: queries over the same span length hit a compiled
executable, and ``donate_argnums=(0,)`` recycles the stacked staging
buffer (RT302).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from retina_tpu.devprog import device_entry
from retina_tpu.ops.countmin import CountMinSketch
from retina_tpu.ops.entropy import EntropyWindow
from retina_tpu.ops.hyperloglog import HyperLogLog
from retina_tpu.ops.invertible import InvertibleSketch, decode_verified
from retina_tpu.ops.topk import TopKTable

# Same families / dims as the fleet tier — ring slots follow the fleet
# array catalog (fleet/codec.py), so the fold speaks the same schema.
HH_FAMILIES = ("flow", "svc", "dns")
ENTROPY_DIMS = ("src_ip", "dst_ip", "dst_port")

# AOT executable disk cache for the query programs (same format and
# counters as parallel/telemetry.py — the BENCH_r06 hits=1/misses=26
# regression was these plus the scrape/export programs re-lowering on
# every restart). The builders keep returning plain lowerable jits
# (devlower RT302 lowers them); the disk consult happens in the host
# wrappers below, which hold both the concrete args and the cache key.
_AOT_CACHE_DIR = ""
_AOT_EXEC_CACHE: dict[Any, Any] = {}


def set_aot_cache_dir(path: str) -> None:
    """Point the query-program disk cache at ``cfg.aot_cache_dir``
    (daemon/bench boot). Empty disables the disk layer — the in-process
    jit caches still apply."""
    global _AOT_CACHE_DIR
    _AOT_CACHE_DIR = path or ""


def _args_sig(args: tuple) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return str(treedef), tuple(
        (np.shape(leaf), np.dtype(
            getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        ).name)
        for leaf in leaves
    )


def _disk_compiled(tag: str, jitted, args: tuple):  # may-block: AOT disk-cache consult, once per (program, signature) — _AOT_EXEC_CACHE serves every later call in-memory; a one-time ms-scale load on the query lane beats a seconds-scale recompile
    """Executable for one (program, concrete-args signature):
    in-memory first, then the shared AOT disk cache, else
    lower+compile+persist. Without a cache dir, the plain jitted fn
    (jax's own jit cache) is returned unchanged."""
    if not _AOT_CACHE_DIR:
        return jitted
    from retina_tpu.parallel.telemetry import (
        aot_disk_load, aot_disk_path, aot_disk_save,
    )

    key = _args_sig(args)
    ck = (tag, key)
    ex = _AOT_EXEC_CACHE.get(ck)
    if ex is None:
        path = aot_disk_path(_AOT_CACHE_DIR, None, tag, "", key)
        ex = aot_disk_load(path, tag=tag)
        if ex is None:
            ex = jitted.lower(*args).compile()
            aot_disk_save(path, ex, tag=tag)
        _AOT_EXEC_CACHE[ck] = ex
    return ex


class RangeFold:
    """Stateless-per-query fold engine with a compiled-executable cache.

    Thread-safe for concurrent ``fold`` calls: the cache dict is only
    ever populated (benign last-writer-wins race), and each call builds
    its own stacked input.
    """

    def __init__(self) -> None:
        self._cache: dict[Any, Any] = {}

    @device_entry("timetravel.range_fold", kind="jit")
    def _fold_fn(self, n: int, seeds: dict[str, int], names: tuple):
        key = (n, names, tuple(sorted(seeds.items())))
        fn = self._cache.get(key)
        if fn is not None:
            return fn

        def fold(stacked):
            out = {}
            for name in names:
                arr = stacked[name]
                if name.startswith("hll_"):
                    out[name] = jnp.max(arr, axis=0)
                elif name.endswith("_keys") or name.endswith("_counts"):
                    continue
                else:
                    out[name] = jnp.sum(arr, axis=0)
            for fam in HH_FAMILIES:
                kname, cname = f"{fam}_keys", f"{fam}_counts"
                if kname not in stacked:  # noqa: RT212 — dict-key test, static per jit cache key
                    continue
                seed = int(seeds.get(fam, 0))
                t = TopKTable(stacked[kname][0], stacked[cname][0],
                              seed=seed)
                for i in range(1, n):
                    t = t.merge(
                        TopKTable(stacked[kname][i], stacked[cname][i],
                                  seed=seed)
                    )
                out[kname], out[cname] = t.key_rows, t.counts
            return out

        fn = jax.jit(fold, donate_argnums=(0,))
        self._cache[key] = fn
        return fn

    def fold(
        self, slots: list[dict[str, Any]], seeds: dict[str, int]
    ) -> dict[str, np.ndarray]:
        """Fold N ring slots (dicts of host arrays sharing the fleet
        array catalog) into one merged host-side snapshot."""
        if not slots:  # noqa: RT212 — host-side slot list, not a tracer
            raise ValueError("range fold over an empty slot selection")
        names = sorted(set.intersection(*(set(s) for s in slots)))
        stacked = {
            name: jnp.asarray(np.stack([s[name] for s in slots]))
            for name in names
        }
        fn = self._fold_fn(len(slots), seeds, tuple(names))
        merged = _disk_compiled("range_fold", fn, (stacked,))(stacked)
        return {k: np.asarray(v) for k, v in merged.items()}


# Compiled extraction programs keyed by (names, shapes, seeds): the
# scalar answers (cardinality, entropy bits, candidate re-counts) come
# out of ONE compiled program per snapshot signature — eager per-sketch
# queries are hundreds of small dispatches, too slow for the query
# path's latency contract.
_EXTRACT_CACHE: dict[Any, Any] = {}


@device_entry("timetravel.range_extract", kind="jit")
def _extract_program(names: tuple, shapes: tuple, seeds: dict[str, int]):
    """Jitted derived-answer extraction over a folded snapshot:
    HLL cardinality, entropy bits, and the span-CMS re-count of every
    heavy-hitter candidate table row."""
    key = (names, shapes, tuple(sorted(seeds.items())))
    fn = _EXTRACT_CACHE.get(key)
    if fn is not None:
        return fn

    def run(merged):
        out = {}
        if "hll_flows" in merged:  # noqa: RT212 — dict-key test, static per jit cache key
            out["cardinality"] = HyperLogLog(
                registers=merged["hll_flows"],
                seed=int(seeds.get("hll_flows", 0)),
            ).estimate()
        if "entropy" in merged:  # noqa: RT212 — dict-key test, static per jit cache key
            out["entropy_bits"] = EntropyWindow(
                counts=merged["entropy"],
                seed=int(seeds.get("entropy", 0)),
            ).entropy_bits()
        for fam in HH_FAMILIES:
            kname = f"{fam}_keys"
            if kname not in merged or f"{fam}_cms" not in merged:  # noqa: RT212 — dict-key test, static per jit cache key
                continue
            cms = CountMinSketch(
                table=merged[f"{fam}_cms"], seed=int(seeds.get(fam, 0))
            )
            kr = merged[kname]
            cols = [kr[:, c] for c in range(kr.shape[1])]
            out[f"{fam}_est"] = cms.query(cols)
        return out

    fn = jax.jit(run)
    _EXTRACT_CACHE[key] = fn
    return fn


def range_extract(
    merged: dict[str, np.ndarray], seeds: dict[str, int]
) -> dict[str, Any]:
    """Host wrapper: run the compiled extraction program and unpack to
    plain python/numpy. Returns ``cardinality`` (float),
    ``entropy_bits`` (dim -> bits), and ``<fam>_est`` aligned with
    ``merged[<fam>_keys]``."""
    wanted = {"hll_flows", "entropy"}
    for fam in HH_FAMILIES:
        if f"{fam}_keys" in merged and f"{fam}_cms" in merged:
            wanted |= {f"{fam}_keys", f"{fam}_cms"}
    sub = {n: jnp.asarray(merged[n]) for n in sorted(wanted & set(merged))}
    if not sub:
        return {}
    names = tuple(sorted(sub))
    shapes = tuple(sub[n].shape for n in names)
    fn = _extract_program(names, shapes, seeds)
    raw = _disk_compiled("range_extract", fn, (sub,))(sub)
    out: dict[str, Any] = {
        k: np.asarray(v) for k, v in raw.items()
    }
    if "cardinality" in out:
        out["cardinality"] = float(out["cardinality"][0])
    if "entropy_bits" in out:
        bits = out["entropy_bits"]
        out["entropy_bits"] = {
            dim: float(bits[i])
            for i, dim in enumerate(ENTROPY_DIMS)
            if i < len(bits)
        }
    return out


# Compiled decode programs keyed by (planes shape, inv seed, cms seed):
# eager decode_verified is hundreds of small dispatches (~0.5s on CPU),
# far too slow for the query path's latency contract.
_DECODE_CACHE: dict[Any, Any] = {}


@device_entry("timetravel.range_decode", kind="jit")
def _decode_program(shape: tuple, inv_seed: int, cms_seed: int):
    """Jitted invertible decode + CMS verification for one region of
    the span-summed snapshot: (planes, weights, cms_table) ->
    (keys (D*W, C), est (D*W,), ok (D*W,))."""
    key = (tuple(shape), inv_seed, cms_seed)
    fn = _DECODE_CACHE.get(key)
    if fn is not None:
        return fn

    def run(planes, weights, table):
        inv = InvertibleSketch(
            planes=planes, weights=weights, seed=inv_seed
        )
        cms = CountMinSketch(table=table, seed=cms_seed)
        cols, est, ok = decode_verified(inv, cms)
        return jnp.stack(cols, axis=1), est, ok

    fn = jax.jit(run)
    _DECODE_CACHE[key] = fn
    return fn


# -- host-side range queries over a folded snapshot -------------------

def range_topk(
    merged: dict[str, np.ndarray],
    seeds: dict[str, int],
    fam: str = "flow",
    k: int = 32,
    candidates: np.ndarray | None = None,
    est: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Top-k over the span: candidate keys (the folded join table, or
    decoded invertible keys) re-counted by the SUMMED CMS — exact
    span-wide totals up to CMS overestimate, mirroring the fleet
    cluster top-k. Pass ``est`` (range_extract's ``<fam>_est``, aligned
    with the folded candidate table) to skip the eager CMS re-count —
    the query service's latency-bounded path."""
    kname, cname = f"{fam}_keys", f"{fam}_counts"
    if candidates is None and est is not None and kname in merged:
        cand, cest = merged[kname], est.astype(np.uint64)
        occupied = merged[cname] > 0
        cand, cest = cand[occupied], cest[occupied]
        order = np.argsort(cest)[::-1][:k]
        sel = cest[order] > 0
        return cand[order][sel], cest[order][sel]
    if candidates is not None and len(candidates):
        cand = candidates.astype(np.uint32).reshape(len(candidates), -1)
    elif kname in merged:
        cand = merged[kname][merged[cname] > 0]
    else:
        return np.zeros((0, 0), np.uint32), np.zeros((0,), np.uint64)
    if not len(cand):
        return np.zeros((0, 0), np.uint32), np.zeros((0,), np.uint64)
    cand = np.unique(cand, axis=0)
    cms = CountMinSketch(
        table=merged[f"{fam}_cms"], seed=int(seeds.get(fam, 0))
    )
    key_cols = [jnp.asarray(cand[:, c]) for c in range(cand.shape[1])]
    est = np.asarray(cms.query(key_cols)).astype(np.uint64)
    order = np.argsort(est)[::-1][:k]
    sel = est[order] > 0
    return cand[order][sel], est[order][sel]


def range_cardinality(
    merged: dict[str, np.ndarray], seeds: dict[str, int]
) -> float:
    """Distinct flows over the span (HLL registers max-merged across
    windows count each flow once however many windows it spans)."""
    if "hll_flows" not in merged:
        return 0.0
    hll = HyperLogLog(
        registers=merged["hll_flows"],
        seed=int(seeds.get("hll_flows", 0)),
    )
    return float(np.asarray(hll.estimate())[0])


def range_entropy(
    merged: dict[str, np.ndarray], seeds: dict[str, int]
) -> dict[str, float]:
    """Plug-in Shannon entropy of the span-summed histograms — exactly
    the single-window estimate of the concatenated stream."""
    if "entropy" not in merged:
        return {}
    ent = EntropyWindow(
        counts=merged["entropy"], seed=int(seeds.get("entropy", 0))
    )
    bits = np.asarray(ent.entropy_bits())
    return {
        dim: float(bits[i])
        for i, dim in enumerate(ENTROPY_DIMS)
        if i < len(bits)
    }


def range_decode(
    merged: dict[str, np.ndarray], seeds: dict[str, int]
) -> dict[str, Any] | None:
    """Heavy-key recovery from the span-summed invertible planes,
    verified against the span-summed flow CMS. A key too light to
    decode in any single window surfaces once its span-wide weight
    dominates a bucket. Returns keys/est/tier sorted descending plus
    per-source packet attribution ``sources = (src_ips, packets)``;
    None when the slots carried no invertible state."""
    if "inv_flow_planes" not in merged or "flow_cms" not in merged:
        return None
    all_keys, all_est, all_tier = [], [], []
    for region, tier in (("inv_flow", 0), ("inv_hi", 1)):
        if f"{region}_planes" not in merged:
            continue
        planes = merged[f"{region}_planes"]
        fn = _decode_program(
            planes.shape,
            int(seeds.get(region, 0)),
            int(seeds.get("flow", 0)),
        )
        args = (
            jnp.asarray(planes),
            jnp.asarray(merged[f"{region}_weights"]),
            jnp.asarray(merged["flow_cms"]),
        )
        cols, est, ok = _disk_compiled("range_decode", fn, args)(*args)
        okh = np.asarray(ok, bool)
        keys = np.asarray(cols)[okh]
        all_keys.append(keys.astype(np.uint32))
        all_est.append(np.asarray(est)[okh].astype(np.uint64))
        all_tier.append(np.full(len(keys), tier, np.uint32))
    if not all_keys:
        return None
    keys = np.concatenate(all_keys)
    est = np.concatenate(all_est)
    tier = np.concatenate(all_tier)
    if len(keys):
        uniq, idx = np.unique(keys, axis=0, return_index=True)
        keys, est, tier = uniq, est[idx], tier[idx]
        order = np.argsort(est)[::-1]
        keys, est, tier = keys[order], est[order], tier[order]
        srcs, sinv = np.unique(keys[:, 0], return_inverse=True)
        spk = np.zeros(len(srcs), np.uint64)
        np.add.at(spk, sinv, est)
        sorder = np.argsort(spk)[::-1]
        sources = (srcs[sorder], spk[sorder])
    else:
        sources = (np.zeros((0,), np.uint32), np.zeros((0,), np.uint64))
    return {"keys": keys, "est": est, "tier": tier, "sources": sources}

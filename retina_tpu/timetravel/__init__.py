"""timetravel: range queries over windowed sketch history + closed loop.

Sketches merge across *time* as well as space (Sketchy, PAPERS.md): a
per-window snapshot of the engine's sketch state is itself a valid
operand of the same semilattice algebra the fleet tier already folds
across nodes. This package keeps a bounded ring of those snapshots
(ring.py), answers ad-hoc ``[t0, t1)`` range queries as ONE jitted fold
over the selected slots (fold.py, registered as
``timetravel.range_fold`` so RT300/RT305 verify the algebra), serves
them through a bounded-latency HTTP endpoint (query.py), and closes the
reference's capture loop (autocapture.py): entropy burst detected →
ring pivoted to the offending windows → sources attributed via
invertible decode → targeted capture of only the attributed keys.
"""

from retina_tpu.timetravel.fold import RangeFold
from retina_tpu.timetravel.ring import RingProtocol, SnapshotRing

__all__ = ["RangeFold", "RingProtocol", "SnapshotRing"]

"""Time-travel closed-loop dryrun (``bench.py --query-dryrun``).

The whole detection → attribution → evidence arc on one process, no
human in the loop, no fake components on the path under test:

1. A synthetic feed (events/synthetic.py TrafficGen, ``zipf`` preset)
   closes ``windows`` windows into a SnapshotRing; window ``burst_at``
   carries a volumetric attack (``ddos_batch``: ``n_attack`` sources
   flooding one pod), which spikes src-IP entropy.
2. The real detector (ops/entropy.py EntropyWindow + AnomalyEWMA)
   observes each window's entropy vector and fires at the burst window;
   the flag calls AutoCapture.notify exactly like the engine's
   anomaly hook does.
3. AutoCapture pivots the query ring to ``[W - 2, W + 2)`` (lookback 2,
   lookahead 1), attributes the burst sources via the span-summed
   invertible decode, and records a targeted capture through the real
   capture subsystem (CaptureManager + ReplayProvider on a live record
   source) — full rows for ONLY the attributed hosts.
4. While the feed keeps closing windows at full rate, concurrent
   scrape threads hammer ``/timetravel/query`` (through
   QueryService.handle, the exact HTTP handler) — half the storm under
   a forced SHEDDING overload state — and the scorecard pins the p99.

Acceptance (bench gate): burst detected AT the burst window, decode
recall >= 0.95 against the exact attack key set, artifact contains
only rows matching the attributed hosts (and does contain the attack),
query p99 bounded, feed never stalled behind the query tier.

Sketch shapes/seeds are the fleet dryrun's (fleet/dryrun.py): ring
slots here carry the sketch catalog PLUS the counter-only invertible
regions, which is exactly what an engine with invertible export on
ships per window.
"""

from __future__ import annotations

import tarfile
import tempfile
import threading
import time
from typing import Any, Callable

import numpy as np

from retina_tpu.capture.manager import CaptureManager
from retina_tpu.capture.providers import ReplayProvider
from retina_tpu.config import Config
from retina_tpu.events.schema import F, u32_to_ip
from retina_tpu.events.synthetic import TrafficGen, preset_params
from retina_tpu.fleet.dryrun import (
    INV_SEEDS, _invertible_arrays, _sketch_arrays,
)
from retina_tpu.log import logger
from retina_tpu.ops.entropy import AnomalyEWMA, EntropyWindow
from retina_tpu.runtime.overload import NOMINAL, SHEDDING
from retina_tpu.sources.pcapdecode import decode_pcap_bytes
from retina_tpu.timetravel.autocapture import AutoCapture
from retina_tpu.timetravel.fold import ENTROPY_DIMS
from retina_tpu.timetravel.query import QueryService
from retina_tpu.timetravel.ring import SnapshotRing

_log = logger("timetravel.dryrun")

# Window-epoch base: arbitrary non-zero so the dryrun exercises real
# epoch arithmetic, not list indices.
_EPOCH0 = 1000


class _Overload:
    """Minimal stand-in for the OverloadController surface the query
    tier reads (``.state``); the storm flips it to SHEDDING."""

    def __init__(self) -> None:
        self.state = NOMINAL


def _keys_from_records(rec: np.ndarray) -> np.ndarray:
    """(N, NUM_FIELDS) records -> (N, 4) flow keys
    (src_ip, dst_ip, proto, dst_port) — col 3 is dst_port so the
    entropy groups line up with fold.ENTROPY_DIMS."""
    return np.stack(
        [
            rec[:, F.SRC_IP],
            rec[:, F.DST_IP],
            rec[:, F.META] >> np.uint32(24),
            rec[:, F.PORTS] & np.uint32(0xFFFF),
        ],
        axis=1,
    ).astype(np.uint32)


# Fixed per-window key-batch shape: np.unique yields a different key
# count every window, and an unpadded build would recompile the whole
# sketch-build grid per window. Padding repeats key row 0 at weight 0 —
# invisible to CMS/top-k/entropy (zero weight) and to HLL (duplicate).
_KEY_PAD = 1 << 12


def _window_arrays(rec: np.ndarray) -> dict[str, np.ndarray]:
    """One window's ring slot: the full sketch catalog plus the
    counter-only invertible regions, from one window of records."""
    keys, w = np.unique(_keys_from_records(rec), axis=0,
                        return_counts=True)
    assert len(keys) <= _KEY_PAD, "raise _KEY_PAD for this feed"
    pad = _KEY_PAD - len(keys)
    keys = np.concatenate([keys, np.repeat(keys[:1], pad, axis=0)])
    w = np.concatenate([w, np.zeros(pad, w.dtype)])
    arrays = _sketch_arrays(keys, w.astype(np.float64))
    # Invertible regions at the same seeds the decode expects; the
    # plain-CMS flow_cms replaces the heavy-hitter one so the decode
    # verification reads the same estimator the planes were fed from.
    arrays.update(_invertible_arrays(keys, w, np.zeros(len(w), bool)))
    return arrays


def run_query_dryrun(
    windows: int = 8,
    burst_at: int = 4,
    n_attack: int = 48,
    bg_events: int = 1024,
    burst_events: int = 98_304,
    storm_threads: int = 6,
    storm_requests: int = 30,
    seed: int = 0,
    log: Callable[[str], None] = lambda s: None,
) -> dict[str, Any]:
    """Run the closed-loop simulation; returns the scorecard dict."""
    assert 2 <= burst_at <= windows - 2, "need lookback+lookahead room"
    gen = TrafficGen(
        n_flows=512, n_pods=16, seed=seed, **preset_params("zipf")
    )
    out_dir = tempfile.mkdtemp(prefix="retina-ttdryrun-")
    cfg = Config(
        node_name="tt-dryrun",
        window_seconds=0.25,
        gen_preset="zipf",
        timetravel_enabled=True,
        timetravel_ring_windows=windows + 8,
        timetravel_query_cache_ttl_s=0.25,
        autocapture_enabled=True,
        autocapture_cooldown_s=300.0,
        autocapture_lookback_windows=2,
        autocapture_lookahead_windows=1,
        autocapture_max_sources=n_attack + 16,
        autocapture_duration_s=1.0,
        autocapture_max_size_mb=4,
        autocapture_output_dir=out_dir,
    )
    ov = _Overload()
    ring = SnapshotRing(cfg.timetravel_ring_windows, name="engine")
    qs = QueryService(cfg, overload=ov)
    qs.add_ring(ring)

    # Live record source for the capture window: the attack is still in
    # flight when the evidence is taken, so every block carries both
    # background and attack rows. Counts what it produced so the
    # scorecard can prove the artifact is a targeted subset.
    feed_rows = [0]
    feed_lock = threading.Lock()

    def capture_source() -> np.ndarray:
        with feed_lock:
            block = np.concatenate([
                gen.batch(256),
                gen.ddos_batch(768, target_pod=1, n_sources=n_attack),
            ])
            feed_rows[0] += len(block)
        return block

    manager = CaptureManager(provider=ReplayProvider(source=capture_source))
    ac = AutoCapture(cfg, qs, ring_name="engine", manager=manager)
    ac.start()

    # --- phase 1: feed windows through the ring + real detector -------
    burst_epoch = _EPOCH0 + burst_at
    attack_keys: set[tuple[int, ...]] = set()
    det = AnomalyEWMA.zeros(len(ENTROPY_DIMS))
    detected_epoch = -1
    detected_dims: list[str] = []
    t_build0 = time.monotonic()
    for i in range(windows):
        epoch = _EPOCH0 + i
        with feed_lock:
            rec = gen.batch(bg_events)
            if i == burst_at:
                atk = gen.ddos_batch(
                    burst_events, target_pod=1, n_sources=n_attack
                )
                attack_keys = {
                    tuple(int(x) for x in row)
                    for row in np.unique(_keys_from_records(atk), axis=0)
                }
                rec = np.concatenate([rec, atk])
        slot = _window_arrays(rec)
        ring.append_host(epoch, slot, cfg.window_seconds, INV_SEEDS)
        h = EntropyWindow(
            counts=slot["entropy"], seed=INV_SEEDS["entropy"]
        ).entropy_bits()
        det, flags, z = det.observe(h, z_thresh=8.0, min_windows=3)
        flags = np.asarray(flags)
        if flags.any() and detected_epoch < 0:
            detected_epoch = epoch
            detected_dims = [
                d for d, f in zip(ENTROPY_DIMS, flags) if f
            ]
            ac.notify(epoch, detected_dims)
            log(f"burst detected at epoch {epoch} on "
                f"{','.join(detected_dims)} (z={np.asarray(z).max():.1f})")
    build_s = time.monotonic() - t_build0

    # --- phase 2: the loop closes (attribution + targeted capture) ----
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline and not ac.captures:
        time.sleep(0.05)
    capture = ac.captures[-1] if ac.captures else None

    # Attribution recall straight off the query tier, over the same
    # range the autocapture pivoted to: [W - 2, W + 2).
    res = qs.query_range("engine", burst_epoch - 2, burst_epoch + 2)
    dec = (res or {}).get("decode")
    recall = 0.0
    if dec is not None and attack_keys:
        decoded = {tuple(int(x) for x in row) for row in dec["keys"]}
        recall = len(decoded & attack_keys) / len(attack_keys)

    # --- phase 3: artifact audit --------------------------------------
    art: dict[str, Any] = {
        "rows": 0, "only_attributed": False, "attack_rows": 0,
        "bytes": 0, "path": None,
    }
    if capture is not None and capture["artifacts"]:
        path = capture["artifacts"][0]
        attr_ips = {ip for ip, _ in capture["sources"]}
        attack_ips = {u32_to_ip(k[0]) for k in attack_keys}
        with tarfile.open(path) as tf:
            member = next(
                m for m in tf.getmembers() if m.name.endswith(".pcap")
            )
            fh = tf.extractfile(member)
            assert fh is not None
            pcap = decode_pcap_bytes(fh.read())
        rows = pcap.records
        srcs = [u32_to_ip(int(r)) for r in rows[:, F.SRC_IP]]
        dsts = [u32_to_ip(int(r)) for r in rows[:, F.DST_IP]]
        art = {
            "rows": int(len(rows)),
            "only_attributed": bool(rows.size) and all(
                s in attr_ips or d in attr_ips
                for s, d in zip(srcs, dsts)
            ),
            "attack_rows": int(sum(s in attack_ips for s in srcs)),
            "bytes": int(capture["artifact_bytes"]),
            "path": path,
            "filter_hosts": len(attr_ips),
            "feed_rows_offered": int(feed_rows[0]),
        }

    # --- phase 4: query storm while the feed keeps running ------------
    # Prewarm the fold shapes the storm uses (first-call jit compiles
    # would otherwise count against the latency budget — the daemon
    # pays those at attach time, not per scrape).
    for span in (2, 3, 4):
        qs.handle({"t0": [str(burst_epoch - 2)],
                   "t1": [str(burst_epoch - 2 + span)]})
        qs.handle({"last": [str(span)]})

    feed_stop = threading.Event()
    feed_appends = [0]
    # Prebuilt slot pool: the feeder's job during the storm is to churn
    # the ring's live edge at full window rate (20ms), not to re-pay
    # the sketch build per append — a real engine builds windows on
    # device while queries run on host threads.
    with feed_lock:
        pool = [_window_arrays(gen.batch(bg_events)) for _ in range(4)]

    def feeder() -> None:
        e = _EPOCH0 + windows
        while not feed_stop.is_set():
            ring.append_host(
                e, pool[e % len(pool)], cfg.window_seconds, INV_SEEDS
            )
            feed_appends[0] += 1
            e += 1
            feed_stop.wait(0.02)

    lat_lock = threading.Lock()
    lats: list[float] = []
    codes: dict[int, int] = {}

    def scraper(tid: int) -> None:
        for j in range(storm_requests):
            if j == storm_requests // 2:
                ov.state = SHEDDING  # second half of the storm sheds
            q = [
                {"t0": [str(burst_epoch - 2)],
                 "t1": [str(burst_epoch + 2)]},
                {"last": ["3"]},
                {"last": ["2"], "fam": ["svc"]},
                {"t0": [str(burst_epoch - 1)],
                 "t1": [str(burst_epoch + 1)]},
            ][(tid + j) % 4]
            t0 = time.monotonic()
            code, _body, _ctype = qs.handle(q)
            dt = time.monotonic() - t0
            with lat_lock:
                lats.append(dt)
                codes[code] = codes.get(code, 0) + 1
            time.sleep(0.01)  # paced like scrape traffic, not a busy loop

    ft = threading.Thread(target=feeder, daemon=True)
    ft.start()
    threads = [
        threading.Thread(target=scraper, args=(t,), daemon=True)
        for t in range(storm_threads)
    ]
    t_storm0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    storm_s = time.monotonic() - t_storm0
    feed_stop.set()
    ft.join(timeout=5.0)
    ov.state = NOMINAL
    ac.stop()

    p50, p99 = (
        (float(np.percentile(lats, 50)), float(np.percentile(lats, 99)))
        if lats else (float("inf"), float("inf"))
    )
    checks = {
        "detected_at_burst": detected_epoch == burst_epoch,
        "recall_ok": recall >= 0.95,
        "capture_ok": capture is not None and art["rows"] > 0,
        "only_attributed": bool(art["only_attributed"]),
        "attack_in_artifact": art["attack_rows"] > 0,
        "artifact_bounded": 0 < art["bytes"]
        <= cfg.autocapture_max_size_mb * 1024 * 1024,
        "p99_ok": p99 <= 0.5,
        "no_errors": all(c in (200, 503) for c in codes),
        "feed_kept_up": feed_appends[0] >= 10,
    }
    res_out: dict[str, Any] = {
        "windows": windows,
        "burst_epoch": burst_epoch,
        "detected_epoch": detected_epoch,
        "detected_dims": detected_dims,
        "n_attack_keys": len(attack_keys),
        "recall": round(recall, 4),
        "capture": {k: v for k, v in art.items() if k != "path"},
        "artifact": art["path"],
        "queries": len(lats),
        "query_codes": codes,
        "query_p50_ms": round(p50 * 1e3, 2),
        "query_p99_ms": round(p99 * 1e3, 2),
        "storm_seconds": round(storm_s, 2),
        "feed_appends_during_storm": feed_appends[0],
        "window_build_seconds": round(build_s, 2),
        "checks": checks,
        "ok": all(checks.values()),
    }
    log(
        f"query dryrun: detect@{detected_epoch} "
        f"(burst@{burst_epoch}), recall {recall:.3f} over "
        f"{len(attack_keys)} attack keys, artifact "
        f"{art['rows']} rows / {art['bytes']}B "
        f"({art['attack_rows']} attack), storm p50 {p50 * 1e3:.1f}ms "
        f"p99 {p99 * 1e3:.1f}ms over {len(lats)} queries "
        f"({feed_appends[0]} windows closed during storm), "
        f"ok={res_out['ok']}"
    )
    return res_out

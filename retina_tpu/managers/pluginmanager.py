"""PluginManager: plugin lifecycle + supervision.

Reference analog: pkg/managers/pluginmanager/pluginmanager.go —
instantiate enabled plugins from the registry (:60-66), Reconcile each
(Generate→Compile→Stop→Init under a 10s SLA, :27-28, :91-113), start each
in an errgroup where any plugin's fatal error tears the whole agent down
for a clean restart (:154-179), broadcast SetupChannel (:206-212), and run
conntrack GC only when packetparser is on (:140-151).

Differences by design: plugins raising UnsupportedPlatform at reconcile
are skipped with a warning (the reference compiles them out per-OS);
reconcile failures are counted in the same
plugin_manager_failed_to_reconcile series.

Supervision: unlike the reference errgroup (one crash tears the whole
agent down), each plugin runs under a restart loop with exponential
backoff and a crash-loop circuit breaker. A crashing plugin is restarted
in place; only a plugin whose circuit opens (persistently crash-looping)
marks the manager ``failed`` so the health endpoint reports unhealthy and
the orchestrator can restart the pod — the process itself stays up and
keeps serving the remaining plugins and the engine.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

from retina_tpu.config import Config
from retina_tpu.log import logger
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import EventSink, Plugin, UnsupportedPlatform
from retina_tpu.runtime import faults
from retina_tpu.runtime.supervisor import RestartPolicy, policy_from_config

RECONCILE_SLA_S = 10.0  # pluginmanager.go:25-28


class PluginManager:
    def __init__(
        self,
        cfg: Config,
        sink: Optional[EventSink] = None,
        engine: Optional[Any] = None,
    ):
        self._log = logger("pluginmanager")
        self.cfg = cfg
        self.engine = engine
        self.plugins: dict[str, Plugin] = {}
        self.errors: list[tuple[str, BaseException]] = []
        self._threads: dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._fatal = threading.Event()
        self._policies: dict[str, RestartPolicy] = {}

        import retina_tpu.plugins  # noqa: F401  (self-registration)

        enabled = list(cfg.enabled_plugins)
        # Conntrack GC rides along when packetparser is enabled
        # (pluginmanager.go:140-151).
        if "packetparser" in enabled and "conntrack" not in enabled:
            enabled.append("conntrack")
        for name in enabled:
            ctor = registry.get(name)  # KeyError is fatal, like the reference
            p = ctor(cfg)
            if sink is not None:
                p.set_sink(sink)
            self.plugins[name] = p
        if engine is not None:
            ct = self.plugins.get("conntrack")
            if ct is not None and hasattr(ct, "attach_engine"):
                ct.attach_engine(engine)
            dns = self.plugins.get("dns")
            if dns is not None and hasattr(dns, "observe_records"):
                # Named "dns": the overload controller sheds this
                # observer first under SHEDDING (runtime/overload.py).
                engine.add_observer(
                    lambda rec, plugin: dns.observe_records(rec),
                    name="dns",
                )

    # -- reconcile (pluginmanager.go:91-113) ---------------------------
    def reconcile(self, name: str) -> bool:
        p = self.plugins[name]
        t0 = time.perf_counter()
        try:
            p.generate()
            p.compile()
            p.stop()
            p.init()
        except UnsupportedPlatform as e:
            self._log.warning("plugin %s unsupported here: %s", name, e)
            del self.plugins[name]
            return False
        except Exception:
            get_metrics().plugin_reconcile_failures.labels(plugin=name).inc()
            self._log.exception("plugin %s reconcile failed", name)
            raise
        took = time.perf_counter() - t0
        if took > RECONCILE_SLA_S:
            self._log.warning(
                "plugin %s reconcile took %.1fs (SLA %.0fs)",
                name, took, RECONCILE_SLA_S,
            )
        return True

    def setup_channel(self, q: queue.Queue) -> None:
        """Broadcast the external channel (pluginmanager.go:206-212)."""
        for p in self.plugins.values():
            p.setup_channel(q)

    # -- start/stop (pluginmanager.go:116-193) -------------------------
    def start(self, stop: threading.Event) -> None:
        """Reconcile + launch every plugin; returns once all are running.

        Each plugin runs under a supervised restart loop: a crash is
        restarted with exponential backoff; a crash-looping plugin trips
        its circuit breaker, which marks the manager ``failed`` (and so
        /healthz unhealthy) without tearing the process down.
        """
        self._stop = stop
        for name in list(self.plugins):
            self.reconcile(name)

        for name, p in self.plugins.items():
            self._policies[name] = policy_from_config(
                self.cfg, seed_key=f"plugin.{name}"
            )
            t = threading.Thread(
                target=self._run_supervised,
                args=(name, p, stop),
                name=f"plugin-{name}",
                daemon=True,
            )
            t.start()
            self._threads[name] = t
        self._log.info("started plugins: %s", sorted(self.plugins))

    def _run_supervised(
        self, name: str, p: Plugin, stop: threading.Event
    ) -> None:
        policy = self._policies[name]
        while not stop.is_set():
            policy.note_start()
            try:
                faults.inject(f"plugin.{name}")
                p.start(stop)
                return  # clean exit (stop requested or plugin done)
            except UnsupportedPlatform as e:
                self._log.warning("plugin %s stopped: %s", name, e)
                return
            except Exception as e:
                self._log.exception("plugin %s crashed", name)
                self.errors.append((name, e))
                del self.errors[:-32]  # bounded crash history
            delay = policy.record_failure()
            if delay is None:
                self._log.error(
                    "plugin %s circuit OPEN (crash-looping); waiting for "
                    "half-open probe — /healthz reports unhealthy", name,
                )
                if not policy.wait_half_open(stop):
                    return
                continue
            get_metrics().plugin_restarts.labels(plugin=name).inc()
            self._log.warning(
                "restarting plugin %s in %.2fs (consecutive crashes: %d)",
                name, delay, policy.stats()["consecutive_failures"],
            )
            # Best-effort teardown + re-init so the restart starts clean.
            try:
                p.stop()
            except Exception:
                self._log.warning(
                    "plugin %s stop before restart failed", name,
                    exc_info=True,
                )
            try:
                p.init()
            except Exception:
                self._log.warning(
                    "plugin %s re-init before restart failed", name,
                    exc_info=True,
                )
            stop.wait(delay)

    def stop(self) -> None:
        self._stop.set()
        for name, t in self._threads.items():
            t.join(timeout=2.0)
        for name, p in self.plugins.items():
            try:
                p.stop()
            except Exception:
                self._log.exception("plugin %s stop failed", name)

    @property
    def failed(self) -> bool:
        """Unhealthy when any plugin's restart circuit is not closed.

        The process stays up either way; ``failed`` is surfaced through
        /healthz so the orchestrator decides whether to restart the pod.
        """
        if self._fatal.is_set():
            return True
        return any(
            pol.state != "closed" for pol in self._policies.values()
        )

    def supervision_stats(self) -> dict:
        return {
            name: pol.stats() for name, pol in sorted(self._policies.items())
        }

"""ControllerManager: composes the HTTP server, plugin manager, engine.

Reference analog: pkg/managers/controllermanager — Init builds the HTTP
server and (pod-level) pubsub/cache/enricher (controllermanager.go:71-90);
Start runs server + pluginmanager in an errgroup (:92-120). Here the
"enricher" seam is the SketchEngine feed loop and the identity-table
rebuild wiring (cache → engine), and servermanager is the thin HTTP
wrapper (reference pkg/servermanager).
"""

from __future__ import annotations

import threading
from typing import Optional

from retina_tpu.config import Config
from retina_tpu.controllers.cache import Cache
from retina_tpu.engine import SketchEngine
from retina_tpu.log import logger
from retina_tpu.managers.filtermanager import FilterManager
from retina_tpu.managers.pluginmanager import PluginManager
from retina_tpu.managers.watchermanager import WatcherManager
from retina_tpu.metrics import initialize_metrics
from retina_tpu.pubsub import PubSub
from retina_tpu.runtime import faults
from retina_tpu.runtime.supervisor import Supervisor, policy_from_config
from retina_tpu.server import Server
from retina_tpu.telemetry import new_telemetry
from retina_tpu.watchers.apiserver import ApiServerWatcher
from retina_tpu.watchers.endpoint import EndpointWatcher


class ControllerManager:
    def __init__(self, cfg: Config, apiserver_host: str = ""):
        self._log = logger("controllermanager")
        self.cfg = cfg
        self.pubsub = PubSub()
        self.metrics = initialize_metrics()
        # Root of the supervision tree: every long-lived thread (feed,
        # dispatch, harvest, warm, plugins, checkpointer) registers a
        # heartbeat; the watchdog escalates stalls past the deadline.
        self.supervisor = Supervisor(
            deadline_s=cfg.watchdog_deadline_s,
            interval_s=cfg.watchdog_interval_s,
        )
        self.engine = SketchEngine(cfg, supervisor=self.supervisor)
        self.cache = Cache(self.pubsub, max_pods=cfg.n_pods)
        self.filtermanager = FilterManager(self.engine.update_filter_ips)
        self.pluginmanager = PluginManager(
            cfg, sink=self.engine.sink, engine=self.engine
        )
        watchers: list = [EndpointWatcher(self.pubsub)]
        if apiserver_host:
            watchers.append(
                ApiServerWatcher(
                    self.pubsub,
                    host=apiserver_host,
                    filtermanager=self.filtermanager,
                    on_ips=self.engine.set_apiserver_ips,
                )
            )
        self.watchermanager = WatcherManager(watchers)
        self.telemetry = new_telemetry(
            cfg.enable_telemetry, cfg.telemetry_interval_s,
            extra=self.supervisor.summary,
        )
        self.server: Optional[Server] = None
        self._ready = threading.Event()
        self._engine_thread: Optional[threading.Thread] = None
        self._warm_thread: Optional[threading.Thread] = None

        # Identity churn → debounced device table rebuild (the enricher's
        # cache lookup seam, enricher.go:102-135, now a device upload).
        self._ident_timer: Optional[threading.Timer] = None
        self.cache.on_identity_change(self._schedule_identity_rebuild)

    def _schedule_identity_rebuild(self) -> None:
        if self._ident_timer is not None:
            self._ident_timer.cancel()
        self._ident_timer = threading.Timer(0.05, self._rebuild_identity)
        self._ident_timer.daemon = True
        self._ident_timer.start()

    def _rebuild_identity(self) -> None:
        try:
            self.engine.update_identities(self.cache.ip_index_map())
        except Exception:
            self._log.exception("identity table rebuild failed")

    # -- lifecycle ----------------------------------------------------
    def init(self) -> None:
        """Build the HTTP server + warm the engine (controllermanager.go
        Init + the jit-warmup Compile analog)."""
        self.server = Server(
            self.cfg.api_server_addr,
            ready_check=self._ready.is_set,
            healthy_check=lambda: not (
                self.pluginmanager.failed
                or self.engine.recovery_failed.is_set()
            ),
            metrics_cache_ttl_s=self.cfg.metrics_cache_ttl_s,
        )
        self.server.expose_var("pods", self.cache.pod_count)
        self.server.expose_var("filter_ips", self.filtermanager.ip_count)
        self.server.expose_var(
            "engine", lambda: {
                "steps": self.engine._steps,
                "events_in": self.engine._events_in,
                "devices": self.engine.n_devices,
                "degraded": self.engine.degraded,
                "restarts": self.engine.restarts,
                "recovery_failed": self.engine.recovery_failed.is_set(),
            }
        )
        self.server.expose_var("supervisor", self.supervisor.stats)
        self.server.expose_var(
            "plugin_supervision", self.pluginmanager.supervision_stats
        )
        self.server.expose_var("faults", faults.stats)
        self.server.expose_var(
            "heartbeat", lambda: self.telemetry.last_heartbeat
        )
        # Sharded-feed backpressure: per-worker fill / staged backlog /
        # handoff wait + drop counters (engine.feed_stats).
        self.server.expose_var("feed", self.engine.feed_stats)
        # Adaptive overload control: state/pressure/signals/shed set
        # (runtime/overload.py; docs/operations.md §6).
        self.server.expose_var("overload", self.engine.overload_stats)
        self.server.expose_var("top_flows", self._top_flows)
        self.server.expose_var("top_services", self._top_services)
        self.server.expose_var("top_dns", self._top_dns)
        self.engine.compile()

    # -- heavy-hitter views for /debug/vars (CLI `top` command) --------
    def _top_flows(self) -> list[list]:
        from retina_tpu.events.schema import u32_to_ip

        keys, counts = self.engine.top_flows(20)
        return [
            [u32_to_ip(int(k[0])), u32_to_ip(int(k[1])),
             int(k[2]) >> 16, int(k[2]) & 0xFFFF, int(k[3]), int(c)]
            for k, c in zip(keys, counts)
        ]

    def _top_services(self) -> list[list]:
        labeler = self.cache.index_label_map()
        keys, counts = self.engine.top_services(20)
        out = []
        for k, c in zip(keys, counts):
            src = labeler.get(int(k[0]))
            dst = labeler.get(int(k[1]))
            out.append([
                src.key() if src else f"pod:{int(k[0])}",
                dst.key() if dst else f"pod:{int(k[1])}",
                int(c),
            ])
        return out

    def _top_dns(self) -> list[list]:
        dns = self.pluginmanager.plugins.get("dns")
        keys, counts = self.engine.top_dns(20)
        return [
            [dns.resolve(int(k[0])) if dns else hex(int(k[0])), int(c)]
            for k, c in zip(keys, counts)
        ]

    def start(self, stop: threading.Event) -> None:
        """Run everything; returns when ``stop`` fires (errgroup shape)."""
        assert self.server is not None, "call init() first"
        self.server.start()
        self.supervisor.start()
        self.telemetry.start_heartbeat()
        self.watchermanager.start(stop)
        self._engine_thread = threading.Thread(
            target=self.engine.start, args=(stop,), name="engine", daemon=True
        )
        self._engine_thread.start()
        self.pluginmanager.start(stop)
        self._ready.set()
        self._log.info("agent ready on %s", self.cfg.api_server_addr)
        # The rest of the bucket grid compiles AFTER ready, interleaved
        # with live dispatches (VERDICT r4 #2: boot SLA over grid warm).
        self._warm_thread = self.engine.start_background_warm(stop)
        if self.cfg.snapshot_dir and self.cfg.snapshot_interval_s > 0:
            self.supervisor.spawn(
                "checkpointer",
                lambda: self._checkpoint_loop(stop),
                stop,
                policy_from_config(self.cfg, seed_key="checkpointer"),
            )
        stop.wait()
        self.shutdown()

    def _checkpoint_loop(self, stop: threading.Event) -> None:
        """Periodic state snapshot; the shutdown save is the last line of
        defense, this bounds how much a crash-only recovery can lose."""
        path = f"{self.cfg.snapshot_dir}/sketch_state.npz"
        hb = self.supervisor.register("checkpointer")
        try:
            while True:
                hb.park()
                if stop.wait(self.cfg.snapshot_interval_s):
                    return
                hb.beat()
                if self.engine.degraded:
                    continue  # don't snapshot mid-recovery
                self.engine.save_snapshot_state(path)
        finally:
            self.supervisor.deregister("checkpointer")

    def shutdown(self) -> None:
        self._ready.clear()
        self.pluginmanager.stop()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=3.0)
        if self._warm_thread is not None:
            # stop is set by now, so the warm exits at the next key
            # boundary; joining keeps the shutdown snapshot from queuing
            # behind more than the one in-flight warm compile.
            self._warm_thread.join(timeout=10.0)
        if self.cfg.snapshot_dir:
            from retina_tpu.utils.device_proxy import fence

            # An in-flight warm compile (cold cache: 30-100s on the
            # tunnel) cannot be aborted and would hold the FIFO proxy
            # queue past a k8s termination grace window. The state at
            # that point is minutes of boot traffic — skipping the save
            # (quarantine-equivalent: next boot starts fresh) beats a
            # SIGKILL mid-write.
            if not fence(timeout=15.0):
                self._log.warning(
                    "device proxy busy (warm compile in flight); "
                    "skipping shutdown state snapshot"
                )
            else:
                try:
                    self.engine.save_snapshot_state(
                        f"{self.cfg.snapshot_dir}/sketch_state.npz"
                    )
                except Exception:
                    self._log.exception("shutdown state snapshot failed")
        if self.server is not None:
            self.server.stop()
        self.supervisor.stop()
        self.telemetry.stop()
        self.pubsub.shutdown()
        self._log.info("agent shut down")

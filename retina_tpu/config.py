"""Layered agent configuration.

Reference analog: pkg/config/config.go:59-125 — viper merges a YAML file
with ``RETINA_``-prefixed environment variables into one static ``Config``
struct consumed by the daemon. Same layering here: dataclass defaults ←
YAML file ← ``RETINA_*`` env vars (env wins), via :func:`load_config`.

TPU-specific knobs (batch capacity, window length, mesh shape, pipeline
table sizes) live alongside the reference's flags because in this framework
the "kernel" is the jit-compiled pipeline and its compile-time shape IS
configuration — the analog of the reference injecting config into eBPF via
generated dynamic.h macros (packetparser_linux.go:82-127).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import yaml

# Data aggregation levels (reference pkg/config/config.go:16-23).
AGG_LOW = "low"
AGG_HIGH = "high"

DEFAULT_PLUGINS = ["packetparser", "dropreason", "packetforward", "dns"]


@dataclasses.dataclass
class Config:
    """Static agent configuration (reference Config, config.go:59-77)."""

    # --- reference-parity fields ---
    api_server_addr: str = "127.0.0.1:10093"
    enabled_plugins: list[str] = dataclasses.field(
        default_factory=lambda: list(DEFAULT_PLUGINS)
    )
    metrics_interval_s: float = 10.0  # map-read plugin cadence
    # /metrics render cache TTL (rendering tens of thousands of pod
    # series is Python-heavy; gauges only change at publish cadence, so
    # a sub-interval cache is lossless). 0 = render every scrape.
    metrics_cache_ttl_s: float = 0.5
    enable_telemetry: bool = False
    enable_pod_level: bool = True
    remote_context: bool = False
    enable_annotations: bool = False
    enable_conntrack_metrics: bool = True
    bypass_lookup_ip_of_interest: bool = False
    data_aggregation_level: str = AGG_LOW
    telemetry_interval_s: float = 900.0
    enable_hubble: bool = False  # flow-relay control plane (cmd/hubble)
    hubble_addr: str = "127.0.0.1:4244"
    hubble_ring_capacity: int = 1 << 12
    # Dedicated hubble metrics mux (reference :9965); "" disables.
    hubble_metrics_addr: str = ""
    # TLS for the flow relay (reference hubble TLS options). PEM paths;
    # client CA set => mutual TLS required.
    hubble_tls_cert: str = ""
    hubble_tls_key: str = ""
    hubble_tls_client_ca: str = ""
    # Local-client unix endpoint beside TCP (the reference serves
    # unix:///var/run/cilium/hubble.sock, SURVEY §3.5). "" disables.
    hubble_sock_path: str = ""
    # Static peer list for the peer service: [{"name", "address"}].
    hubble_peers: list = dataclasses.field(default_factory=list)
    node_name: str = ""
    # Identity from a real cluster: core/v1 pods/services/nodes list+watch
    # feeding the cache (pkg/k8s watcher analog). "" = in-process only.
    kubeconfig: str = ""
    kube_namespace: str = ""  # namespace scope for pod/service watches
    # Pod identity source when watching a cluster: "pods" (core/v1) or
    # "cilium" (consume the Cilium CNI's CiliumEndpoints — the
    # cilium-crds interop mode; services/nodes still come from core/v1).
    identity_source: str = "pods"

    # --- multi-host distributed runtime (jax.distributed over DCN;
    # SURVEY.md §5.8: cross-slice merges ride the distributed runtime
    # while intra-slice psum rides ICI). "" = single-process. ---
    distributed_coordinator: str = ""  # "host:port" of process 0
    distributed_num_processes: int = 1
    distributed_process_id: int = 0
    log_level: str = "info"
    log_file: str = ""  # empty = stderr only

    # --- event source (the kernel-hook analog; SURVEY.md §7 mapping) ---
    event_source: str = "synthetic"  # synthetic | pcap | live | external
    pcap_path: str = ""  # replay file for event_source=pcap
    pcap_loop: bool = True  # loop the replay
    synthetic_rate: float = 1e6  # target events/s for the generator
    synthetic_flows: int = 100_000
    # Pre-generate this many 8192-event blocks at compile() and cycle
    # them in the feed loop (0 = generate live). Keeps the numpy
    # generator out of the hot loop for max-rate benchmarking — the
    # trafficgen-replay analog.
    synthetic_pregen: int = 0
    # Generator regime preset (events/synthetic.py PRESETS): "default"
    # keeps the generator's own parameters; "zipf" is the heavy-tail
    # regime (steeper Zipf exponent, fewer dominating flows — the
    # PSketch-style skew the detector/attribution arc is validated
    # against); "uniform" flattens the flow-size distribution (the
    # worst case for top-k recall).
    gen_preset: str = "default"
    capture_iface: str = ""  # live AF_PACKET interface ("" = default)
    external_socket: str = "/tmp/retina-events.sock"  # external feed
    # Cilium agent monitor socket (gob payload stream) for the
    # ciliumeventobserver plugin (reference config.go MonitorSockPath).
    monitor_sock_path: str = "/var/run/cilium/monitor1_2.sock"
    # pktmon plugin (Windows): stream-server command + its socket. ""
    # command = the platform default (controller-pktmon.exe).
    pktmon_command: str = ""
    pktmon_socket: str = ""

    # --- TPU runtime knobs ---
    device_platform: str = ""  # "" = let JAX pick; "cpu" to force host
    # Persistent XLA compilation cache: full-shape pipeline compile is
    # ~100 s on TPU; caching it makes agent restarts (and the <1 s scrape
    # SLA after restart) feasible. "" disables (default: opt in via the
    # deploy configmap — DEFAULT_CACHE_DIR — so bare library/test use
    # never touches global host state).
    compilation_cache_dir: str = ""
    batch_capacity: int = 1 << 15  # events per device batch
    window_seconds: float = 1.0  # entropy/anomaly window
    # Host-side batching latency when the dispatch pipeline is IDLE: a
    # lightly-loaded agent flushes small batches at this cadence for
    # low metric latency.
    flush_interval_s: float = 0.05
    # Under load (dispatches in flight) the feed keeps accumulating past
    # flush_interval_s — bigger quanta raise the combine ratio and
    # amortize per-flush fixed costs — but never beyond this age. Must
    # stay below the metrics publish interval (1s) or scrapes lag.
    flush_max_age_s: float = 0.4
    mesh_devices: int = 0  # 0 = all local devices
    # Host-side RLE combining before the host->device transfer (the eBPF
    # map pre-aggregation analog, parallel/combine.py). Lossless; off only
    # for debugging raw row flow.
    host_combine: bool = True
    # Worker threads for the native combiner (combine.cpp
    # rt_combine_mt): per-thread partial combines + one small merge.
    # 0 = auto (RETINA_COMBINE_THREADS env, else cores-1 capped at 4 —
    # 1 on single-core hosts, i.e. the single-threaded pass).
    host_combine_threads: int = 0
    # Depth of the in-flight transfer queue between the batcher thread and
    # the device dispatch thread (engine.py), and the bound on concurrent
    # fire-and-forget device submissions (transfers queued back-to-back on
    # the device proxy so the host->device link never idles between
    # dispatch round-trips). 0 = synchronous dispatch on the feed thread
    # (no overlap).
    feed_pipeline_depth: int = 3
    # Sharded multi-worker host feed (parallel/feed.py): N feed workers
    # each own a staging buffer, combine+partition their quantum in
    # parallel (the native combiner releases the GIL), and hand
    # finished batches to the single dispatch thread through a
    # double-buffered transfer queue. 0 = auto (cores-1 capped at 4);
    # values <= 1 keep the inline single-thread feed — a pool of one
    # adds a handoff without adding a core. Requires
    # feed_pipeline_depth > 0 (the sync path has no dispatch thread to
    # hand off to).
    feed_workers: int = 0
    # Per-worker staging bound, in raw sink blocks. A block that finds
    # every worker's staging full is dropped + counted (lost_events
    # stage="handoff") — backpressure never blocks the distributor.
    # Sized so one worker can stage a FULL flush quantum even from
    # small sink blocks (quantum / typical-block-rows with headroom):
    # at 256 the staging ring capped quanta at ~18% fill under
    # sustained load (BENCH_r05 staging fill 0.184) — flushes were
    # capacity-cut, not age-cut, and every fixed per-flush cost was
    # paid 5x too often. Memory bound: blocks are staged by reference
    # (the sink's arrays, no copy), so the bound is backlog, not
    # allocation.
    feed_staging_blocks: int = 1024
    # Background bucket-grid warm proxy duty cycle: after each warmed
    # key the warm thread yields cost*(1-d)/d seconds (capped at 10s)
    # to live traffic. 0.5 = equal yield (~50% proxy share, the
    # historical behavior); raise toward 1.0 to finish the warm faster
    # at the cost of feed throughput while it runs.
    warm_duty_cycle: float = 0.5
    # Max windows of batch_capacity coalesced into ONE host->device
    # transfer when a flush quantum combines to more than one device
    # batch: the wire crosses the link once and is sliced into
    # batch_capacity-sized step inputs on device. Amortizes per-transfer
    # round-trip latency (dominant on high-RTT links; one RTT per flush
    # instead of one per device batch).
    feed_coalesce_windows: int = 4
    # Smallest power-of-two host->device transfer shape: batches cross the
    # link at their own (bucketed) size and are padded to batch_capacity
    # on device, where HBM bandwidth makes padding free (engine pad jit).
    transfer_min_bucket: int = 1 << 12
    # 12-lane packed wire format (parallel/wire.py) instead of the 16-lane
    # schema layout; unpacked on device. Off only for debugging.
    transfer_packed: bool = True
    # v2/v3 wire: device-resident flow-descriptor dictionary. Each
    # distinct combined-flow descriptor crosses the link ONCE (12 lanes
    # + id); every later occurrence crosses as an 8-byte
    # [id | packets << id_bits, bytes] pair and the descriptor lanes are
    # gathered back from HBM (parallel/flowdict.py + engine ingest).
    # Steady-state wire bytes/event drop ~6x on long-lived flows.
    # Requires transfer_packed.
    wire_flow_dict: bool = True
    # v4 wire: pack known-flow rows as a DENSE bitstream —
    # (id_bits + 10 + 22) contiguous bits per row (parallel/wire.py
    # dense layer) instead of two full u32 lanes: 6.25 B/row at the
    # default 18-bit id space vs 8. Rows whose PACKETS/BYTES overflow
    # the narrow lanes escalate to the full-row side (same contract as
    # the v3 packet-overflow escalation). Off = v3 two-lane rows, for
    # debugging/bisection only.
    wire_dense_known: bool = True
    # Device descriptor-table slots (48 B/slot/device). Must exceed the
    # live distinct-descriptor count or the dictionary cycles
    # (generation clear -> one re-upload burst).
    flow_dict_slots: int = 1 << 18
    # Under sustained load, accumulate up to this many events per
    # combine+flush quantum (bigger quanta raise the combine ratio — more
    # duplicate descriptors per pass — at bounded added latency). The
    # flush_interval_s timeout still bounds latency at low rates.
    flush_max_events: int = 1 << 21
    snapshot_dir: str = ""  # sketch-state checkpoint dir ("" = off)
    snapshot_interval_s: float = 0.0  # 0 = only on shutdown

    # --- supervised runtime (runtime/supervisor.py) ---
    # A registered thread that neither beats nor parks for this long is
    # a stall: counted in watchdog_stalls and escalated (hung harvest
    # threads get replaced). Also the default bound on blocking fences
    # in the crash-only recovery path.
    watchdog_deadline_s: float = 30.0
    # Watchdog scan cadence.
    watchdog_interval_s: float = 0.5
    # Shutdown drain bound for the final harvest queue flush (was a
    # hard-coded 30.0 in engine._harvest_window).
    harvest_timeout_s: float = 30.0
    # Restart policy: exponential backoff base/cap with multiplicative
    # jitter; after restart_max_failures consecutive crashes inside
    # restart_window_s the circuit OPENS (the plugin/thread stops being
    # restarted and /healthz goes unhealthy) and half-open probes run
    # every circuit_half_open_s until one stays healthy.
    restart_backoff_base_s: float = 0.2
    restart_backoff_max_s: float = 30.0
    restart_backoff_jitter: float = 0.2
    restart_max_failures: int = 5
    restart_window_s: float = 60.0
    circuit_half_open_s: float = 30.0
    # Deterministic fault injection (runtime/faults.py), e.g.
    # "transfer:raise@3,plugin.packetparser:raise@1". Empty = disarmed.
    # Settable via RETINA_FAULT_SPEC for chaos drills against a
    # deployed agent.
    fault_spec: str = ""

    # --- adaptive overload control (runtime/overload.py) ---
    # NOMINAL -> SAMPLING -> SHEDDING -> DEGRADED driven by the max of
    # the normalized pressure signals (worker staging fill, dispatch
    # in-flight fill, handoff wait rate, harvest lag).
    overload_enabled: bool = True
    # Controller cadence; the feed loop calls tick() at least this often.
    overload_tick_s: float = 0.1
    # 1-in-k row sampling applied by feed workers in SAMPLING and above;
    # the device step rescales surviving non-exempt rows by k so every
    # packet-weighted estimate stays unbiased (Horvitz-Thompson).
    overload_sample_k: int = 8
    # Combined rows with at least this packet weight are heavy-hitter
    # candidates: exempt from sampling on the host AND from rescaling on
    # the device (the predicates must agree — both read F.PACKETS of
    # the post-combine row). 0 exempts everything (sampling disabled).
    overload_exempt_packets: int = 64
    # Hysteresis thresholds on the [0, 1] pressure scale. Escalation is
    # immediate at enter/shed/degrade; de-escalation needs pressure at
    # or below exit continuously for dwell_s (one level per dwell).
    overload_enter_pressure: float = 0.75
    overload_exit_pressure: float = 0.45
    overload_shed_pressure: float = 0.90
    overload_degrade_pressure: float = 0.98
    overload_dwell_s: float = 2.0
    # In SHEDDING the shed set widens one stage per this many seconds
    # of sustained at-or-above-shed pressure.
    overload_shed_escalate_s: float = 1.0
    # Enrichment shed order (cheapest-to-lose first); a prefix is shed
    # before ANY raw event is dropped. Stages: dns (qname hashing),
    # conntrack (accounting/GC scrape), labels (per-pod resolution).
    overload_shed_order: list[str] = dataclasses.field(
        default_factory=lambda: ["dns", "conntrack", "labels"]
    )
    # Priority-tier lattice (runtime/overload.py row_tiers): rows whose
    # src OR dst IP matches (ip & mask) == match form the per-(tenant,
    # service) priority class — exempt from sampling, and routed into
    # the invertible sketch's full-accuracy high-priority region.
    # mask 0 disables the class.
    overload_priority_ip_mask: int = 0
    overload_priority_ip_match: int = 0

    # --- invertible sketch (ops/invertible.py; heavy-key recovery) ---
    # Where heavy-flow KEYS come from:
    #   flowdict   — host flow-descriptor dictionary (the historical
    #                path; serialized, unbounded-memory)
    #   invertible — decode keys from device sketch state at window
    #                close; the flow dict leaves the hot path entirely
    #   both       — run both, report recovery recall/precision as
    #                metrics (the migration validation mode)
    heavy_keys_source: str = "flowdict"
    # Sketch shape: D hash rows x W buckets x 160 bit planes (u32), per
    # region. Update cost scales with D*B per row; decode with D*W*B.
    invertible_depth: int = 2
    invertible_width: int = 1 << 12
    # High-priority region width (receives only priority-class rows —
    # small because the priority class is small by construction).
    invertible_hi_width: int = 1 << 9
    # Decoded keys with a CMS estimate under this weight are rejected
    # (noise floor for the recovered-key set).
    invertible_min_weight: int = 0

    # --- AOT executable disk cache (parallel/telemetry.py AotProgram) ---
    # Persist AOT-compiled step/end-window executables keyed by (jax
    # version, topology, config signature) so bucket-grid warm survives
    # process restarts. "" disables (bench/deploy opt in).
    aot_cache_dir: str = ""

    # --- fleet rollup tier (fleet/) ---
    # Node side: ship the window-close sketch export over the relay.
    fleet_enabled: bool = False
    # Operator side: run the FleetAggregator (epoch-aligned merge +
    # fleet_* metric families). Both may be on in one process (the
    # in-process pubsub transport loops back).
    fleet_aggregator: bool = False
    fleet_node_name: str = ""  # wire identity ("" = node_name or pid)
    fleet_tenant: str = "default"
    # Higher priority tenants are shed LAST by the cardinality
    # guardrails (PSketch-style priority awareness).
    fleet_priority: int = 0
    # gRPC Ship target ("host:port"); "" ships over the in-process bus.
    fleet_relay_addr: str = ""
    # Close an epoch as soon as this many nodes reported; 0 = close on
    # the straggler timeout only.
    fleet_expected_nodes: int = 0
    # Epoch close deadline measured from the FIRST arrival — a dead
    # node delays the rollup at most this long, never forever.
    fleet_straggler_timeout_s: float = 2.0
    # Max open (unclosed) epochs buffered before the oldest is
    # force-closed: bounds aggregator memory under clock skew.
    fleet_epoch_history: int = 8
    # Node-side ship queue depth; a full queue drops the snapshot
    # (never blocks the window close).
    fleet_ship_queue: int = 4
    # Under SHEDDING and above, ship only 1 window in this many.
    fleet_shed_ship_every: int = 4
    # Node-side seed generation stamped on shipped frames; bump it when
    # rotating sketch seeds so the aggregator and fleet query plane
    # re-admit the node under the new generation instead of
    # quarantining it forever (fleet/codec.py "sgen" header field).
    fleet_seed_generation: int = 0
    # Send-failure spool: frames held in memory while the relay is
    # unreachable, replayed oldest-first on heal; the oldest frame is
    # evicted (and counted) when full. 0 disables spooling and restores
    # drop-on-error (still counted, never silent).
    fleet_ship_spool: int = 64
    # Jittered exponential backoff between send retries while the ship
    # circuit is open: delay is uniform in [base/2, min(max, base*2^n)].
    fleet_ship_backoff_base_s: float = 0.05
    fleet_ship_backoff_max_s: float = 2.0
    # Two-level rollup: re-ship each merged epoch as a valid RFLT
    # snapshot to a parent aggregator's relay at this address (the
    # zone -> root hop). "" disables — this aggregator is the root.
    fleet_reship_addr: str = ""
    # Defer quorum-closed epoch merges to the aggregator's poll thread
    # instead of running them inline on the ingest (gRPC handler)
    # thread. Keeps ingest latency flat through merge jit compiles —
    # otherwise the quorum-completing node's ship RPC pays the whole
    # merge and can blow its deadline, pushing that node into
    # spool/backoff every epoch. Off by default: inline merges publish
    # the rollup before ingest returns, which synchronous callers
    # (tests, co-located daemons) rely on.
    fleet_merge_async: bool = False
    fleet_topk_k: int = 32  # cluster-wide heavy-hitter series cap
    fleet_service_top: int = 16  # per-service cardinality series cap
    # Per-tenant exported-series cap (the label-space guardrail).
    fleet_tenant_series_max: int = 64
    # Max tenants exported per epoch; lowest-priority shed first.
    fleet_max_tenants: int = 16

    # --- time-travel query ring (timetravel/) ---
    # Retain the last N window-close sketch exports in a bounded ring
    # and serve [t0, t1) range queries over them (one jitted
    # semilattice fold). Off by default: the ring holds ~N x the
    # fleet-export footprint in host memory.
    timetravel_enabled: bool = False
    timetravel_ring_windows: int = 32  # ring capacity (slots)
    # Range-query result cache TTL; concurrent/overlapping queries are
    # served from cache so at most one fold runs at a time (the p99
    # bound). Under SHEDDING the TTL is ignored (serve stale freely).
    timetravel_query_cache_ttl_s: float = 1.0
    timetravel_query_topk: int = 32  # default k for /timetravel/query

    # --- closed-loop capture (timetravel/autocapture.py) ---
    # When the entropy burst detector fires, pivot the query ring to
    # the burst range, attribute sources via invertible decode, and
    # record a targeted capture of only the attributed keys. Needs
    # timetravel_enabled + enable_invertible for attribution.
    autocapture_enabled: bool = False
    autocapture_cooldown_s: float = 60.0  # min spacing between captures
    # Query range around burst window W: [W - lookback, W + lookahead].
    autocapture_lookback_windows: int = 2
    autocapture_lookahead_windows: int = 1
    autocapture_max_sources: int = 8  # top attributed src IPs captured
    autocapture_duration_s: float = 2.0  # capture recording window
    autocapture_max_size_mb: int = 8  # evidence bound: a few MB
    # Artifact sink directory (capture host_path output).
    autocapture_output_dir: str = "/tmp/retina-autocapture"

    # --- fleet query plane (fleetquery/) ---
    # Federated [t0, t1) range queries: GET /fleet/query scatter-gathers
    # per-node ring slots (or folds the aggregator's epoch ring) into
    # cluster-wide answers, with the node tier's bounded-latency
    # contract plus per-node deadline / hedged retry / partial coverage.
    fleetquery_enabled: bool = False
    fleetquery_node_deadline_s: float = 0.25  # per-node answer budget
    # After this long with nodes still unanswered, send ONE hedged
    # duplicate request per straggler (tail ≠ dead).
    fleetquery_hedge_delay_s: float = 0.05
    fleetquery_fanout: int = 16  # scatter pool concurrency bound
    fleetquery_cache_ttl_s: float = 1.0  # fleet result cache TTL
    fleetquery_topk: int = 32  # default k for /fleet/query

    # --- pluggable detector bank (detect/) ---
    # Derived device-program detectors (port-scan HLL, DNS-tunnel qname
    # entropy, SYN-flood asymmetry) over the engine's record tap; the
    # per-window winner (priority arbitration + cooldown) feeds the
    # same AutoCapture sink as the entropy detector.
    detectors_enabled: bool = False
    detector_cooldown_s: float = 60.0  # per-detector min firing spacing
    detector_z_thresh: float = 8.0  # adaptive (EWMA z-flag) threshold
    detector_min_windows: int = 3  # EWMA warmup before z-flags count

    # --- flight recorder + on-demand profiling (obs/) ---
    # Always-on span recorder over every pipeline stage
    # (docs/observability.md). Off only for A/B overhead measurement —
    # the recorder is the instrument every perf PR reads.
    trace_enabled: bool = True
    # Record 1 span in this many per thread (hot-path sampling gate).
    # Spans are per-flush/per-window cadence, so 1 (record everything)
    # is affordable; raise on very hot deployments.
    trace_sample_every: int = 1
    # Per-thread span ring capacity (preallocated slots).
    trace_ring_spans: int = 4096
    # POST /debug/profile: jax.profiler session + all-thread stack
    # dump artifacts land under this dir, newest profile_max_artifacts
    # session dirs kept.
    profile_artifact_dir: str = "/tmp/retina-profile"
    profile_max_seconds: float = 10.0  # per-session trace length cap
    profile_cooldown_s: float = 30.0  # min spacing between sessions
    profile_max_artifacts: int = 4

    # --- endurance soak harness (soak/; bench.py --soak) ---
    # Total soak wall clock for the default rotating schedule of
    # heavy-tail regimes + injected faults (docs/operations.md §9).
    soak_seconds: float = 1800.0
    # Per-phase duration; 0 = divide soak_seconds evenly over the
    # default schedule's phases.
    soak_phase_seconds: float = 0.0
    # After a phase's fault spec is cleared, the overload controller
    # must report NOMINAL within this bound (the no-latch-up
    # sentinel; recovery_seconds in the SOAK artifact).
    soak_recovery_deadline_s: float = 30.0
    # Post-warmup RSS leak gate: least-squares slope of the sampled
    # RSS series must stay under this (MB per minute).
    soak_rss_slope_mb_per_min: float = 5.0
    # Flow-descriptor dictionary generation bumps tolerated per phase
    # (the churn regimes cycle the table by design — but boundedly).
    soak_fd_generations_per_phase: int = 8
    # SOAK_*.json scorecard artifact directory.
    soak_artifact_dir: str = "/tmp/retina-soak"

    # --- pipeline shapes (jit keys; see models/pipeline.py) ---
    n_pods: int = 1 << 12
    cms_width: int = 1 << 15
    cms_depth: int = 4
    topk_slots: int = 1 << 11
    hll_precision: int = 12
    entropy_buckets: int = 1 << 12
    conntrack_slots: int = 1 << 18
    identity_slots: int = 1 << 16

    def validate(self) -> None:
        if self.identity_source not in ("pods", "cilium"):
            raise ValueError(
                f"identity_source must be 'pods' or 'cilium', "
                f"got {self.identity_source!r}"
            )
        if self.data_aggregation_level not in (AGG_LOW, AGG_HIGH):
            raise ValueError(
                f"dataAggregationLevel must be {AGG_LOW!r} or {AGG_HIGH!r}, "
                f"got {self.data_aggregation_level!r}"
            )
        if not (0.0 < self.warm_duty_cycle <= 1.0):
            raise ValueError(
                f"warm_duty_cycle must be in (0, 1], "
                f"got {self.warm_duty_cycle}"
            )
        for f in ("watchdog_deadline_s", "watchdog_interval_s",
                  "harvest_timeout_s", "restart_backoff_base_s",
                  "restart_backoff_max_s", "restart_window_s",
                  "circuit_half_open_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"{f} must be > 0, got {getattr(self, f)}")
        if self.restart_max_failures < 1:
            raise ValueError(
                f"restart_max_failures must be >= 1, "
                f"got {self.restart_max_failures}"
            )
        if self.restart_backoff_jitter < 0:
            raise ValueError(
                f"restart_backoff_jitter must be >= 0, "
                f"got {self.restart_backoff_jitter}"
            )
        if self.fault_spec:
            # Fail at config load, not mid-flight in a hot-path hook:
            # faults.configure re-parses the same grammar when the
            # daemon arms it, so a parse-only dry run here is cheap.
            import re as _re

            # Keep this pattern in sync with faults._ENTRY.
            for raw in self.fault_spec.split(","):
                raw = raw.strip()
                if raw and not _re.match(
                    r"^[\w.\-]+:(raise|corrupt|hang(\d+(\.\d+)?)?"
                    r"|press(\d+(\.\d+)?)?)(@\d+)?$",
                    raw,
                ):
                    raise ValueError(f"bad fault_spec entry {raw!r}")
        for f in ("batch_capacity", "n_pods", "cms_width", "topk_slots",
                  "entropy_buckets", "conntrack_slots", "identity_slots"):
            v = getattr(self, f)
            if v <= 0 or (v & (v - 1)):
                raise ValueError(f"{f} must be a positive power of two, got {v}")
        if self.overload_sample_k < 1:
            raise ValueError(
                f"overload_sample_k must be >= 1, "
                f"got {self.overload_sample_k}"
            )
        if self.overload_exempt_packets < 0:
            raise ValueError(
                f"overload_exempt_packets must be >= 0, "
                f"got {self.overload_exempt_packets}"
            )
        thresholds = (
            self.overload_exit_pressure, self.overload_enter_pressure,
            self.overload_shed_pressure, self.overload_degrade_pressure,
        )
        if not all(0.0 < t <= 1.0 for t in thresholds) or any(
            a >= b for a, b in zip(thresholds, thresholds[1:])
        ):
            raise ValueError(
                "overload thresholds must satisfy 0 < exit < enter < "
                f"shed < degrade <= 1, got {thresholds}"
            )
        for f in ("overload_tick_s", "overload_dwell_s",
                  "overload_shed_escalate_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"{f} must be > 0, got {getattr(self, f)}")
        from retina_tpu.runtime.overload import validate_shed_order

        validate_shed_order(self.overload_shed_order)
        if self.fleet_straggler_timeout_s <= 0:
            raise ValueError(
                f"fleet_straggler_timeout_s must be > 0, "
                f"got {self.fleet_straggler_timeout_s}"
            )
        for f in ("fleet_epoch_history", "fleet_ship_queue",
                  "fleet_shed_ship_every", "fleet_topk_k",
                  "fleet_service_top", "fleet_tenant_series_max"):
            if getattr(self, f) < 1:
                raise ValueError(
                    f"{f} must be >= 1, got {getattr(self, f)}"
                )
        for f in ("fleet_expected_nodes", "fleet_max_tenants",
                  "fleet_seed_generation", "fleet_ship_spool"):
            if getattr(self, f) < 0:
                raise ValueError(
                    f"{f} must be >= 0, got {getattr(self, f)}"
                )
        if self.fleet_ship_backoff_base_s <= 0:
            raise ValueError(
                f"fleet_ship_backoff_base_s must be > 0, "
                f"got {self.fleet_ship_backoff_base_s}"
            )
        if self.fleet_ship_backoff_max_s < self.fleet_ship_backoff_base_s:
            raise ValueError(
                "fleet_ship_backoff_max_s must be >= "
                f"fleet_ship_backoff_base_s, got "
                f"{self.fleet_ship_backoff_max_s}"
            )
        # Single source of truth for legal preset names: the PRESETS
        # table in events/synthetic.py (a name added there is legal
        # here automatically — no hand-maintained copy to drift, the
        # RT230 philosophy). Local import like validate_shed_order
        # above: synthetic pulls numpy at module load, which config
        # must not do for bare Config() construction.
        from retina_tpu.events.synthetic import PRESETS as _gen_presets

        if self.gen_preset not in _gen_presets:
            raise ValueError(
                f"gen_preset must be one of {sorted(_gen_presets)}, "
                f"got {self.gen_preset!r}"
            )
        for f in ("timetravel_ring_windows", "timetravel_query_topk",
                  "autocapture_max_sources", "autocapture_max_size_mb"):
            if getattr(self, f) < 1:
                raise ValueError(
                    f"{f} must be >= 1, got {getattr(self, f)}"
                )
        for f in ("timetravel_query_cache_ttl_s",
                  "autocapture_cooldown_s",
                  "autocapture_lookback_windows",
                  "autocapture_lookahead_windows"):
            if getattr(self, f) < 0:
                raise ValueError(
                    f"{f} must be >= 0, got {getattr(self, f)}"
                )
        for f in ("fleetquery_fanout", "fleetquery_topk"):
            if getattr(self, f) < 1:
                raise ValueError(
                    f"{f} must be >= 1, got {getattr(self, f)}"
                )
        if self.fleetquery_node_deadline_s <= 0:
            raise ValueError(
                f"fleetquery_node_deadline_s must be > 0, "
                f"got {self.fleetquery_node_deadline_s}"
            )
        for f in ("fleetquery_hedge_delay_s", "fleetquery_cache_ttl_s",
                  "detector_cooldown_s"):
            if getattr(self, f) < 0:
                raise ValueError(
                    f"{f} must be >= 0, got {getattr(self, f)}"
                )
        if self.detector_z_thresh <= 0:
            raise ValueError(
                f"detector_z_thresh must be > 0, "
                f"got {self.detector_z_thresh}"
            )
        if self.detector_min_windows < 1:
            raise ValueError(
                f"detector_min_windows must be >= 1, "
                f"got {self.detector_min_windows}"
            )
        if self.autocapture_duration_s <= 0:
            raise ValueError(
                f"autocapture_duration_s must be > 0, "
                f"got {self.autocapture_duration_s}"
            )
        if self.heavy_keys_source not in ("flowdict", "invertible", "both"):
            raise ValueError(
                "heavy_keys_source must be 'flowdict', 'invertible' or "
                f"'both', got {self.heavy_keys_source!r}"
            )
        if self.heavy_keys_source == "both" and not (
            self.transfer_packed and self.wire_flow_dict
        ):
            raise ValueError(
                "heavy_keys_source='both' validates the invertible decode "
                "against the flow dict, which requires transfer_packed "
                "and wire_flow_dict"
            )
        for f in ("invertible_width", "invertible_hi_width"):
            v = getattr(self, f)
            if v <= 0 or (v & (v - 1)):
                raise ValueError(
                    f"{f} must be a positive power of two, got {v}"
                )
        if self.invertible_depth < 1:
            raise ValueError(
                f"invertible_depth must be >= 1, got {self.invertible_depth}"
            )
        if self.invertible_min_weight < 0:
            raise ValueError(
                f"invertible_min_weight must be >= 0, "
                f"got {self.invertible_min_weight}"
            )
        for f in ("soak_seconds", "soak_recovery_deadline_s",
                  "soak_rss_slope_mb_per_min"):
            if getattr(self, f) <= 0:
                raise ValueError(
                    f"{f} must be > 0, got {getattr(self, f)}"
                )
        if self.soak_phase_seconds < 0:
            raise ValueError(
                f"soak_phase_seconds must be >= 0, "
                f"got {self.soak_phase_seconds}"
            )
        if self.soak_fd_generations_per_phase < 1:
            raise ValueError(
                f"soak_fd_generations_per_phase must be >= 1, "
                f"got {self.soak_fd_generations_per_phase}"
            )
        for f in ("trace_sample_every", "trace_ring_spans",
                  "profile_max_artifacts"):
            if getattr(self, f) < 1:
                raise ValueError(
                    f"{f} must be >= 1, got {getattr(self, f)}"
                )
        if self.profile_max_seconds <= 0:
            raise ValueError(
                f"profile_max_seconds must be > 0, "
                f"got {self.profile_max_seconds}"
            )
        if self.profile_cooldown_s < 0:
            raise ValueError(
                f"profile_cooldown_s must be >= 0, "
                f"got {self.profile_cooldown_s}"
            )
        for f in ("overload_priority_ip_mask", "overload_priority_ip_match"):
            v = getattr(self, f)
            if not (0 <= v <= 0xFFFFFFFF):
                raise ValueError(f"{f} must fit in u32, got {v}")


_BOOL_TRUE = {"1", "true", "yes", "on"}


def _coerce(value: str, target_type: Any) -> Any:
    if target_type is bool:
        return value.strip().lower() in _BOOL_TRUE
    if target_type is int:
        return int(value, 0)
    if target_type is float:
        return float(value)
    if target_type is list or target_type == list[str]:
        return [p.strip() for p in value.split(",") if p.strip()]
    return value


# YAML keys accepted in camelCase (reference configmap style) or snake_case.
def _normalize_key(key: str) -> str:
    out = []
    for ch in key:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out).lstrip("_")


_ALIASES = {
    "enabled_plugin": "enabled_plugins",
    "enabled_plugin_linux": "enabled_plugins",
    "metrics_interval_duration": "metrics_interval_s",
    "telemetry_interval": "telemetry_interval_s",
}


def load_config(
    path: str | None = None,
    overrides: dict[str, Any] | None = None,
    env: dict[str, str] | None = None,
) -> Config:
    """YAML file ← RETINA_* env ← explicit overrides (later wins)."""
    cfg = Config()
    fields = {f.name: f for f in dataclasses.fields(Config)}

    def apply(key: str, raw: Any, from_env: bool) -> None:
        key = _ALIASES.get(_normalize_key(key), _normalize_key(key))
        if key not in fields:
            return  # unknown keys ignored, like viper
        f = fields[key]
        ftype = f.type if not isinstance(f.type, str) else {
            "str": str, "int": int, "float": float, "bool": bool,
            "list[str]": list,
        }.get(f.type, str)
        if from_env or isinstance(raw, str) and ftype is not str:
            raw = _coerce(str(raw), ftype)
        setattr(cfg, key, raw)

    if path:
        with open(path) as fh:
            doc = yaml.safe_load(fh) or {}
        if not isinstance(doc, dict):
            raise ValueError(f"config file {path} must be a YAML mapping")
        for k, v in doc.items():
            apply(k, v, from_env=False)

    env = dict(os.environ if env is None else env)
    for k, v in env.items():
        if k.startswith("RETINA_"):
            apply(k[len("RETINA_"):].lower(), v, from_env=True)

    for k, v in (overrides or {}).items():
        apply(k, v, from_env=False)

    cfg.validate()
    return cfg


# Where the deploy manifests point compilation_cache_dir on a node.
DEFAULT_CACHE_DIR = "/var/cache/retina-tpu/xla"


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True if enabled. Failure (unwritable dir, old jax) is
    non-fatal but logged: the agent still boots, restarts just pay the
    full compile again. JAX's default min-compile-time/size thresholds
    are kept — the target is the ~100 s fused-step compile, and the
    thresholds stop trivial compiles from growing the dir unboundedly.
    """
    if not cache_dir:
        return False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        return True
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        from retina_tpu.log import logger

        logger("config").warning(
            "compilation cache at %s unavailable (%s: %s); "
            "restarts will pay full XLA compile",
            cache_dir, type(e).__name__, e,
        )
        return False

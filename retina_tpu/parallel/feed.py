"""Sharded multi-worker host feed: staging, combine/partition workers,
and the double-buffered transfer handoff to the dispatch thread.

The reference agent parallelizes its ingest the same way the kernel
does — per-CPU perf rings drained by independent readers
(packetparser_linux.go:556-652). Here the engine's feed loop (the
*distributor*) drains the plugin sink and deals raw record blocks
round-robin across N :class:`FeedWorker` threads. Each worker owns a
private staging deque, accumulates a flush quantum, and runs the
CPU-heavy half of a flush — combine + partition — off the distributor
thread (the native combiner releases the GIL, so workers overlap on
real cores). Finished :class:`~retina_tpu.parallel.partition.ShardedBatch`
items hand off to the single dispatch thread through a
:class:`TransferQueue`: a depth-2 (double-buffered) SPSC deque — one
batch in flight on the dispatch side while the next is fully built —
with no lock on the hot path (CPython deque append/popleft are atomic;
events only park a side that has nothing to do).

What does NOT move off the dispatch thread: flow-dict assignment, wire
build, and the proxy submission. The v3 wire ordering contract (a new
descriptor row must reach the device table before any known row
references its slot — engine._dispatch_flowdict) requires ONE
serialization point, and the dispatch thread is it.

Backpressure contract (same as everywhere else in the tree): never
block a producer. A block that finds every worker's staging full is
dropped and counted (per-worker drop counters + the lost_events
``handoff`` stage); a worker whose handoff queue stays full because the
dispatch thread died drops the finished batch through the pool's
``drop`` callback, which counts it exactly like the inline feed's
dead-worker path.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from retina_tpu.log import logger

_log = logger("feed")

# Handoff queue depth: double buffering. One batch being consumed, one
# built and waiting. Deeper queues only add host memory and latency —
# the engine's _inflight semaphore already bounds device-side overlap.
TRANSFER_DEPTH = 2


class TransferQueue:
    """Bounded SPSC handoff (producer: one feed worker; consumer: the
    dispatch thread via :class:`TransferMux`). append/popleft are the
    only hot-path operations; the events are parking lots, not locks."""

    __slots__ = ("q", "depth", "space", "data", "wait_s")

    def __init__(self, depth: int, data: threading.Event):
        self.q: deque = deque()
        self.depth = depth
        self.space = threading.Event()
        self.data = data  # shared with the mux: any producer wakes it
        self.wait_s = 0.0  # producer-side seconds spent waiting for space

    def put(self, item: Any, alive: Optional[Callable[[], bool]] = None,
            ) -> bool:
        """Enqueue, waiting for a free slot. Returns False (item NOT
        enqueued) once ``alive`` goes falsy — the consumer died and the
        caller must drop + count instead of wedging forever."""
        t0 = None
        while len(self.q) >= self.depth:
            if alive is not None and not alive():
                if t0 is not None:
                    self.wait_s += time.monotonic() - t0
                return False
            if t0 is None:
                t0 = time.monotonic()
            # Timeout bounds the one benign race (consumer sets space
            # between our len check and wait).
            self.space.wait(0.02)
            self.space.clear()
        if t0 is not None:
            self.wait_s += time.monotonic() - t0
        self.q.append(item)  # noqa: RT402 — bounded: the loop above spins until len(q) < depth; consumer poplefts via TransferMux.get
        self.data.set()
        return True


class TransferMux:
    """Single-consumer fan-in over every worker's TransferQueue plus a
    control lane (window ticks, shutdown sentinel). Drop-in for the
    inline feed's queue.Queue in engine._dispatch_loop: ``get()``
    blocks and returns items; ``None`` means shut down.

    The control lane has priority — window closes stay on cadence even
    under a step backlog. A close overtaking batches still staged in
    the workers just shifts those events into the next window, exactly
    as if they were still in the sink. The shutdown sentinel is the one
    exception: it is delivered only after EVERY worker queue has
    drained (workers are joined before the sentinel is enqueued, so
    their queues are strictly draining by then)."""

    def __init__(self, queues: list[TransferQueue], data: threading.Event):
        self._qs = queues
        self._ctl: deque = deque()
        self._data = data
        self._rr = 0

    def put_ctl(self, item: Any) -> None:
        self._ctl.append(item)
        self._data.set()

    def get(self, timeout: float | None = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._ctl and self._ctl[0] is not None:
                return self._ctl.popleft()
            draining = bool(self._ctl)  # head is the None sentinel
            n = len(self._qs)
            for k in range(n):
                tq = self._qs[(self._rr + k) % n]
                try:
                    item = tq.q.popleft()
                except IndexError:
                    continue
                tq.space.set()
                self._rr = (self._rr + k + 1) % n
                return item
            if draining:
                return self._ctl.popleft()
            if deadline is not None and time.monotonic() >= deadline:
                raise queue_mod.Empty
            self._data.wait(0.002)
            self._data.clear()


class FeedWorker(threading.Thread):
    """One ingest shard: staging deque -> quantum flush -> handoff.

    Counter discipline (lock-free accounting): ``*_in`` fields are
    written only by the distributor, ``*_out`` only by this worker —
    both monotonic, so ``pending = in - out`` is always consistent
    without a lock (a torn read can only be momentarily stale)."""

    def __init__(self, idx: int, pool: "FeedWorkerPool",
                 data: threading.Event):
        super().__init__(name=f"feed-worker-{idx}", daemon=True)
        self.idx = idx
        self.pool = pool
        self.staging: deque = deque()
        self.outq = TransferQueue(pool.depth, data)
        self.wake = threading.Event()
        self.events_in = 0       # distributor-only
        self.blocks_in = 0       # distributor-only
        self.events_out = 0      # worker-only
        self.blocks_out = 0      # worker-only
        # Stamp of the oldest staged block. Written by BOTH the
        # distributor (push, on empty->nonempty) and the worker
        # (_flush restamp) without a lock: the race is bounded —
        # a lost store skews ONE flush-age decision by at most one
        # block interval, and a lock here would put the distributor's
        # hot path behind every worker flush.
        self.first_t = 0.0  # noqa: RT200 — benign bounded race, see above
        self.fill = 0.0          # last flush's quantum fill ratio
        self.batches = 0
        self.handoff_dropped = 0  # worker-only: items the consumer lost

    # -- distributor side --------------------------------------------
    def pending_blocks(self) -> int:
        return self.blocks_in - self.blocks_out

    def pending_events(self) -> int:
        return self.events_in - self.events_out

    def push(self, block) -> None:  # hot-path: event
        if self.pending_events() == 0:
            self.first_t = time.monotonic()
        self.staging.append(block)
        self.blocks_in += 1
        self.events_in += len(block)
        self.wake.set()

    # -- worker side --------------------------------------------------
    def run(self) -> None:
        """Supervised run: the ingest loop restarts under the pool's
        restart policy when it crashes (staging survives — it lives on
        the worker object, not the loop frame); a crash loop gives up
        and lets the distributor's liveness check route blocks to the
        surviving shards."""
        hb = (
            self.pool.register_hb(self.name)
            if self.pool.register_hb is not None else None
        )
        policy = (
            self.pool.restart_policy(self.name)
            if self.pool.restart_policy is not None else None
        )
        try:
            while True:
                try:
                    self._loop(hb)
                    return
                except Exception:
                    from retina_tpu.metrics import get_metrics

                    get_metrics().engine_errors.labels(
                        site="feed_worker"
                    ).inc()
                    delay = (
                        policy.record_failure()
                        if policy is not None else None
                    )
                    if delay is None:
                        _log.exception(
                            "feed worker %d crash-looping; giving up "
                            "(blocks route to surviving shards)",
                            self.idx,
                        )
                        return
                    _log.exception(
                        "feed worker %d crashed; restart in %.2fs",
                        self.idx, delay,
                    )
                    get_metrics().thread_restarts.labels(
                        thread=self.name
                    ).inc()
                    if self.pool.stop_evt.wait(delay):
                        return
        finally:
            if self.pool.deregister_hb is not None:
                self.pool.deregister_hb(self.name)

    def _loop(self, hb) -> None:  # hot-path: event
        while True:
            stopping = self.pool.stop_evt.is_set()
            pend = self.pending_events()
            if pend == 0:
                if stopping:
                    return
                if hb is not None:
                    hb.park()
                self.wake.wait(0.002)
                self.wake.clear()
                continue
            if hb is not None:
                hb.beat()
            age = time.monotonic() - self.first_t
            # Same flush policy as the inline feed: full quantum,
            # or the hard age bound, or an interval flush when the
            # dispatch pipeline is idle (latency priority only when
            # nothing is in flight).
            if not (
                pend >= self.pool.quantum
                or stopping
                or age >= self.pool.flush_max_age_s
                or (age >= self.pool.flush_interval_s
                    and self.pool.busy() == 0)
            ):
                self.wake.wait(0.002)
                self.wake.clear()
                continue
            self._flush()

    def _flush(self) -> None:
        blocks = []
        n_raw = 0
        while n_raw < self.pool.quantum:
            try:
                b = self.staging.popleft()
            except IndexError:
                break
            blocks.append(b)
            n_raw += len(b)
        if not blocks:
            return
        # Release staging capacity BEFORE the (long) combine: the
        # backpressure signal tracks what is staged, not what is being
        # crunched.
        self.blocks_out += len(blocks)
        self.events_out += n_raw
        self.first_t = time.monotonic()
        self.fill = n_raw / max(self.pool.quantum, 1)
        from retina_tpu.obs.recorder import get_recorder
        from retina_tpu.utils import metric_names as mn

        rec = get_recorder()
        t0 = rec.begin()
        items = self.pool.build_steps(blocks, n_raw, int(time.time()))
        rec.record(mn.STAGE_FEED_FILL, t0)
        t0 = rec.begin()
        for it in items:
            if not self.outq.put(it, alive=self.pool.alive):
                self.handoff_dropped += 1
                self.pool.drop(it)
        rec.record(mn.STAGE_STAGING_HANDOFF, t0)
        self.batches += 1
        self._publish_metrics()

    def _publish_metrics(self) -> None:
        from retina_tpu.metrics import get_metrics

        m = get_metrics()
        w = str(self.idx)
        m.feed_worker_fill.labels(worker=w).set(self.fill)
        # Counters are cumulative; publish the delta since last flush
        # by tracking the high-water mark locally.
        m.feed_handoff_wait.labels(worker=w).inc(
            max(0.0, self.outq.wait_s - getattr(self, "_wait_pub", 0.0))
        )
        self._wait_pub = self.outq.wait_s

    def stat(self) -> dict[str, Any]:
        return {
            "worker": self.idx,
            "fill": round(self.fill, 3),
            "staged_blocks": self.pending_blocks(),
            "staged_events": self.pending_events(),
            "handoff_wait_s": round(self.outq.wait_s, 3),
            "batches": self.batches,
            "events": self.events_out,
            "handoff_dropped": self.handoff_dropped,
        }


class FeedWorkerPool:
    """N feed workers + the mux the dispatch thread consumes.

    ``build_steps(blocks, n_raw, now_s) -> list[item]`` is the engine's
    combine+partition stage (pure host work, safe concurrently);
    ``drop(item)`` is called for any finished item the dispatch side
    will never consume (dead consumer) so losses are counted, never
    silent; ``busy()`` returns the in-flight dispatch count (interval
    flush gating); ``alive()`` reports dispatch-thread liveness."""

    def __init__(
        self,
        n_workers: int,
        quantum: int,
        staging_blocks: int,
        flush_interval_s: float,
        flush_max_age_s: float,
        build_steps: Callable[[list, int, int], list],
        drop: Callable[[Any], None],
        busy: Callable[[], int] = lambda: 0,
        alive: Callable[[], bool] = lambda: True,
        depth: int = TRANSFER_DEPTH,
        register_hb: Optional[Callable[[str], Any]] = None,
        deregister_hb: Optional[Callable[[str], None]] = None,
        restart_policy: Optional[Callable[[str], Any]] = None,
    ):
        self.quantum = max(1, int(quantum))
        self.staging_blocks = max(1, int(staging_blocks))
        self.flush_interval_s = flush_interval_s
        self.flush_max_age_s = flush_max_age_s
        self.build_steps = build_steps
        self.drop = drop
        self.busy = busy
        self.alive = alive
        self.depth = max(1, int(depth))
        # Supervision seams (engine passes its heartbeat registrar and
        # config-derived restart policy factory; bare pools run
        # unsupervised exactly as before).
        self.register_hb = register_hb
        self.deregister_hb = deregister_hb
        self.restart_policy = restart_policy
        self.stop_evt = threading.Event()
        data = threading.Event()
        self.workers = [
            FeedWorker(i, self, data) for i in range(max(1, n_workers))
        ]
        self.mux = TransferMux([w.outq for w in self.workers], data)
        self._rr = 0
        # Distributor-only counters: blocks no worker could take.
        self.staging_dropped_blocks = 0
        self.staging_dropped_events = 0

    def start(self) -> None:
        for w in self.workers:
            w.start()

    def stage(self, block) -> bool:
        """Deal one raw block to a worker (round-robin, skipping full
        or dead shards). Returns False — caller drops + counts — only
        when EVERY worker is saturated or gone."""
        n = len(self.workers)
        for k in range(n):
            w = self.workers[(self._rr + k) % n]
            if w.is_alive() and w.pending_blocks() < self.staging_blocks:
                self._rr = (self._rr + k + 1) % n
                w.push(block)
                return True
        return False

    def count_drop(self, n_events: int) -> None:
        """Distributor-side drop accounting for a block no worker could
        take (the caller also counts it into lost_events)."""
        from retina_tpu.metrics import get_metrics

        self.staging_dropped_blocks += 1
        self.staging_dropped_events += n_events
        get_metrics().feed_blocks_dropped.labels(
            worker=str(self._rr % len(self.workers))
        ).inc()

    def stop(self, timeout: float = 30.0) -> None:
        """Signal stop and join the workers; each final-flushes its
        staged quantum first (handoffs still drain: the dispatch thread
        keeps consuming until it sees the mux sentinel, which the
        engine enqueues only after this returns)."""
        self.stop_evt.set()
        deadline = time.monotonic() + timeout
        for w in self.workers:
            w.wake.set()
        for w in self.workers:
            w.join(max(0.0, deadline - time.monotonic()))
            if w.is_alive():
                _log.error("feed worker %d did not stop in time", w.idx)

    # -- pressure signals (overload controller, runtime/overload.py) ---
    def max_staging_fill(self) -> float:
        """Worst per-worker staging occupancy in [0, 1] — the leading
        saturation signal: 1.0 means the NEXT block dealt to that shard
        is one skip away from a raw handoff drop."""
        if not self.workers:
            return 0.0
        return max(
            w.pending_blocks() / self.staging_blocks for w in self.workers
        )

    def handoff_wait_total(self) -> float:
        """Cumulative producer seconds spent waiting on a full transfer
        slot, summed over workers; the controller turns the delta into
        a wait rate (seconds waited per wall second)."""
        return sum(w.outq.wait_s for w in self.workers)

    def stats(self) -> dict[str, Any]:
        return {
            "workers": len(self.workers),
            "mode": "sharded",
            "quantum": self.quantum,
            "dropped_blocks": self.staging_dropped_blocks,
            "dropped_events": self.staging_dropped_events,
            "per_worker": [w.stat() for w in self.workers],
        }

"""Host-side record combining: the eBPF-map pre-aggregation analog.

The reference never ships the per-packet firehose to userspace raw: its
kernel programs aggregate in eBPF maps first (packetforward sums per-
direction counters in a map, `pkg/plugin/packetforward/packetforward_linux.go`
reads totals; conntrack accumulates per-connection packet/byte counts in
its LRU map and emits per-connection reports, `_cprog/conntrack.c`). The
TPU analog of "the kernel map" is this combiner: before records cross the
host->device link (the system's scarcest bandwidth — PCIe in production, a
network tunnel on the bench harness), identical flow descriptors within a
flush interval are run-length encoded into one record carrying summed
PACKETS/BYTES and the latest timestamp.

Losslessness contract: every device-side aggregator weights by F.PACKETS
(models/pipeline.py), so feeding ``combine_records(batch)`` produces
EXACTLY the same device state as feeding ``batch`` row by row — the group
key is every column except the weight columns (BYTES, PACKETS) and the
timestamps. Two packets that differ in ANY descriptor bit (tcp flags, drop
reason, DNS rcode, interface, TSval...) stay separate rows, so nothing a
per-event aggregator could distinguish is merged away.

The compression ratio is the packets-per-distinct-descriptor factor of the
traffic — the same factor the reference's kernel maps exploit (flows are
few, packets are many). Worst case (every descriptor unique) the combiner
returns the input unchanged, minus the sort cost.
"""

from __future__ import annotations

import numpy as np

from retina_tpu.events.schema import F, NUM_FIELDS
from retina_tpu.parallel.partition import hash_cols_np

# Group key: every column EXCEPT the accumulated weights and timestamps.
# TSVAL/TSECR stay IN the key: latency matching (pipeline.py apiserver RTT)
# needs exact TSval/TSecr values, and same-TSval packets (ms granularity)
# still combine.
KEY_COLS = (
    F.SRC_IP,
    F.DST_IP,
    F.PORTS,
    F.META,
    F.VERDICT,
    F.DROP_REASON,
    F.TSVAL,
    F.TSECR,
    F.DNS,
    F.DNS_QHASH,
    F.EVENT_TYPE,
    F.IFINDEX,
)

_U32_MAX = np.uint64(0xFFFFFFFF)


def combine_records_numpy(records: np.ndarray) -> np.ndarray:
    """Pure-numpy combine: sort by descriptor hash + segmented reduce.

    Aggregation: PACKETS/BYTES sum (saturating at u32 max), timestamp is
    the group's latest. Returns the input array itself (no copy) when
    nothing merges. Row order of the output is arbitrary (hash order).
    """
    n = len(records)
    if n <= 1:
        return records
    assert records.shape[1] == NUM_FIELDS
    h = hash_cols_np([records[:, c] for c in KEY_COLS], seed=0xC0B1)
    order = np.argsort(h, kind="stable")
    r = records[order]
    # Group boundary = any key column differs from the previous sorted
    # row. Equal keys hash equally so they are adjacent (stable sort keeps
    # equal-hash rows in input order, so a hash collision between two
    # interleaved descriptors can only SPLIT a group — never merge one).
    bounds = np.empty(n, bool)
    bounds[0] = True
    acc = np.zeros(n - 1, bool)
    for c in KEY_COLS:
        col = r[:, c]
        acc |= col[1:] != col[:-1]
    bounds[1:] = acc
    starts = np.flatnonzero(bounds)
    if len(starts) == n:
        return records
    out = r[starts].copy()
    pkts = np.add.reduceat(r[:, F.PACKETS].astype(np.uint64), starts)
    byts = np.add.reduceat(r[:, F.BYTES].astype(np.uint64), starts)
    out[:, F.PACKETS] = np.minimum(pkts, _U32_MAX).astype(np.uint32)
    out[:, F.BYTES] = np.minimum(byts, _U32_MAX).astype(np.uint32)
    ts = (r[:, F.TS_HI].astype(np.uint64) << np.uint64(32)) | r[
        :, F.TS_LO
    ].astype(np.uint64)
    tmax = np.maximum.reduceat(ts, starts)
    out[:, F.TS_LO] = (tmax & _U32_MAX).astype(np.uint32)
    out[:, F.TS_HI] = (tmax >> np.uint64(32)).astype(np.uint32)
    return out


def combine_records(records: np.ndarray) -> np.ndarray:
    """(N, NUM_FIELDS) -> (G, NUM_FIELDS) with identical descriptors merged.

    Dispatches to the C++ single-pass hash combiner (native/combine.cpp —
    releases the GIL, so it overlaps device transfers) and falls back to
    the numpy sort-based path when the native library is unavailable.
    """
    from retina_tpu.native import combine_native

    out = combine_native(records)
    if out is not None:
        return out
    return combine_records_numpy(records)


def combine_blocks(blocks: list[np.ndarray]) -> np.ndarray:
    """Combine a LIST of record blocks (the feed loop's flush quantum)
    without concatenating them first — the concat alone costs a full
    row-copy pass at production quanta (~40% of the stage on a 1-core
    host). The key -> (packets, bytes, latest-ts) map is identical to
    ``combine_records(np.concatenate(blocks))`` in every regime (the
    losslessness contract above); ROW ORDER matches it on the
    single-thread paths and is arbitrary on the multi-consumer striped
    path (consumers never depend on it — rows are partitioned and
    re-bucketed immediately downstream). Falls back to concat +
    combine when the native library is unavailable."""
    from retina_tpu.native import (
        combine_native_blocks, combine_native_blocks_striped,
        get_combine_threads,
    )

    total = sum(len(b) for b in blocks)
    n_threads = get_combine_threads()
    if n_threads > 1 and total >= 2 * (1 << 15):
        # Multi-consumer territory: T stripe workers each combine ONE
        # key-hash stripe of the block list into a private table and
        # output buffer (combine.cpp rt_combine_stripe) — key-disjoint
        # stripes need no merge pass, no locks, and no concat
        # (rt_combine_mt paid a full row-copy concat + a serial merge
        # of T partial tables; the stripes replace both). Works
        # directly on a single oversized block too.
        out = combine_native_blocks_striped(blocks, n_threads)
        if out is not None:
            return out
        # Library unavailable: the old concat + chunk-parallel path.
        return combine_records(np.concatenate(blocks, axis=0))
    if len(blocks) == 1:
        return combine_records(blocks[0])
    out = combine_native_blocks(blocks)
    if out is not None:
        return out
    return combine_records(np.concatenate(blocks, axis=0))

"""Sharded telemetry: the multi-chip version of models/pipeline.py.

Reference analog (SURVEY.md §2.6): the reference's cross-node story is N
independent agents + Prometheus scrape-side merges + the Hubble relay; the
TPU-native replacement runs the SAME fused pipeline step on every mesh
device over a connection-partitioned event shard, and merges at scrape
time with XLA collectives:

    dense counter rectangles, CMS tables, entropy histograms  -> psum
    HLL register banks                                        -> pmax
    heavy-hitter candidate tables                             -> all_gather
    conntrack tables                                          -> no merge
        (connection-consistent partitioning makes them disjoint; only the
        active-connection gauge is psum'd)

On a multi-host mesh (jax.distributed), the same psum reduces over ICI
within a slice and DCN across hosts — no NCCL/MPI analog is written by
hand, XLA inserts the collectives from the shardings.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import threading
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from retina_tpu.devprog import device_entry
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, PipelineState, TelemetryPipeline
from retina_tpu.ops.invertible import decode_verified
from retina_tpu.ops.topk import TopKTable

# jax >= 0.5 promotes shard_map to the top-level namespace and renames
# the replication checker kwarg check_rep -> check_vma; 0.4.x keeps both
# the experimental home and the old name. Resolve once so every _build_*
# site stays version agnostic.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(f, **kw)  # noqa: RT305 — version shim, not a program site; callers carry @device_entry


# On-disk AOT executable cache accounting (ROADMAP item 5: compile cost
# swings 2.1s->96.1s and bucket-grid warm is 214s PER PROCESS — a disk
# cache keyed on (jax version, topology, config signature) makes warm
# cost survive restarts). Module-level so bench diag can report hit/miss
# across every AotProgram instance in the process.
_AOT_DISK_LOCK = threading.Lock()
_AOT_DISK_STATS = {"hits": 0, "misses": 0, "errors": 0}
# Per-program-tag breakdown of the same counters: the BENCH_r06
# regression (hits=1 misses=26) was invisible in the totals — the
# per-tag view names exactly which programs keep re-compiling.
_AOT_TAG_STATS: dict[str, dict[str, int]] = {}


def aot_disk_cache_stats() -> dict[str, Any]:
    """Process-wide disk-cache counters: ``hits`` (deserialized from
    disk, compile skipped), ``misses`` (compiled + persisted),
    ``errors`` (load/save attempts that failed; always fell back to a
    fresh compile, never fatal). ``by_tag`` breaks the same counters
    down per program tag (step, snapshot, ingest buckets, ...)."""
    with _AOT_DISK_LOCK:
        out: dict[str, Any] = dict(_AOT_DISK_STATS)
        out["by_tag"] = {t: dict(s) for t, s in _AOT_TAG_STATS.items()}
        return out


def _aot_disk_bump(field: str, tag: str = "") -> None:
    with _AOT_DISK_LOCK:
        _AOT_DISK_STATS[field] += 1
        if tag:
            _AOT_TAG_STATS.setdefault(
                tag, {"hits": 0, "misses": 0, "errors": 0}
            )[field] += 1


# -- free-function disk layer -----------------------------------------
# Shared by AotProgram (the telemetry step/end-window programs) and the
# engine's per-bucket ingest jits (engine._compile_cached): the bucket
# grid is the bulk of the 214s r05 warm, so it must ride the same disk
# cache as the step programs for a warm boot to land under 10s.

def aot_disk_path(
    cache_dir: str, mesh: Mesh | None, tag: str, config_sig: str, key
) -> str:
    """Cache-file path for one (program tag, input-signature) pair,
    keyed by jax version + backend topology + config signature so a
    stale entry can never load into a mismatched process. ``mesh=None``
    keys on the full default device set — the mesh-less query programs
    (timetravel/fold.py) compile against it."""
    devs = (
        mesh.devices.ravel() if mesh is not None
        else np.asarray(jax.devices())
    )
    topo = "{}:{}:{}".format(
        jax.default_backend(), len(devs),
        getattr(devs[0], "device_kind", "?"),
    )
    raw = "|".join((jax.__version__, topo, tag, config_sig, repr(key)))
    h = hashlib.sha256(raw.encode()).hexdigest()[:32]
    return os.path.join(cache_dir, f"{tag}-{h}.aotx")


def aot_disk_load(path: str, tag: str = ""):
    """Deserialize a cached executable, or None (best-effort: stale jax,
    corrupt/truncated file, incompatible executable all fall back to a
    fresh compile). ``tag`` feeds the per-program counters and the
    hit/miss log line."""
    if not os.path.exists(path):
        return None
    try:
        from jax.experimental import serialize_executable as se

        with open(path, "rb") as f:
            payload = pickle.load(f)
        ex = se.deserialize_and_load(
            payload["exe"], payload["in_tree"], payload["out_tree"]
        )
        _aot_disk_bump("hits", tag)
        if tag:
            _aot_log().debug("aot disk HIT tag=%s path=%s", tag, path)
        return ex
    except Exception:
        _aot_disk_bump("errors", tag)
        return None


def aot_disk_save(path: str, ex, tag: str = "") -> None:
    """Persist a compiled executable (best-effort; never fails the
    caller — persisting is an optimization only)."""
    try:
        from jax.experimental import serialize_executable as se

        payload_exe, in_tree, out_tree = se.serialize(ex)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(
                {"exe": payload_exe, "in_tree": in_tree,
                 "out_tree": out_tree},
                f,
            )
        os.replace(tmp, path)
        _aot_disk_bump("misses", tag)
        if tag:
            _aot_log().info(
                "aot disk MISS tag=%s (compiled + persisted)", tag
            )
    except Exception:
        _aot_disk_bump("errors", tag)


def _aot_log():
    from retina_tpu.log import logger

    return logger("aot.cache")


class AotProgram:
    """Aval-keyed AOT executable cache around a jitted program.

    The plain ``jax.jit`` cache keys on input *shardings* as well as
    avals, and the state pytree's sharding spelling flips between
    ``init_state``'s ``out_shardings`` (``P(('data',))``) and the
    jit-normalized step output — so the very first warm-up step used to
    compile TWICE (the 2.1s->96.1s cold-start swings, ROADMAP item 5).
    This wrapper keys ONLY on (tree structure, per-leaf shape/dtype) and
    lowers each signature once with canonical shardings; the compiled
    executable then accepts committed arrays with any equivalent
    sharding spelling as well as raw host (numpy) arrays, so ragged
    feeds and recovery rebuilds reuse the one resident executable.

    ``donate_argnums`` declared on the wrapped jit carry through
    ``lower().compile()`` untouched. ``_cache_size()`` mirrors the
    private jit introspection hook the stability tests assert on.

    When ``cache_dir`` is set, each compiled executable is additionally
    persisted to disk via ``jax.experimental.serialize_executable``,
    keyed by (jax version, backend topology, ``config_sig``, program
    tag, input signature) — a later process with the same key skips XLA
    compilation entirely. Every disk interaction is best-effort: any
    failure (old jax without the API, unpicklable trees, corrupt file,
    read-only dir) falls back to a fresh in-process compile.
    """

    def __init__(self, jitted, mesh: Mesh, sharded_spec,
                 sharded_argnums: tuple[int, ...],
                 cache_dir: str = "", tag: str = "prog",
                 config_sig: str = ""):
        self._jitted = jitted
        self._mesh = mesh
        self._spec = sharded_spec
        self._sharded_argnums = frozenset(sharded_argnums)
        self._execs: dict[Any, Any] = {}
        self._cache_dir = cache_dir
        self._tag = tag
        self._config_sig = config_sig

    def _signature(self, args) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return treedef, tuple(
            (np.shape(leaf), np.dtype(
                getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
            ).name)
            for leaf in leaves
        )

    # -- disk layer (delegates to the module-level free functions so the
    # engine's bucket-grid compiles share one format and one stats pool) -
    def _disk_path(self, key) -> str:
        return aot_disk_path(
            self._cache_dir, self._mesh, self._tag, self._config_sig, key
        )

    def _disk_load(self, path: str):
        return aot_disk_load(path, tag=self._tag)

    def _disk_save(self, path: str, ex) -> None:
        aot_disk_save(path, ex, tag=self._tag)

    def _lower(self, args, key=None):
        if self._cache_dir and key is not None:
            path = self._disk_path(key)
            ex = self._disk_load(path)
            if ex is not None:
                return ex

        def struct(i, leaf):
            sh = NamedSharding(
                self._mesh,
                self._spec if i in self._sharded_argnums else P(),
            )
            return jax.ShapeDtypeStruct(
                np.shape(leaf), np.asarray(leaf).dtype
                if not hasattr(leaf, "dtype") else leaf.dtype,
                sharding=sh,
            )

        specs = tuple(
            jax.tree.map(lambda leaf, i=i: struct(i, leaf), arg)
            for i, arg in enumerate(args)
        )
        ex = self._jitted.lower(*specs).compile()
        if self._cache_dir and key is not None:
            self._disk_save(self._disk_path(key), ex)
        return ex

    def __call__(self, *args):
        key = self._signature(args)
        ex = self._execs.get(key)
        if ex is None:
            ex = self._lower(args, key=key)
            self._execs[key] = ex
        return ex(*args)

    def _cache_size(self) -> int:
        return len(self._execs)


class ShardedTelemetry:
    """TelemetryPipeline spread over a jax.sharding.Mesh.

    Per-device state carries a leading device axis of size D; events arrive
    as (D, B, F) connection-partitioned batches (parallel/partition.py).
    """

    def __init__(self, config: PipelineConfig, mesh: Mesh,
                 aot_cache_dir: str = ""):
        self.pipeline = TelemetryPipeline(config)
        self.mesh = mesh
        self.axes = tuple(mesh.axis_names)
        self.n_devices = mesh.size
        self._sharded_spec = P(self.axes)  # dim0 split over every mesh axis
        self._aot_cache_dir = aot_cache_dir
        # Config identity for the disk cache key: the dataclass repr
        # covers every field that changes compiled code (widths, depths,
        # feature toggles) deterministically.
        self._config_sig = repr(config)
        self._step = None
        self._end_window = None
        self._snapshot = None
        self._snapshot_flat = None
        self._fleet_export = None
        self._inv_decode = None

    # ------------------------------------------------------------------
    @device_entry("sharded.init_state", kind="jit")
    def _build_init_state(self):
        """Builder split from init_state so the device-program analysis
        (tools/analyze/rt300.py) can lower and audit the jit without
        executing it."""
        single = jax.eval_shape(self.pipeline.init_state)
        d = self.n_devices

        @partial(
            jax.jit,
            out_shardings=NamedSharding(self.mesh, self._sharded_spec),
        )
        def mk():
            return jax.tree.map(
                lambda s: jnp.zeros((d,) + s.shape, s.dtype), single
            )

        return mk

    def init_state(self) -> PipelineState:
        return self._build_init_state()()

    # ------------------------------------------------------------------
    @device_entry("sharded.step", kind="shard_map")
    def _build_step(self):
        def local_step(
            state, records, n_valid, now_s, ident, apiserver_ip, filt, lost,
            sample_k,
        ):
            s = jax.tree.map(lambda x: x[0], state)
            new, summary = self.pipeline.step(
                s, records[0], n_valid[0], now_s, ident, apiserver_ip,
                filter_map=filt, sample_k=sample_k,
            )
            # Host-side partition overflow losses land in totals[7] ("lost")
            # on one device only, so the snapshot psum counts them once —
            # the reference's LostEventsCounter accounting rule
            # (packetparser_linux.go:692-697: drop, count, never block).
            first = jax.lax.axis_index(self.axes) == 0
            new = dataclasses.replace(
                new,
                totals=new.totals.at[7].add(jnp.where(first, lost, 0)),
            )
            new = jax.tree.map(lambda x: x[None], new)
            out = {
                "events": jax.lax.psum(summary["events"], self.axes),
                "ct_reports": jax.lax.psum(summary["ct_reports"], self.axes),
                "report_mask": summary["report_mask"][None],
                "report_packets": summary["report_packets"][None],
                "report_bytes": summary["report_bytes"][None],
            }
            return new, out

        sh = self._sharded_spec
        fn = _shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(sh, sh, sh, P(), P(), P(), P(), P(), P()),
            out_specs=(
                sh,
                {
                    "events": P(),
                    "ct_reports": P(),
                    "report_mask": sh,
                    "report_packets": sh,
                    "report_bytes": sh,
                },
            ),
        )
        # AOT-wrapped (AotProgram): argnums 0-2 (state, records, n_valid)
        # carry the mesh sharding, the scalar/replicated tail does not.
        return AotProgram(
            jax.jit(fn, donate_argnums=(0,)), self.mesh,
            self._sharded_spec, (0, 1, 2),
            cache_dir=self._aot_cache_dir, tag="step",
            config_sig=self._config_sig,
        )

    def _put_sharded(self, x):
        """Place a dim0-sharded step input. Host (numpy/list) batches get
        an explicit ``device_put`` onto the mesh sharding so each device
        receives ONLY its shard — ``jnp.asarray`` used to commit the full
        batch to the default device first and let the executable reshard
        it, which made the 8-device feed SLOWER than 1 device (the
        MULTICHIP_r05 replication overhead). Device-resident arrays pass
        through with a dtype check only — no extra transfer."""
        if isinstance(x, jax.Array):
            return x if x.dtype == jnp.uint32 else x.astype(jnp.uint32)
        host = np.asarray(x, dtype=np.uint32)
        if self.n_devices == 1:
            return jnp.asarray(host)
        return jax.device_put(
            host, NamedSharding(self.mesh, self._sharded_spec)
        )

    def step(
        self,
        state: PipelineState,
        records,  # (D, B, F) uint32
        n_valid,  # (D,) uint32
        now_s,  # scalar uint32
        ident: IdentityMap,
        apiserver_ip=0,
        filter_map: IdentityMap | None = None,  # explicit IPs of interest
        lost=0,  # host-side partition overflow count (ShardedBatch.lost)
        sample_k=1,  # overload 1-in-k factor (ShardedBatch.sample_k)
    ) -> tuple[PipelineState, dict[str, jnp.ndarray]]:
        if self._step is None:
            self._step = self._build_step()
        if filter_map is None:
            filter_map = IdentityMap.zeros(1 << 4, seed=99)
        return self._step(
            state,
            self._put_sharded(records),
            self._put_sharded(n_valid),
            jnp.asarray(now_s, jnp.uint32),
            ident,
            jnp.asarray(apiserver_ip, jnp.uint32),
            filter_map,
            # Packet-weighted loss counts can exceed 2^32 in one batch;
            # the device totals are u32 and wrap (like every reference
            # kernel counter) — the host-side Prometheus lost_events
            # counter (float64) stays exact. Device-resident scalars
            # (the engine's coalesced-ingest outputs) pass through
            # untouched — coercing them via int() would force a
            # device->host readback per step.
            jnp.asarray(
                int(lost) & 0xFFFFFFFF
                if isinstance(lost, (int, np.integer)) else lost,
                jnp.uint32,
            ),
            # Same pass-through rule as ``lost``: the engine hands a
            # device-resident scalar from its per-k cache on the hot
            # path; host ints only show up in tests/direct callers.
            jnp.asarray(
                int(sample_k) & 0xFFFFFFFF
                if isinstance(sample_k, (int, np.integer)) else sample_k,
                jnp.uint32,
            ),
        )

    # ------------------------------------------------------------------
    @device_entry("sharded.end_window", kind="shard_map")
    def _build_end_window(self):
        def local_end(state, z_thresh):
            s = jax.tree.map(lambda x: x[0], state)
            # Merge window histograms first so every device computes the
            # entropy of the UNION stream, then updates its (replicated)
            # anomaly EWMA identically.
            merged_ent = dataclasses.replace(
                s.entropy, counts=jax.lax.psum(s.entropy.counts, self.axes)
            )
            h = merged_ent.entropy_bits()
            # Idle windows (including the engine's compile() warm-up)
            # must not seed/poison the EWMA baseline — same contract as
            # the single-chip end_window (models/pipeline.py).
            active = merged_ent.counts.sum(axis=-1) > 0
            anomaly, flags, z = s.anomaly.observe(
                h, z_thresh=z_thresh, active=active
            )
            new = dataclasses.replace(
                s, entropy=s.entropy.reset(), anomaly=anomaly
            )
            new = jax.tree.map(lambda x: x[None], new)
            return new, {"entropy_bits": h, "anomaly": flags, "zscore": z}

        sh = self._sharded_spec
        fn = _shard_map(
            local_end,
            mesh=self.mesh,
            in_specs=(sh, P()),
            out_specs=(sh, {"entropy_bits": P(), "anomaly": P(), "zscore": P()}),
            # anomaly/zscore derive from the per-device EWMA state, which is
            # replicated by construction (only ever updated with the psum'd
            # window entropy) — the checker cannot prove that invariant.
            check_vma=False,
        )
        return AotProgram(
            jax.jit(fn, donate_argnums=(0,)), self.mesh,
            self._sharded_spec, (0,),
            cache_dir=self._aot_cache_dir, tag="endwin",
            config_sig=self._config_sig,
        )

    def end_window(
        self, state: PipelineState, z_thresh: float = 4.0
    ) -> tuple[PipelineState, dict[str, jnp.ndarray]]:
        if self._end_window is None:
            self._end_window = self._build_end_window()
        return self._end_window(state, jnp.asarray(z_thresh, jnp.float32))

    # ------------------------------------------------------------------
    @device_entry("sharded.snapshot", kind="shard_map")
    def _build_snapshot(self):
        ax = self.axes

        def local_snap(state, now_s):
            s = jax.tree.map(lambda x: x[0], state)
            psum = lambda x: jax.lax.psum(x, ax)
            pmax = lambda x: jax.lax.pmax(x, ax)
            gather = lambda x: jax.lax.all_gather(x, ax, axis=0)

            def hll_est(hll):
                merged = dataclasses.replace(hll, registers=pmax(hll.registers))
                return merged.estimate()

            def hh_gather(hh):
                return {
                    # (D, S, C) and (D, S): union of per-device candidates.
                    "keys": gather(hh.table.key_rows),
                    "counts": gather(hh.table.counts),
                }

            return {
                "pod_forward": psum(s.pod_forward),
                "pod_drop": psum(s.pod_drop),
                "pod_tcpflags": psum(s.pod_tcpflags),
                "pod_dns": psum(s.pod_dns),
                "pod_retrans": psum(s.pod_retrans),
                "node_counters": psum(s.node_counters),
                "totals": psum(s.totals),
                # Two-limb u32 counters cannot psum (a summed lo limb may
                # wrap and lose the carry) — gather per-device limbs and
                # reassemble 64-bit values on host (conntrack_gc()).
                "ct_totals": gather(s.ct_totals),
                "lat_hist": psum(s.lat_hist),
                "hll_flows": hll_est(s.hll_flows),
                "hll_src_per_reason": hll_est(s.hll_src_per_reason),
                "hll_src_per_pod": hll_est(s.hll_src_per_pod),
                "flow_hh": hh_gather(s.flow_hh),
                "svc_hh": hh_gather(s.svc_hh),
                "dns_hh": hh_gather(s.dns_hh),
                "active_conns": psum(s.conntrack.active_connections(now_s)),
            }

        fn = _shard_map(
            local_snap,
            mesh=self.mesh,
            in_specs=(self._sharded_spec, P()),
            out_specs=P(),  # every output is collective-merged => replicated
            # The vma checker cannot see through estimate()/gather chains,
            # but psum/pmax/all_gather outputs are replicated by definition.
            check_vma=False,
        )
        # AOT-wrapped like _build_step: the scrape/export programs were
        # the bulk of the BENCH_r06 hits=1/misses=26 warm regression —
        # every restart re-lowered them while only the step program hit
        # disk.
        return AotProgram(
            jax.jit(fn), self.mesh, self._sharded_spec, (0,),
            cache_dir=self._aot_cache_dir, tag="snapshot",
            config_sig=self._config_sig,
        )

    def snapshot(self, state: PipelineState, now_s) -> dict[str, Any]:
        """Merged scrape-time readout (device dict; np.asarray leaves to read)."""
        if self._snapshot is None:
            self._snapshot = self._build_snapshot()
        return self._snapshot(state, jnp.asarray(now_s, jnp.uint32))

    # ------------------------------------------------------------------
    @device_entry("sharded.fleet_export", kind="shard_map")
    def _build_fleet_export(self):
        ax = self.axes
        d = self.n_devices

        def local_fx(state):
            s = jax.tree.map(lambda x: x[0], state)
            psum = lambda x: jax.lax.psum(x, ax)
            pmax = lambda x: jax.lax.pmax(x, ax)
            gather = lambda x: jax.lax.all_gather(x, ax, axis=0)

            def fold_table(table):
                # Gather every device's candidate table, then fold with
                # the join-semilattice merge (ops/topk.py) so the wire
                # snapshot carries ONE (S, C) table per family.
                keys = gather(table.key_rows)  # (D, S, C)
                counts = gather(table.counts)  # (D, S)
                t = TopKTable(keys[0], counts[0], seed=table.seed)
                for i in range(1, d):
                    t = t.merge(
                        TopKTable(keys[i], counts[i], seed=table.seed)
                    )
                return t

            out = {}
            for fam, hh in (  # noqa: RT212 — static 3-family tuple; intended unroll
                ("flow", s.flow_hh), ("svc", s.svc_hh), ("dns", s.dns_hh)
            ):
                t = fold_table(hh.table)
                out[f"{fam}_cms"] = psum(hh.cms.table)
                out[f"{fam}_keys"] = t.key_rows
                out[f"{fam}_counts"] = t.counts
            out["hll_flows"] = pmax(s.hll_flows.registers)
            out["hll_src_per_pod"] = pmax(s.hll_src_per_pod.registers)
            out["entropy"] = psum(s.entropy.counts)
            out["totals"] = psum(s.totals)
            if self.pipeline.config.enable_invertible:
                # Pure sums: the aggregator's default sum-merge branch
                # recovers cluster-wide keys from these without any node
                # shipping raw keys (fleet/aggregator.py).
                out["inv_flow_planes"] = psum(s.inv_flow.planes)
                out["inv_flow_weights"] = psum(s.inv_flow.weights)
                out["inv_hi_planes"] = psum(s.inv_hi.planes)
                out["inv_hi_weights"] = psum(s.inv_hi.weights)
            return out

        fn = _shard_map(
            local_fx,
            mesh=self.mesh,
            in_specs=(self._sharded_spec,),
            out_specs=P(),  # every output collective-merged => replicated
            check_vma=False,
        )
        return AotProgram(
            jax.jit(fn), self.mesh, self._sharded_spec, (0,),
            cache_dir=self._aot_cache_dir, tag="fleet_export",
            config_sig=self._config_sig,
        )

    def fleet_export(self, state: PipelineState) -> dict[str, Any]:
        """Device-merged wire snapshot for the fleet rollup tier
        (fleet/codec.py array catalog). Async dispatch: the shipper does
        the readback off the proxy (fleet/shipper.py)."""
        if self._fleet_export is None:
            self._fleet_export = self._build_fleet_export()
        return self._fleet_export(state)

    @staticmethod
    def fleet_seeds(state: PipelineState) -> dict[str, int]:
        """Per-family sketch hash seeds (pytree aux — host-side attribute
        reads, no device sync). Shipped in every frame so the aggregator
        can refuse cross-seed merges."""
        return {
            "flow": int(state.flow_hh.cms.seed),
            "svc": int(state.svc_hh.cms.seed),
            "dns": int(state.dns_hh.cms.seed),
            "hll_flows": int(state.hll_flows.seed),
            "hll_src_per_pod": int(state.hll_src_per_pod.seed),
            "entropy": int(state.entropy.seed),
            "inv_flow": int(state.inv_flow.seed),
            "inv_hi": int(state.inv_hi.seed),
        }

    # ------------------------------------------------------------------
    @device_entry("sharded.inv_decode", kind="shard_map")
    def _build_inv_decode(self):
        ax = self.axes

        def local_dec(state, min_weight):
            s = jax.tree.map(lambda x: x[0], state)
            psum = lambda x: jax.lax.psum(x, ax)
            # Decode the UNION sketch (devices hold connection-disjoint
            # shards, the arrays are pure sums) against the union CMS —
            # same merge contract the fleet aggregator applies node-wide.
            merged_cms = dataclasses.replace(
                s.flow_hh.cms, table=psum(s.flow_hh.cms.table)
            )

            def region(inv, tier):
                merged = dataclasses.replace(
                    inv,
                    planes=psum(inv.planes),
                    weights=psum(inv.weights),
                )
                cols, est, ok = decode_verified(
                    merged, merged_cms, min_weight=0
                )
                ok = ok & (est >= min_weight)
                tiers = jnp.full(est.shape, tier, jnp.uint32)
                return cols, jnp.where(ok, est, 0), ok, tiers

            f_cols, f_est, f_ok, f_tier = region(s.inv_flow, 0)
            h_cols, h_est, h_ok, h_tier = region(s.inv_hi, 1)
            keys = jnp.stack(
                [jnp.concatenate([a, b]) for a, b in zip(f_cols, h_cols)],
                axis=1,
            )  # (M, C) u32
            return {
                "keys": keys,
                "est": jnp.concatenate([f_est, h_est]),
                "ok": jnp.concatenate([f_ok, h_ok]),
                "tier": jnp.concatenate([f_tier, h_tier]),
            }

        fn = _shard_map(
            local_dec,
            mesh=self.mesh,
            in_specs=(self._sharded_spec, P()),
            out_specs=P(),  # psum-merged inputs => replicated decode
            check_vma=False,
        )
        return AotProgram(
            jax.jit(fn), self.mesh, self._sharded_spec, (0,),
            cache_dir=self._aot_cache_dir, tag="inv_decode",
            config_sig=self._config_sig,
        )

    def inv_decode(self, state: PipelineState, min_weight=0) -> dict[str, Any]:
        """Window-close invertible decode (fixed shape, async dispatch
        like fleet_export — caller reads back off the proxy). Returns
        device arrays: ``keys (M, C) u32``, ``est (M,)``, ``ok (M,)``,
        ``tier (M,)`` (0 = main region, 1 = priority region); rows with
        ``ok == False`` are noise. M = D*W_flow + D*W_hi; the same key
        can decode from up to D buckets — hosts dedupe (np.unique)."""
        if self._inv_decode is None:
            self._inv_decode = self._build_inv_decode()
        return self._inv_decode(state, jnp.asarray(min_weight, jnp.uint32))

    # ------------------------------------------------------------------
    @device_entry("sharded.snapshot_flat", kind="jit")
    def _build_snapshot_flat(self, state: PipelineState):
        # Trace through the UNDERLYING jit (an AotProgram cannot run
        # under eval_shape/jit tracing — its executables take concrete
        # arrays); the flat program gets its own AOT disk entry below.
        base = self._build_snapshot()._jitted
        shapes = jax.eval_shape(base, state, np.uint32(0))
        leaves, treedef = jax.tree_util.tree_flatten(shapes)

        def flat_fn(st, now_s):
            d = base(st, now_s)
            out = []
            for leaf in jax.tree_util.tree_leaves(d):
                if leaf.dtype != jnp.uint32:
                    leaf = jax.lax.bitcast_convert_type(
                        leaf.astype(
                            jnp.float32
                            if jnp.issubdtype(leaf.dtype, jnp.floating)
                            else jnp.uint32
                        ),
                        jnp.uint32,
                    )
                out.append(leaf.reshape(-1))
            return jnp.concatenate(out)

        prog = AotProgram(
            jax.jit(flat_fn), self.mesh, self._sharded_spec, (0,),
            cache_dir=self._aot_cache_dir, tag="snapshot_flat",
            config_sig=self._config_sig,
        )
        return prog, leaves, treedef

    def snapshot_host(self, state: PipelineState, now_s) -> dict[str, Any]:
        """Merged snapshot delivered to HOST memory in ONE device->host
        transfer: every leaf is bitcast to u32, raveled, and concatenated
        on device, so the readback is a single contiguous buffer instead
        of ~25 per-leaf round trips (each round trip costs full link
        latency; measured 2.7-21s per scrape on a congested link vs the
        <1s budget)."""
        return self.snapshot_flat_finish(
            self.snapshot_flat_dispatch(state, now_s)
        )

    def snapshot_flat_dispatch(self, state: PipelineState, now_s):
        """Enqueue the flat-snapshot computation and return the DEVICE
        array immediately (async dispatch) — no blocking transfer.

        Split from :meth:`snapshot_flat_finish` so the engine can run
        the dispatch on the device-proxy thread (ordered against steps;
        the state reference is captured before any later donating step
        executes) while the multi-second device->host readback blocks
        only the snapshot *caller's* thread. Before the split the proxy
        spent ~30% of its steady-state wall clock inside snapshot
        readbacks on a congested link, stalling the whole dispatch
        pipeline behind scrape/GC traffic."""
        if self._snapshot_flat is None:
            self._snapshot_flat = self._build_snapshot_flat(state)
        fn, _, _ = self._snapshot_flat
        return fn(state, jnp.asarray(now_s, jnp.uint32))

    def snapshot_flat_finish(self, flat_dev) -> dict[str, Any]:
        """Unflatten a flat snapshot buffer back into the snapshot
        dict. Pass a HOST (numpy) buffer when calling off the device
        proxy (engine.snapshot uses fetch_on_device for the readback);
        a device array is also accepted, but then the np.asarray below
        is a blocking device call and must run on the proxy thread."""
        fn, leaf_shapes, treedef = self._snapshot_flat
        flat = np.asarray(flat_dev)
        out = []
        off = 0
        for spec in leaf_shapes:
            n = int(np.prod(spec.shape)) if spec.shape else 1
            chunk = flat[off : off + n]
            off += n
            if np.issubdtype(spec.dtype, np.floating):
                chunk = chunk.view(np.float32).astype(spec.dtype)
            elif chunk.dtype != spec.dtype:
                chunk = chunk.view(np.uint32).astype(spec.dtype)
            out.append(
                chunk.reshape(spec.shape) if spec.shape else chunk[0]
            )
        return jax.tree_util.tree_unflatten(treedef, out)


def topk_from_snapshot(
    snap: dict[str, Any], name: str, k: int
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side top-k over a snapshot's gathered candidate tables.

    Returns (keys (k', C), counts (k',)) sorted descending, k' <= k.
    Per-device counts for the SAME key are summed before ranking: sketches
    keyed above the connection level (svc_hh pod pairs, dns_hh query
    hashes) split one key's traffic across devices, so each device's table
    holds a partial count of its shard — the sum of per-device CMS
    estimates of disjoint sub-streams estimates the total. For
    connection-level keys (flow_hh) devices are key-disjoint and the
    group-sum is a no-op.
    """
    hh = snap[name]
    keys = np.asarray(hh["keys"])  # (D, S, C)
    counts = np.asarray(hh["counts"])  # (D, S)
    d, sl, c = keys.shape
    flat_keys = keys.reshape(d * sl, c)
    flat_counts = counts.reshape(d * sl).astype(np.uint64)
    nonzero = flat_counts > 0
    flat_keys, flat_counts = flat_keys[nonzero], flat_counts[nonzero]
    if not len(flat_keys):
        return flat_keys, flat_counts
    uniq, inv = np.unique(flat_keys, axis=0, return_inverse=True)
    summed = np.zeros(len(uniq), np.uint64)
    np.add.at(summed, inv, flat_counts)
    order = np.argsort(summed)[::-1][:k]
    return uniq[order], summed[order]

"""Host-side event partitioning across mesh devices.

Connection-consistent sharding: both directions of a connection must land
on the same device, or per-device conntrack tables (ops/conntrack.py) would
see half-connections and double-report. The partition key is therefore the
same canonical (sorted-endpoint) key conntrack uses — mirroring how the
reference's kernel conntrack keys the 5-tuple after reverse-key lookup
(conntrack.c ct_process_packet :344).

This is the numpy mirror of ops/hashing.py (host batcher must not touch
the device), plus the bucketing that turns one (N, F) host batch into a
(D, B, F) sharded batch with per-device validity counts and drop accounting
(the reference never blocks, it counts losses — packetparser_linux.go:692-697).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from retina_tpu.events.schema import F, NUM_FIELDS

_PHI32 = np.uint32(0x9E3779B9)


def fmix32_np(x: np.ndarray) -> np.ndarray:
    """Host mirror of ops.hashing.fmix32 (must stay bit-identical)."""
    x = x.astype(np.uint32).copy()
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x85EBCA6B)
    x ^= x >> np.uint32(13)
    x *= np.uint32(0xC2B2AE35)
    x ^= x >> np.uint32(16)
    return x


def hash_cols_np(cols: list[np.ndarray], seed: int) -> np.ndarray:
    """Host mirror of ops.hashing.hash_cols."""
    h0 = (int(seed) * 0x9E3779B9) & 0xFFFFFFFF
    h = np.full(cols[0].shape, h0, np.uint32)
    for c in cols:
        c = c.astype(np.uint32)
        h = fmix32_np(h ^ (c + _PHI32 + (h << np.uint32(6)) + (h >> np.uint32(2))))
    return h


def canonical_conn_hash(records: np.ndarray, seed: int = 0x5A) -> np.ndarray:
    """(N, F) records -> (N,) direction-independent connection hashes."""
    src, dst = records[:, F.SRC_IP], records[:, F.DST_IP]
    ports = records[:, F.PORTS]
    proto = records[:, F.META] >> np.uint32(24)
    sp, dp = ports >> np.uint32(16), ports & np.uint32(0xFFFF)
    fwd = (src < dst) | ((src == dst) & (sp <= dp))
    a_ip = np.where(fwd, src, dst).astype(np.uint32)
    b_ip = np.where(fwd, dst, src).astype(np.uint32)
    a_pt = np.where(fwd, sp, dp).astype(np.uint32)
    b_pt = np.where(fwd, dp, sp).astype(np.uint32)
    return hash_cols_np([a_ip, b_ip, (a_pt << np.uint32(16)) | b_pt, proto], seed)


@dataclasses.dataclass
class ShardedBatch:
    """One host batch split across D devices."""

    records: np.ndarray  # (D, B, NUM_FIELDS) uint32
    n_valid: np.ndarray  # (D,) uint32
    lost: int  # EVENTS dropped because a shard overflowed (sum of the
    # dropped rows' F.PACKETS weights — a combined row stands for many
    # events, parallel/combine.py)
    events: int = 0  # EVENTS the kept rows stand for (same packet
    # weighting as ``lost``) — what to count if this batch is dropped
    # downstream instead of reaching the device
    sample_k: int = 1  # overload 1-in-k applied before partitioning
    # (runtime/overload.py): the device step rescales non-exempt rows
    # by this factor so packet-weighted estimates stay unbiased; 1 =
    # unsampled


def _next_bucket(n: int) -> int:
    """Smallest m * 2^k >= n with mantissa m in {4,6}: transfer shapes
    quantize to within 50% of the payload (vs up to 100% for pure
    powers of two) while keeping the distinct-shape count — and thus
    the engine's per-shape ingest jits — small. Two shapes per octave
    (was four, mantissa {4,5,6,7}): each grid key costs seconds of
    trace+lower on the device-proxy thread at boot warm, and halving
    the grid halved that for a bounded ~17% average padding cost on a
    wire that is already <0.5 B/event."""
    if n <= 4:
        return max(n, 1)
    k = (n - 1).bit_length() - 3  # so that 4*2^k <= n-1 < 8*2^k
    step = 1 << (k + 1)  # multiples of 2^(k+1): mantissa 4 or 6
    return ((n + step - 1) // step) * step


def partition_events(
    records: np.ndarray,
    n_devices: int,
    capacity: int,
    min_bucket: int | None = None,
) -> ShardedBatch:
    """Split (N, F) valid records into a (D, B', F) sharded batch.

    Overflowing rows are dropped and counted, never blocked on (the
    reference's universal backpressure rule, SURVEY.md §3.2).

    ``min_bucket=None`` emits the full (D, capacity, F) shape. With an
    integer, the minor batch dim B' is the smallest bucket (see
    ``_next_bucket``) >= max(shard fill, min_bucket), capped at capacity —
    so a lightly-filled batch crosses the host->device link at its own
    size and is padded to the step's static (D, capacity, F) shape ON
    DEVICE (engine ingest jit), where HBM bandwidth makes the padding
    free. Quantized buckets keep the number of distinct transfer shapes
    (and ingest-kernel compiles) logarithmic.

    ALIASING CONTRACT: for ``n_devices == 1`` with a bucket-full
    contiguous batch, ``records`` is returned as a zero-copy VIEW —
    consume the ShardedBatch (e.g. ``jax.device_put``, as the engine
    does) before reusing the input buffer. Multi-device output is always
    a fresh array.

    Hashing and loss weighting use schema columns only; trailing
    columns beyond NUM_FIELDS (none in-tree today) would ride along
    untouched.
    """
    assert records.ndim == 2 and records.shape[1] >= NUM_FIELDS
    width = records.shape[1]

    def bucket_for(n_max: int) -> int:
        if min_bucket is None:
            return capacity
        return min(_next_bucket(max(n_max, min_bucket)), capacity)

    if n_devices == 1:
        # Fast path: one shard takes everything — no connection hashing,
        # and a full batch is a zero-copy reshape (the hash pass cost
        # ~22 ms per 131k-event batch, dominating the host feed loop).
        n = min(len(records), capacity)
        lost = int(records[n:, F.PACKETS].astype(np.uint64).sum())
        kept = int(records[:n, F.PACKETS].astype(np.uint64).sum())
        b = bucket_for(n)
        if n == b:
            out = np.ascontiguousarray(records[:n], np.uint32)
            out = out.reshape(1, b, width)
        else:
            out = np.zeros((1, b, width), np.uint32)
            out[0, :n] = records[:n]
        return ShardedBatch(records=out, n_valid=np.array([n], np.uint32),
                            lost=lost, events=kept)
    n_valid = np.zeros((n_devices,), np.uint32)
    lost = 0
    kept = 0
    if len(records):
        dev = canonical_conn_hash(records) % np.uint32(n_devices)
        counts = np.bincount(dev, minlength=n_devices)
        b = bucket_for(int(min(counts.max(), capacity)))
        out = np.zeros((n_devices, b, width), np.uint32)
        total = int(records[:, F.PACKETS].astype(np.uint64).sum())
        for d in range(n_devices):
            rows = records[dev == d]
            n = min(len(rows), capacity)
            out[d, :n] = rows[:n]
            n_valid[d] = n
            lost += int(rows[n:, F.PACKETS].astype(np.uint64).sum())
        kept = total - lost
    else:
        out = np.zeros((n_devices, bucket_for(0), width), np.uint32)
    return ShardedBatch(records=out, n_valid=n_valid, lost=lost, events=kept)

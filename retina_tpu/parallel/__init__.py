"""Mesh, partitioning, and collective merges (multi-chip scale-out).

Reference analog: SURVEY.md §2.6 — N independent agents + Prometheus
scrape-merge + Hubble relay become one device mesh running the fused
pipeline per-shard with psum/pmax/all_gather merges over ICI/DCN.
"""

from retina_tpu.parallel.mesh import batch_mesh, make_mesh  # noqa: F401
from retina_tpu.parallel.partition import (  # noqa: F401
    ShardedBatch,
    canonical_conn_hash,
    partition_events,
)
from retina_tpu.parallel.telemetry import (  # noqa: F401
    ShardedTelemetry,
    topk_from_snapshot,
)

"""Device mesh construction for sharded telemetry.

Reference analog (SURVEY.md §2.6): the reference scales by running N
independent node agents whose metrics are merged at Prometheus-scrape time,
and ships cluster-wide flows over the Hubble relay. The TPU-native design
replaces both with a **device mesh**: events are hash-partitioned across
chips, every chip runs the identical fused pipeline step, and merges ride
XLA collectives — `psum` over ICI within a slice, and over DCN between
hosts when the mesh spans multiple processes (jax.distributed).

Mesh shapes:
- single host, N chips:           1-D mesh  ("chip",)
- multi-host slice/cluster:       2-D mesh  ("node", "chip") — collectives
  over the ("node", "chip") tuple reduce over ICI first, then DCN, which is
  exactly the hierarchy the reference's scrape/relay topology implies.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    devices: Sequence[jax.Device] | None = None,
    n_nodes: int | None = None,
) -> Mesh:
    """Build the telemetry mesh over ``devices`` (default: all).

    With ``n_nodes`` set, returns a 2-D ("node", "chip") mesh — the shape
    used for cross-node service-graph export (BASELINE config 5, v5e-8 as
    8 "nodes"). Otherwise a 1-D ("chip",) mesh.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_nodes is not None:
        assert len(devs) % n_nodes == 0, (
            f"{len(devs)} devices do not split into {n_nodes} nodes"
        )
        per = len(devs) // n_nodes
        return Mesh(np.array(devs).reshape(n_nodes, per), ("node", "chip"))
    return Mesh(np.array(devs), ("chip",))


def batch_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """1-D ingest mesh named for WHAT is sharded over it: the event
    batch. ``Mesh(devices, ("batch",))`` with
    ``NamedSharding(mesh, PartitionSpec("batch"))`` is the data-parallel
    ingest layout (SNIPPETS.md [2]) — each device holds one feed shard,
    sketch state merges once per window over the same axis. Identical
    topology to ``make_mesh(devices)``; the axis name documents intent
    in every downstream PartitionSpec and jaxpr."""
    devs = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devs), ("batch",))

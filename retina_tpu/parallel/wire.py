"""Packed host->device wire format for event records.

The host->device link is the system's scarcest bandwidth (PCIe in
production, a network tunnel on the bench harness), so records cross it
packed: 12 uint32 lanes instead of the schema's 16 (events/schema.py),
unpacked back to the full 16-lane layout ON DEVICE where HBM bandwidth
makes the expansion free. Together with descriptor combining
(parallel/combine.py) and power-of-two transfer buckets
(parallel/partition.py), wire bytes per represented event drop from 64 to
~48/combine_ratio.

Layout (indices into the packed minor axis):

==  =========  ========================================================
ix  name       contents
==  =========  ========================================================
0   TS_REL     1 + nanoseconds since the batch base timestamp (u32;
               spreads beyond ~4.29 s saturate — harmless: the device
               consumes per-row time only for apiserver RTT matching).
               0 means "no timestamp": a source that never stamps
               round-trips to ts 0 exactly instead of inheriting the
               batch base (which would feed phantom values into the
               apiserver RTT latency matcher)
1   SRC_IP     = schema F.SRC_IP
2   DST_IP     = schema F.DST_IP
3   PORTS      = schema F.PORTS
4   META       = schema F.META
5   BYTES      = schema F.BYTES
6   PACKETS    = schema F.PACKETS
7   MISC       VERDICT(3b) << 29 | DROP_REASON(8b) << 21 |
               EVENT_TYPE(4b) << 17 | IFINDEX(17b)   (each saturating)
8   TSVAL      = schema F.TSVAL
9   TSECR      = schema F.TSECR
10  DNS        = schema F.DNS
11  DNS_QHASH  = schema F.DNS_QHASH
==  =========  ========================================================

The batch base timestamp travels as two u32 scalars (lo, hi) beside the
array. Saturation bounds (verdict 7, reason 255, event type 15, ifindex
131071) exceed every value the reference emits (flow.Verdict <= 5, drop
reason ids < 200, EV_* < 8; pkg/utils/flow_utils.go).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from retina_tpu.events.schema import F, NUM_FIELDS

PACKED_FIELDS = 12

_U32 = np.uint64(0xFFFFFFFF)


def batch_ts_base(records: np.ndarray) -> np.uint64:
    """Minimum nonzero 64-bit timestamp of the batch (0 if none) — the
    TS_REL base shared by every wire array cut from one flush."""
    ts = (records[..., F.TS_HI].astype(np.uint64) << np.uint64(32)) | records[
        ..., F.TS_LO
    ].astype(np.uint64)
    nz = ts[ts > 0]
    return np.uint64(nz.min()) if len(nz) else np.uint64(0)


def ts_rel(records: np.ndarray, base: np.uint64) -> np.ndarray:
    """Biased relative timestamps: 1 + ns since ``base`` (saturating),
    0 for unstamped rows — the TS_REL lane encoding."""
    ts = (records[..., F.TS_HI].astype(np.uint64) << np.uint64(32)) | records[
        ..., F.TS_LO
    ].astype(np.uint64)
    return np.where(
        ts > 0,
        np.minimum(ts - base, _U32 - np.uint64(1)) + np.uint64(1),
        0,
    ).astype(np.uint32)


def known_rows(
    rows: np.ndarray, ids: np.ndarray, id_bits: int, out: np.ndarray
) -> None:
    """Fill the 2-word known-row wire encoding in place:
    ``word0 = flow_id | packets << id_bits``, ``word1 = bytes``.

    One definition shared by the engine's numpy fallback
    (engine._dispatch_flowdict) and bench's host-path probe — the
    encoding IS the v3 wire contract, and two hand-rolled copies of the
    bit layout can silently drift apart."""
    out[:, 0] = ids | (rows[:, F.PACKETS] << id_bits)
    out[:, 1] = rows[:, F.BYTES]


# -- v4 dense known-row bitstream -------------------------------------
#
# The v3 known row spends two full u32 lanes per row; at the default
# 18-bit flow dictionary only 18 + ~14 of the first 32 bits carry
# information and BYTES almost never needs 32. v4 packs each known row
# as (id_bits + DENSE_PK_BITS + DENSE_BY_BITS) CONTIGUOUS bits —
# ``id | packets << id_bits | bytes << (id_bits + DENSE_PK_BITS)`` —
# streamed into one u32 word array: 50 bits = 6.25 B/row at id_bits=18
# vs 8, and the row narrows further for smaller dictionaries. Rows
# whose PACKETS or BYTES overflow their lane escalate to the full
# 13-word new-row side exactly like the v3 packet-overflow escalation
# (engine._dispatch_flowdict adds the bytes term to the mask), so the
# stream stores every surviving row exactly. The +1 pad word keeps the
# device unpack's two-word gather in bounds for the final row.
#
# Three implementations, cross-checked bit-for-bit by
# tests/test_wire.py: native/pack.cpp rt_flowwire_dense (the fast
# path), dense_known_rows below (numpy fallback), and
# dense_known_unpack_device (the device-side reader).

DENSE_PK_BITS = 10
DENSE_BY_BITS = 22


def dense_row_bits(id_bits: int) -> int:
    """Bits per dense known row. <= 64 for every legal dictionary size
    (id_bits <= 32)."""
    return int(id_bits) + DENSE_PK_BITS + DENSE_BY_BITS


def dense_words(n_rows: int, id_bits: int) -> int:
    """u32 words needed for ``n_rows`` dense known rows, including the
    pad word the device unpack's two-word gather requires."""
    return (int(n_rows) * dense_row_bits(id_bits) + 31) // 32 + 1


def dense_known_rows(
    rows: np.ndarray, ids: np.ndarray, id_bits: int, out: np.ndarray
) -> None:
    """Numpy twin of native rt_flowwire_dense's known side: OR the
    dense bit rows into the ZEROED 1-D u32 ``out`` stream in row order.
    Caller guarantees packets < 2**DENSE_PK_BITS and bytes <
    2**DENSE_BY_BITS (the escalation mask's job)."""
    k = len(rows)
    if k == 0:
        return
    rb = dense_row_bits(id_bits)
    v = (
        ids.astype(np.uint64)
        | (rows[:, F.PACKETS].astype(np.uint64) << np.uint64(id_bits))
        | (rows[:, F.BYTES].astype(np.uint64)
           << np.uint64(id_bits + DENSE_PK_BITS))
    )
    p = np.arange(k, dtype=np.uint64) * np.uint64(rb)
    wi = (p >> np.uint64(5)).astype(np.int64)
    sh = p & np.uint64(31)
    # A <=64-bit value shifted by <=31 spans <=3 words; split explicitly
    # (v << sh would overflow u64 for sh > 64 - rb).
    lo = ((v & _U32) << sh) & _U32
    mid = (v >> (np.uint64(32) - sh)) & _U32  # sh==0 -> v >> 32: word 1
    hi_sh = np.where(sh > 0, np.uint64(64) - sh, np.uint64(63))
    hi = np.where(sh > 0, v >> hi_sh, np.uint64(0))
    np.bitwise_or.at(out, wi, lo.astype(np.uint32))
    np.bitwise_or.at(out, wi + 1, mid.astype(np.uint32))
    np.bitwise_or.at(out, wi + 2, hi.astype(np.uint32))


def dense_known_unpack_device(words, n_rows: int, id_bits: int):
    """jax: dense known stream -> (ids, packets, bytes), each (..., n).

    ``words`` is (..., W) u32 (per-device streams stack on the leading
    axis); gathers two words per field and shifts them together — every
    field is <= 32 bits wide, so two words always suffice. Runs inside
    the engine's known-ingest jit.
    """
    rb = dense_row_bits(id_bits)
    i = jnp.arange(n_rows, dtype=jnp.uint32)

    def field(off: int, width: int):
        p = i * np.uint32(rb) + np.uint32(off)
        wi = (p >> np.uint32(5)).astype(jnp.int32)
        sh = p & np.uint32(31)
        lo = words[..., wi] >> sh
        up = words[..., wi + 1]
        # sh==0 would shift by 32 (undefined); (32-sh)&31 makes it a
        # shift by 0 and the where() discards the lane.
        up = jnp.where(
            sh > 0, up << ((np.uint32(32) - sh) & np.uint32(31)), 0
        )
        return (lo | up) & np.uint32((1 << width) - 1)

    return (
        field(0, id_bits),
        field(id_bits, DENSE_PK_BITS),
        field(id_bits + DENSE_PK_BITS, DENSE_BY_BITS),
    )


def dense_known_unpack_numpy(
    words: np.ndarray, n_rows: int, id_bits: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host mirror of dense_known_unpack_device (tests)."""
    rb = dense_row_bits(id_bits)
    i = np.arange(n_rows, dtype=np.uint32)

    def field(off: int, width: int) -> np.ndarray:
        p = i * np.uint32(rb) + np.uint32(off)
        wi = (p >> np.uint32(5)).astype(np.int64)
        sh = p & np.uint32(31)
        lo = words[..., wi] >> sh
        up = words[..., wi + 1]
        up = np.where(
            sh > 0, up << ((np.uint32(32) - sh) & np.uint32(31)), 0
        ).astype(np.uint32)
        return (lo | up) & np.uint32((1 << width) - 1)

    return (
        field(0, id_bits),
        field(id_bits, DENSE_PK_BITS),
        field(id_bits + DENSE_PK_BITS, DENSE_BY_BITS),
    )


def pack_records(
    records: np.ndarray, base: np.uint64 | None = None
) -> tuple[np.ndarray, np.uint32, np.uint32]:
    """(..., 16) u32 -> ((..., 12) u32, base_lo, base_hi).

    Works on (N, 16) host batches and (D, B, 16) sharded batches alike;
    padding rows (all zeros) pack to all-zero rows given base handling
    below. The base defaults to the minimum valid timestamp of THIS
    array; pass one explicitly when several wire arrays cut from one
    flush must share it. Zero-timestamp rows (padding or sources that
    never stamp) keep TS_REL 0.
    """
    if records.ndim == 2:
        # Native single pass (native/pack.cpp) when available: packing
        # sits on the flush critical path, and the strided column
        # copies + u64 timestamp math below are ~19% of the host feed
        # cost at production quanta.
        try:
            from retina_tpu.native import pack_native
        except ImportError:
            got = None
        else:
            # Binding errors must surface, not silently fall back to
            # the slow path on every flush.
            got = pack_native(
                records, None if base is None else int(base)
            )
        if got is not None:
            out, nbase = got
            nbase = np.uint64(nbase)
            return (
                out,
                np.uint32(nbase & _U32),
                np.uint32(nbase >> np.uint64(32)),
            )
    if base is None:
        base = batch_ts_base(records)
    rel = ts_rel(records, base)
    out = np.empty(records.shape[:-1] + (PACKED_FIELDS,), np.uint32)
    out[..., 0] = rel
    out[..., 1] = records[..., F.SRC_IP]
    out[..., 2] = records[..., F.DST_IP]
    out[..., 3] = records[..., F.PORTS]
    out[..., 4] = records[..., F.META]
    out[..., 5] = records[..., F.BYTES]
    out[..., 6] = records[..., F.PACKETS]
    out[..., 7] = (
        (np.minimum(records[..., F.VERDICT], 7) << np.uint32(29))
        | (np.minimum(records[..., F.DROP_REASON], 255) << np.uint32(21))
        | (np.minimum(records[..., F.EVENT_TYPE], 15) << np.uint32(17))
        | np.minimum(records[..., F.IFINDEX], 0x1FFFF)
    )
    out[..., 8] = records[..., F.TSVAL]
    out[..., 9] = records[..., F.TSECR]
    out[..., 10] = records[..., F.DNS]
    out[..., 11] = records[..., F.DNS_QHASH]
    return (
        out,
        np.uint32(base & _U32),
        np.uint32(base >> np.uint64(32)),
    )


def unpack_records_device(packed, base_lo, base_hi):
    """jax: (..., 12) u32 + base scalars -> (..., 16) u32 (schema layout).

    Runs inside the engine's per-bucket unpack-pad jit; XLA fuses the bit
    surgery with the zero-extension to the step's static shape.
    """
    rel = packed[..., 0]
    relm1 = rel - np.uint32(1)  # wraps for rel==0; masked below
    ts_lo = base_lo + relm1
    carry = (ts_lo < relm1).astype(jnp.uint32)
    stamped = rel > 0
    misc = packed[..., 7]
    cols = [None] * NUM_FIELDS
    cols[F.TS_LO] = jnp.where(stamped, ts_lo, 0)
    cols[F.TS_HI] = jnp.where(stamped, base_hi + carry, 0)
    cols[F.SRC_IP] = packed[..., 1]
    cols[F.DST_IP] = packed[..., 2]
    cols[F.PORTS] = packed[..., 3]
    cols[F.META] = packed[..., 4]
    cols[F.BYTES] = packed[..., 5]
    cols[F.PACKETS] = packed[..., 6]
    cols[F.VERDICT] = misc >> 29
    cols[F.DROP_REASON] = (misc >> 21) & np.uint32(0xFF)
    cols[F.EVENT_TYPE] = (misc >> 17) & np.uint32(0xF)
    cols[F.IFINDEX] = misc & np.uint32(0x1FFFF)
    cols[F.TSVAL] = packed[..., 8]
    cols[F.TSECR] = packed[..., 9]
    cols[F.DNS] = packed[..., 10]
    cols[F.DNS_QHASH] = packed[..., 11]
    return jnp.stack(cols, axis=-1)


def unpack_records_numpy(packed: np.ndarray, base_lo, base_hi) -> np.ndarray:
    """Host mirror of unpack_records_device (tests)."""
    rel = packed[..., 0]
    relm1 = (rel - np.uint32(1)).astype(np.uint32)  # wraps for rel==0
    ts_lo = (np.uint32(base_lo) + relm1).astype(np.uint32)
    carry = (ts_lo < relm1).astype(np.uint32)
    stamped = rel > 0
    misc = packed[..., 7]
    out = np.empty(packed.shape[:-1] + (NUM_FIELDS,), np.uint32)
    out[..., F.TS_LO] = np.where(stamped, ts_lo, 0)
    out[..., F.TS_HI] = np.where(stamped, np.uint32(base_hi) + carry, 0)
    out[..., F.SRC_IP] = packed[..., 1]
    out[..., F.DST_IP] = packed[..., 2]
    out[..., F.PORTS] = packed[..., 3]
    out[..., F.META] = packed[..., 4]
    out[..., F.BYTES] = packed[..., 5]
    out[..., F.PACKETS] = packed[..., 6]
    out[..., F.VERDICT] = misc >> 29
    out[..., F.DROP_REASON] = (misc >> 21) & np.uint32(0xFF)
    out[..., F.EVENT_TYPE] = (misc >> 17) & np.uint32(0xF)
    out[..., F.IFINDEX] = misc & np.uint32(0x1FFFF)
    out[..., F.TSVAL] = packed[..., 8]
    out[..., F.TSECR] = packed[..., 9]
    out[..., F.DNS] = packed[..., 10]
    out[..., F.DNS_QHASH] = packed[..., 11]
    return out

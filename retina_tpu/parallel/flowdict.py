"""Host-side flow-descriptor dictionary for the v2 wire format.

The combiner (parallel/combine.py) already collapses a flush quantum to
its distinct flow descriptors — but across quanta the SAME descriptors
recur (flows are long-lived; the reference's kernel maps bank on exactly
that). This dictionary closes the loop: every distinct descriptor gets a
stable id once, the descriptor's 12 packed lanes cross the host->device
link once (a "new" row), and every later occurrence crosses as an
8-byte ``[id | packets << id_bits, bytes]`` pair (v3 wire; v2 used a
16-byte 4-tuple) against the device-resident descriptor table (engine
ingest gathers the lanes back in HBM, where the bandwidth is ~3 orders
of magnitude above the link). Packet counts beyond the id lane's
headroom escalate to a full-row re-upload (idempotent), keeping exact
counters exact.

Reference analog: the eBPF map key set — pkg/plugin/conntrack and
packetforward keep per-flow keys resident kernel-side and move only
counters per read interval. Here the "map" spans the host/device link.

Capacity contract: ids are slots in the device table. When the table
fills, the dictionary CLEARS and bumps its generation — every flow is
"new" again and re-uploads its descriptor (a one-quantum burst, not an
error). The engine never references an id the current generation did not
assign, so the device table needs no generation tag: slots are always
rewritten by a new-row upload before a known-row references them (proxy
FIFO order).
"""

from __future__ import annotations

import numpy as np

from retina_tpu.parallel.combine import KEY_COLS

_KEY_COLS = np.asarray(KEY_COLS, np.int64)


class HostFlowDict:
    """descriptor bytes -> stable device-table slot id."""

    def __init__(self, capacity: int = 1 << 20):
        self.capacity = int(capacity)
        self.generation = 0
        self._ids: dict[bytes, int] = {}

    def __len__(self) -> int:
        return len(self._ids)

    def clear(self) -> None:
        self._ids.clear()
        self.generation += 1

    def lookup_or_assign(
        self, records: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """(N, >=16) records -> (ids (N,) u32, is_new (N,) bool).

        Assigns fresh ids to unseen descriptors in row order. If the
        batch would overflow capacity, the dictionary clears first
        (generation bump) and every row in this batch is "new"; a batch
        with more distinct descriptors than capacity gets ids only for
        the first ``capacity`` rows — the rest return id 0 with
        ``is_new`` True, which the engine ships as plain full rows that
        never enter the table (id slot 0 is sacrificed for this
        sentinel; the dictionary never assigns it).
        """
        n = len(records)
        ids = np.zeros(n, np.uint32)
        is_new = np.zeros(n, bool)
        if n == 0:
            return ids, is_new
        descs = np.ascontiguousarray(
            records[:, _KEY_COLS].astype(np.uint32, copy=False)
        )
        keys = descs.view(
            np.dtype((np.void, descs.shape[1] * 4))
        ).ravel()
        table = self._ids
        # Pessimistic overflow check: clearing mid-batch would violate
        # the "never reference an id this generation didn't assign"
        # contract for rows already marked known.
        if len(table) + n > self.capacity:
            fresh = set(keys.tolist()) - table.keys()
            if len(table) + len(fresh) > self.capacity:
                self.clear()
                table = self._ids
        next_id = len(table) + 1  # slot 0 reserved as overflow sentinel
        for i, k in enumerate(keys.tolist()):
            got = table.get(k)
            if got is None:
                is_new[i] = True
                if next_id < self.capacity:
                    table[k] = next_id
                    ids[i] = next_id
                    next_id += 1
                # else: id stays 0 — ships as a table-less full row
            else:
                ids[i] = got
        return ids, is_new


def flow_dict_stats(fd) -> dict:
    """Residency summary for debug vars / bench JSON. Duck-typed over
    both implementations (capacity / __len__ / generation); ``fd`` may
    be None when packed wire or the flow dict is disabled."""
    if fd is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "entries": len(fd),
        "capacity": int(fd.capacity),
        "generation": int(fd.generation),
    }


def make_flow_dict(capacity: int):
    """Native (GIL-released single pass, native/flowdict.cpp) when the
    library is available, else the Python dict. Same contract either
    way — tests cross-check them on random batches."""
    try:
        from retina_tpu.native import NativeFlowDict

        return NativeFlowDict(capacity)
    except Exception:
        return HostFlowDict(capacity)

"""Looping pcap replay — the real `event_source=pcap` feed.

`config.py` has declared ``pcap_path`` + ``pcap_loop`` since the seed,
but the original replay loop just re-sliced the decoded record array
from position 0, so every loop pass re-emitted the capture's ORIGINAL
timestamps: windowing state saw time jump backwards once per pass, and
conntrack saw the same connections reborn in the past. This module
makes the loop a real feed:

- **Timestamp rebasing**: each pass re-emits the capture shifted
  forward by ``pass_index * (capture_span + one median inter-packet
  gap)``, so TS_LO/TS_HI advance monotonically across loop seams —
  an infinite capture, not a stuck one.
- **Graceful degradation**: truncated or outright garbage pcap bytes
  decode to an empty/partial record set with a counted drop
  (`lost_events{stage="decode"}`) instead of raising out of the
  plugin's compile step and taking the source down — a bad capture
  file is an operational input, not a programming error (the
  crash-only philosophy stops at inputs the operator hands us).

Built on sources/pcapdecode.py (:func:`decode_pcap_bytes`); the
packetparser plugin wires this through the plugin registry for
``event_source=pcap``.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Iterator

import numpy as np

from retina_tpu.events.schema import F, NUM_FIELDS
from retina_tpu.log import logger
from retina_tpu.sources.pcapdecode import (
    PCAP_MAGIC_NS,
    PCAP_MAGIC_US,
    PcapDecodeResult,
    decode_pcap_bytes,
)

_log = logger("pcapreplay")

_SWAPPED = {
    int.from_bytes(m.to_bytes(4, "little"), "big")
    for m in (PCAP_MAGIC_US, PCAP_MAGIC_NS)
}


def _undecoded_tail(data: bytes) -> int:
    """Bytes after the last complete pcap record — nonzero for a
    capture truncated mid-record (or mid-header). 0 for a clean file
    or an unrecognizable blob (the caller's except path owns those)."""
    if not data:
        return 0
    if len(data) < 24:
        return len(data)  # not even a global header
    magic = struct.unpack_from("<I", data)[0]
    if magic in (PCAP_MAGIC_US, PCAP_MAGIC_NS):
        fmt = "<IIII"
    elif magic in _SWAPPED:
        fmt = ">IIII"
    else:
        return 0
    unpack = struct.Struct(fmt).unpack_from
    off = 24
    while off + 16 <= len(data):
        _, _, incl, _ = unpack(data, off)
        if off + 16 + incl > len(data):
            break
        off += 16 + incl
    return len(data) - off


@dataclasses.dataclass
class SafeDecode:
    """Outcome of a tolerant decode: always usable, never raises."""

    result: PcapDecodeResult
    dropped: int  # packets (or whole blobs) that could not decode
    error: str = ""  # non-empty when the blob itself was undecodable


def safe_decode_bytes(data: bytes, **kw) -> SafeDecode:
    """Decode pcap bytes, degrading instead of raising.

    - A valid capture with a truncated tail decodes its complete
      prefix (pcapdecode stops at the first short record); the
      undecoded remainder counts as ``dropped``.
    - Garbage bytes (bad magic, mid-file corruption the decoder cannot
      skip) yield an EMPTY result with ``dropped=1`` and the error
      string — one counted drop for the whole blob, since a corrupt
      header leaves no packet count to attribute.
    """
    try:
        res = decode_pcap_bytes(data, **kw)
    except Exception as e:  # noqa: BLE001 — operator input, degrade not crash
        empty = PcapDecodeResult(
            records=np.zeros((0, NUM_FIELDS), np.uint32),
            dns_names={}, n_packets_total=0, n_decoded=0,
        )
        return SafeDecode(empty, dropped=1,
                          error=f"{type(e).__name__}: {e}")
    dropped = res.n_packets_total - res.n_decoded
    if _undecoded_tail(data):
        dropped += 1  # the truncated trailing record
    return SafeDecode(res, dropped=dropped)


def _ts_ns(records: np.ndarray) -> np.ndarray:
    """(N,) uint64 timestamps from the TS_LO/TS_HI u32 lanes."""
    return (
        records[:, F.TS_HI].astype(np.uint64) << np.uint64(32)
    ) | records[:, F.TS_LO].astype(np.uint64)


class PcapReplaySource:
    """Block iterator over decoded pcap records with per-pass
    timestamp rebasing.

    One decode up front (compile-time cost, like every other source);
    each :meth:`blocks` pass yields copies with TS lanes shifted so
    replayed time advances monotonically forever. The source array is
    never mutated — loops share it by reference.
    """

    def __init__(self, records: np.ndarray, block: int = 8192):
        self.records = records
        self.block = max(1, int(block))
        if len(records):
            ts = _ts_ns(records)
            span = int(ts.max()) - int(ts.min())
            # Seam gap: the median inter-packet gap (1 µs floor) so the
            # rebased pass starts one "typical packet" after the last,
            # not at the identical instant.
            gaps = np.diff(np.sort(ts)).astype(np.int64)
            gap = int(np.median(gaps)) if len(gaps) else 0
            self.pass_stride_ns = span + max(gap, 1_000)
        else:
            self.pass_stride_ns = 0
        self.passes_done = 0

    def __len__(self) -> int:
        return len(self.records)

    def _rebase(self, block: np.ndarray, shift_ns: int) -> np.ndarray:
        if shift_ns == 0:
            return block
        out = block.copy()
        ts = _ts_ns(out) + np.uint64(shift_ns)
        out[:, F.TS_LO] = (ts & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        out[:, F.TS_HI] = (ts >> np.uint64(32)).astype(np.uint32)
        return out

    def blocks(self) -> Iterator[np.ndarray]:
        """Yield one full pass of block-sized slices, rebased for the
        current pass index; call again for the next (later) pass."""
        shift = self.passes_done * self.pass_stride_ns
        for pos in range(0, len(self.records), self.block):
            yield self._rebase(self.records[pos : pos + self.block], shift)
        self.passes_done += 1

"""/proc and /sys parsers for host-stat plugins.

Reference analog: pkg/plugin/linuxutil/netstat_stats_linux.go:20-21 parses
``/proc/net/netstat`` + ``/proc/net/snmp``; ethtool_stats_linux.go reads
per-NIC counters via ioctl (here: ``/sys/class/net/<if>/statistics``);
infiniband_stats_linux.go walks ``/sys/class/infiniband``.
"""

from __future__ import annotations

import os
from pathlib import Path


def parse_kv_pairs_file(path: str) -> dict[str, dict[str, int]]:
    """Parse the netstat/snmp two-line format:
    ``Proto: name1 name2...`` / ``Proto: v1 v2...`` → {proto: {name: val}}.
    """
    out: dict[str, dict[str, int]] = {}
    try:
        lines = Path(path).read_text().splitlines()
    except OSError:
        return out
    for head, vals in zip(lines[::2], lines[1::2]):
        hp, _, hnames = head.partition(":")
        vp, _, vvals = vals.partition(":")
        if hp != vp:
            continue
        names = hnames.split()
        values = []
        for v in vvals.split():
            try:
                values.append(int(v))
            except ValueError:
                values.append(0)
        out[hp] = dict(zip(names, values))
    return out


def read_netstat(proc_root: str = "/proc") -> dict[str, dict[str, int]]:
    return parse_kv_pairs_file(f"{proc_root}/net/netstat")


def read_snmp(proc_root: str = "/proc") -> dict[str, dict[str, int]]:
    return parse_kv_pairs_file(f"{proc_root}/net/snmp")


def read_softnet_drops(proc_root: str = "/proc") -> int:
    """Sum of per-CPU softnet drop counters (column 2, hex)."""
    total = 0
    try:
        for line in Path(f"{proc_root}/net/softnet_stat").read_text().splitlines():
            cols = line.split()
            if len(cols) >= 2:
                total += int(cols[1], 16)
    except OSError:  # noqa: RT101 — softnet_stat absent on this kernel
        pass
    return total


def read_iface_stats(sys_root: str = "/sys") -> dict[str, dict[str, int]]:
    """{iface: {stat: value}} from /sys/class/net/*/statistics (the
    ethtool-stats analog — same per-NIC counters without the ioctl)."""
    out: dict[str, dict[str, int]] = {}
    base = Path(f"{sys_root}/class/net")
    try:
        ifaces = sorted(os.listdir(base))
    except OSError:
        return out
    for iface in ifaces:
        stats_dir = base / iface / "statistics"
        stats: dict[str, int] = {}
        try:
            for stat in os.listdir(stats_dir):
                try:
                    stats[stat] = int((stats_dir / stat).read_text())
                except (OSError, ValueError):
                    continue
        except OSError:
            continue
        if stats:
            out[iface] = stats
    return out


def read_infiniband_counters(
    sys_root: str = "/sys",
) -> dict[tuple[str, str], dict[str, int]]:
    """{(device, port): {counter: value}} from /sys/class/infiniband."""
    out: dict[tuple[str, str], dict[str, int]] = {}
    base = Path(f"{sys_root}/class/infiniband")
    try:
        devices = sorted(os.listdir(base))
    except OSError:
        return out
    for dev in devices:
        ports_dir = base / dev / "ports"
        try:
            ports = sorted(os.listdir(ports_dir))
        except OSError:
            continue
        for port in ports:
            counters: dict[str, int] = {}
            cdir = ports_dir / port / "counters"
            try:
                for c in os.listdir(cdir):
                    try:
                        counters[c] = int((cdir / c).read_text())
                    except (OSError, ValueError):
                        continue
            except OSError:
                continue
            if counters:
                out[(dev, port)] = counters
    return out


def read_infiniband_status_params(
    sys_root: str = "/sys",
) -> dict[str, dict[str, str]]:
    """{iface: {param: value}} from /sys/class/net/*/debug (status params
    the reference reads, infiniband_stats_linux.go)."""
    out: dict[str, dict[str, str]] = {}
    base = Path(f"{sys_root}/class/net")
    try:
        ifaces = sorted(os.listdir(base))
    except OSError:
        return out
    for iface in ifaces:
        dbg = base / iface / "debug"
        params: dict[str, str] = {}
        try:
            for p in os.listdir(dbg):
                try:
                    params[p] = (dbg / p).read_text().strip()
                except OSError:
                    continue
        except OSError:
            continue
        if params:
            out[iface] = params
    return out

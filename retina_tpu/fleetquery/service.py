"""Fleet-level federated range queries (``GET /fleet/query``).

Query params are the node tier's (timetravel/query.py): ``t0``/``t1``
window-epoch range or ``last=N``, plus ``k`` and ``fam``. The answer is
the node tier's doc shape plus a ``coverage`` block::

    {"coverage": {"nodes_answered": 58, "nodes_total": 64,
                  "partial": true}, ...}

Latency contract (inherited verbatim from PR 10's node tier — the
thing tests/test_fleetquery.py and the dryrun p99 gate pin): handler
threads NEVER queue behind a fold or a scatter. One gather+fold runs at
a time (non-blocking single-flight); concurrent requests serve from the
TTL result cache — stale if need be — or answer ``busy`` immediately.
Ranges ending at or before the fleet's newest known epoch are immutable
and key with a zero edge token (stable cache key). Under SHEDDING no
scatter is ever initiated: any cached result serves (TTL ignored),
everything else is ``busy`` — backing off the whole fleet exactly when
this node is shedding its own load.

Fan-out mechanics: every node is asked once on a shared bounded pool;
after ``fleetquery_hedge_delay_s`` of quiet, unfinished nodes get ONE
hedged duplicate request; whatever lands by
``fleetquery_node_deadline_s`` merges, everyone else is counted in
``fleet_query_node_errors`` and the answer ships partial.

Federation splits the fold in two, leaning on the RFLT semilattice
(fold.py: every per-array op associative + commutative): each NODE
folds its own span slots locally and ships one merged snapshot — the
same bytes-on-the-wire argument as the fleet shipper, and node folds
run in parallel across the scatter pool — then this service folds the
node snapshots in fixed-size chunks (``_fold_many``). Chunking keeps
every jit signature in ``{2..FOLD_CHUNK}`` no matter the fan-out or how
many nodes answered, so a mid-storm node kill never triggers a
recompile on the query path.
"""

from __future__ import annotations

import concurrent.futures
import json
import threading
import time
from collections import Counter
from typing import Any

from retina_tpu.fleet.aggregator import format_key
from retina_tpu.log import logger, rate_limited
from retina_tpu.metrics import get_metrics
from retina_tpu.runtime.overload import SHEDDING
from retina_tpu.timetravel.fold import (
    RangeFold, range_decode, range_extract, range_topk, set_aot_cache_dir,
)
from retina_tpu.timetravel.ring import RingProtocol

_JSON = "application/json"

# Max operands per fold call in the cluster merge (see module
# docstring: bounds jit signatures under any fan-out / answer count).
FOLD_CHUNK = 8


def _reply(code: int, doc: dict) -> tuple[int, bytes, str]:
    return code, json.dumps(doc, default=str).encode(), _JSON


class LocalNodeClient:
    """A fleet member reachable in-process: one snapshot ring + the
    node-side span fold behind the NodeClient surface
    (``query(e0, e1, deadline_s)`` -> answer dict or None). The dryrun
    and tests build fleets of these; a transport-backed client (gRPC /
    relay) answers the same shape::

        {"node": str, "epochs": [int, ...], "window_s": float,
         "seeds": {...}, "arrays": {name: ndarray} | None}

    ``arrays`` is the node's span-folded snapshot (None when the range
    is empty there). Immutable spans are cached per ring generation, so
    a repeat query is a dict hit — exactly what a real node's own query
    tier would serve.
    """

    def __init__(
        self,
        name: str,
        ring: RingProtocol,
        fold: RangeFold,
        latency_s: float = 0.0,
    ) -> None:
        self.name = name
        self.ring = ring
        self.fold = fold
        self.latency_s = float(latency_s)
        self.dead = False  # harness kill switch (simulated node loss)
        self.calls = 0
        self._cache: dict[Any, dict] = {}

    def query(  # hot-path: query
        self, e0: int, e1: int, deadline_s: float
    ) -> dict[str, Any] | None:
        self.calls += 1
        if self.dead:
            return None
        if self.latency_s > 0:
            time.sleep(self.latency_s)  # noqa: RT400 — simulated wire latency; LocalNodeClient is the in-process harness transport, 0.0 by default
        if self.dead:  # died while "on the wire"
            return None
        key = (int(e0), int(e1), self.ring.appended)
        hit = self._cache.get(key)
        if hit is not None:
            return dict(hit)
        slots = self.ring.select(e0, e1)
        if not slots:
            ans: dict[str, Any] = {
                "node": self.name, "epochs": [], "window_s": 0.0,
                "seeds": {}, "arrays": None,
            }
        else:
            seeds = slots[0][3]
            arrays = (
                slots[0][1] if len(slots) == 1
                else self.fold.fold([s[1] for s in slots], seeds)
            )
            ans = {
                "node": self.name,
                "epochs": [s[0] for s in slots],
                "window_s": slots[0][2],
                "seeds": dict(seeds),
                "arrays": arrays,
            }
        self._cache[key] = ans
        while len(self._cache) > 32:
            self._cache.pop(next(iter(self._cache)))
        return dict(ans)


class FleetQueryService:
    """One per daemon; owns the scatter pool, the fold jit cache and
    the fleet-level result cache."""

    def __init__(self, cfg, overload=None, fold: RangeFold | None = None):
        self.cfg = cfg
        self.log = logger("fleetquery")
        self._overload = overload
        # Fleet folds share the engine's AOT disk cache like the node
        # query tier does (restart cost, BENCH_r06).
        set_aot_cache_dir(getattr(cfg, "aot_cache_dir", ""))
        self.fold = fold or RangeFold()
        self.clients: list[Any] = []
        self.ring: RingProtocol | None = None  # aggregator epoch ring
        # (e0, e1, k, fam, edge) -> (monotonic_t, result doc)
        self._cache: dict[Any, tuple[float, dict]] = {}
        self._cache_lock = threading.Lock()
        self._flight = threading.Lock()
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None
        self._pool_lock = threading.Lock()
        # Live-edge token: bumped whenever new fleet epochs may exist
        # (note_append, or a gather that saw a newer epoch). Ranges
        # ending at or before the last known newest epoch key with
        # edge 0 — a stable key, like the node tier's immutable ranges.
        self._edge = 0
        self._newest = -1
        self.queries = 0
        self.hedges = 0
        self.node_errors: dict[str, int] = {}

    # -- wiring --------------------------------------------------------
    def add_client(self, client: Any) -> None:
        """Register one fleet member (NodeClient surface)."""
        self.clients.append(client)

    def add_ring(self, ring: RingProtocol) -> None:
        """Aggregator-resident mode: no scatter, fold the merged-epoch
        ring directly (every epoch there is already cluster-merged)."""
        self.ring = ring

    def note_append(self) -> None:
        """Signal that new fleet epochs may have landed (aggregator
        merge tick / shipper close). Invalidates live-edge cache keys."""
        self._edge += 1

    def attach(self, server) -> None:
        server.register_route("/fleet/query", self.handle)
        server.expose_var("fleetquery", self.stats)

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def stats(self) -> dict:
        return {
            "clients": len(self.clients),
            "dead_clients": sum(
                1 for c in self.clients if getattr(c, "dead", False)
            ),
            "ring": self.ring.name if self.ring is not None else None,
            "queries": self.queries,
            "hedges": self.hedges,
            "node_errors": dict(self.node_errors),
            "newest_epoch": self._newest,
            "cache_entries": len(self._cache),
        }

    # -- HTTP entry (handler threads; must bound latency) --------------
    def handle(self, q: dict) -> tuple[int, bytes, str]:  # hot-path: query
        m = get_metrics()
        t0 = time.monotonic()
        status = "error"
        try:
            code, doc, status = self._handle(q)
            return _reply(code, doc)
        except Exception:
            if rate_limited("fleetquery"):
                self.log.exception("fleet query failed")
            return _reply(500, {"error": "internal"})
        finally:
            m.fleet_query_seconds.observe(time.monotonic() - t0)
            m.fleet_query_requests.labels(status=status).inc()
            self.queries += 1

    def _handle(self, q: dict) -> tuple[int, dict, str]:
        if not self.clients and self.ring is None:
            return 404, {"error": "no fleet sources attached"}, "bad_request"
        newest = self._newest
        if self.ring is not None and not self.clients:
            _, newest = self.ring.span()
        if "last" in q:
            if newest < 0:
                return 400, {
                    "error": "fleet span unknown yet; use t0+t1"
                }, "bad_request"
            n = max(1, int(q["last"][0]))
            e0, e1 = newest - n + 1, newest + 1
        else:
            try:
                e0 = int(q["t0"][0])
                e1 = int(q["t1"][0])
            except (KeyError, ValueError, IndexError):
                return 400, {"error": "need t0+t1 (window epochs) "
                             "or last=N"}, "bad_request"
        if e1 <= e0:
            return 400, {"error": "empty range: t1 <= t0"}, "bad_request"
        k = int(q.get("k", [self.cfg.fleetquery_topk])[0])
        fam = q.get("fam", ["flow"])[0]
        return self._query_cached(e0, e1, k, fam)

    # -- cached + single-flight gather/fold ----------------------------
    def _query_cached(
        self, e0: int, e1: int, k: int, fam: str
    ) -> tuple[int, dict, str]:
        ov = self._overload
        shedding = ov is not None and ov.state >= SHEDDING
        edge = self._edge if (self._newest < 0 or e1 > self._newest) else 0
        key = (e0, e1, k, fam, edge)
        ttl = float(self.cfg.fleetquery_cache_ttl_s)
        now = time.monotonic()
        with self._cache_lock:
            hit = self._cache.get(key)
        if hit is not None and (shedding or now - hit[0] < ttl):
            doc = dict(hit[1])
            if shedding and now - hit[0] >= ttl:
                doc["stale"] = True
            status = "stale" if doc.get("stale") else (
                "partial" if doc.get("coverage", {}).get("partial")
                else "ok"
            )
            return 200, doc, status
        if shedding:
            # Shedding: NEVER start a fleet scatter — a cluster-wide
            # fan-out is exactly the load this node must not add while
            # it is dropping its own. Any cached doc already served
            # above; with nothing cached, back off.
            if hit is not None:
                doc = dict(hit[1])
                doc["stale"] = True
                return 200, doc, "stale"
            return 503, {"error": "busy", "retry": True}, "busy"
        if not self._flight.acquire(blocking=False):
            if hit is not None:
                doc = dict(hit[1])
                doc["stale"] = True
                return 200, doc, "stale"
            return 503, {"error": "busy", "retry": True}, "busy"
        try:
            code, doc, status = self._query(e0, e1, k, fam)
            if code == 200:
                with self._cache_lock:
                    self._cache[key] = (time.monotonic(), doc)
                    while len(self._cache) > 128:
                        self._cache.pop(next(iter(self._cache)))
            return code, doc, status
        finally:
            self._flight.release()

    # -- the actual federated query (single flight) --------------------
    def _query(
        self, e0: int, e1: int, k: int, fam: str
    ) -> tuple[int, dict, str]:
        m = get_metrics()
        if self.clients:
            results = self._scatter(e0, e1)
            total = len(self.clients)
        else:
            assert self.ring is not None
            slots = self.ring.select(e0, e1)
            results = [{
                "node": self.ring.name,
                "epochs": [s[0] for s in slots],
                "window_s": slots[0][2] if slots else 0.0,
                "seeds": dict(slots[0][3]) if slots else {},
                "arrays": (
                    None if not slots else
                    slots[0][1] if len(slots) == 1 else
                    self.fold.fold([s[1] for s in slots], slots[0][3])
                ),
            }]
            total = 1
        answered = len(results)
        m.fleet_query_nodes_answered.set(answered)
        coverage = {
            "nodes_answered": answered,
            "nodes_total": total,
            "partial": 0 < answered < total,
        }
        m.fleet_query_coverage.set(answered / total if total else 0.0)
        doc: dict[str, Any] = {"t0": e0, "t1": e1, "coverage": coverage}
        if answered == 0:
            # A scatter nobody answered is an outage signal, not an
            # empty range.
            doc["error"] = "no nodes answered"
            return 503, doc, "error"

        # Seed agreement: sketches only merge under one seed set; a
        # misconfigured node's arrays would silently corrupt the fold.
        # MAJORITY vote, not first-answerer: mid-rotation the fold
        # follows whichever seed set most answering nodes hold, so a
        # rotated fleet re-admits as soon as the majority flips instead
        # of being held hostage by one stale (or fast) first responder.
        # Ties break deterministically on the serialized seed set.
        tally = Counter(
            tuple(sorted(r["seeds"].items()))
            for r in results if r["arrays"] is not None
        )
        winner = max(tally, key=lambda s: (tally[s], s), default=())
        seeds = dict(winner)
        parts: list[dict] = []
        epochs: set[int] = set()
        for r in results:
            if r["arrays"] is None:
                continue
            if r["seeds"] != seeds:
                self._count_node_error("seed_mismatch")
                coverage["nodes_answered"] -= 1
                coverage["partial"] = True
                continue
            parts.append(r["arrays"])
            epochs.update(int(e) for e in r["epochs"])
        if not parts:
            doc["windows"] = 0
            doc["empty"] = True
            return 200, doc, "empty"
        newest_seen = max(epochs)
        if newest_seen > self._newest:
            self._newest = newest_seen
            self._edge += 1
        doc["windows"] = len(epochs)
        doc["epochs"] = sorted(epochs)

        merged = self._fold_many(parts, seeds)
        extras = range_extract(merged, seeds)
        dec = range_decode(merged, seeds)
        keys, counts = range_topk(merged, seeds, fam=fam, k=k,
                                  est=extras.get(f"{fam}_est"))
        doc["topk"] = {
            "family": fam,
            "keys": [
                {"key": format_key(row), "count": int(c)}
                for row, c in zip(keys, counts)
            ],
        }
        doc["cardinality"] = extras.get("cardinality", 0.0)
        doc["entropy_bits"] = extras.get("entropy_bits", {})
        if dec is not None:
            srcs, pkts = dec["sources"]
            doc["decode"] = {
                "n_keys": int(len(dec["keys"])),
                "keys": [format_key(row) for row in dec["keys"][:k]],
                "est": [int(x) for x in dec["est"][:k]],
                "sources": [
                    {"src_ip": int(s), "packets": int(p)}
                    for s, p in zip(srcs[:k], pkts[:k])
                ],
            }
        return 200, doc, "partial" if coverage["partial"] else "ok"

    def _fold_many(self, parts: list[dict], seeds: dict) -> dict:
        """Chunked semilattice reduction: fold at most FOLD_CHUNK
        operands per call until one snapshot remains. Associativity
        makes this exactly the flat fold while keeping every jit
        signature small and fan-out-independent."""
        while len(parts) > 1:
            nxt = []
            for i in range(0, len(parts), FOLD_CHUNK):
                chunk = parts[i:i + FOLD_CHUNK]
                nxt.append(
                    chunk[0] if len(chunk) == 1
                    else self.fold.fold(chunk, seeds)
                )
            parts = nxt
        return parts[0]

    def _count_node_error(self, reason: str) -> None:
        get_metrics().fleet_query_node_errors.labels(reason=reason).inc()
        self.node_errors[reason] = self.node_errors.get(reason, 0) + 1

    # -- scatter with per-node deadline + hedged retry -----------------
    def _ensure_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=max(2, int(self.cfg.fleetquery_fanout)),
                    thread_name_prefix="fleetquery",
                )
            return self._pool

    def _scatter(self, e0: int, e1: int) -> list[dict]:
        m = get_metrics()
        deadline_s = float(self.cfg.fleetquery_node_deadline_s)
        hedge_s = float(self.cfg.fleetquery_hedge_delay_s)
        pool = self._ensure_pool()
        t0 = time.monotonic()
        first = {
            c.name: pool.submit(c.query, e0, e1, deadline_s)
            for c in self.clients
        }
        # Hedge window: after hedge_s of quiet, unfinished nodes get
        # one duplicate request (tail latency is usually one slow
        # replica, not a dead one).
        concurrent.futures.wait(
            list(first.values()), timeout=min(hedge_s, deadline_s)
        )
        hedged: dict[str, concurrent.futures.Future] = {}
        for c in self.clients:
            if not first[c.name].done():
                hedged[c.name] = pool.submit(c.query, e0, e1, deadline_s)
                self.hedges += 1
                m.fleet_query_hedges.inc()
        results: list[dict] = []
        for c in self.clients:
            res, reason = None, None
            budget = deadline_s - (time.monotonic() - t0)
            try:
                res = first[c.name].result(timeout=max(0.0, budget))
            except concurrent.futures.TimeoutError:
                reason = "timeout"
            except Exception:
                reason = "error"
            if res is None and c.name in hedged:
                # The hedge launched hedge_s late; give it the same
                # grace past the primary deadline.
                budget = (deadline_s + hedge_s) - (time.monotonic() - t0)
                try:
                    res = hedged[c.name].result(timeout=max(0.0, budget))
                    reason = None if res is not None else reason
                except concurrent.futures.TimeoutError:
                    reason = reason or "timeout"
                except Exception:
                    reason = reason or "error"
            if res is not None:
                results.append(res)
            else:
                self._count_node_error(reason or "dead")
        return results

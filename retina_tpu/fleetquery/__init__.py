"""fleetquery: federated time-travel range queries over the fleet.

The node tier (timetravel/) answers ``[t0, t1)`` range queries over one
process's snapshot rings. This package lifts the same contract to the
cluster: ``GET /fleet/query`` scatter-gathers per-node ring slots (or
folds the aggregator's merged-epoch ring when this process IS the
aggregator), merges them with the SAME RFLT semilattice fold the fleet
rollup uses — sketches merge across nodes exactly as they merge across
time — and answers cluster-wide top-k / cardinality / entropy with an
explicit coverage annotation (``nodes_answered / nodes_total``) when
part of the fleet misses its deadline.

The bounded-latency contract is the node tier's, verbatim: one fold in
flight, TTL result cache, serve-stale or 503-busy, immutable ranges
cached forever, SHEDDING never initiates a scatter. Fan-out adds the
federation knobs on top: per-node deadline, hedged retry after a quiet
delay, partial answers over whoever made it.
"""

from retina_tpu.fleetquery.service import FleetQueryService, LocalNodeClient

__all__ = ["FleetQueryService", "LocalNodeClient"]

"""Fleet query + detector-diversity dryrun (``bench.py --fleetquery-dryrun``).

Two arcs on one process, no fake components on the paths under test:

1. **Federated query storm.** ``nodes`` simulated fleet members
   (LocalNodeClient over per-node SnapshotRings holding the same
   synthetic windows) behind one FleetQueryService. Scrape threads
   hammer ``/fleet/query`` (FleetQueryService.handle, the exact HTTP
   handler) with a 1,000-query storm; at the midpoint 10% of the nodes
   are killed — answers must degrade to explicit partial coverage
   (``nodes_answered/nodes_total``), never to errors — and the last
   stretch runs under a forced SHEDDING state (cache-only backoff).
   The scorecard pins the p99.

2. **Detector trio closed loops.** For each builtin detector
   (detect/detectors.py: synflood, portscan, dnstunnel) a fresh
   DetectorBank + SnapshotRing + AutoCapture runs benign warmup
   windows, then one window carrying the matching attack regime mixed
   into benign background. The matching detector — and ONLY a
   detector whose regime is present — must fire at the attack window,
   win arbitration, and drive the full detect → range-query →
   invertible-decode → targeted-capture loop; attribution recall is
   measured against the exact attack key set. A benign sweep over
   every benign preset pins zero false firings.
"""

from __future__ import annotations

import json
import tempfile
import threading
import time
from typing import Any, Callable

import numpy as np

from retina_tpu.capture.manager import CaptureManager
from retina_tpu.capture.providers import ReplayProvider
from retina_tpu.config import Config
from retina_tpu.detect import DetectorBank, build_default_bank
from retina_tpu.events.synthetic import TrafficGen, preset_params
from retina_tpu.fleet.dryrun import INV_SEEDS
from retina_tpu.fleetquery.service import (
    FOLD_CHUNK, FleetQueryService, LocalNodeClient,
)
from retina_tpu.log import logger
from retina_tpu.runtime.overload import NOMINAL, SHEDDING
from retina_tpu.timetravel.autocapture import AutoCapture
from retina_tpu.timetravel.dryrun import (
    _EPOCH0, _keys_from_records, _Overload, _window_arrays,
)
from retina_tpu.timetravel.fold import RangeFold
from retina_tpu.timetravel.query import QueryService
from retina_tpu.timetravel.ring import SnapshotRing

_log = logger("fleetquery.dryrun")

# Benign presets that must never fire a detector (the FP gate).
_BENIGN_PRESETS = ("zipf", "uniform", "elephant_mice")


def _make_config(nodes: int, windows: int, out_dir: str) -> Config:
    return Config(
        node_name="fleetquery-dryrun",
        window_seconds=0.25,
        gen_preset="zipf",
        timetravel_enabled=True,
        timetravel_ring_windows=windows + 8,
        fleetquery_enabled=True,
        fleetquery_node_deadline_s=0.2,
        fleetquery_hedge_delay_s=0.01,
        fleetquery_fanout=max(2, nodes),
        fleetquery_cache_ttl_s=0.25,
        detectors_enabled=True,
        autocapture_enabled=True,
        autocapture_cooldown_s=300.0,
        autocapture_lookback_windows=2,
        autocapture_lookahead_windows=1,
        autocapture_max_sources=64,
        autocapture_duration_s=1.0,
        autocapture_max_size_mb=4,
        autocapture_output_dir=out_dir,
    )


# ---------------------------------------------------------------------
# arc 1: federated query storm
# ---------------------------------------------------------------------

def _run_storm(
    cfg: Config,
    nodes: int,
    windows: int,
    storm_threads: int,
    storm_requests: int,
    seed: int,
    fold: RangeFold,
    log: Callable[[str], None],
) -> dict[str, Any]:
    gen = TrafficGen(
        n_flows=512, n_pods=16, seed=seed, **preset_params("zipf")
    )
    ov = _Overload()
    svc = FleetQueryService(cfg, overload=ov, fold=fold)

    # Every node holds the same window set (every node closes every
    # window in a healthy fleet); slot arrays are shared host buffers,
    # so fleet memory stays one window-set regardless of node count.
    slots = [_window_arrays(gen.batch(2048)) for _ in range(windows)]
    for i in range(nodes):
        ring = SnapshotRing(windows + 4, name=f"node{i:03d}")
        for w, slot in enumerate(slots):
            ring.append_host(
                _EPOCH0 + w, slot, cfg.window_seconds, INV_SEEDS
            )
        # Deterministic latency spread; two designated stragglers sit
        # past the hedge delay (so hedging provably engages) but well
        # under the node deadline.
        latency = 0.03 if i in (3, 11 % nodes) else 0.0005 * (1 + i % 5)
        svc.add_client(
            LocalNodeClient(f"node{i:03d}", ring, svc.fold, latency)
        )

    newest = _EPOCH0 + windows - 1
    shapes = [
        {"t0": [str(_EPOCH0 + windows - 5)], "t1": [str(newest)]},
        {"t0": [str(newest - 3)], "t1": [str(newest)]},
        {"t0": [str(newest - 2)], "t1": [str(newest)], "fam": ["svc"]},
        {"last": ["3"]},
    ]

    # Prewarm: chunk-fold signatures (2..FOLD_CHUNK cover any node
    # span and any answered count), then each node's span cache
    # SEQUENTIALLY — a real fleet folds node spans on 64 machines in
    # parallel, and 64 simultaneous first-fold executions inside this
    # one process would blow the per-node deadline on CPU contention
    # the production topology doesn't have — then one pass over the
    # storm shapes (extract/decode/topk programs + the result cache).
    t_warm0 = time.monotonic()
    for n in range(2, FOLD_CHUNK + 1):
        svc.fold.fold([slots[0]] * n, INV_SEEDS)
    spans = {
        (newest - 4, newest), (newest - 3, newest),
        (newest - 2, newest), (newest - 2, newest + 1),  # last=3
    }
    for c in svc.clients:
        for e0, e1 in spans:
            c.query(e0, e1, deadline_s=30.0)
    for q in shapes:
        svc.handle(q)
    warm_s = time.monotonic() - t_warm0

    n_kill = max(1, nodes // 10)
    lat_lock = threading.Lock()
    lats: list[float] = []
    codes: dict[int, int] = {}
    statuses: dict[str, int] = {}
    coverages: set[tuple[int, int]] = set()

    def scraper(tid: int) -> None:
        for j in range(storm_requests):
            if tid == 0 and j == storm_requests // 2:
                for c in svc.clients[:n_kill]:
                    c.dead = True
                log(f"killed {n_kill}/{nodes} nodes mid-storm")
            if tid == 0 and j == (storm_requests * 9) // 10:
                ov.state = SHEDDING  # final stretch sheds
            q = shapes[(tid + j) % len(shapes)]
            t0 = time.monotonic()
            code, body, _ctype = svc.handle(q)
            dt = time.monotonic() - t0
            doc = json.loads(body)
            cov = doc.get("coverage") or {}
            with lat_lock:
                lats.append(dt)
                codes[code] = codes.get(code, 0) + 1
                s = (
                    "busy" if code == 503 else
                    "stale" if doc.get("stale") else
                    "partial" if cov.get("partial") else "ok"
                )
                statuses[s] = statuses.get(s, 0) + 1
                if cov.get("partial"):
                    coverages.add(
                        (cov["nodes_answered"], cov["nodes_total"])
                    )
            time.sleep(0.005)  # paced like scrape traffic

    threads = [
        threading.Thread(target=scraper, args=(t,), daemon=True)
        for t in range(storm_threads)
    ]
    t_storm0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    storm_s = time.monotonic() - t_storm0
    ov.state = NOMINAL
    svc.close()

    p50, p99 = (
        (float(np.percentile(lats, 50)), float(np.percentile(lats, 99)))
        if lats else (float("inf"), float("inf"))
    )
    return {
        "nodes": nodes,
        "killed": n_kill,
        "queries": len(lats),
        "codes": codes,
        "statuses": statuses,
        "hedges": svc.hedges,
        "node_errors": dict(svc.node_errors),
        "partial_coverages": sorted(coverages),
        "p50_ms": round(p50 * 1e3, 2),
        "p99_ms": round(p99 * 1e3, 2),
        "prewarm_seconds": round(warm_s, 2),
        "storm_seconds": round(storm_s, 2),
        "checks": {
            "p99_ok": p99 <= 0.1,
            "no_errors": all(c in (200, 503) for c in codes),
            # The steady post-kill answer must be exactly the
            # survivors over the full roster.
            "partial_coverage_observed": (
                (nodes - n_kill, nodes) in coverages
            ),
            "hedged": svc.hedges >= 1,
            "node_loss_counted": sum(
                v for k, v in svc.node_errors.items()
                if k in ("dead", "timeout")
            ) >= 1,
        },
    }


# ---------------------------------------------------------------------
# arc 2: detector closed loops
# ---------------------------------------------------------------------

def _attack_mix(
    name: str, gen: TrafficGen
) -> tuple[np.ndarray, np.ndarray]:
    """(attack_records, window_records): the attack regime mixed into
    enough benign background that ONLY the matching detector's
    signature is present — a port sweep rides normal traffic, it does
    not replace it (this is also what keeps the synflood detector
    quiet on a scan: SYN:ACK stays benign)."""
    if name == "synflood":
        atk = gen.ddos_batch(24576, target_pod=1, n_sources=48)
        bg = gen.batch(8192)
    elif name == "portscan":
        atk = gen.portscan_batch(24576, n_scanners=4, n_ports=24)
        bg = gen.batch(32768)
    elif name == "dnstunnel":
        atk = gen.tunnel_batch(24576, n_clients=48)
        bg = gen.batch(4096)
    else:
        raise ValueError(name)
    return atk, np.concatenate([bg, atk])


def _detector_scenario(
    cfg: Config,
    name: str,
    fold,
    seed: int,
    log: Callable[[str], None],
    windows: int = 8,
    attack_at: int = 5,
) -> dict[str, Any]:
    gen = TrafficGen(
        n_flows=256, n_pods=16, seed=seed,
        # The tunnel detector needs a real benign DNS baseline to
        # contrast against (MIN_DNS floor); the others run the default
        # 1% DNS sprinkle.
        dns_fraction=0.25 if name == "dnstunnel" else 0.01,
    )
    ring = SnapshotRing(cfg.timetravel_ring_windows, name="engine")
    qs = QueryService(cfg, fold=fold)
    qs.add_ring(ring)

    feed_lock = threading.Lock()

    def capture_source() -> np.ndarray:
        with feed_lock:
            atk, _mix = _attack_mix(name, gen)
            return np.concatenate([gen.batch(256), atk[:768]])

    manager = CaptureManager(
        provider=ReplayProvider(source=capture_source)
    )
    ac = AutoCapture(cfg, qs, ring_name="engine", manager=manager)
    ac.start()
    bank: DetectorBank = build_default_bank(cfg, sink=ac.notify)

    attack_epoch = _EPOCH0 + attack_at
    attack_keys: set[tuple[int, ...]] = set()
    fired: list[Any] = []
    for i in range(windows):
        epoch = _EPOCH0 + i
        with feed_lock:
            if i == attack_at:
                atk, rec = _attack_mix(name, gen)
                attack_keys = {
                    tuple(int(x) for x in row)
                    for row in np.unique(_keys_from_records(atk), axis=0)
                }
            else:
                rec = gen.batch(4096)
        fired += bank.observe(epoch, rec, now_s=float(i))
        ring.append_host(
            epoch, _window_arrays(rec), cfg.window_seconds, INV_SEEDS
        )
    fired += bank.flush(now_s=float(windows))

    # The loop closes: wait for the capture the winner's sink queued.
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and not ac.captures:
        time.sleep(0.05)
    capture = ac.captures[-1] if ac.captures else None
    ac.stop()

    res = qs.query_range("engine", attack_epoch - 2, attack_epoch + 2)
    dec = (res or {}).get("decode")
    recall = 0.0
    if dec is not None and attack_keys:
        decoded = {tuple(int(x) for x in row) for row in dec["keys"]}
        recall = len(decoded & attack_keys) / len(attack_keys)

    at_attack = [d for d in fired if d.epoch == attack_epoch]
    off_attack = [d for d in fired if d.epoch != attack_epoch]
    scores = {d.name: round(d.last_score, 3) for d in bank.detectors}
    out = {
        "detector": name,
        "attack_epoch": attack_epoch,
        "fired": [(d.detector, d.epoch) for d in fired],
        "final_scores": scores,
        "n_attack_keys": len(attack_keys),
        "recall": round(recall, 4),
        "capture": None if capture is None else {
            "attributed_keys": capture["attributed_keys"],
            "sources": len(capture["sources"]),
            "artifact_bytes": capture["artifact_bytes"],
        },
        "checks": {
            "fired_at_attack": any(
                d.detector == name for d in at_attack
            ),
            "no_off_window_firings": not off_attack,
            "won_arbitration": [d.detector for d in at_attack] == [name],
            "recall_ok": recall >= 0.95,
            "capture_ok": capture is not None
            and capture["artifact_bytes"] > 0,
        },
    }
    log(
        f"detector {name}: fired={out['fired']} scores={scores} "
        f"recall={recall:.3f} over {len(attack_keys)} keys, "
        f"capture={'yes' if capture else 'NO'}"
    )
    return out


def _benign_sweep(
    cfg: Config, seed: int, windows: int = 8
) -> dict[str, Any]:
    """Every benign preset through a fresh bank: zero firings."""
    firings: dict[str, list] = {}
    for preset in _BENIGN_PRESETS:
        gen = TrafficGen(
            n_flows=256, n_pods=16, seed=seed, **preset_params(preset)
        )
        bank = build_default_bank(cfg)
        fired: list = []
        for i in range(windows):
            fired += bank.observe(
                _EPOCH0 + i, gen.batch(4096), now_s=float(i)
            )
        fired += bank.flush(now_s=float(windows))
        firings[preset] = [(d.detector, d.epoch) for d in fired]
    return {
        "firings": firings,
        "checks": {
            "benign_quiet": not any(firings.values()),
        },
    }


# ---------------------------------------------------------------------

def run_fleetquery_dryrun(
    nodes: int = 64,
    windows: int = 6,
    storm_threads: int = 8,
    storm_requests: int = 125,
    seed: int = 0,
    log: Callable[[str], None] = lambda s: None,
) -> dict[str, Any]:
    """Run both arcs; returns the scorecard dict (``ok`` rolls up every
    check)."""
    out_dir = tempfile.mkdtemp(prefix="retina-fleetquery-")
    cfg = _make_config(nodes, windows, out_dir)
    fold = RangeFold()  # one compile cache across both arcs

    storm = _run_storm(
        cfg, nodes, windows, storm_threads, storm_requests, seed,
        fold, log,
    )
    log(
        f"storm: {storm['queries']} queries p50 {storm['p50_ms']}ms "
        f"p99 {storm['p99_ms']}ms, {storm['hedges']} hedges, "
        f"statuses {storm['statuses']}"
    )

    detectors: dict[str, dict] = {}
    for i, name in enumerate(("synflood", "portscan", "dnstunnel")):
        sc = _detector_scenario(cfg, name, fold, seed + 100 + i, log)
        detectors[name] = sc
    benign = _benign_sweep(cfg, seed + 7)

    checks: dict[str, bool] = {
        f"storm_{k}": v for k, v in storm["checks"].items()
    }
    for name, sc in detectors.items():
        checks.update(
            {f"{name}_{k}": v for k, v in sc["checks"].items()}
        )
    checks.update(benign["checks"])
    res: dict[str, Any] = {
        "storm": {k: v for k, v in storm.items() if k != "checks"},
        "detectors": {
            n: {k: v for k, v in sc.items() if k != "checks"}
            for n, sc in detectors.items()
        },
        "benign": benign["firings"],
        "checks": checks,
        "ok": all(checks.values()),
    }
    log(f"fleetquery dryrun ok={res['ok']}")
    return res

"""externalevents: ingest records from an external process.

Reference analog: pkg/plugin/ciliumeventobserver — connects to another
dataplane's monitor unix socket, decodes its payloads, and re-emits them
as Retina flows (ciliumeventobserver_linux.go). Generalized here: a unix
socket server accepting length-prefixed msgpack frames
``{"records": <bytes of (N,16) uint32 le>, "dns_names": {hash: name}}``
from any producer (another agent, a Go control plane, a replay tool),
re-emitted into the sink.
"""

from __future__ import annotations

import os
import socket
import struct
import threading

import msgpack
import numpy as np

from retina_tpu.config import Config
from retina_tpu.events.schema import NUM_FIELDS
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin

MAX_FRAME = 64 << 20


def send_frame(sock: socket.socket, records: np.ndarray,
               dns_names: dict[int, str] | None = None) -> None:
    """Producer-side helper: ship a record block to the plugin socket."""
    payload = msgpack.packb(
        {
            "records": np.ascontiguousarray(records, np.uint32).tobytes(),
            "dns_names": dns_names or {},
        }
    )
    sock.sendall(struct.pack("<I", len(payload)) + payload)


@registry.register
class ExternalEventsPlugin(Plugin):
    name = "externalevents"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self._server: socket.socket | None = None

    def init(self) -> None:
        path = self.cfg.external_socket
        try:
            os.unlink(path)
        except OSError:
            pass
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(path)
        self._server.listen(4)
        self._server.settimeout(0.2)
        self.log.info("listening on %s", path)

    def _serve_conn(self, conn: socket.socket, stop: threading.Event) -> None:
        conn.settimeout(0.2)
        buf = b""
        while not stop.is_set():
            try:
                chunk = conn.recv(1 << 20)
            except (TimeoutError, socket.timeout):
                continue
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while len(buf) >= 4:
                (n,) = struct.unpack_from("<I", buf)
                if n > MAX_FRAME:
                    self.log.error("frame too large (%d bytes); dropping conn", n)
                    conn.close()
                    return
                if len(buf) < 4 + n:
                    break
                frame, buf = buf[4 : 4 + n], buf[4 + n :]
                self._handle_frame(frame)
        conn.close()

    def _handle_frame(self, frame: bytes) -> None:
        try:
            doc = msgpack.unpackb(frame, strict_map_key=False)
            raw = doc["records"]
            rec = np.frombuffer(raw, np.uint32).reshape(-1, NUM_FIELDS).copy()
        except Exception:
            self.count_lost("decode", 1)
            self.log.exception("bad external frame")
            return
        names = doc.get("dns_names") or {}
        if names:
            from retina_tpu.plugins.dns import TOPIC_DNS_NAMES
            from retina_tpu.pubsub import get_pubsub

            get_pubsub().publish(TOPIC_DNS_NAMES, dict(names))
        self.emit(rec)

    def start(self, stop: threading.Event) -> None:
        assert self._server is not None
        workers: list[threading.Thread] = []
        while not stop.is_set():
            try:
                conn, _ = self._server.accept()
            except (TimeoutError, socket.timeout):
                continue
            except OSError:
                break
            t = threading.Thread(
                target=self._serve_conn, args=(conn, stop), daemon=True
            )
            t.start()
            workers.append(t)
        for t in workers:
            t.join(timeout=1.0)

    def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
            try:
                os.unlink(self.cfg.external_socket)
            except OSError:
                pass

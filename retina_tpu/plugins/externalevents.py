"""externalevents: ingest records from an external process.

Reference analog: pkg/plugin/ciliumeventobserver — connects to another
dataplane's monitor unix socket, decodes its payloads, and re-emits them
as Retina flows (ciliumeventobserver_linux.go). Generalized here: a unix
socket server accepting length-prefixed msgpack frames
``{"records": <bytes of (N,16) uint32 le>, "dns_names": {hash: name}}``
from any producer (another agent, a Go control plane, a replay tool),
re-emitted into the sink.
"""

from __future__ import annotations

import os
import socket
import threading

from retina_tpu.config import Config
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin
from retina_tpu.plugins.framing import (  # noqa: F401 — re-exported API
    MAX_FRAME,
    decode_record_frame,
    publish_dns_names,
    read_frames,
    send_frame,
)


@registry.register
class ExternalEventsPlugin(Plugin):
    name = "externalevents"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self._server: socket.socket | None = None

    def init(self) -> None:
        path = self.cfg.external_socket
        try:
            os.unlink(path)
        except OSError:  # noqa: RT101 — stale socket may not exist
            pass
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(path)
        self._server.listen(4)
        self._server.settimeout(0.2)
        self.log.info("listening on %s", path)

    def _serve_conn(self, conn: socket.socket, stop: threading.Event) -> None:
        conn.settimeout(0.2)
        try:
            read_frames(conn, stop, self._handle_frame, self.log)
        finally:
            conn.close()

    def _handle_frame(self, frame: bytes) -> None:
        try:
            rec, names = decode_record_frame(frame)
        except Exception:
            self.count_lost("decode", 1)
            self.log.exception("bad external frame")
            return
        publish_dns_names(names)
        self.emit(rec)

    def start(self, stop: threading.Event) -> None:
        assert self._server is not None
        workers: list[threading.Thread] = []
        while not stop.is_set():
            try:
                conn, _ = self._server.accept()
            except (TimeoutError, socket.timeout):
                continue
            except OSError:
                break
            t = threading.Thread(
                target=self._serve_conn, args=(conn, stop), daemon=True
            )
            t.start()
            workers.append(t)
        for t in workers:
            t.join(timeout=1.0)

    def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
            try:
                os.unlink(self.cfg.external_socket)
            except OSError:  # noqa: RT101 — socket already removed
                pass

"""packetparser: the flow-event firehose plugin.

Reference analog: pkg/plugin/packetparser — tc classifiers parse every
packet on the host device + pod veths into ``struct packet`` records that
stream to userspace over a perf ring and become flows
(packetparser_linux.go:556-652). Here the packet-parse step is the
host-side decoder (sources/pcapdecode.py, optionally the C++ native fast
path), and the plugin's start loop streams decoded record blocks into the
sink at a paced rate. Conntrack sampling/enrichment runs on-device inside
the pipeline step rather than in a kernel map (ops/conntrack.py).

Sources (cfg.event_source):
- ``synthetic``: TrafficGen Zipf flows (the trafficgen analog) at
  cfg.synthetic_rate events/s.
- ``pcap``: replay cfg.pcap_path (optionally looped), preserving record
  order; DNS names feed the host string table via pubsub.
- ``live``: AF_PACKET raw-socket capture (root only), decoded in batches.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from retina_tpu.config import Config
from retina_tpu.events.synthetic import TrafficGen, preset_params
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin, UnsupportedPlatform

BLOCK = 8192  # records per emitted block


@registry.register
class PacketParserPlugin(Plugin):
    name = "packetparser"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self._gen: TrafficGen | None = None
        self._pregen: list[np.ndarray] | None = None
        self._replay = None  # PcapReplaySource (event_source=pcap)
        self.dns_names: dict[int, str] = {}
        self._sock = None
        self._regime_switches = 0

    # -- lifecycle ---------------------------------------------------
    def generate(self) -> None:
        src = self.cfg.event_source
        if src not in ("synthetic", "pcap", "live"):
            raise ValueError(f"packetparser: unknown event_source {src!r}")
        if src == "pcap" and not self.cfg.pcap_path:
            raise ValueError("packetparser: event_source=pcap needs pcap_path")

    def compile(self) -> None:
        """Decode/prepare the source up front (the clang-compile analog:
        pay parse cost before Start, never in the hot loop).

        Synthetic block pre-generation does NOT happen here: generating
        a 2M-event ring takes ~20s on a small host, breaching the
        pluginmanager's 10s reconcile SLA (the contract this repo itself
        enforces — pluginmanager.go:25-28). The ring fills lazily inside
        the Start feed loop instead.
        """
        src = self.cfg.event_source
        if src == "synthetic":
            self._gen = TrafficGen(
                n_flows=self.cfg.synthetic_flows, n_pods=self.cfg.n_pods,
                **preset_params(self.cfg.gen_preset),
            )
            if self.cfg.gen_preset != "default":
                self.log.info(
                    "generator preset %r: %s", self.cfg.gen_preset,
                    preset_params(self.cfg.gen_preset),
                )
            if self.cfg.synthetic_pregen > 0:
                self._pregen = []
        elif src == "pcap":
            from retina_tpu.sources.pcapreplay import (
                PcapReplaySource, safe_decode_bytes,
            )

            with open(self.cfg.pcap_path, "rb") as fh:
                sd = safe_decode_bytes(fh.read())
            # Degrade, never crash: a truncated tail decodes its
            # prefix; an undecodable blob replays as empty. Either way
            # the gap is a COUNTED drop — compile() raising here would
            # take the whole source down over an operator-supplied
            # file (sources/pcapreplay.py).
            if sd.dropped:
                self.count_lost("decode", sd.dropped)
            if sd.error:
                self.log.error(
                    "pcap %s undecodable (%s): replaying empty, "
                    "drop counted", self.cfg.pcap_path, sd.error,
                )
            res = sd.result
            self._replay = PcapReplaySource(res.records, block=BLOCK)
            self.dns_names = res.dns_names
            self.log.info(
                "pcap decoded: %d/%d packets from %s",
                res.n_decoded, res.n_packets_total, self.cfg.pcap_path,
            )

    def _publish_dns_names(self, names: dict[int, str]) -> None:
        """Feed the DnsPlugin string table (externalevents does the same
        for its frames) so hubble l7_dns.query / top_dns labels resolve
        for pcap and live sources, not just external frames."""
        if not names:
            return
        from retina_tpu.plugins.dns import TOPIC_DNS_NAMES
        from retina_tpu.pubsub import get_pubsub

        get_pubsub().publish(TOPIC_DNS_NAMES, dict(names))

    def set_regime(self, preset: str) -> None:
        """Swap the synthetic generator's traffic regime LIVE (the soak
        harness rotates heavy-tail regimes mid-run). Atomic reference
        assignment: the feed loop reads ``self._gen`` once per block,
        so the switch lands on a block boundary with no lock. No-op
        for non-synthetic sources; the pre-generated ring (if any) is
        intentionally left alone — a soak runs with
        ``synthetic_pregen=0`` so every block reflects the active
        regime.
        """
        if self.cfg.event_source != "synthetic" or self._gen is None:
            return
        self._regime_switches += 1
        self._gen = TrafficGen(
            n_flows=self.cfg.synthetic_flows, n_pods=self.cfg.n_pods,
            seed=self._regime_switches,
            **preset_params(preset),
        )
        self.log.info("traffic regime -> %r (%s)", preset,
                      preset_params(preset))

    def init(self) -> None:
        if self.cfg.event_source == "live":
            self._open_socket()

    def _open_socket(self) -> None:
        import socket

        try:
            self._sock = socket.socket(
                socket.AF_PACKET, socket.SOCK_RAW, socket.htons(3)  # ETH_P_ALL
            )
        except (PermissionError, AttributeError, OSError) as e:
            raise UnsupportedPlatform(
                f"live capture needs AF_PACKET + root: {e}"
            ) from e
        if self.cfg.capture_iface:
            self._sock.bind((self.cfg.capture_iface, 0))
        self._sock.settimeout(0.1)

    # -- feed loop ---------------------------------------------------
    def start(self, stop: threading.Event) -> None:
        # Publish any names decoded during compile() only now: Start runs
        # after every plugin's Init, so the DnsPlugin subscription exists
        # (publishing from compile() would race plugin reconcile order).
        self._publish_dns_names(self.dns_names)
        src = self.cfg.event_source
        if src == "synthetic":
            self._run_synthetic(stop)
        elif src == "pcap":
            self._run_pcap(stop)
        else:
            self._run_live(stop)

    def _run_synthetic(self, stop: threading.Event) -> None:
        assert self._gen is not None
        per_block_s = BLOCK / max(self.cfg.synthetic_rate, 1.0)
        next_t = time.monotonic()
        i = 0
        # Lazy ring fill: generate in large chunks (per-call cost of the
        # Zipf sampler is O(n_flows)) sliced into emit-sized blocks,
        # interleaved with emitting — the ring completes within the
        # first ~total/rate seconds of feed instead of stalling
        # reconcile past its SLA.
        ring_total = self.cfg.synthetic_pregen * BLOCK
        chunk = BLOCK * 16
        while not stop.is_set():
            if self._pregen is not None:
                if len(self._pregen) * BLOCK < ring_total:
                    a = self._gen.batch(
                        min(chunk, ring_total - len(self._pregen) * BLOCK)
                    )
                    new = [
                        a[j : j + BLOCK] for j in range(0, len(a), BLOCK)
                    ]
                    self._pregen += new
                    if len(self._pregen) * BLOCK >= ring_total:
                        self.log.info(
                            "pre-generated %d blocks (%d events)",
                            len(self._pregen), ring_total,
                        )
                block = self._pregen[i % len(self._pregen)]
                i += 1
            else:
                block = self._gen.batch(BLOCK)
            accepted = self.emit(block)
            # Burst emit: behind schedule with a complete ring, push up
            # to 7 more pre-generated blocks before re-reading the
            # clock — at unpaced rates the per-iteration Python
            # overhead (clock reads, stop checks, ring fill branch) is
            # the source's dominant cost, and the sharded feed workers
            # downstream can absorb whole bursts. A paced feed never
            # qualifies: it is at most one block behind by design.
            if (
                accepted
                and self._pregen is not None
                and len(self._pregen) * BLOCK >= ring_total
                and time.monotonic() >= next_t + per_block_s
            ):
                for _ in range(7):
                    if not self.emit(self._pregen[i % len(self._pregen)]):
                        break  # sink full: counted, stop pushing
                    i += 1
                    next_t += per_block_s
            next_t += per_block_s
            delay = next_t - time.monotonic()
            if delay > 0:
                stop.wait(delay)
            elif accepted == 0:
                # Sink full and unpaced: yield instead of busy-spinning
                # (the loss is already counted; a hot loop here only
                # starves the feed thread of the GIL).
                stop.wait(0.001)
            else:
                next_t = time.monotonic()  # behind: don't accumulate debt

    def _run_pcap(self, stop: threading.Event) -> None:
        replay = self._replay
        assert replay is not None
        if len(replay) == 0:
            self.log.warning("pcap replay: no decodable packets")
            stop.wait()
            return
        # Looping replay (sources/pcapreplay.py): each pass re-emits
        # the capture with TS lanes rebased one capture-span forward,
        # so replayed time advances monotonically across loop seams
        # instead of jumping back to the capture start.
        while not stop.is_set():
            for block in replay.blocks():
                if stop.is_set():
                    return
                self.emit(block)
                if self.cfg.synthetic_rate > 0:
                    stop.wait(len(block) / self.cfg.synthetic_rate)
            if not self.cfg.pcap_loop:
                self.log.info("pcap replay complete")
                return

    def _run_live_native(self, stop: threading.Event) -> bool:
        """TPACKET_V3 mmap ring capture (native/afpacket.cpp): the
        kernel hands over whole blocks of frames and the C decoder
        writes records directly — no per-packet syscall or Python cost.
        Returns False when the ring is unavailable (no native lib /
        capability) so the caller can fall back to the socket loop."""
        from retina_tpu.events.schema import OP_FROM_NETWORK
        from retina_tpu.native import AfPacketRing
        from retina_tpu.sources.pcapdecode import dns_names_from_frames

        try:
            ring = AfPacketRing(
                iface=self.cfg.capture_iface, obs_point=OP_FROM_NETWORK
            )
        except RuntimeError as e:
            self.log.info("native AF_PACKET ring unavailable (%s); "
                          "using socket loop", e)
            return False
        # The init()-opened raw socket would keep receiving (and the
        # kernel keep cloning) every packet for the process lifetime —
        # the ring replaces it entirely.
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        self.log.info("live capture via TPACKET_V3 ring (iface=%r)",
                      self.cfg.capture_iface or "all")
        last_drops = 0
        try:
            while not stop.is_set():
                rec, _seen, dns_frames = ring.poll(timeout_ms=100)
                if len(rec):
                    self.emit(rec)
                if dns_frames:
                    names = dns_names_from_frames(dns_frames)
                    if names:
                        self.dns_names.update(names)
                        self._publish_dns_names(names)
                drops = ring.drops()
                if drops > last_drops:
                    self.count_lost("kernel", drops - last_drops)
                    last_drops = drops
        finally:
            ring.close()
        return True

    def _run_live(self, stop: threading.Event) -> None:
        if self._run_live_native(stop):
            return
        from retina_tpu.sources.pcapdecode import synthesize_pcap, decode_pcap_bytes

        assert self._sock is not None
        import socket as socket_mod
        import struct as struct_mod

        # Wrap raw frames in an in-memory pcap so one decoder serves all
        # sources (and the C++ fast path drops in transparently).
        hdr = struct_mod.pack(
            "<IHHiIII", 0xA1B23C4D, 2, 4, 0, 0, 65535, 1
        )
        while not stop.is_set():
            frames: list[bytes] = []
            deadline = time.monotonic() + 0.05
            while time.monotonic() < deadline and len(frames) < BLOCK:
                try:
                    frames.append(self._sock.recv(65535))
                except (TimeoutError, socket_mod.timeout):
                    break
                except OSError:
                    return
            if not frames:
                continue
            now = time.time_ns()
            parts = [hdr]
            for fr in frames:
                parts.append(
                    struct_mod.pack(
                        "<IIII", now // 10**9, now % 10**9, len(fr), len(fr)
                    )
                )
                parts.append(fr)
            res = decode_pcap_bytes(b"".join(parts))
            if res.dns_names:
                self.dns_names.update(res.dns_names)
                self._publish_dns_names(res.dns_names)
            self.emit(res.records)

    def stop(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

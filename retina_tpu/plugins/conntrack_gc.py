"""conntrack: shared connection-tracking table + GC loop.

Reference analog: pkg/plugin/conntrack — a 262,144-entry LRU BPF map
updated inline by packetparser's eBPF (``ct_process_packet``,
conntrack.c:344) with a Go-side GC loop expiring stale entries
(conntrack_linux.go:95-163); the plugin manager runs GC only when
packetparser is enabled (pluginmanager.go:140-151).

Here the table lives on device (ops/conntrack.py) and is updated inline by
the pipeline step — same shape as the reference. This plugin is the GC/
stats side: it periodically asks the engine to expire stale connections
(one tiny jitted pass) and publishes conntrack gauges.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin

GC_INTERVAL_S = 15.0  # reference conntrack_linux.go GC cadence


@registry.register
class ConntrackPlugin(Plugin):
    name = "conntrack"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.engine: Optional[Any] = None  # set by pluginmanager wiring

    def attach_engine(self, engine: Any) -> None:
        self.engine = engine

    def gc_once(self) -> dict[str, int]:
        if self.engine is None:
            return {}
        shed = getattr(self.engine, "shed_active", None)
        if shed is not None and shed("conntrack"):
            # Overload SHEDDING (runtime/overload.py): skip the GC +
            # gauge scrape pass — one fewer device round-trip per
            # cadence while the pipeline is saturated. The device
            # table keeps updating inline; entries just age until the
            # shed clears. Counted per skipped pass.
            self.engine.overload.note_shed("conntrack")
            return {}
        stats = self.engine.conntrack_gc()
        if stats:
            m = get_metrics()
            m.conntrack_packets.labels(direction="total").set(
                stats.get("packets", 0)
            )
            m.conntrack_bytes.labels(direction="total").set(
                stats.get("bytes", 0)
            )
            m.active_connections.set(stats.get("active", 0))
        return stats

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.gc_once()
            except Exception:
                self.log.exception("conntrack gc failed")
            stop.wait(GC_INTERVAL_S)

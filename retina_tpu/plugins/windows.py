"""Windows plugins (parity stubs).

Reference analogs: pkg/plugin/hnsstats (HNS/VFP port counters via hcsshim)
and pkg/plugin/pktmon (pktmon server subprocess streamed over gRPC). Both
are Windows-kernel surfaces with no Linux/TPU-host equivalent; they
register only on win32 and raise UnsupportedPlatform elsewhere, matching
the reference's _windows.go build tags.
"""

from __future__ import annotations

import sys
import threading

from retina_tpu.config import Config
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin, UnsupportedPlatform


class HnsStatsPlugin(Plugin):
    name = "hnsstats"

    def init(self) -> None:
        if sys.platform != "win32":
            raise UnsupportedPlatform("hnsstats requires Windows HNS")

    def start(self, stop: threading.Event) -> None:
        raise UnsupportedPlatform("hnsstats requires Windows HNS")


class PktmonPlugin(Plugin):
    name = "pktmon"

    def init(self) -> None:
        if sys.platform != "win32":
            raise UnsupportedPlatform("pktmon requires Windows")

    def start(self, stop: threading.Event) -> None:
        raise UnsupportedPlatform("pktmon requires Windows")


if sys.platform == "win32":  # pragma: no cover
    registry.add(HnsStatsPlugin.name, HnsStatsPlugin)
    registry.add(PktmonPlugin.name, PktmonPlugin)

"""dns: DNS request/response metrics + query-name string table.

Reference analog: pkg/plugin/dns — the Inspektor-Gadget DNS tracer turns
kernel DNS packets into flows with query/response/rcode/IPs
(dns_linux.go:49-62). Here DNS decode happens in the shared packet decoder
(sources/pcapdecode.py DNS pass), so this plugin owns the host-side pieces:
the qname hash → string table (merged from all sources via pubsub) and the
basic request/response gauges, while per-pod DNS counts and qname heavy
hitters ride the device pipeline (pod_dns rectangle, dns_hh sketch).
"""

from __future__ import annotations

import threading

import numpy as np

from retina_tpu.config import Config
from retina_tpu.events.schema import EV_DNS_REQ, EV_DNS_RESP, F
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin

QTYPE_NAMES = {1: "A", 5: "CNAME", 28: "AAAA", 12: "PTR", 15: "MX", 16: "TXT",
               33: "SRV", 6: "SOA", 2: "NS"}
RCODE_NAMES = {0: "NOERROR", 1: "FORMERR", 2: "SERVFAIL", 3: "NXDOMAIN",
               4: "NOTIMP", 5: "REFUSED"}

TOPIC_DNS_NAMES = "dns_names"  # pubsub topic carrying {hash: qname} dicts


@registry.register
class DnsPlugin(Plugin):
    name = "dns"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.names: dict[int, str] = {}  # qname hash -> name
        self._req = np.zeros(32, np.int64)  # per-qtype-slot request counts
        self._resp: dict[tuple[int, int], int] = {}
        self._lock = threading.Lock()
        self._sub: str | None = None

    def init(self) -> None:
        from retina_tpu.pubsub import get_pubsub

        self._sub = get_pubsub().subscribe(TOPIC_DNS_NAMES, self._on_names)

    def _on_names(self, table: dict[int, str]) -> None:
        with self._lock:
            self.names.update(table)
            # Bound the host string table (the device sketch is fixed-size;
            # the host side must be too).
            if len(self.names) > 65536:
                for k in list(self.names)[: len(self.names) - 65536]:
                    del self.names[k]

    def observe_records(self, records: np.ndarray) -> None:
        """Tally DNS events from a record block (called by the engine on
        the same blocks the device consumes — host-side cheap counts for
        the basic gauges; heavy aggregation stays on device)."""
        ev = records[:, F.EVENT_TYPE]
        dns_col = records[:, F.DNS]
        is_req = ev == EV_DNS_REQ
        is_resp = ev == EV_DNS_RESP
        if not (is_req.any() or is_resp.any()):
            return
        m = get_metrics()
        for qtype in np.unique(dns_col[is_req] >> 16):
            n = int(((dns_col[is_req] >> 16) == qtype).sum())
            m.dns_request_count.labels(
                query_type=QTYPE_NAMES.get(int(qtype), str(int(qtype)))
            ).inc(n)
        if is_resp.any():
            resp = dns_col[is_resp]
            pairs = np.stack([resp >> 16, (resp >> 8) & 0xFF], axis=1)
            uniq, counts = np.unique(pairs, axis=0, return_counts=True)
            for (qtype, rcode), n in zip(uniq, counts):
                m.dns_response_count.labels(
                    query_type=QTYPE_NAMES.get(int(qtype), str(int(qtype))),
                    return_code=RCODE_NAMES.get(int(rcode), str(int(rcode))),
                ).inc(int(n))

    def resolve(self, qname_hash: int) -> str:
        """Hash → query name for scrape-time heavy-hitter labels."""
        with self._lock:
            return self.names.get(qname_hash, f"unknown:{qname_hash:#x}")

    def qname_length_hist(self, nbins: int = 64) -> np.ndarray:
        """(1, nbins) f32 histogram of resolved qname string lengths —
        the high-fidelity ``extras["qname_hist"]`` feed for the
        dnstunnel detector: pcap-decoded records only carry a req/resp
        marker in the F.DNS low byte, but this table has the real
        names the wire carried."""
        hist = np.zeros((1, nbins), np.float32)
        with self._lock:
            lens = [len(v) for v in self.names.values()]
        if lens:
            ln = np.clip(np.asarray(lens, np.int64), 0, nbins - 1)
            hist[0] = np.bincount(ln, minlength=nbins).astype(np.float32)
        return hist

    def start(self, stop: threading.Event) -> None:
        stop.wait()  # passive: work happens in observe_records/pubsub

    def stop(self) -> None:
        if self._sub is not None:
            from retina_tpu.pubsub import get_pubsub

            try:
                get_pubsub().unsubscribe(TOPIC_DNS_NAMES, self._sub)
            except KeyError:  # noqa: RT101 — unsubscribe after pubsub shutdown
                pass
            self._sub = None

"""Data-plane plugins (reference pkg/plugin, SURVEY.md §2.2).

Importing this package registers every platform-supported plugin with the
registry (the reference's ``init()`` + ``registry.Add`` self-registration,
registry.go:42-47).
"""

import sys

from retina_tpu.plugins import registry
from retina_tpu.plugins.api import (
    EventSink,
    Plugin,
    QueueSink,
    UnsupportedPlatform,
)

# Self-registration imports (each module calls registry.add at import).
from retina_tpu.plugins import (  # noqa: F401
    conntrack_gc,
    dns,
    dropreason,
    externalevents,
    infiniband,
    linuxutil,
    mockplugin,
    packetforward,
    packetparser,
    tcpretrans,
)

if sys.platform == "win32":  # pragma: no cover - parity stubs
    from retina_tpu.plugins import windows  # noqa: F401

__all__ = [
    "EventSink",
    "Plugin",
    "QueueSink",
    "UnsupportedPlatform",
    "registry",
]

"""Capture output locations.

Reference analog: pkg/capture/outputlocation/ — hostPath (hostpath.go),
PVC (pvc.go), Azure blob SAS upload (blob.go), S3 (s3.go). Every location
implements {Name, Enabled, Output(srcFile)}. Blob/S3 need cloud SDKs +
credentials with network egress — both are implemented against the same
interface but report unavailable in this environment (Enabled() false
unless their SDK + creds exist), exactly how the reference disables
locations that aren't configured.
"""

from __future__ import annotations

import os
import shutil

from retina_tpu.log import logger

_log = logger("capture.output")


class HostPathOutput:
    """outputlocation/hostpath.go."""

    name = "hostpath"

    def __init__(self, path: str):
        self.path = path

    def enabled(self) -> bool:
        return bool(self.path)

    def output(self, src_file: str) -> str:
        os.makedirs(self.path, exist_ok=True)
        dst = os.path.join(self.path, os.path.basename(src_file))
        shutil.copy2(src_file, dst)
        _log.info("capture artifact: %s", dst)
        return dst


class PvcOutput(HostPathOutput):
    """outputlocation/pvc.go — a PVC is a mounted path node-side; the
    operator resolves the claim to its mount point."""

    name = "pvc"

    def __init__(self, claim: str, mount_root: str = "/mnt"):
        super().__init__(os.path.join(mount_root, claim) if claim else "")
        self.claim = claim


class BlobOutput:
    """outputlocation/blob.go — Azure blob SAS-URL upload."""

    name = "blob"

    def __init__(self, sas_url_secret: str = ""):
        self.sas_url = sas_url_secret

    def enabled(self) -> bool:
        if not self.sas_url:
            return False
        try:
            import azure.storage.blob  # noqa: F401

            return True
        except ImportError:
            _log.warning("blob output configured but azure SDK unavailable")
            return False

    def output(self, src_file: str) -> str:  # pragma: no cover - needs SDK
        from azure.storage.blob import BlobClient

        blob = BlobClient.from_blob_url(self.sas_url)
        with open(src_file, "rb") as fh:
            blob.upload_blob(fh, overwrite=True)
        return self.sas_url


class S3Output:
    """outputlocation/s3.go — S3 PutObject upload."""

    name = "s3"

    def __init__(self, bucket: str = "", region: str = "",
                 key_prefix: str = "retina/captures"):
        self.bucket, self.region, self.key_prefix = bucket, region, key_prefix

    def enabled(self) -> bool:
        if not self.bucket:
            return False
        try:
            import boto3  # noqa: F401

            return True
        except ImportError:
            _log.warning("s3 output configured but boto3 unavailable")
            return False

    def output(self, src_file: str) -> str:  # pragma: no cover - needs SDK
        import boto3

        key = f"{self.key_prefix}/{os.path.basename(src_file)}"
        boto3.client("s3", region_name=self.region).upload_file(
            src_file, self.bucket, key
        )
        return f"s3://{self.bucket}/{key}"


def outputs_from_spec(output: dict) -> list:
    """Build enabled output sinks from a CaptureOutput-shaped dict."""
    sinks = [
        HostPathOutput(output.get("host_path", "")),
        PvcOutput(output.get("persistent_volume_claim", "")),
        BlobOutput(output.get("blob_upload_secret", "")),
        S3Output(**{
            k: v for k, v in (output.get("s3_upload") or {}).items()
            if k in ("bucket", "region", "key_prefix")
        }),
    ]
    return [s for s in sinks if s.enabled()]
